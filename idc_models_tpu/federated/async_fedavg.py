"""Async buffered FedAvg (FedBuff): stragglers stop gating the round.

The synchronous round — one-shot or streamed (population.py) — is a
BARRIER: the server cannot update until its slowest cohort member
reports, so one straggler sets the round's wall-clock (exactly the
failure mode the PR 3 fault plans inject and the PR 7 round-latency
SLOs observe). The buffered-asynchronous server (Nguyen et al.,
*FedBuff*) removes the barrier:

- a CONTINUOUS sampled dispatch stream keeps `concurrency` virtual
  clients in flight; each trains against the server params of its
  dispatch moment and completes after a seeded duration (base latency
  + the fault plan's straggler delay);
- completions fill a buffer of size K; a full buffer triggers ONE
  staleness-weighted server update (weight x `staleness_decay**s`,
  where s = server updates since the client's dispatch) instead of a
  round barrier;
- a straggler's slot is simply refilled — its update lands rounds
  later with a high staleness discount, while the server keeps moving
  on everyone else's work.

Mapped onto `federated/driver.py run_rounds`, one driver "round" =
dispatch-and-process `cohort_size` completions (however many buffered
updates that triggers), so the self-healing loop, round-latency SLOs,
`fed.client` markers, checkpoints, and `round_health` events all apply
unchanged. Under an injected straggler plan the sync round's wall is
max(delay) per round and its latency SLO burns; the async round's wall
is set by the K earliest arrivals and the same SLO stays silent —
`bench_federated_robustness` asserts both.

Memory: in-flight state is (arrival, client id, version) tuples plus
one retained param snapshot per server version still referenced —
O(concurrency) bookkeeping and O(ceil(concurrency/K) + staleness span)
model-sized snapshots, independent of the population size.

Determinism: every choice — dispatch stream, durations, fault codes,
per-client rng — is a pure function of (seed, dispatch index), and
arrivals pop in (arrival time, dispatch index) order, so a full run
replays bit-identically (gated). A RESUMED run restarts with an empty
in-flight pool at the checkpointed round boundary (in-flight work is
not checkpointed — the honest analogue of a real server restart,
documented in docs/ROBUSTNESS.md).

Secure aggregation CANNOT compose with buffering: the pairwise masks
cancel only when the full round cohort sums together, and a K-of-N
buffered update leaves unmatched masks in the aggregate —
`ensure_async_compatible` rejects the combination at build with that
explanation (gated in tests and at the CLI).
"""

from __future__ import annotations

import heapq
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu import faults as faults_lib
from idc_models_tpu.federated.fedavg import (
    ServerState, copy_tree, finite_clients, make_local_trainer,
)
from idc_models_tpu.federated.population import (
    ClientPopulation, CohortSampler,
)
from idc_models_tpu.observe import metrics_registry as mreg

# staleness histogram buckets for the fed_cohort event: updates at lag
# 0,1,2,3,4 and a 5+ tail — frozen with the event schema
STALENESS_BUCKETS = 6


def ensure_async_compatible(*, secure: bool, aggregator=None) -> None:
    """Reject compositions the buffered server cannot honor, at build.

    Secure aggregation: each client's pairwise masks cancel only in the
    sum over the FULL round cohort; a buffered K-of-N update would
    carry every unmatched mask straight into the server params —
    silently destroying the model while "working". Trimmed/median
    aggregation: order statistics need a synchronized cohort view,
    which is the barrier async removes — use norm_clip (per-client,
    composes exactly) or the sync streamed round.
    """
    from idc_models_tpu.federated import robust

    if secure:
        raise ValueError(
            "async buffered FedAvg cannot compose with secure "
            "aggregation: pairwise masks cancel only when the FULL "
            "cohort sums together in one round, and a buffered K-of-N "
            "update leaves unmatched masks in the aggregate — run "
            "secure rounds synchronously, or drop --async-buffer")
    if aggregator is not None and isinstance(
            aggregator, (robust.TrimmedMean, robust.Median)):
        raise ValueError(
            f"{type(aggregator).__name__} cannot compose with async "
            f"buffering: coordinate-wise order statistics need a "
            f"synchronized cohort view, which is exactly the barrier "
            f"the buffer removes — use norm_clip (per-client bound, "
            f"composes exactly) or the sync streamed round")


def make_async_round(
    model,
    optimizer,
    loss_fn,
    population: ClientPopulation,
    sampler: CohortSampler,
    *,
    buffer_size: int,
    staleness_decay: float = 0.9,
    concurrency: int | None = None,
    local_epochs: int = 1,
    batch_size: int = 32,
    compute_dtype=jnp.float32,
    drop_nonfinite: bool = True,
    aggregator=None,
    faults=None,
    base_latency_s: tuple[float, float] = (0.0, 0.0),
    realtime: bool = False,
    seed: int = 0,
    secure_aggregation: bool = False,
    logger=None,
    log_from_round: int = -1,
):
    """Build the buffered-async round (driver-compatible signature).

    ``round_fn(server, images, labels, weights, rng, *, round_idx=None)``
    processes `cohort_size` client completions: dispatches keep
    `concurrency` (default: the sampler's cohort size) clients in
    flight from the continuous sampled stream, every `buffer_size`
    completions trigger one staleness-weighted server update, and the
    returned metrics carry the buffered-mode observability
    (updates/staleness/buffer fill). `weights`, when given, only sets
    how many completions the attempt processes (the driver's
    reseeded-subset retry shrinks it) — the stream itself is a pure
    function of (seed, dispatch index).

    `aggregator` may be None/WeightedMean (plain staleness-weighted
    mean) or a NormClip instance (each buffered delta is L2-clipped
    before weighting — exact composition); trimmed/median and secure
    mode are rejected by `ensure_async_compatible` at build.

    `realtime=True` maps simulated arrival times onto the wall clock
    (sleeping until each processed completion's arrival) — the mode
    the wall-clock drills run; leave False for full-speed unit tests.
    """
    from idc_models_tpu.federated import robust

    ensure_async_compatible(secure=secure_aggregation,
                            aggregator=robust.get_aggregator(aggregator)
                            if aggregator is not None else None)
    agg = robust.get_aggregator(aggregator)
    clip_norm = agg.max_norm if isinstance(agg, robust.NormClip) else None
    if buffer_size < 1:
        raise ValueError(f"need buffer_size >= 1, got {buffer_size}")
    if not 0.0 < staleness_decay <= 1.0:
        raise ValueError(
            f"staleness_decay must be in (0, 1], got {staleness_decay} "
            f"(1.0 = no discount; smaller discounts staler updates "
            f"harder)")
    concurrency = (sampler.cohort_size if concurrency is None
                   else int(concurrency))
    if concurrency < 1:
        raise ValueError(f"need concurrency >= 1, got {concurrency}")
    if buffer_size > concurrency:
        raise ValueError(
            f"buffer_size {buffer_size} > concurrency {concurrency}: "
            f"the buffer could never fill — shrink the buffer or raise "
            f"concurrency")
    lo, hi = float(base_latency_s[0]), float(base_latency_s[1])
    if not 0.0 <= lo <= hi:
        raise ValueError(f"base_latency_s must be 0 <= lo <= hi, got "
                         f"{base_latency_s}")
    if faults is not None and faults.population != population.size:
        raise ValueError(
            f"fault plan covers a population of {faults.population} "
            f"but the server trains {population.size} virtual clients")
    if not population.same_config(sampler.population):
        raise ValueError(
            "sampler and server must draw from the same virtual "
            "population (size/seed/shape differ) — the server would "
            "train different clients than it sampled")

    local_train = make_local_trainer(
        model, optimizer, loss_fn, local_epochs=local_epochs,
        batch_size=batch_size, compute_dtype=compute_dtype)

    def train_one(params, model_state, imgs, labels, rng):
        new_p, new_ms, (losses, accs) = local_train(
            params, model_state, imgs, labels, rng)
        return new_p, new_ms, jnp.mean(losses), jnp.mean(accs)

    train_jit = jax.jit(train_one)
    K = int(buffer_size)

    def apply_buffer(params, model_state, cl, snap, wts, decays,
                     codes, scales):
        """One buffered server update: staleness-decayed weighted mean
        of K client deltas, each taken against ITS OWN dispatch-time
        snapshot. `wts` are the RAW client weights and `decays` the
        per-update staleness factors — the denominator normalizes by
        the raw weights so the discount attenuates a stale update's
        contribution ABSOLUTELY (normalizing by decayed weights would
        cancel a uniform discount: a buffer of equally-stale updates
        must still take a smaller step, not a full one). `decay=1`
        recovers the plain weighted mean bit-for-bit. Fault codes
        transform the deltas exactly like the sync path's
        `apply_faults` (straggler codes are inert here — async
        staleness IS the fault model)."""
        server = (params, model_state)
        ok = jnp.ones((K,), bool)
        if drop_nonfinite:
            ok = finite_clients(K, cl)

        def leafwise(new, old):
            shape = (K,) + (1,) * (new.ndim - 1)
            if not jnp.issubdtype(new.dtype, jnp.inexact):
                return new
            c = codes.reshape(shape)
            s = scales.reshape(shape).astype(new.dtype)
            delta = new - old
            out = jnp.where(c == faults_lib.NAN,
                            jnp.asarray(jnp.nan, new.dtype), new)
            out = jnp.where(c == faults_lib.INF,
                            jnp.asarray(jnp.inf, new.dtype), out)
            out = jnp.where(c == faults_lib.SCALE, old + s * delta, out)
            out = jnp.where(c == faults_lib.SIGN_FLIP,
                            old - s * delta, out)
            return out

        cl = jax.tree.map(leafwise, cl, snap)
        if drop_nonfinite:
            ok = ok & finite_clients(K, cl)
        w = jnp.where(ok, jnp.maximum(wts, 0.0), 0.0)
        dropped = jnp.sum((jnp.maximum(wts, 0.0) > 0) & ~ok).astype(
            jnp.float32)

        if clip_norm is not None:
            sq = jnp.zeros((K,), jnp.float32)
            for new, old in zip(jax.tree.leaves(cl),
                                jax.tree.leaves(snap)):
                if not jnp.issubdtype(new.dtype, jnp.inexact):
                    continue
                d = (new - old).astype(jnp.float32)
                sq = sq + jnp.sum(d * d,
                                  axis=tuple(range(1, d.ndim)))
            factor = jnp.minimum(
                1.0, clip_norm / jnp.maximum(jnp.sqrt(sq), 1e-12))
            clipped = jnp.sum(
                jnp.where(w > 0, (jnp.sqrt(sq)
                                  > clip_norm).astype(jnp.float32),
                          0.0))
        else:
            factor = jnp.ones((K,), jnp.float32)
            clipped = jnp.zeros((), jnp.float32)

        total = jnp.maximum(jnp.sum(w), jnp.float32(1e-30))
        any_alive = jnp.sum(w) > 0
        aw = w * decays

        def combine(cur, new, old):
            if not jnp.issubdtype(new.dtype, jnp.inexact):
                return cur
            shape = (K,) + (1,) * (new.ndim - 1)
            f = factor.reshape(shape).astype(new.dtype)
            wb = aw.reshape(shape).astype(new.dtype)
            delta = f * (new - old)
            step = jnp.where(wb > 0, wb * delta,
                             jnp.zeros_like(delta)).sum(axis=0)
            out = cur + step / total.astype(cur.dtype)
            return jnp.where(any_alive, out, cur)

        new_server = jax.tree.map(combine, server, cl, snap)
        return new_server[0], new_server[1], dropped, clipped

    apply_jit = jax.jit(apply_buffer, donate_argnums=(0, 1))

    m_buffer = mreg.REGISTRY.gauge(
        "fed_buffer_fill", "client updates currently buffered by the "
        "async federated server")
    m_updates = mreg.REGISTRY.counter(
        "fed_async_updates_total", "staleness-weighted buffered server "
        "updates applied")
    m_staleness = mreg.REGISTRY.histogram(
        "fed_update_staleness", "server-update lag (server versions) "
        "of buffered client updates when applied",
        buckets=(0.5, 1.5, 2.5, 3.5, 4.5))

    # --- simulation state (closure; survives across driver rounds) ----
    state: dict[str, Any] = {
        "version": 0,            # server updates applied so far
        "dispatch_i": 0,         # continuous dispatch-stream index
        "heap": [],              # (arrival_s, dispatch_i, cid, version)
        "buffer": [],            # completed-but-unapplied updates
        "snapshots": {},         # version -> (params, ms) copy
        "refs": {},              # version -> in-flight + buffered count
        "sim_t": 0.0,
        "wall_t0": None,
        "crashed": 0,
        "last_round": None,      # retry/rollback detector
        "logged_rounds": set(),  # ONE fed_cohort record per round
    }

    def _reset_inflight() -> None:
        """Drop every in-flight dispatch and buffered update. Called
        when the driver RETRIES or rolls back a round (round index not
        advancing): the pool's pending work was trained against the
        discarded attempt's params, and re-applying it to the restored
        server would re-poison exactly what the rollback threw away."""
        state["heap"].clear()
        state["buffer"].clear()
        state["snapshots"] = {
            v: s for v, s in state["snapshots"].items()
            if v == state["version"]}
        state["refs"] = {v: 0 for v in state["snapshots"]}

    def _duration(i: int, cid: int, round_idx: int) -> float:
        d = lo if lo == hi else float(
            lo + (hi - lo) * np.random.default_rng((seed, 5, i)).random())
        if faults is not None:
            d += float(faults.delay_s(round_idx, np.asarray([cid]))[0])
        return d

    def _retain(server: ServerState):
        v = state["version"]
        if v not in state["snapshots"]:
            state["snapshots"][v] = copy_tree(
                (server.params, server.model_state))
            state["refs"][v] = 0
        state["refs"][v] += 1
        return v

    def _release(v: int):
        state["refs"][v] -= 1
        if state["refs"][v] == 0 and v != state["version"]:
            del state["snapshots"][v], state["refs"][v]

    def _dispatch(server: ServerState, round_idx: int) -> bool:
        """Sample + dispatch one client; False when it crashed (no
        completion will ever arrive — its sampled slot is simply
        refilled, which is what a real server sees)."""
        i = state["dispatch_i"]
        state["dispatch_i"] += 1
        cid = sampler.client_at(i)
        code = faults_lib.OK
        scale = 1.0
        if faults is not None:
            c, s = faults.codes_for(round_idx, np.asarray([cid]))
            code, scale = int(c[0]), float(s[0])
        if code == faults_lib.CRASH:
            state["crashed"] += 1
            return False
        v = _retain(server)
        heapq.heappush(state["heap"],
                       (state["sim_t"] + _duration(i, cid, round_idx),
                        i, cid, v, code, scale))
        return True

    def _fill(server: ServerState, round_idx: int) -> None:
        misses = 0
        while len(state["heap"]) < concurrency:
            if not _dispatch(server, round_idx):
                misses += 1
                if misses > 1_000 * concurrency:
                    raise RuntimeError(
                        f"could not keep {concurrency} clients in "
                        f"flight after {misses} crashed dispatches — "
                        f"the fault plan crashes (nearly) the whole "
                        f"population")

    def round_fn(server: ServerState, images=None, labels=None,
                 weights=None, rng=None, *, round_idx: int | None = None):
        r = int(server.round) if round_idx is None else int(round_idx)
        n_process = sampler.cohort_size
        if weights is not None:
            mask = np.asarray(jax.device_get(weights), np.float32)
            n_process = max(int((mask > 0).sum()), 1)
        if state["last_round"] is not None and r <= state["last_round"]:
            # the driver is retrying (or rolled back past) this round:
            # everything in flight belongs to the discarded attempt
            _reset_inflight()
        state["last_round"] = r
        # cleared at ENTRY: if this attempt raises mid-round, the
        # driver's fed.client markers must not name the PREVIOUS
        # attempt's completions as this attempt's participants
        round_fn.last_participants = np.zeros((0,), np.int64)
        if state["wall_t0"] is None:
            state["wall_t0"] = time.monotonic()
        params, model_state = server.params, server.model_state
        # the incoming server IS the current version's params: refresh
        # the live snapshot so dispatches reference what the driver
        # actually handed us (a rollback re-anchors here)
        state["snapshots"].setdefault(state["version"], None)
        state["refs"].setdefault(state["version"], 0)
        state["snapshots"][state["version"]] = copy_tree(
            (params, model_state))

        processed_ids: list[int] = []
        stalenesses: list[int] = []
        updates_applied = 0
        dropped_total = 0.0
        clipped_total = 0.0
        crashed_before = state["crashed"]
        wloss = wacc = wtot = 0.0
        _fill(server, r)
        for _ in range(n_process):
            arrival, i, cid, v, code, scale = heapq.heappop(
                state["heap"])
            state["sim_t"] = max(state["sim_t"], arrival)
            if realtime:
                ahead = (state["wall_t0"] + state["sim_t"]
                         - time.monotonic())
                if ahead > 0:
                    time.sleep(ahead)
            snap_p, snap_ms = state["snapshots"][v]
            imgs, lbls = population.shard(cid)
            crng = jax.random.fold_in(jax.random.key(seed), i)
            new_p, new_ms, loss, acc = train_jit(
                snap_p, snap_ms, jnp.asarray(imgs), jnp.asarray(lbls),
                crng)
            s = state["version"] - v
            cw = population.weight(cid)
            state["buffer"].append(
                ((new_p, new_ms), (snap_p, snap_ms), cw,
                 staleness_decay ** s, code, scale))
            stalenesses.append(s)
            m_staleness.observe(float(s))
            processed_ids.append(cid)
            wloss += cw * float(loss)
            wacc += cw * float(acc)
            wtot += cw
            _release(v)
            _fill(server.replace(params=params,
                                 model_state=model_state), r)

            if len(state["buffer"]) >= K:
                buf, state["buffer"] = state["buffer"][:K], \
                    state["buffer"][K:]
                cl = jax.tree.map(lambda *xs: jnp.stack(xs),
                                  *[b[0] for b in buf])
                snap = jax.tree.map(lambda *xs: jnp.stack(xs),
                                    *[b[1] for b in buf])
                wts = jnp.asarray([b[2] for b in buf], jnp.float32)
                decays = jnp.asarray([b[3] for b in buf], jnp.float32)
                codes = jnp.asarray([b[4] for b in buf], jnp.int32)
                scales = jnp.asarray([b[5] for b in buf], jnp.float32)
                params, model_state, dropped, clipped = apply_jit(
                    params, model_state, cl, snap, wts, decays, codes,
                    scales)
                dropped_total += float(dropped)
                clipped_total += float(clipped)
                state["version"] += 1
                state["snapshots"][state["version"]] = copy_tree(
                    (params, model_state))
                state["refs"].setdefault(state["version"], 0)
                updates_applied += 1
                m_updates.inc()
                # prune the superseded snapshot if nothing references it
                for old_v in [vv for vv, n in state["refs"].items()
                              if n == 0 and vv != state["version"]]:
                    del state["snapshots"][old_v], state["refs"][old_v]

        m_buffer.set(len(state["buffer"]))
        new_server = server.replace(
            round=server.round + 1, params=params,
            model_state=model_state)
        st = np.asarray(stalenesses, np.float64)
        hist = np.bincount(
            np.minimum(st.astype(np.int64), STALENESS_BUCKETS - 1),
            minlength=STALENESS_BUCKETS).tolist() if len(st) else \
            [0] * STALENESS_BUCKETS
        safe = max(wtot, 1e-30)
        metrics = {
            "loss": wloss / safe if wtot > 0 else float("nan"),
            "accuracy": wacc / safe if wtot > 0 else float("nan"),
            "clients_dropped": dropped_total,
            "clients_clipped": clipped_total,
            "cohort": sampler.cohort_size,
            "participants": len(processed_ids),
            "updates": updates_applied,
            "buffer_fill": len(state["buffer"]),
            "staleness_mean": float(st.mean()) if len(st) else 0.0,
            "staleness_max": int(st.max()) if len(st) else 0,
            "crashed": state["crashed"] - crashed_before,
        }
        round_fn.last_participants = np.asarray(processed_ids, np.int64)
        if (logger is not None and r > log_from_round
                and r not in state["logged_rounds"]):
            # one record per ROUND: a driver retry re-runs the round
            # but must not re-log (same contract as the CLI's
            # append-only round records)
            state["logged_rounds"].add(r)
            logger.log(event="fed_cohort", round=r, mode="async",
                       population=population.size,
                       cohort=sampler.cohort_size,
                       participants=len(processed_ids),
                       buffer=K, updates=updates_applied,
                       staleness_mean=metrics["staleness_mean"],
                       staleness_max=metrics["staleness_max"],
                       staleness_hist=hist)
        return new_server, metrics

    round_fn.last_participants = np.zeros((0,), np.int64)
    round_fn.sampler = sampler
    round_fn.population = population
    round_fn.buffer_size = K
    round_fn.staleness_decay = float(staleness_decay)
    return round_fn
