from idc_models_tpu.federated.fedavg import (  # noqa: F401
    ServerState,
    initialize_server,
    make_fedavg_round,
    make_federated_eval,
    seed_server_with,
)
from idc_models_tpu.federated.robust import (  # noqa: F401
    Aggregator,
    Median,
    NormClip,
    TrimmedMean,
    WeightedMean,
    get_aggregator,
)
from idc_models_tpu.federated.driver import (  # noqa: F401
    DriverConfig,
    DriverResult,
    RoundFailure,
    run_rounds,
)
from idc_models_tpu.federated.population import (  # noqa: F401
    ClientPopulation,
    CohortSampler,
    make_population_round,
)
from idc_models_tpu.federated.async_fedavg import (  # noqa: F401
    ensure_async_compatible,
    make_async_round,
)
