from idc_models_tpu.federated.fedavg import (  # noqa: F401
    ServerState,
    initialize_server,
    make_fedavg_round,
    make_federated_eval,
    seed_server_with,
)
