"""Byzantine-robust aggregation hooks for the FedAvg round boundary.

`drop_nonfinite` (fedavg.py) catches clients whose updates went NaN/Inf,
but a FINITE-but-malicious update — a gradient-scaling or sign-flip
attacker (faults.py) — sails through every finite-ness check and, under
the weighted mean, steers the server arbitrarily: the mean has breakdown
point 0. The aggregators here bound that influence:

- ``WeightedMean``     the existing behavior (example-weighted mean) —
                       fastest, zero robustness;
- ``NormClip(c)``      each client's update delta is L2-clipped to norm
                       c before the weighted mean: one attacker moves
                       the server at most c/n per round, honest updates
                       (typically « c) pass untouched;
- ``TrimmedMean(t)``   coordinate-wise: drop the t lowest and t highest
                       values among participating clients, mean the
                       rest. Tolerates up to t Byzantine clients and
                       needs n_alive > 2t (breakdown point t < n/2);
- ``Median``           coordinate-wise median — the t = ⌊(n−1)/2⌋
                       extreme of trimming, maximally robust, highest
                       variance.

All are jit-traceable and run INSIDE the round's shard_map body over the
"client" mesh axis, so robustness costs no extra host round-trips.
TrimmedMean/Median all-gather the per-client update leaves across the
axis (the coordinate-wise order statistics need every client's value),
which bounds their scale: fine for O(10-100) clients on ICI, the regime
the reference simulates. NormClip and WeightedMean stay collective-lean
(one psum) and are also compatible with the secure-aggregation masked
path, where per-client transforms are allowed but cross-client
PLAINTEXT views (sorting!) are exactly what the protocol forbids —
`secure_compatible` records which is which, and
`make_secure_fedavg_round` enforces it.

Per-round metrics report how many clients were clipped
(``clients_clipped``) or near-always trimmed (``clients_trimmed``) — a
live detector for who is attacking.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from idc_models_tpu import collectives


class Aggregator:
    """One round-boundary aggregation policy.

    ``per_client(updates, server)`` is the optional per-client
    transform (leaves carry the leading [k] client axis; `server` is the
    incoming global tree) returning (updates, {name: [k] metric});
    ``combine(updates, weight, server, axis_name)`` reduces across the
    client axis to the new global tree plus scalar metrics. Calling the
    aggregator runs both and globalizes the per-client metrics (counted
    over weight>0 clients only — padding dummies and dropped clients
    are not "clipped").
    """

    name = "base"
    secure_compatible = False

    def per_client(self, updates, server):
        return updates, {}

    def combine(self, updates, weight, server, axis_name):
        raise NotImplementedError

    def __call__(self, updates, weight, server, axis_name):
        updates, per_client_m = self.per_client(updates, server)
        agg, metrics = self.combine(updates, weight, server, axis_name)
        for key, vals in per_client_m.items():
            metrics[key] = collectives.psum(
                jnp.sum(jnp.where(weight > 0, vals, 0.0)), axis_name)
        return agg, metrics

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


class WeightedMean(Aggregator):
    """The example-weighted mean — current FedAvg behavior, bit-for-bit
    (TFF parity; weight=1 recovers the reference's unweighted server)."""

    name = "mean"
    secure_compatible = True

    def combine(self, updates, weight, server, axis_name):
        return collectives.weighted_pmean_local(updates, weight,
                                                axis_name), {}


class NormClip(Aggregator):
    """Per-client update-norm clipping before the weighted mean.

    Each client's delta (update − server) is L2-clipped across ALL
    leaves to `max_norm`, so a scaling attacker contributes at most as
    much displacement as a large honest update — influence is bounded
    by c·w/Σw per round — while honest updates below the threshold are
    bit-untouched (factor exactly 1). Secure-compatible: the clip is a
    per-client transform, the aggregate stays a mean.
    """

    name = "norm_clip"
    secure_compatible = True

    def __init__(self, max_norm: float = 10.0):
        if not max_norm > 0:
            raise ValueError(f"need max_norm > 0, got {max_norm}")
        self.max_norm = float(max_norm)

    def per_client(self, updates, server):
        leaves = [(new, old) for new, old in zip(
            jax.tree.leaves(updates), jax.tree.leaves(server))
            if jnp.issubdtype(new.dtype, jnp.inexact)]
        k = jax.tree.leaves(updates)[0].shape[0]
        sq = jnp.zeros((k,), jnp.float32)
        for new, old in leaves:
            d = (new - old[None]).astype(jnp.float32)
            sq = sq + jnp.sum(d * d, axis=tuple(range(1, d.ndim)))
        norm = jnp.sqrt(sq)
        factor = jnp.minimum(1.0, self.max_norm
                             / jnp.maximum(norm, 1e-12))

        def clip(new, old):
            if not jnp.issubdtype(new.dtype, jnp.inexact):
                return new
            f = factor.reshape((k,) + (1,) * (new.ndim - 1)).astype(
                new.dtype)
            return old[None] + f * (new - old[None])

        clipped = jax.tree.map(clip, updates, server)
        return clipped, {"clients_clipped":
                         (norm > self.max_norm).astype(jnp.float32)}

    def combine(self, updates, weight, server, axis_name):
        return collectives.weighted_pmean_local(updates, weight,
                                                axis_name), {}

    def __repr__(self) -> str:
        return f"NormClip(max_norm={self.max_norm})"


def _gathered_alive(weight, axis_name):
    """([C] bool alive, n_alive int32) across the whole client axis."""
    w_all = collectives.all_gather(weight, axis_name, axis=0, tiled=True)
    alive = w_all > 0
    return alive, jnp.sum(alive).astype(jnp.int32)


class TrimmedMean(Aggregator):
    """Coordinate-wise trimmed mean over the participating clients.

    Per coordinate: sort the alive clients' values (dead clients pinned
    to +inf, past the kept band; NaNs sort after +inf — also out), drop
    the `trim` lowest and `trim` highest, mean the rest. UNWEIGHTED
    over the kept values — order statistics have no natural example
    weighting, and a Byzantine client could otherwise buy influence by
    claiming a huge example count. Guarantee: up to `trim` Byzantine
    clients cannot move any coordinate outside the honest clients'
    value range; needs n_alive > 2·trim. A plan that can NEVER satisfy
    that (2·trim >= total client slots) is rejected at build/trace
    time; a round where the live population dips to n_alive <= 2·trim
    (dead weights, dropped clients) keeps the INCOMING server state for
    that round and reports ``trim_degenerate`` = 1 — a silent all-zero
    aggregate must never replace the model.

    ``clients_trimmed`` counts alive clients whose coordinates fell in
    the trimmed band ≥90% of the time — honest clients under random
    trimming land there ~2t/n of the time, an attacker ~always, so the
    metric is the live suspected-Byzantine count.
    """

    name = "trimmed_mean"
    secure_compatible = False

    def __init__(self, trim: int = 1, *, track_clients: bool = True):
        if trim < 0:
            raise ValueError(f"need trim >= 0, got {trim}")
        self.trim = int(trim)
        self.track_clients = track_clients

    def combine(self, updates, weight, server, axis_name):
        alive, n_alive = _gathered_alive(weight, axis_name)
        n_total = alive.shape[0]
        if n_total <= 2 * self.trim:
            raise ValueError(
                f"trim={self.trim} can never keep a value: only "
                f"{n_total} client slots exist and 2*trim of them are "
                f"always dropped — lower trim below {n_total / 2:.0f} "
                f"or add clients")
        lo = jnp.int32(self.trim)
        hi = n_alive - self.trim
        # n_alive <= 2*trim at runtime (dead weights): the kept band is
        # empty — keep the incoming server state rather than emit the
        # degenerate 0/1 "mean", and flag it
        band_ok = hi > lo
        denom = jnp.maximum(hi - lo, 1).astype(jnp.float32)
        trimmed_counts = jnp.zeros((n_total,), jnp.float32)
        n_coords = 0

        def per_leaf(x_k, old):
            nonlocal trimmed_counts, n_coords
            if not jnp.issubdtype(x_k.dtype, jnp.inexact):
                return collectives.weighted_pmean_local(
                    x_k, weight, axis_name)
            x = collectives.all_gather(x_k, axis_name, axis=0,
                                       tiled=True)
            mask_shape = (n_total,) + (1,) * (x.ndim - 1)
            xm = jnp.where(alive.reshape(mask_shape), x,
                           jnp.asarray(jnp.inf, x.dtype))
            srt = jnp.sort(xm, axis=0)
            ranks = jnp.arange(n_total).reshape(mask_shape)
            keep = (ranks >= lo) & (ranks < hi)
            agg = (jnp.where(keep, srt, 0.0).astype(jnp.float32).sum(0)
                   / denom)
            if self.track_clients:
                order = jnp.argsort(xm, axis=0)
                rank_of = jnp.argsort(order, axis=0)
                out_of_band = (rank_of < lo) | (rank_of >= hi)
                trimmed_counts = trimmed_counts + out_of_band.reshape(
                    n_total, -1).sum(axis=1).astype(jnp.float32)
                n_coords += int(x[0].size)
            return jnp.where(band_ok, agg.astype(x_k.dtype), old)

        agg = jax.tree.map(per_leaf, updates, server)
        metrics = {"trim_degenerate":
                   (~band_ok).astype(jnp.float32)}
        if self.track_clients and n_coords:
            frac = trimmed_counts / float(n_coords)
            metrics["clients_trimmed"] = jnp.sum(
                jnp.where(alive, (frac >= 0.9).astype(jnp.float32), 0.0))
        return agg, metrics

    def __repr__(self) -> str:
        return f"TrimmedMean(trim={self.trim})"


class Median(Aggregator):
    """Coordinate-wise median over the participating clients — the
    maximally-trimmed estimator: any minority coalition (< n_alive/2)
    cannot move a coordinate outside the honest value range. Dead
    clients are pinned past the median (+inf); even counts average the
    two middle order statistics."""

    name = "median"
    secure_compatible = False

    def combine(self, updates, weight, server, axis_name):
        alive, n_alive = _gathered_alive(weight, axis_name)
        n_total = alive.shape[0]
        i_lo = jnp.maximum((n_alive - 1) // 2, 0)
        i_hi = jnp.maximum(n_alive // 2, 0)

        def per_leaf(x_k, old):
            if not jnp.issubdtype(x_k.dtype, jnp.inexact):
                return collectives.weighted_pmean_local(
                    x_k, weight, axis_name)
            x = collectives.all_gather(x_k, axis_name, axis=0,
                                       tiled=True)
            mask_shape = (n_total,) + (1,) * (x.ndim - 1)
            xm = jnp.where(alive.reshape(mask_shape), x,
                           jnp.asarray(jnp.inf, x.dtype))
            srt = jnp.sort(xm, axis=0)

            def take(i):
                sel = jax.nn.one_hot(i, n_total).reshape(mask_shape)
                # where, not multiply: inf·0 at the dead tail is NaN
                return jnp.where(sel > 0, srt, 0.0).astype(
                    jnp.float32).sum(0)

            med = (take(i_lo) + take(i_hi)) / 2.0
            return med.astype(x_k.dtype)

        return jax.tree.map(per_leaf, updates, server), {}


_BY_NAME = {"mean": WeightedMean, "trimmed_mean": TrimmedMean,
            "median": Median, "norm_clip": NormClip}


def get_aggregator(spec, **kwargs) -> Aggregator:
    """Resolve an aggregator: None -> WeightedMean (current behavior),
    a name from {mean, trimmed_mean, median, norm_clip} (kwargs
    forwarded, e.g. trim=3 / max_norm=5.0), or an Aggregator instance
    passed through."""
    if spec is None:
        return WeightedMean()
    if isinstance(spec, Aggregator):
        if kwargs:
            raise ValueError("kwargs only apply when building by name")
        return spec
    if spec in _BY_NAME:
        return _BY_NAME[spec](**kwargs)
    raise ValueError(f"unknown aggregator {spec!r}; one of "
                     f"{sorted(_BY_NAME)} or an Aggregator instance")
