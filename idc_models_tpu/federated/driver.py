"""Self-healing multi-round federated driver.

`make_fedavg_round` hardens ONE round (non-finite detection, robust
aggregation); this module hardens the RUN: R rounds with per-round wall
budget, bounded retry with a reseeded client subset on a failed round,
divergence detection with automatic rollback to the last good server
state, periodic atomic checkpoints, and per-round health events through
`observe.JsonlLogger` — the loop the reference writes by hand with zero
failure handling (fed_model.py:225-233, SURVEY.md §5).

Failure semantics, per round:

- **timeout** — a round whose wall-clock (dispatch through the blocking
  metrics fetch) exceeds `timeout_s` is treated as straggled: its
  result is DISCARDED and the round is retried with a reseeded rng and
  a freshly-drawn client subset (`retry_subset_fraction` of the
  positive-weight clients). A jitted round cannot be preempted
  mid-flight, so the budget is enforced at the round boundary — the
  right granularity for a synchronous-rounds protocol.
- **diverged** — the candidate server params contain a non-finite
  value, the round's training loss is non-finite (e.g. every client was
  dropped), or the loss spiked past `loss_spike_ratio` x the last
  healthy round's loss. The candidate is discarded — rollback to the
  last good state is implicit, since the good state was never
  overwritten — and the round retries reseeded.
- **error** — the round function raised; retried like the others, with
  the final exception chained into `RoundFailure`.

After `max_attempts` failures of the SAME round the driver raises
`RoundFailure`: a round that cannot be healed by reseeding is a
systemic problem (bad data, broken aggregator, hostile majority) that
silent retries would only hide.

Determinism: attempt a of round r uses
``fold_in(fold_in(key(seed), r), a)`` and a subset drawn from
``default_rng((seed, r, a))`` — resumed or replayed runs reproduce the
exact stream, and a fault plan (faults.py) replays bit-identically
through the driver too.
"""

from __future__ import annotations

import dataclasses
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.federated.fedavg import ServerState, copy_tree
from idc_models_tpu.observe import metrics_registry as mreg
from idc_models_tpu.observe import profile as prof
from idc_models_tpu.observe import trace


class RoundFailure(RuntimeError):
    """A federated round kept failing after the configured retries."""


@dataclasses.dataclass(frozen=True)
class DriverConfig:
    """Knobs for `run_rounds`. `timeout_s=None` disables the wall
    budget; `loss_spike_ratio=None` disables spike detection (non-finite
    divergence detection is always on)."""

    rounds: int
    timeout_s: float | None = None
    # the driver's chronologically FIRST attempt pays every XLA compile
    # in its wall time (minutes for a big model — nothing to do with
    # straggling); exempting it keeps timeout_s meaningful as a
    # steady-state round budget. Set False to budget the compile too.
    timeout_exempt_first: bool = True
    max_attempts: int = 3
    loss_spike_ratio: float | None = 10.0
    retry_subset_fraction: float = 0.7
    checkpoint_path: str | os.PathLike | None = None
    checkpoint_every: int = 10

    def __post_init__(self):
        if self.rounds < 1:
            raise ValueError(f"need rounds >= 1, got {self.rounds}")
        if self.max_attempts < 1:
            raise ValueError(f"need max_attempts >= 1, got "
                             f"{self.max_attempts}")
        if not 0.0 < self.retry_subset_fraction <= 1.0:
            raise ValueError(f"retry_subset_fraction must be in (0, 1], "
                             f"got {self.retry_subset_fraction}")
        if self.loss_spike_ratio is not None and self.loss_spike_ratio <= 1:
            raise ValueError(f"loss_spike_ratio must be > 1, got "
                             f"{self.loss_spike_ratio}")


@dataclasses.dataclass
class DriverResult:
    server: ServerState          # the last GOOD server state
    history: list[dict]          # one entry per completed round
    events: list[dict]           # one entry per attempt (health log)


def reseeded_subset(weights, seed: int, round_idx: int, attempt: int,
                    fraction: float) -> np.ndarray:
    """A deterministic retry population: keep `fraction` of the
    positive-weight clients (at least 1), drawn from
    default_rng((seed, round, attempt)) — a straggling or poisoned
    participant from the failed attempt has a fresh chance of being
    excluded, without the driver having to know who it was."""
    w = np.asarray(jax.device_get(weights), np.float32).copy()
    pos = np.flatnonzero(w > 0)
    if len(pos) == 0:
        return w
    keep = max(1, int(round(fraction * len(pos))))
    chosen = np.random.default_rng((seed, round_idx, attempt)).choice(
        pos, size=keep, replace=False)
    out = np.zeros_like(w)
    out[chosen] = w[chosen]
    return out


def run_rounds(round_fn, server: ServerState, images, labels, weights, *,
               config: DriverConfig, seed: int = 0, eval_fn=None,
               on_round=None, logger=None, clock=time.monotonic,
               verbose: bool = False, log_from_round: int = -1,
               log_round_records: bool = True, fault_plan=None,
               slo=None, participant_ids_fn=None) -> DriverResult:
    """Run `config.rounds` federated rounds with self-healing.

    `round_fn` is a `make_fedavg_round` product (or anything with the
    same signature); `eval_fn(server) -> metrics` is an optional
    per-round evaluation folded into history/logging; `on_round(entry)`
    is called after each HEALTHY round with its history entry (live
    progress printing without the driver owning a format). Starts at
    `int(server.round)`, so a restored checkpoint resumes where it left
    off. `log_from_round` suppresses logger records for rounds <= it
    (resume replay must not double-append to an append-only jsonl);
    `log_round_records=False` leaves the per-round ``round`` records to
    the caller (e.g. a CLI preserving its historical field names) while
    the driver still emits ``round_health``.

    `fault_plan` (faults.FaultPlan, usually the same plan the round_fn
    injects) labels the per-client ``fed.client`` trace spans with each
    participant's fault outcome for the round. When a tracer is armed,
    every attempt's ``fed.round`` span gains one nested ``fed.client``
    marker per participating client (attrs: client, weight, fault —
    markers, not timings: clients run fused inside one jitted dispatch,
    so no per-client host interval exists to measure).

    `slo` (observe.slo.SLOEngine) receives ``round_seconds`` (latency,
    wall seconds per attempt) and ``round_failure_rate`` (rate, bad =
    attempt status != ok) for whichever of the two it declares, with a
    burn-rate evaluation after every attempt — `slo_alert` jsonl events
    go through the engine's own logger.

    `participant_ids_fn(round_idx) -> ids` overrides which client ids
    the ``fed.client`` markers name: population-scale rounds
    (federated/population.py, async_fedavg.py) participate by VIRTUAL
    client id, not by position in a materialized weight vector — the
    hook is called after the attempt completes, so an async round can
    report the completions it actually processed. A fault plan exposing
    ``codes_for(round, ids)`` (faults.PopulationFaultPlan) is queried
    per-id; the materialized-plan ``codes(round)`` path is unchanged.
    Returns the last good server state + per-round history + per-attempt
    health events; raises `RoundFailure` when a round exhausts its
    attempts (the last good state is the exception's `.server`).
    """
    import inspect

    # a fault-injecting round_fn takes round_idx= to skip its own
    # blocking int(server.round) fetch (~50-90 ms/round on a tunneled
    # runtime) — the driver already knows r, so thread it through
    takes_round_idx = False
    try:
        takes_round_idx = ("round_idx"
                           in inspect.signature(round_fn).parameters)
    except (TypeError, ValueError):
        pass
    finite_fn = jax.jit(lambda t: jnp.all(jnp.stack(
        [jnp.all(jnp.isfinite(l)) for l in jax.tree.leaves(t)
         if jnp.issubdtype(l.dtype, jnp.inexact)] or [jnp.asarray(True)])))

    good = server
    ref_loss = None
    first_attempt_done = False
    history: list[dict] = []
    events: list[dict] = []
    start = int(server.round)
    if start >= config.rounds:
        # a fully-trained restore is a no-op run, not an error (the
        # resume path hits this when --rounds already completed)
        return DriverResult(server=server, history=[], events=[])
    if prof.accounting_enabled():
        # opt-in program accounting (observe/profile.py): register the
        # round program's cost/memory report under "fed.round" before
        # the loop (lowering neither executes nor donates, so `good`
        # is safe to pass); best-effort — a host-side wrapper round_fn
        # warns and skips
        kw = {"round_idx": start} if takes_round_idx else {}
        prof.register_jit("fed.round", round_fn, good, images, labels,
                          weights, jax.random.key(seed), **kw)

    def health(record):
        events.append(record)
        if logger is not None and record["round"] > log_from_round:
            logger.log(event="round_health", **record)

    # process-wide registry instruments (idempotent — resumed runs and
    # multiple drivers share them); the jsonl `round`/`round_health`
    # record schemas above are the back-compat contract and unchanged
    m_attempts = mreg.REGISTRY.counter(
        "fed_round_attempts_total", "federated round attempts by "
        "outcome", labels=("status",))
    m_seconds = mreg.REGISTRY.histogram(
        "fed_round_seconds", "wall seconds per round attempt")
    m_loss = mreg.REGISTRY.gauge(
        "fed_train_loss", "last healthy round's training loss")

    last_error: Exception | None = None
    for r in range(start, config.rounds):
        for attempt in range(config.max_attempts):
            rng = jax.random.fold_in(
                jax.random.fold_in(jax.random.key(seed), r), attempt)
            w = (weights if attempt == 0 else reseeded_subset(
                weights, seed, r, attempt, config.retry_subset_fraction))
            # fresh buffers (copy_tree): the anchor survives round_fn's
            # donation of its input state — rollback is keeping `good`
            anchor = copy_tree(good)
            t0 = clock()
            status, tm_host = "ok", {}
            candidate = None
            # the with-block (not paired __enter__/__exit__ calls)
            # guarantees the span closes even on exits the except below
            # does not catch (KeyboardInterrupt, an error materializing
            # the record) — a leaked open span would corrupt the
            # parenting of every later span on this thread
            with trace.span("fed.round", round=r,
                            attempt=attempt) as att_span:
                try:
                    kw = {"round_idx": r} if takes_round_idx else {}
                    candidate, tm = round_fn(anchor, images, labels, w,
                                             rng, **kw)
                    # ONE blocking fetch: materializes the round's
                    # metrics AND fences the wall-clock window (the
                    # dispatch alone returns before the device
                    # finishes) — bracketed as device.sync so a
                    # DeviceTimeline splits fed.round into device-wait
                    # vs host gap
                    with trace.span("device.sync"):
                        tm_host = {k: float(v)
                                   for k, v in jax.device_get(tm).items()}
                    params_ok = bool(finite_fn(candidate.params)) and bool(
                        finite_fn(candidate.model_state))
                    if not params_ok or not np.isfinite(
                            tm_host.get("loss", np.nan)):
                        status = "diverged"
                    elif (config.loss_spike_ratio is not None
                          and ref_loss is not None
                          and tm_host["loss"]
                          > config.loss_spike_ratio * ref_loss):
                        status = "diverged"
                except Exception as e:  # noqa: BLE001 — chained into RoundFailure
                    last_error = e
                    status = "error"
                    tm_host = {"error": f"{type(e).__name__}: {e}"}
                elapsed = clock() - t0
                timeout_exempt = (config.timeout_exempt_first
                                  and not first_attempt_done)
                first_attempt_done = True
                if (status == "ok" and config.timeout_s is not None
                        and not timeout_exempt
                        and elapsed > config.timeout_s):
                    status = "timeout"
                w_host = np.asarray(jax.device_get(w))
                record = {"round": r, "attempt": attempt,
                          "status": status,
                          "seconds": round(elapsed, 4),
                          "participants": int((w_host > 0).sum()),
                          **{k: v for k, v in tm_host.items()
                             if k in ("loss", "accuracy",
                                      "clients_dropped",
                                      "clients_clipped",
                                      "clients_trimmed",
                                      "trim_degenerate", "error")}}
                att_span.set(status=status,
                             participants=record["participants"])
                if trace.get_tracer() is not None:
                    ids = (participant_ids_fn(r)
                           if participant_ids_fn is not None else None)
                    _client_spans(att_span, w_host, r, attempt,
                                  fault_plan, ids=ids)
            m_attempts.inc(status=status)
            m_seconds.observe(elapsed)
            health(record)
            if slo is not None:
                if slo.has("round_seconds"):
                    slo.observe("round_seconds", elapsed)
                if slo.has("round_failure_rate"):
                    slo.record("round_failure_rate", ok=status == "ok")
                slo.evaluate()
            if status == "ok":
                good = candidate
                ref_loss = tm_host["loss"]
                m_loss.set(ref_loss)
                entry = {"round": r, "attempts": attempt + 1, **{
                    k: v for k, v in tm_host.items()}}
                if eval_fn is not None:
                    entry.update(eval_fn(good))
                history.append(entry)
                if (log_round_records and logger is not None
                        and r > log_from_round):
                    logger.log(event="round", **entry)
                if on_round is not None:
                    on_round(entry)
                break
            if verbose:
                import sys

                print(f"[idc_models_tpu] round {r} attempt {attempt} "
                      f"{status} after {elapsed:.2f}s — "
                      f"{'rolling back and ' if candidate is not None else ''}"
                      f"retrying with a reseeded client subset",
                      file=sys.stderr)
        else:
            err = RoundFailure(
                f"round {r} failed {config.max_attempts} attempt(s) "
                f"(last status: {events[-1]['status']}); last good "
                f"server state is at round {int(good.round)}")
            err.server = good           # the rollback anchor, recoverable
            raise err from last_error
        if (config.checkpoint_path is not None
                and (r + 1) % max(config.checkpoint_every, 1) == 0):
            _save(config.checkpoint_path, good)
    if (config.checkpoint_path is not None
            and int(good.round) % max(config.checkpoint_every, 1) != 0):
        _save(config.checkpoint_path, good)
    return DriverResult(server=good, history=history, events=events)


def _client_spans(att_span, weights, round_idx: int, attempt: int,
                  fault_plan, ids=None) -> None:
    """One `fed.client` marker span per participating client, nested
    under the attempt's fed.round span, carrying the client's fault
    outcome for the round (from the plan's pure (plan, round) function
    — the same codes the jitted round program branched on). Markers,
    not timings: the clients execute fused inside one dispatch.
    `weights` is the attempt's already host-fetched array; `ids`, when
    given, are VIRTUAL client ids from a population-scale round (the
    weight attr is then omitted — the positional weight vector does
    not describe them)."""
    from idc_models_tpu import faults as faults_lib

    w = np.asarray(weights)
    by_position = ids is None
    ids = np.flatnonzero(w > 0) if by_position else np.asarray(ids)
    if not by_position and len(ids) == len(w):
        # sync population rounds: `ids` are the cohort's virtual ids,
        # position-aligned with the [cohort] participation mask the
        # driver's reseeded retry zeroes — a masked-out client did not
        # participate in this attempt and gets no marker
        ids = ids[w > 0]
    codes = scales = None
    if fault_plan is not None:
        if hasattr(fault_plan, "codes_for"):
            codes, scales = fault_plan.codes_for(round_idx, ids)
        else:
            codes, scales = fault_plan.codes(round_idx)
    for i, cid in enumerate(ids):
        cid = int(cid)
        attrs = {"round": round_idx, "attempt": attempt, "client": cid}
        if by_position:
            attrs["weight"] = float(w[cid])
        # population plans align codes to the ids array; materialized
        # plans index by client position
        ci = i if (fault_plan is not None
                   and hasattr(fault_plan, "codes_for")) else cid
        if codes is not None and ci < len(codes):
            code = int(codes[ci])
            attrs["fault"] = faults_lib.kind_of(code)
            if code in (faults_lib.SCALE, faults_lib.SIGN_FLIP):
                attrs["fault_scale"] = float(scales[ci])
            elif code == faults_lib.STRAGGLER:
                attrs["staleness"] = fault_plan.staleness(round_idx)
        trace.point("fed.client", parent=att_span.span_id, **attrs)


def _save(path, server: ServerState) -> None:
    from idc_models_tpu.train.checkpoint import save_checkpoint

    save_checkpoint(path, jax.device_get(server))
