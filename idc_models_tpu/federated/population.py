"""Population-scale federated training: virtual clients, cohort
sampling, and streamed hierarchical aggregation.

`make_fedavg_round` materializes EVERY client as a stacked
[C, S, ...] array and aggregates the whole round in one dispatch — the
right shape for the 10–32 clients the reference simulates, and a dead
end at the ROADMAP's "millions of users" scale: memory grows with the
population and a synchronous barrier waits on its slowest member.
Production FL systems (Bonawitz et al., *Towards Federated Learning at
Scale*) instead SELECT a small cohort from a huge population each round
and aggregate it in a streamed, hierarchical fashion. This module is
that layer:

- `ClientPopulation` — 10k+ *virtual* clients whose data shards are
  derived lazily from `(seed, client_id)`. No population-sized array
  ever exists (statically gated by the AST scan in
  test_static_robustness.py); memory is bounded by whatever cohort is
  materialized.
- `CohortSampler` — deterministic per-round cohort selection, uniform
  (Floyd's algorithm, O(cohort) memory) or weighted-by-size (rejection
  sampling against the population's known weight bound). The cohort is
  a pure function of `(seed, round)`: there is no sampler state to
  checkpoint — a driver resume at round r regenerates round r's cohort
  byte-identically (gated).
- `make_population_round` — a driver-compatible round function that
  streams the cohort through fixed-size WAVES: each wave materializes
  O(wave) client data, trains its clients fused (the same vmapped
  local program as `make_fedavg_round`), reduces over the device shard
  (level 1, `psum`), and folds into a running weighted aggregate
  (level 2, cross-wave). Server memory is O(wave) client data plus one
  accumulator tree — constant in BOTH population and cohort size.

Aggregation parity contract (the chunk-prefill precedent): wave
partial sums use the IDENTICAL masked-sum reduction as
`collectives.weighted_pmean_local`, so a single wave covering the
cohort is bit-identical to the one-shot `make_fedavg_round` (gated),
and splitting the cohort into waves that mirror a device-sharded
one-shot layout reproduces its psum association (gated on the 2-wave /
2-device pair). Any other wave split changes only the cross-wave
ADDITION ORDER — fp-close, never a different estimator — while the
round itself replays bit-identically from `(seed, round)` (gated, the
hard requirement every drill in this tree shares).

Robust aggregators (`federated/robust.py`) compose as follows:

- `WeightedMean` / `NormClip` — exact: both are per-client transforms
  followed by a weighted mean, and weighted sums stream losslessly.
- `TrimmedMean` — runs PER WAVE: each wave trims its own extremes and
  the wave aggregates combine by alive-count-weighted running mean.
  The guarantee becomes "up to `trim` Byzantine clients *per wave*"
  (documented in docs/ROBUSTNESS.md); a wave too small to ever keep a
  value (wave clients <= 2*trim) is rejected at build.
- `Median` — rejected at build with a teaching error: cross-cohort
  order statistics need every client's value at once, which is exactly
  what streaming gives up; per-wave median-of-means is a DIFFERENT
  estimator, so refusing beats silently running one.
"""

from __future__ import annotations

import time
from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from idc_models_tpu.compat import shard_map

from idc_models_tpu import collectives
from idc_models_tpu import mesh as meshlib
from idc_models_tpu.federated.fedavg import (
    ServerState, copy_tree, finite_clients, make_local_trainer,
)
from idc_models_tpu.models import core
from idc_models_tpu.observe import metrics_registry as mreg


class ClientPopulation:
    """`size` virtual clients, each a pure function of (seed, id).

    `shard(cid)` synthesizes the client's data lazily —
    `data.synthetic.make_idc_like` seeded by `(seed, 1, cid)` unless a
    custom ``make_shard(cid) -> (imgs [S,H,W,3], labels [S])`` is
    given — and `weight(cid)` is the client's aggregation weight /
    dataset-size proxy, seeded uniform in `weight_range`. Shards are
    fixed-shape ([examples_per_client] each) so cohorts stack; the
    WEIGHT models differing client dataset sizes (it drives both the
    weighted sampler and the round's example weighting). Nothing here
    allocates O(population): the only population-sized helper is the
    explicitly documented `all_weights` (validation only), and the
    static scan in test_static_robustness.py keeps it that way.
    """

    def __init__(self, size: int, *, examples_per_client: int = 16,
                 image_size: int = 10, seed: int = 0,
                 weight_range: tuple[float, float] = (1.0, 1.0),
                 make_shard: Callable[[int], tuple] | None = None):
        if size < 1:
            raise ValueError(f"need a population of >= 1 virtual "
                             f"clients, got {size}")
        if examples_per_client < 1:
            raise ValueError(f"need examples_per_client >= 1, got "
                             f"{examples_per_client}")
        lo, hi = float(weight_range[0]), float(weight_range[1])
        if not (0.0 < lo <= hi):
            raise ValueError(f"weight_range must satisfy 0 < lo <= hi, "
                             f"got {weight_range}")
        self.size = int(size)
        self.examples_per_client = int(examples_per_client)
        self.image_size = int(image_size)
        self.seed = int(seed)
        self.weight_range = (lo, hi)
        self._make_shard = make_shard

    @property
    def weight_max(self) -> float:
        """The known upper bound the weighted sampler rejects against."""
        return self.weight_range[1]

    def _check_cid(self, cid: int) -> int:
        cid = int(cid)
        if not 0 <= cid < self.size:
            raise ValueError(f"virtual client id {cid} outside the "
                             f"population (0..{self.size - 1})")
        return cid

    def shard(self, cid: int) -> tuple[np.ndarray, np.ndarray]:
        """(imgs [S,H,W,3] f32, labels [S] i32), derived lazily —
        byte-identical on every call (gated)."""
        cid = self._check_cid(cid)
        if self._make_shard is not None:
            return self._make_shard(cid)
        from idc_models_tpu.data import synthetic

        return synthetic.make_idc_like(
            self.examples_per_client, size=self.image_size,
            seed=(self.seed, 1, cid))

    def weight(self, cid: int) -> float:
        cid = self._check_cid(cid)
        lo, hi = self.weight_range
        if lo == hi:
            return lo
        u = np.random.default_rng((self.seed, 2, cid)).random()
        return lo + (hi - lo) * u

    def materialize(self, ids) -> tuple[np.ndarray, np.ndarray,
                                        np.ndarray]:
        """Stack a cohort/wave: (imgs [C,S,...], labels [C,S],
        weights [C]) — O(len(ids)) memory, the ONLY way client data
        ever exists on the host."""
        ids = np.asarray(ids, np.int64)
        imgs, labels, weights = [], [], []
        for cid in ids:
            im, lb = self.shard(int(cid))
            imgs.append(im)
            labels.append(lb)
            weights.append(self.weight(int(cid)))
        return (np.stack(imgs), np.stack(labels),
                np.asarray(weights, np.float32))

    def all_weights(self) -> np.ndarray:
        """[size] weights — the one deliberately O(population) helper,
        for validating the weighted sampler's distribution on SMALL
        populations in tests. Never on the training path (the static
        scan allowlists exactly this function)."""
        out = np.empty((self.size,), np.float32)
        for cid in range(self.size):
            out[cid] = self.weight(cid)
        return out

    def same_config(self, other: "ClientPopulation") -> bool:
        """True when `other` derives the SAME virtual clients — the
        compatibility check between a sampler and a round builder
        (identity is too strict: a process restart rebuilds both)."""
        return (self.size == other.size
                and self.examples_per_client == other.examples_per_client
                and self.image_size == other.image_size
                and self.seed == other.seed
                and self.weight_range == other.weight_range
                and self._make_shard is other._make_shard)

    def __repr__(self) -> str:
        return (f"ClientPopulation(size={self.size}, "
                f"examples_per_client={self.examples_per_client}, "
                f"seed={self.seed}, weight_range={self.weight_range})")


class CohortSampler:
    """Deterministic per-round cohort selection over a
    `ClientPopulation`.

    `cohort(r)` is a pure function of `(seed, r)` — there is NO mutable
    sampler state, which is the whole checkpoint/resume story: the
    driver checkpoints only `ServerState.round`, and a resumed run
    regenerates every later round's cohort byte-identically (gated).
    Uniform sampling is Floyd's algorithm (O(cohort) memory, no
    population-sized permutation); `weighted=True` samples without
    replacement proportional to `population.weight(cid)` by rejection
    against the population's `weight_max` bound — still O(cohort)
    memory, expected O(cohort * w_max / w_mean) draws.
    """

    def __init__(self, population: ClientPopulation, cohort_size: int,
                 *, seed: int = 0, weighted: bool = False):
        if not 1 <= cohort_size <= population.size:
            raise ValueError(
                f"cohort_size must be in [1, population={population.size}"
                f"], got {cohort_size} — a cohort cannot exceed the "
                f"population it samples from")
        self.population = population
        self.cohort_size = int(cohort_size)
        self.seed = int(seed)
        self.weighted = bool(weighted)

    def cohort(self, round_idx: int) -> np.ndarray:
        """[cohort_size] sorted unique virtual-client ids for one round
        — byte-identical across calls, processes, and resumes."""
        rng = np.random.default_rng((self.seed, 3, int(round_idx)))
        if self.weighted:
            return self._weighted(rng)
        return self._uniform(rng)

    def _uniform(self, rng) -> np.ndarray:
        n, k = self.population.size, self.cohort_size
        chosen: set[int] = set()
        for j in range(n - k, n):
            t = int(rng.integers(0, j + 1))
            if t in chosen:
                t = j
            chosen.add(t)
        return np.sort(np.fromiter(chosen, np.int64, len(chosen)))

    def _weighted(self, rng) -> np.ndarray:
        n, k = self.population.size, self.cohort_size
        w_max = self.population.weight_max
        chosen: set[int] = set()
        draws, limit = 0, max(10_000, 1_000 * k)
        while len(chosen) < k:
            draws += 1
            if draws > limit:
                raise RuntimeError(
                    f"weighted cohort sampling did not converge after "
                    f"{limit} draws (cohort {k} of {n}; is weight_max "
                    f"{w_max} far above the typical weight?)")
            c = int(rng.integers(0, n))
            if c in chosen:
                continue
            if rng.random() * w_max <= self.population.weight(c):
                chosen.add(c)
        return np.sort(np.fromiter(chosen, np.int64, len(chosen)))

    def client_at(self, i: int) -> int:
        """The i-th client of the CONTINUOUS sampled dispatch stream —
        the async server's unit of selection (with replacement over
        time, like repeated cohort draws). Pure function of
        `(seed, i)`."""
        rng = np.random.default_rng((self.seed, 4, int(i)))
        n = self.population.size
        if not self.weighted:
            return int(rng.integers(0, n))
        w_max = self.population.weight_max
        for _ in range(100_000):
            c = int(rng.integers(0, n))
            if rng.random() * w_max <= self.population.weight(c):
                return c
        raise RuntimeError("weighted stream sampling did not converge")

    def __repr__(self) -> str:
        return (f"CohortSampler(population={self.population.size}, "
                f"cohort_size={self.cohort_size}, seed={self.seed}, "
                f"weighted={self.weighted})")


def _teach_aggregator(agg) -> str:
    from idc_models_tpu.federated import robust

    if isinstance(agg, robust.Median):
        return (
            "Median cannot stream: the coordinate-wise median needs "
            "every cohort member's value at once, and a per-wave "
            "median of means is a DIFFERENT estimator with weaker "
            "guarantees. Use trimmed_mean (runs per wave with the "
            "documented per-wave tolerance) or the one-shot "
            "make_fedavg_round for exact cross-cohort order statistics.")
    return (
        f"aggregator {agg!r} has no streaming strategy: streamed "
        f"rounds support mean/norm_clip (exact — per-client transform "
        f"+ weighted mean) and trimmed_mean (per-wave, documented in "
        f"docs/ROBUSTNESS.md).")


def make_population_round(
    model: core.Module,
    optimizer,
    loss_fn,
    mesh: Mesh,
    population: ClientPopulation,
    sampler: CohortSampler,
    *,
    wave_size: int,
    local_epochs: int = 1,
    batch_size: int = 32,
    compute_dtype=jnp.float32,
    drop_nonfinite: bool = True,
    aggregator=None,
    faults=None,
    barrier_sleep: bool = False,
    logger=None,
    log_from_round: int = -1,
    rules=None,
):
    """Build the streamed population round.

    Returns ``round_fn(server, images, labels, weights, rng, *,
    round_idx=None) -> (server, metrics)`` — driver-compatible
    (`federated/driver.py run_rounds`): `images`/`labels` are unused
    (the population synthesizes wave data lazily) and `weights`, when
    given, is a [cohort_size] participation MASK over cohort positions
    (the driver's reseeded-subset retry drops members by zeroing it);
    pass None (or ones) for full participation. Each round:

    1. `sampler.cohort(r)` draws the round's virtual clients —
       replayable from `(seed, r)`;
    2. the cohort streams through `cohort_size / wave_size` waves: each
       wave materializes O(wave) data, trains fused, device-shard
       reduces (`psum`), and folds into the running aggregate (one
       fixed-shape jitted program, zero recompiles after the first
       wave);
    3. a finalize program divides the accumulated sums and applies the
       all-dead guard exactly like the one-shot round.

    `faults` is a `faults.PopulationFaultPlan`: codes address VIRTUAL
    ids and are evaluated per cohort (O(cohort)); straggler staleness
    replays the server state from round r-k via the same history the
    one-shot fault path keeps. With `barrier_sleep=True` the round
    also SLEEPS max(plan delay) — the synchronous barrier a straggler
    imposes, which the async buffered server (async_fedavg.py) is
    built to remove; leave False to run drills at full speed.

    `logger` (observe.JsonlLogger) gets one ``fed_cohort`` event per
    round (frozen schema, test_observability.py) for rounds >
    `log_from_round` — the same append-only-resume contract as the
    CLI's round records.
    """
    from idc_models_tpu import faults as faults_lib
    from idc_models_tpu.federated import robust

    agg = robust.get_aggregator(aggregator)
    cohort_size = sampler.cohort_size
    if not population.same_config(sampler.population):
        raise ValueError(
            "sampler and round must draw from the same virtual "
            "population (size/seed/shape differ) — they would train "
            "different clients than they sampled")
    n_devices = mesh.shape[meshlib.CLIENT_AXIS]
    if wave_size < 1 or cohort_size % wave_size:
        raise ValueError(
            f"wave_size {wave_size} must divide the cohort "
            f"({cohort_size}) — waves are fixed-shape so one compiled "
            f"program serves every wave")
    if wave_size % n_devices:
        raise ValueError(
            f"wave_size {wave_size} must be a multiple of the "
            f"{n_devices}-device client mesh (each device trains "
            f"wave_size/devices clients per wave)")
    per_wave_mode = isinstance(agg, robust.TrimmedMean)
    if isinstance(agg, robust.Median) or not isinstance(
            agg, (robust.WeightedMean, robust.NormClip,
                  robust.TrimmedMean)):
        raise ValueError(_teach_aggregator(agg))
    if per_wave_mode and wave_size <= 2 * agg.trim:
        raise ValueError(
            f"trim={agg.trim} can never keep a value inside a "
            f"{wave_size}-client wave (2*trim are always dropped) — "
            f"trimmed_mean runs PER WAVE when streamed, so lower trim "
            f"below {wave_size / 2:.0f} or grow wave_size")
    with_faults = faults is not None
    if with_faults and faults.population != population.size:
        raise ValueError(
            f"fault plan covers a population of {faults.population} "
            f"but the round trains {population.size} virtual clients")

    local_train = make_local_trainer(
        model, optimizer, loss_fn, local_epochs=local_epochs,
        batch_size=batch_size, compute_dtype=compute_dtype)
    k = wave_size // n_devices

    m_cohort = mreg.REGISTRY.gauge(
        "fed_cohort_size", "virtual clients sampled into the last "
        "federated round's cohort")
    m_sampled = mreg.REGISTRY.counter(
        "fed_clients_sampled_total", "virtual clients sampled into "
        "round cohorts, cumulative")

    def per_device(params, model_state, acc, acc_w, acc_m, imgs, labels,
                   weight, pos, rng, *fault_args):
        # one wave's device block: k clients. Per-client rng streams
        # fold the round rng by COHORT POSITION, matching the one-shot
        # round's dev*k+arange(k) stream on the materialized cohort —
        # the parity gates ride on this.
        rngs = jax.vmap(lambda p: jax.random.fold_in(rng, p))(pos)
        new_params, new_ms, (losses, accs) = jax.vmap(
            local_train, in_axes=(None, None, 0, 0, 0))(
            params, model_state, imgs, labels, rngs)

        if with_faults:
            codes, scales, stale_params, stale_state = fault_args
            new_params, new_ms, weight = faults_lib.apply_faults(
                codes, scales, new_params, new_ms, weight,
                params, model_state, stale_params, stale_state)

        w = jnp.maximum(weight, 0.0)
        dropped = jnp.zeros((), jnp.float32)
        if drop_nonfinite:
            ok = finite_clients(k, new_params, new_ms, losses)
            dropped = collectives.psum(
                jnp.sum((w > 0) & ~ok).astype(jnp.float32),
                meshlib.CLIENT_AXIS)
            w = jnp.where(ok, w, 0.0)

        updates = {"params": new_params, "model_state": new_ms}
        server_tree = {"params": params, "model_state": model_state}
        updates, pc_metrics = agg.per_client(updates, server_tree)

        # weighted per-client stats, accumulated as (sum, total) pairs
        # and divided once at finalize — same weighting as the
        # one-shot's weighted_pmean_local metrics
        wave_w = collectives.psum(w.sum(), meshlib.CLIENT_AXIS)
        cl_loss = jnp.mean(losses, axis=tuple(range(1, losses.ndim)))
        cl_acc = jnp.mean(accs, axis=tuple(range(1, accs.ndim)))
        wloss = collectives.psum(
            jnp.where(w > 0, w * cl_loss, 0.0).sum(),
            meshlib.CLIENT_AXIS)
        wacc = collectives.psum(
            jnp.where(w > 0, w * cl_acc, 0.0).sum(),
            meshlib.CLIENT_AXIS)
        new_m = dict(acc_m)
        new_m["wloss"] = acc_m["wloss"] + wloss
        new_m["wacc"] = acc_m["wacc"] + wacc
        new_m["wtotal"] = acc_m["wtotal"] + wave_w
        new_m["dropped"] = acc_m["dropped"] + dropped
        for key, vals in pc_metrics.items():
            new_m[key] = acc_m[key] + collectives.psum(
                jnp.sum(jnp.where(w > 0, vals, 0.0)),
                meshlib.CLIENT_AXIS)

        if per_wave_mode:
            # level 1b: trimmed aggregate OVER THIS WAVE (all-gather
            # inside — the wave bounds its scale), level 2: alive-
            # count-weighted running mean of wave aggregates; a
            # degenerate wave (kept band empty) contributes weight 0
            # instead of smuggling the incoming server state into the
            # average
            wave_agg, agg_m = agg.combine(
                updates, w, server_tree, meshlib.CLIENT_AXIS)
            n_alive = collectives.psum(
                (w > 0).sum().astype(jnp.float32), meshlib.CLIENT_AXIS)
            band_ok = 1.0 - agg_m["trim_degenerate"]
            vw = n_alive * band_ok
            acc = jax.tree.map(
                lambda a, x: a + vw.astype(x.dtype) * x, acc, wave_agg)
            acc_w = acc_w + vw
            new_m["degenerate_waves"] = (new_m["degenerate_waves"]
                                         + agg_m["trim_degenerate"])
            if "clients_trimmed" in agg_m:
                new_m["clients_trimmed"] = (new_m["clients_trimmed"]
                                            + agg_m["clients_trimmed"])
        else:
            # level 1: the IDENTICAL masked weighted sum + device-shard
            # psum as weighted_pmean_local; level 2: running sums. The
            # division happens once, at finalize.
            def wsum(a, x):
                wb = w.reshape((-1,) + (1,) * (x.ndim - 1)).astype(
                    x.dtype)
                s = jnp.where(wb > 0, x * wb, jnp.zeros_like(x)).sum(
                    axis=0)
                return a + collectives.psum(s, meshlib.CLIENT_AXIS)

            acc = jax.tree.map(wsum, acc, updates)
            acc_w = acc_w + wave_w
        return acc, acc_w, new_m

    fault_specs = ((P(meshlib.CLIENT_AXIS), P(meshlib.CLIENT_AXIS),
                    P(), P()) if with_faults else ())
    mapped = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(meshlib.CLIENT_AXIS),
                  P(meshlib.CLIENT_AXIS), P(meshlib.CLIENT_AXIS),
                  P(meshlib.CLIENT_AXIS), P()) + fault_specs,
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    # acc buffers are donated (wave N+1 reuses wave N's memory, so the
    # aggregation footprint is one accumulator tree no matter how many
    # waves stream through) and every sharding is PINNED: without
    # explicit in/out shardings the accumulator's sharding drifts
    # between wave 0 (fresh zeros) and wave 1 (program output), which
    # recompiles the wave program mid-round — minutes per round on a
    # big model. The server-shaped pins (params, model_state, and the
    # wave ACCUMULATORS mirroring them) resolve through the shared
    # partition layer when `rules` is given — the accumulators inherit
    # the rules' shardings instead of a pinned ad-hoc replicate; on the
    # 1-D client mesh every rule adapts to replicated (bit-identical).
    rep = meshlib.replicated(mesh)
    csh = meshlib.sharding(mesh, meshlib.CLIENT_AXIS)
    _jits: dict[str, object] = {}

    def _server_shardings(server):
        if rules is None:
            return rep, rep
        sh = rules.shardings(
            mesh, {"params": server.params,
                   "model_state": server.model_state})
        return sh["params"], sh["model_state"]

    def _get_jits(server):
        # built on FIRST use: rules resolve against the server's tree
        # structure, which the builder does not hold
        if "wave" not in _jits:
            p_sh, m_sh = _server_shardings(server)
            acc_sh = {"params": p_sh, "model_state": m_sh}
            wave_in_sh = (p_sh, m_sh, acc_sh, rep, rep, csh, csh, csh,
                          csh, rep) + ((csh, csh, p_sh, m_sh)
                                       if with_faults else ())
            _jits["wave"] = jax.jit(
                mapped, in_shardings=wave_in_sh,
                out_shardings=(acc_sh, rep, rep),
                donate_argnums=(2, 3, 4))
            _jits["finalize"] = jax.jit(
                finalize, in_shardings=(p_sh, m_sh, acc_sh, rep, rep),
                out_shardings=(p_sh, m_sh, rep), donate_argnums=(2,))
            # the placement tree too: resolved once, reused per round
            _jits["place_sh"] = acc_sh if rules is not None else None
        return _jits["wave"], _jits["finalize"]

    def finalize(params, model_state, acc, acc_w, acc_m):
        total = jnp.maximum(acc_w, jnp.float32(1e-30))
        old = {"params": params, "model_state": model_state}
        new = jax.tree.map(
            lambda a: a / total.astype(a.dtype), acc)
        any_alive = acc_w > 0
        metrics = {
            "loss": acc_m["wloss"] / jnp.maximum(
                acc_m["wtotal"], jnp.float32(1e-30)),
            "accuracy": acc_m["wacc"] / jnp.maximum(
                acc_m["wtotal"], jnp.float32(1e-30)),
        }
        metrics = jax.tree.map(
            lambda x: jnp.where(any_alive, x, jnp.float32(jnp.nan)),
            metrics)
        metrics["clients_dropped"] = acc_m["dropped"]
        for key in acc_m:
            if key not in ("wloss", "wacc", "wtotal", "dropped"):
                metrics[key] = acc_m[key]
        if per_wave_mode:
            metrics["trim_degenerate"] = (
                acc_m["degenerate_waves"] > 0).astype(jnp.float32)
        new = jax.tree.map(
            lambda n, o: jnp.where(any_alive, n, o), new, old)
        return new["params"], new["model_state"], metrics

    def _acc_metrics_init():
        m = {"wloss": jnp.zeros((), jnp.float32),
             "wacc": jnp.zeros((), jnp.float32),
             "wtotal": jnp.zeros((), jnp.float32),
             "dropped": jnp.zeros((), jnp.float32)}
        if isinstance(agg, robust.NormClip):
            m["clients_clipped"] = jnp.zeros((), jnp.float32)
        if per_wave_mode:
            m["degenerate_waves"] = jnp.zeros((), jnp.float32)
            if agg.track_clients:
                m["clients_trimmed"] = jnp.zeros((), jnp.float32)
        return m

    n_waves = cohort_size // wave_size
    history: dict[int, Any] = {}
    logged_rounds: set[int] = set()

    def round_fn(server: ServerState, images=None, labels=None,
                 weights=None, rng=None, *, round_idx: int | None = None):
        wave_jit, finalize_jit = _get_jits(server)
        if rules is not None:
            # placement through the shared resolution point's CACHED
            # shardings (no-op once the server carries the layout)
            placed = jax.tree.map(
                meshlib.put_with_sharding,
                {"params": server.params,
                 "model_state": server.model_state},
                _jits["place_sh"])
            server = server.replace(params=placed["params"],
                                    model_state=placed["model_state"])
        r = int(server.round) if round_idx is None else int(round_idx)
        ids = sampler.cohort(r)
        mask = (np.ones((cohort_size,), np.float32) if weights is None
                else np.asarray(jax.device_get(weights), np.float32))
        if mask.shape != (cohort_size,):
            raise ValueError(
                f"weights must be a [{cohort_size}] cohort-position "
                f"participation mask, got shape {mask.shape}")
        codes = scales = None
        stale = None
        if with_faults:
            codes, scales = faults.codes_for(r, ids)
            if faults.max_staleness > 0:
                # straggler history: the server state ENTERING each
                # round, keyed by round index (the one-shot fault
                # path's scheme). Clamped to the oldest RETAINED entry
                # on early rounds — which, after a checkpoint/resume,
                # is the resume round itself: the first max_staleness
                # resumed rounds replay with shallower staleness than
                # the uninterrupted run (in-memory history is not part
                # of the checkpoint; documented resume semantics, same
                # as make_fedavg_round's)
                history[r] = copy_tree(
                    (server.params, server.model_state))
                for old_r in [x for x in history
                              if x < r - max(faults.max_staleness, 1)]:
                    del history[old_r]
                want = r - faults.staleness(r)
                stale = history.get(want, history[min(history)])
            else:
                # no straggler in the plan: STRAGGLER codes cannot
                # occur, so the stale operands are never selected —
                # alias the live server trees instead of copying a
                # full model snapshot per round for nothing
                stale = (server.params, server.model_state)
            if barrier_sleep and faults.delay_unit_s > 0:
                # the synchronous barrier: the round is not done until
                # its slowest participating member reports
                delay = faults.delay_s(r, ids)
                wait = float(np.max(delay * (mask > 0), initial=0.0))
                if wait > 0:
                    time.sleep(wait)

        acc = jax.tree.map(
            jnp.zeros_like,
            {"params": server.params, "model_state": server.model_state})
        acc_w = jnp.zeros((), jnp.float32)
        acc_m = _acc_metrics_init()
        participants = int((mask > 0).sum())
        for wv in range(n_waves):
            sl = slice(wv * wave_size, (wv + 1) * wave_size)
            wave_ids = ids[sl]
            imgs_w, labels_w, w_w = population.materialize(wave_ids)
            w_w = w_w * (mask[sl] > 0)
            pos = np.arange(sl.start, sl.stop, dtype=np.int32)
            args = [server.params, server.model_state, acc, acc_w,
                    acc_m,
                    jax.device_put(imgs_w, csh),
                    jax.device_put(labels_w, csh),
                    jax.device_put(w_w, csh),
                    jax.device_put(pos, csh), rng]
            if with_faults:
                args += [jax.device_put(jnp.asarray(codes[sl]), csh),
                         jax.device_put(jnp.asarray(scales[sl]), csh),
                         *stale]
            acc, acc_w, acc_m = wave_jit(*args)

        params, model_state, metrics = finalize_jit(
            server.params, server.model_state, acc, acc_w, acc_m)
        new_server = server.replace(
            round=server.round + 1, params=params,
            model_state=model_state)
        metrics = dict(metrics)
        metrics["cohort"] = cohort_size
        metrics["participants"] = participants
        metrics["waves"] = n_waves
        m_cohort.set(cohort_size)
        m_sampled.inc(participants)
        if (logger is not None and r > log_from_round
                and r not in logged_rounds):
            # one record per ROUND: a driver retry re-runs the round
            # but must not append a duplicate to the append-only log
            logged_rounds.add(r)
            logger.log(event="fed_cohort", round=r, mode="sync",
                       population=population.size, cohort=cohort_size,
                       participants=participants, waves=n_waves,
                       wave_size=wave_size)
        return new_server, metrics

    round_fn.sampler = sampler
    round_fn.population = population
    return round_fn
