"""FedAvg as a TPU-native program: k clients per device on the "client"
mesh axis (client count is a workload property, independent of chip
count — the reference simulates 10 clients on one host, fed_model.py:47).

Capability parity with the reference's federated stack (SURVEY.md D3,
C9-C11): TFF's `build_federated_averaging_process` (fed_model.py:207-208)
broadcasts server weights, runs E local epochs per client, and averages the
results example-weighted; `build_federated_evaluation` (fed_model.py:210)
evaluates the global model over held-out clients; server state is seeded
from pretrained weights via `state_with_new_model_weights`
(fed_model.py:219-223).

The TPU-native re-design replaces TFF's in-process async executor with a
single jitted `shard_map` program over a "client" mesh axis:

- broadcast = the replicated server params entering the shard_map body;
- E local epochs = a `lax.scan` per device with NO collectives inside
  (clients are independent between round boundaries, exactly like the
  simulated TFF clients);
- the round boundary = one example-weighted `psum`-based mean over ICI
  (`collectives.weighted_pmean`), fixing quirk Q7 (the reference's
  hand-rolled server is unweighted while TFF's is weighted — weighted is
  the primitive here; equal shard sizes recover the unweighted mean).

Client optimizer state is created fresh each round (TFF semantics: the
client optimizer is constructed per round, fed_model.py:208) and BatchNorm
statistics remain per-client during local training, then are averaged with
the weights at the round boundary (the reference averages *all* Keras
weights, trainable and not — secure_fed_model.py:160-168 zips the full
get_weights() list).
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from idc_models_tpu.compat import shard_map

from idc_models_tpu import collectives
from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models import core
from idc_models_tpu.train import metrics as metrics_lib

LossFn = Callable[[jax.Array, jax.Array], jax.Array]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ServerState:
    """The federated server's state: the global model between rounds."""

    round: jax.Array
    params: Any
    model_state: Any

    def replace(self, **kw) -> "ServerState":
        return dataclasses.replace(self, **kw)


def initialize_server(model: core.Module, rng: jax.Array) -> ServerState:
    """Fresh server state (`fed_avg.initialize()`, fed_model.py:216)."""
    variables = model.init(rng)
    return ServerState(
        round=jnp.zeros((), jnp.int32),
        params=variables.params,
        model_state=variables.state,
    )


def seed_server_with(state: ServerState, params: Any,
                     model_state: Any) -> ServerState:
    """Replace the server model wholesale — the parity operation for TFF's
    `state_with_new_model_weights` seeding from a pretrained Keras model
    (fed_model.py:219-223)."""
    return state.replace(params=params, model_state=model_state)


def make_local_trainer(
    model: core.Module,
    optimizer: optax.GradientTransformation,
    loss_fn: LossFn,
    *,
    local_epochs: int,
    batch_size: int,
    compute_dtype=jnp.float32,
):
    """The per-client E-local-epochs training program (no collectives).

    Returns ``local_train(params, model_state, imgs [S,...], labels [S],
    rng) -> (params, model_state, (losses, accs))`` — shared by the plain
    FedAvg round and the secure-aggregation round, which differ only in
    what happens at the round boundary.
    """

    def local_train(params, model_state, imgs, labels, rng):
        imgs = imgs.astype(compute_dtype)
        shard_size = imgs.shape[0]
        steps = max(shard_size // batch_size, 1)
        take = min(steps * batch_size, shard_size)
        bsz = take // steps

        opt_state = optimizer.init(params)

        def local_step(carry, inp):
            params, model_state, opt_state = carry
            idx, step_rng = inp
            x, y = imgs[idx], labels[idx]

            def loss_of(p):
                logits, new_ms = model.apply(p, model_state, x, train=True,
                                             rng=step_rng)
                logits = logits.astype(jnp.float32)
                return loss_fn(logits, y), (logits, new_ms)

            (loss, (logits, new_ms)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            acc = metrics_lib.auto_accuracy(logits, y)
            return (params, new_ms, opt_state), (loss, acc)

        def epoch(carry, epoch_rng):
            perm_rng, steps_rng = jax.random.split(epoch_rng)
            perm = jax.random.permutation(perm_rng, shard_size)[:take]
            idx = perm.reshape(steps, bsz)
            step_rngs = jax.random.split(steps_rng, steps)
            return lax.scan(local_step, carry, (idx, step_rngs))

        carry = (params, model_state, opt_state)
        carry, stats = lax.scan(
            epoch, carry, jax.random.split(rng, local_epochs))
        new_params, new_model_state, _ = carry
        return new_params, new_model_state, stats

    return local_train


_copy_tree = jax.jit(lambda t: jax.tree.map(jnp.copy, t))


def copy_tree(tree):
    """Deep-copy a pytree into FRESH device buffers — jnp.copy under a
    non-donating jit. Snapshots taken this way survive a later donation
    of the original arrays (the round programs donate their incoming
    server state), which is what the driver's rollback anchor and the
    fault harness's straggler history rely on."""
    return _copy_tree(tree)


def finite_clients(k: int, *trees) -> jax.Array:
    """[k] bool: which of a device's k vmapped clients produced an
    all-finite local result (every leaf of `trees` carries the leading
    [k] client axis). The shared divergence test for the plain round's
    drop and the secure round's replace."""
    ok = jnp.ones((k,), bool)
    for leaf in jax.tree.leaves(trees):
        # axis-wise reduce (not reshape(k, -1)): stays well-defined for
        # zero-size leaves and any trailing shape
        ok &= jnp.all(jnp.isfinite(leaf), axis=tuple(range(1, leaf.ndim)))
    return ok


def make_fedavg_round(
    model: core.Module,
    optimizer: optax.GradientTransformation,
    loss_fn: LossFn,
    mesh: Mesh,
    *,
    local_epochs: int = 1,
    batch_size: int = 32,
    compute_dtype=jnp.float32,
    drop_nonfinite: bool = True,
    aggregator=None,
    faults=None,
    rules=None,
):
    """Build the jitted one-round FedAvg program.

    Returns ``round_fn(server_state, images, labels, weights, rng) ->
    (server_state, metrics)`` where

    - ``images``  [C, S, H, W, 3] and ``labels`` [C, S] are the stacked
      client shards (from `data.partition.partition_clients`), sharded over
      the "client" mesh axis. C may be any multiple of the mesh size:
      each device trains its k = C/D clients with a vmapped local
      program, so client count is independent of chip count (the
      reference simulates 10 clients on one host, fed_model.py:47 — pad
      with weight-0 dummy clients when C is not a multiple of D);
    - ``weights`` [C] are per-client aggregation weights (example counts
      for TFF parity; ones for the reference's unweighted secure server;
      0 drops a client — dead/padding clients cannot poison the round);
    - ``drop_nonfinite`` (default on) is automatic failure DETECTION on
      top of that manual dropping: a client whose local update contains
      any non-finite value (diverged, or fed corrupt data) has its
      weight forced to 0 inside the round, so it is excluded from the
      aggregate and the metrics without the caller having to know it
      died (the reference has no failure detection at all, SURVEY.md §5;
      `fed_metrics["clients_dropped"]` reports how many were cut);
    - ``aggregator`` selects the round-boundary aggregation
      (`federated/robust.py`): None keeps the example-weighted mean
      bit-for-bit; "trimmed_mean"/"median"/"norm_clip" (or an
      `robust.Aggregator` instance) bound the influence of
      finite-but-malicious updates that drop_nonfinite cannot see, and
      add their own metrics (clients_clipped / clients_trimmed);
    - ``faults`` is an optional `faults.FaultPlan`: the plan's per-round
      fault codes are applied to the client update tensors after local
      training and BEFORE detection/aggregation (crash, straggler,
      NaN/Inf poison, scale, sign-flip — see faults.py), deterministic
      per (plan, round) so runs replay bit-identically. Stale straggler
      params come from an internal per-round history of server states
      (depth = the plan's max staleness);
    - metrics are the example-weighted means of per-client local-training
      loss/accuracy over all local steps (the `train_metrics` half of the
      reference's per-round CSV print, fed_model.py:229);
    - ``rules`` (partition.PartitionRules) routes the server state's
      placement through the shared regex->PartitionSpec layer
      (partition.shard_tree) instead of the caller's ad-hoc replicate:
      on the 1-D "client" mesh every rule adapts to replicated (bit-
      identical to the historical layout), so federated placement and
      train/serve placement resolve through ONE point.
    """
    from idc_models_tpu import faults as faults_lib, partition
    from idc_models_tpu.federated import robust

    _server_sh: dict[str, object] = {}   # resolved ONCE, reused per round

    def place_server(server: ServerState) -> ServerState:
        if rules is None:
            return server
        tree = {"params": server.params,
                "model_state": server.model_state}
        if "sh" not in _server_sh:
            _server_sh["sh"] = rules.shardings(mesh, tree)
        placed = jax.tree.map(meshlib.put_with_sharding, tree,
                              _server_sh["sh"])
        return server.replace(params=placed["params"],
                              model_state=placed["model_state"])

    agg_fn = robust.get_aggregator(aggregator)
    n_devices = mesh.shape[meshlib.CLIENT_AXIS]
    local_train = make_local_trainer(
        model, optimizer, loss_fn, local_epochs=local_epochs,
        batch_size=batch_size, compute_dtype=compute_dtype)
    with_faults = faults is not None

    def per_device(params, model_state, imgs, labels, weight, rng,
                   codes=None, scales=None, stale_params=None,
                   stale_state=None):
        # shard_map gives each device a [k, S, ...] block: its k clients.
        k = imgs.shape[0]
        dev = collectives.axis_index(meshlib.CLIENT_AXIS)
        # global client ids seed per-client rng streams, so the math is
        # invariant to how clients are laid out over devices
        cids = dev * k + jnp.arange(k)
        rngs = jax.vmap(lambda c: jax.random.fold_in(rng, c))(cids)

        new_params, new_model_state, (losses, accs) = jax.vmap(
            local_train, in_axes=(None, None, 0, 0, 0))(
            params, model_state, imgs, labels, rngs)

        if with_faults:
            # injected failures perturb the UPDATE tensors, upstream of
            # detection and aggregation — exactly where real crashes/
            # stragglers/attackers land from the server's point of view
            new_params, new_model_state, weight = faults_lib.apply_faults(
                codes, scales, new_params, new_model_state, weight,
                params, model_state, stale_params, stale_state)

        dropped = jnp.zeros((), jnp.float32)
        if drop_nonfinite:
            # failure detection: cut any client whose update went
            # non-finite
            ok = finite_clients(k, new_params, new_model_state, losses)
            dropped = collectives.psum(
                jnp.sum((weight > 0) & ~ok).astype(jnp.float32),
                meshlib.CLIENT_AXIS)
            weight = jnp.where(ok, weight, 0.0)

        # Round boundary: the only collectives in the program.
        agg, agg_metrics = agg_fn(
            {"params": new_params, "model_state": new_model_state},
            weight, {"params": params, "model_state": model_state},
            meshlib.CLIENT_AXIS)
        metrics = collectives.weighted_pmean_local(
            {"loss": jnp.mean(losses, axis=tuple(range(1, losses.ndim))),
             "accuracy": jnp.mean(accs, axis=tuple(range(1, accs.ndim)))},
            weight, meshlib.CLIENT_AXIS)
        # all clients dropped (total weight 0, e.g. every participant
        # failed): keep the incoming global state instead of the
        # degenerate zero aggregate, and report NaN metrics — the
        # all-zero-weight mean would otherwise read as a perfect 0.0
        # loss in the round logs while training silently stalls
        any_alive = collectives.psum(
            jnp.maximum(weight, 0.0).sum(), meshlib.CLIENT_AXIS) > 0
        metrics = jax.tree.map(
            lambda x: jnp.where(any_alive, x, jnp.float32(jnp.nan)),
            metrics)
        metrics["clients_dropped"] = dropped
        metrics.update(agg_metrics)
        agg = jax.tree.map(
            lambda new, old: jnp.where(any_alive, new, old), agg,
            {"params": params, "model_state": model_state})
        return agg["params"], agg["model_state"], metrics

    fault_specs = ((P(meshlib.CLIENT_AXIS), P(meshlib.CLIENT_AXIS),
                    P(), P()) if with_faults else ())
    mapped = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P(meshlib.CLIENT_AXIS), P(meshlib.CLIENT_AXIS),
                  P(meshlib.CLIENT_AXIS), P()) + fault_specs,
        out_specs=(P(), P(), P()),
        check_vma=False,
    )

    if not with_faults:
        def round_body(server: ServerState, images, labels, weights,
                       rng):
            _check_client_shapes(images, weights, n_devices)
            params, model_state, metrics = mapped(
                server.params, server.model_state, images, labels,
                jnp.asarray(weights, jnp.float32), rng)
            new_server = server.replace(
                round=server.round + 1, params=params,
                model_state=model_state)
            return new_server, metrics

        jitted_round = jax.jit(round_body, donate_argnums=(0,))
        if rules is None:
            return jitted_round   # the historical product, bit-for-bit

        def round_fn(server: ServerState, images, labels, weights, rng):
            # placement (host-side: device_put must not trace) through
            # the one shared resolution point, then the jitted round
            return jitted_round(place_server(server), images, labels,
                                weights, rng)

        return round_fn

    def round_core(server, images, labels, weights, rng, codes, scales,
                   stale_params, stale_state):
        params, model_state, metrics = mapped(
            server.params, server.model_state, images, labels,
            jnp.asarray(weights, jnp.float32), rng, codes, scales,
            stale_params, stale_state)
        new_server = server.replace(
            round=server.round + 1, params=params,
            model_state=model_state)
        return new_server, metrics

    jitted = jax.jit(round_core, donate_argnums=(0,))
    history: dict[int, Any] = {}

    def faulty_round_fn(server: ServerState, images, labels, weights,
                        rng, *, round_idx: int | None = None):
        _check_client_shapes(images, weights, n_devices)
        server = place_server(server)
        c = images.shape[0]
        if faults.n_clients > c:
            raise ValueError(
                f"fault plan covers {faults.n_clients} clients but only "
                f"{c} client shards were passed")
        r = int(server.round) if round_idx is None else int(round_idx)
        codes, scales = faults.codes(r)
        codes = np.concatenate(
            [codes, np.zeros((c - faults.n_clients,), np.int32)])
        scales = np.concatenate(
            [scales, np.ones((c - faults.n_clients,), np.float32)])
        # straggler history: the server state ENTERING each round, keyed
        # by round index; round r staleness k replays history[r-k]
        # (clamped to the oldest retained entry on early rounds)
        history[r] = copy_tree((server.params, server.model_state))
        for old_r in [x for x in history
                      if x < r - max(faults.max_staleness, 1)]:
            del history[old_r]
        want = r - faults.staleness(r)
        stale = history.get(want, history[min(history)])
        new_server, metrics = jitted(
            server, images, labels, weights, rng, jnp.asarray(codes),
            jnp.asarray(scales), *stale)
        return new_server, metrics

    return faulty_round_fn


def _check_client_shapes(images, weights, n_devices: int) -> None:
    if images.shape[0] % n_devices:
        raise ValueError(
            f"got {images.shape[0]} client shards for a "
            f"{n_devices}-device mesh; pad with weight-0 clients to a "
            f"multiple (data.partition.pad_clients)")
    if np.shape(weights)[0] != images.shape[0]:
        raise ValueError(
            f"{np.shape(weights)[0]} client weights for "
            f"{images.shape[0]} client shards — pad them together "
            f"(data.partition.pad_clients takes the weight vectors too)")


def make_federated_eval(model: core.Module, loss_fn: LossFn, mesh: Mesh, *,
                        compute_dtype=jnp.float32):
    """Build the jitted federated evaluation (fed_model.py:210).

    Returns ``eval_fn(server_state, images [C,S,...], labels [C,S],
    weights [C]) -> metrics`` — the global model evaluated on every test
    client's shard, metrics example-weighted-averaged across clients.
    """

    def per_client_eval(imgs, labels, params, model_state):
        logits, _ = model.apply(params, model_state,
                                imgs.astype(compute_dtype), train=False)
        logits = logits.astype(jnp.float32)
        return {"loss": loss_fn(logits, labels),
                "accuracy": metrics_lib.auto_accuracy(logits, labels)}

    def per_device(params, model_state, imgs, labels, weight):
        # [k, S, ...] block: evaluate each of the device's k clients
        m = jax.vmap(per_client_eval, in_axes=(0, 0, None, None))(
            imgs, labels, params, model_state)
        return collectives.weighted_pmean_local(m, weight,
                                                meshlib.CLIENT_AXIS)

    mapped = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P(meshlib.CLIENT_AXIS), P(meshlib.CLIENT_AXIS),
                  P(meshlib.CLIENT_AXIS)),
        out_specs=P(),
        check_vma=False,
    )

    n_devices = mesh.shape[meshlib.CLIENT_AXIS]
    jitted = jax.jit(lambda server, images, labels, weights: mapped(
        server.params, server.model_state, images, labels,
        jnp.asarray(weights, jnp.float32)))

    def eval_fn(server: ServerState, images, labels, weights):
        _check_client_shapes(images, weights, n_devices)
        return jitted(server, images, labels, weights)

    return eval_fn

