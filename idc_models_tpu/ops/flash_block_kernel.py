"""Pallas TPU kernel: fused flash-attention block update for the ring.

`ring_attention._block_attend` is the ring's hot op: per visiting K/V
block it materializes a [B,H,Tq,Tk] score tensor in HBM, then separate
max/exp/matmul passes re-read it. This kernel fuses the whole online-
softmax update — scores, running max `m`, normalizer `l`, accumulator
`acc` — into one grid cell per (batch, head, q-tile, k-chunk), with the
K axis innermost so the output refs carry the recurrence across chunks:
scores never leave VMEM, and the only HBM traffic is q/k/v in and
(m, l, acc) out — q/k/v ship in their OWN dtype (bf16 stays bf16 in
HBM; each tile upcasts to f32 on load). That converts the per-step score memory from O(Tq*Tk)
HBM to one [q-tile, k-chunk] VMEM tile, which is what lets local blocks
grow past the jnp path's comfort zone (the module docstring of
ring_attention.py states the (T/n)^2 caveat this kernel removes on the
forward).

Semantics are EXACTLY `_block_attend`'s recurrence (same _MASKED
sentinel, same self-healing first-block property); the causal mask is
reconstructed inside the kernel from two scalar offsets (global q / kv
block starts) — no mask tensor is built or shipped.

Measured on one TPU v5 lite chip (causal, B=1 H=8 D=64 bf16, ring of 1
so t_local == T; 20 chained calls per timing window so the tunneled
runtime's ~90 ms dispatch overhead is amortized out): t_local=4096
1.07x (6.2 vs 6.7 ms/call), 8192 1.41x (10.2 vs 14.4 ms), 16384
1.44-1.62x across rounds (25.5-38.4 vs ~41-55 ms; the shared chip
drifts +/-10%, so bench.py records best AND median every round rather
than a single headline) — the jnp path's t_local^2 f32 score tensor goes
HBM-bound exactly where the fused kernel keeps scores in VMEM. The
kernel is the right choice once t_local reaches the many-thousands;
`block_impl="jnp"` stays the default for the moderate blocks typical
of many-device rings.

Gradients come in two tiers:

- `make_flash_block_update` (the per-block online-softmax update)
  carries a custom_vjp whose backward recomputes the block with the
  plain-jnp reference and differentiates that — exact w.r.t. the
  recurrence, but it materializes the block's [B,H,Tq,Tk] scores in
  HBM. It serves standalone block-update users.
- `make_flash_block_grads` is the BLOCKWISE FLASH BACKWARD: given the
  final per-row logsumexp L = m + log(l) and D = rowsum(dout*out), it
  recomputes p = exp(s - L) per (q-tile, k-chunk) in VMEM and
  accumulates dq (k innermost, dq carried across chunks) and dk/dv
  (q innermost, carried across tiles) in two passes — the standard
  flash-attention backward; scores never touch HBM in either
  direction. `ring_attention`'s pallas path wraps its whole per-device
  ring in a custom_vjp built on this (forward ring saves only
  q/k/v/out/L; backward ring rotates dk/dv accumulators home), so
  TRAINING at long local blocks keeps the memory win — gated by a
  jaxpr test asserting no [t_local, t_local] intermediate exists.

  Measured fwd+bwd on the v5 lite chip (causal, B=1 H=8 D=64 bf16,
  ring of 1, chained-call amortization; `experiments/
  flash_bwd_bench.py`): t_local=4096 19.6 vs 20.8 ms (1.06x), 8192
  32.8 vs 31.2 ms (0.95x) — time parity — and at 16384 the jnp path's
  f32 score tensor (8.6 GB, x2-3 live for autodiff) FAILS TPU
  compilation outright while the flash backward trains at 50.9 ms.
  The backward's price is ~5 matmuls per tile vs autodiff's 4: you
  buy the sequence length, not speed at small blocks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from idc_models_tpu.ring_attention import (
    _MASKED, _block_attend, causal_block_mask,
)

TILE_MIN = 128   # hard floor: Mosaic tile alignment
REP = 128        # lane replication width for the per-query scalars m/l


def _pick_tile(t, prefer):
    for cand in prefer:
        if t % cand == 0:
            return cand
    return 0


def _kernel(off_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
            om_ref, ol_ref, oacc_ref, *, scale, causal, tq, ck):
    """One (q-tile, k-chunk) cell. The K axis is the INNERMOST grid dim,
    so the output refs act as the online-softmax carry across k-chunks
    (revisited blocks stay resident in VMEM); only one [TQ, CK] score
    tile and one [CK, D] K/V chunk are ever live — VMEM use is O(tiles),
    independent of the local block length."""
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _seed_carry():
        om_ref[0, 0] = m_ref[0, 0]
        ol_ref[0, 0] = l_ref[0, 0]
        oacc_ref[0, 0] = acc_ref[0, 0]

    q = q_ref[0, 0].astype(jnp.float32)   # [TQ, D] (tile-local upcast)
    # m/l ride with REP(=128) identical lanes (the layout Mosaic accepts
    # for per-query scalars); arithmetic uses the [TQ, 1] column slice
    # so the score chunk width CK is free to differ from REP
    m = om_ref[0, 0][:, 0:1]           # [TQ, 1]
    l = ol_ref[0, 0][:, 0:1]
    acc = oacc_ref[0, 0]               # [TQ, D]
    k = k_ref[0, 0].astype(jnp.float32)   # [CK, D]
    v = v_ref[0, 0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [TQ, CK]
    if causal:
        q_pos = (off_ref[0] + iq * tq
                 + jax.lax.broadcasted_iota(jnp.int32, (tq, ck), 0))
        k_pos = (off_ref[1] + ik * ck
                 + jax.lax.broadcasted_iota(jnp.int32, (tq, ck), 1))
        s = jnp.where(q_pos >= k_pos, s, _MASKED)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))  # [TQ, 1]
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    om_ref[0, 0] = jnp.broadcast_to(m_new, (tq, REP))
    ol_ref[0, 0] = jnp.broadcast_to(
        l * corr + jnp.sum(p, axis=-1, keepdims=True), (tq, REP))
    oacc_ref[0, 0] = acc * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _pallas_impl(q, k, v, m, l, acc, offsets, *, scale, causal, interpret):
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    # bigger chunks amortize grid overhead (measured: a 128x128 grid of
    # cells loses to the jnp path; 512-wide K chunks win at T=8k)
    tq = _pick_tile(t_q, (256, 128))
    ck = _pick_tile(t_k, (512, 256, 128))
    if not tq or not ck:
        raise ValueError(
            f"flash block kernel needs T_local multiples of {TILE_MIN} "
            f"(got q {t_q}, k {t_k}); use the jnp block impl instead")
    n_q = t_q // tq
    n_k = t_k // ck
    # K is the innermost (fastest) grid dim: the out refs carry (m, l,
    # acc) across its iterations — the flash accumulation pattern
    grid = (b, h, n_q, n_k)
    kern = functools.partial(_kernel, scale=float(scale),
                             causal=bool(causal), tq=tq, ck=ck)
    # Mosaic wants the last two BLOCK dims (8, 128)-aligned or equal to
    # the array dims: everything is laid out [B, H, T, D] (blocks
    # (1, 1, T-tile, D)), and the per-query scalars m/l travel as
    # [B, H, T, 128] with identical lanes (the layout the official TPU
    # flash kernels use); lane 0 is peeled back off on the way out.
    bht = lambda x: jnp.transpose(x, (0, 2, 1, 3))  # [B,T,H,D]->[B,H,T,D]
    rep = lambda x: jnp.broadcast_to(x[..., None], x.shape + (REP,))
    q_spec = pl.BlockSpec((1, 1, tq, d),
                          lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, ck, d),
                           lambda bi, hi, qi, ki: (bi, hi, ki, 0))
    ml_spec = pl.BlockSpec((1, 1, tq, REP),
                           lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    om, ol, oacc = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            q_spec, kv_spec, kv_spec,
            ml_spec, ml_spec, q_spec,
        ],
        out_specs=[ml_spec, ml_spec, q_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t_q, REP), jnp.float32),
            jax.ShapeDtypeStruct((b, h, t_q, REP), jnp.float32),
            jax.ShapeDtypeStruct((b, h, t_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(offsets.astype(jnp.int32), bht(q), bht(k), bht(v),
      rep(m), rep(l), bht(acc))
    return (om[..., 0], ol[..., 0], jnp.transpose(oacc, (0, 2, 1, 3)))


def _dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, L_ref, D_ref,
               odq_ref, *, scale, causal, tq, ck):
    """One (q-tile, k-chunk) backward cell for dq. K innermost: odq_ref
    carries the accumulation across chunks. p is recomputed from the
    saved logsumexp L — one [TQ, CK] tile in VMEM, never in HBM."""
    iq, ik = pl.program_id(2), pl.program_id(3)

    @pl.when(ik == 0)
    def _zero():
        odq_ref[0, 0] = jnp.zeros_like(odq_ref[0, 0])

    q = q_ref[0, 0].astype(jnp.float32)       # [TQ, D]
    k = k_ref[0, 0].astype(jnp.float32)       # [CK, D]
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)     # [TQ, D]
    L = L_ref[0, 0][:, 0:1]                   # [TQ, 1]
    Dr = D_ref[0, 0][:, 0:1]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = (off_ref[0] + iq * tq
                 + jax.lax.broadcasted_iota(jnp.int32, (tq, ck), 0))
        k_pos = (off_ref[1] + ik * ck
                 + jax.lax.broadcasted_iota(jnp.int32, (tq, ck), 1))
        s = jnp.where(q_pos >= k_pos, s, _MASKED)
    p = jnp.exp(s - L)                        # masked entries -> exactly 0
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - Dr) * scale
    odq_ref[0, 0] += jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, L_ref, D_ref,
                odk_ref, odv_ref, *, scale, causal, tq, ck):
    """One (k-chunk, q-tile) backward cell for dk/dv. Q innermost:
    odk/odv carry the accumulation across q-tiles."""
    ik, iq = pl.program_id(2), pl.program_id(3)

    @pl.when(iq == 0)
    def _zero():
        odk_ref[0, 0] = jnp.zeros_like(odk_ref[0, 0])
        odv_ref[0, 0] = jnp.zeros_like(odv_ref[0, 0])

    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    L = L_ref[0, 0][:, 0:1]
    Dr = D_ref[0, 0][:, 0:1]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = (off_ref[0] + iq * tq
                 + jax.lax.broadcasted_iota(jnp.int32, (tq, ck), 0))
        k_pos = (off_ref[1] + ik * ck
                 + jax.lax.broadcasted_iota(jnp.int32, (tq, ck), 1))
        s = jnp.where(q_pos >= k_pos, s, _MASKED)
    p = jnp.exp(s - L)                        # [TQ, CK]
    odv_ref[0, 0] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)   # p^T do -> [CK, D]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - Dr) * scale
    odk_ref[0, 0] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)   # ds^T q -> [CK, D]


def make_flash_block_grads(*, scale, causal, interpret=False):
    """Blockwise flash backward for ONE visiting K/V block.

    ``grads(q, k, v, dout, L, D, offsets) -> (dq, dk, dv)`` where
    q/dout are [B,Tq,H,Dh], k/v [B,Tk,H,Dh], L (final per-row logsumexp
    of the WHOLE sequence, m_final + log l_final) and D
    (rowsum(dout * out)) are [B,H,Tq] f32, and offsets are the global
    block starts (the forward kernel's convention). Returns f32 grads;
    dq is this block's partial contribution (sum over visiting blocks
    to get the total), dk/dv are complete w.r.t. these queries.

    Two pallas passes recompute p = exp(s - L) per tile: a dq pass
    (K innermost, dq carried across chunks) and a dk/dv pass
    (Q innermost, carried across tiles) — 5 matmuls per tile total,
    nothing [Tq, Tk]-shaped ever leaves VMEM."""

    def grads(q, k, v, dout, L, D, offsets):
        b, t_q, h, d = q.shape
        t_k = k.shape[1]
        tq = _pick_tile(t_q, (256, 128))
        ck = _pick_tile(t_k, (512, 256, 128))
        if not tq or not ck:
            raise ValueError(
                f"flash backward needs T_local multiples of {TILE_MIN} "
                f"(got q {t_q}, k {t_k})")
        bht = lambda x: jnp.transpose(x, (0, 2, 1, 3))
        rep = lambda x: jnp.broadcast_to(x[..., None], x.shape + (REP,))
        offs = offsets.astype(jnp.int32)
        qh, kh, vh, doh = bht(q), bht(k), bht(v), bht(dout)
        Lr, Dr = rep(L.astype(jnp.float32)), rep(D.astype(jnp.float32))

        q_spec = lambda im: pl.BlockSpec((1, 1, tq, d), im)
        kv_spec = lambda im: pl.BlockSpec((1, 1, ck, d), im)
        ml_spec = lambda im: pl.BlockSpec((1, 1, tq, REP), im)

        # dq pass: grid (b, h, n_q, n_k), K innermost.
        qi_map = lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ki_map = lambda bi, hi, qi, ki: (bi, hi, ki, 0)
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, scale=float(scale),
                              causal=bool(causal), tq=tq, ck=ck),
            grid=(b, h, t_q // tq, t_k // ck),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      q_spec(qi_map), kv_spec(ki_map), kv_spec(ki_map),
                      q_spec(qi_map), ml_spec(qi_map), ml_spec(qi_map)],
            out_specs=q_spec(qi_map),
            out_shape=jax.ShapeDtypeStruct((b, h, t_q, d), jnp.float32),
            interpret=interpret,
        )(offs, qh, kh, vh, doh, Lr, Dr)

        # dk/dv pass: grid (b, h, n_k, n_q), Q innermost.
        ko_map = lambda bi, hi, ki, qi: (bi, hi, ki, 0)
        qo_map = lambda bi, hi, ki, qi: (bi, hi, qi, 0)
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=float(scale),
                              causal=bool(causal), tq=tq, ck=ck),
            grid=(b, h, t_k // ck, t_q // tq),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      q_spec(qo_map), kv_spec(ko_map), kv_spec(ko_map),
                      q_spec(qo_map), ml_spec(qo_map), ml_spec(qo_map)],
            out_specs=[kv_spec(ko_map), kv_spec(ko_map)],
            out_shape=[jax.ShapeDtypeStruct((b, h, t_k, d), jnp.float32),
                       jax.ShapeDtypeStruct((b, h, t_k, d), jnp.float32)],
            interpret=interpret,
        )(offs, qh, kh, vh, doh, Lr, Dr)
        ithb = lambda x: jnp.transpose(x, (0, 2, 1, 3))
        return ithb(dq), ithb(dk), ithb(dv)

    return grads


def block_grads_reference(q, k, v, dout, L, D, offsets, *, scale, causal):
    """Dense jnp mirror of `make_flash_block_grads` (tests pin the
    kernels against this, and this against autodiff of full
    attention)."""
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    do = dout.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = causal_block_mask(q.shape[1], k.shape[1], offsets[0],
                                 offsets[1])
        s = jnp.where(mask, s, _MASKED)
    p = jnp.exp(s - L[..., None])
    dp = jnp.einsum("bqhd,bkhd->bhqk", do, vf)
    ds = p * (dp - D[..., None]) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, do)
    return dq, dk, dv


def reference_impl(q, k, v, m, l, acc, offsets, *, scale, causal):
    """The jnp recurrence — delegates to ring_attention's
    `_block_attend` (ONE implementation of the math, so the two block
    impls cannot silently diverge), building the mask from the same two
    offsets the kernel uses."""
    mask = (causal_block_mask(q.shape[1], k.shape[1], offsets[0],
                              offsets[1]) if causal else None)
    return _block_attend(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32), m, l, acc, scale=scale,
                         mask=mask)


def make_flash_block_update(*, scale, causal, interpret=False):
    """Differentiable fused block update: forward runs the Pallas kernel,
    backward rematerializes through `reference_impl` (flash tradeoff)."""

    @jax.custom_vjp
    def update(q, k, v, m, l, acc, offsets):
        return _pallas_impl(q, k, v, m, l, acc, offsets, scale=scale,
                            causal=causal, interpret=interpret)

    def fwd(q, k, v, m, l, acc, offsets):
        return update(q, k, v, m, l, acc, offsets), (q, k, v, m, l, acc,
                                                     offsets)

    def bwd(res, g):
        q, k, v, m, l, acc, offsets = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_, m_, l_, acc_: reference_impl(
                q_, k_, v_, m_, l_, acc_, offsets, scale=scale,
                causal=causal),
            q, k, v, m, l, acc)
        return vjp(g) + (None,)

    update.defvjp(fwd, bwd)
    return update
