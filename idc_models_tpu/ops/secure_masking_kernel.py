"""Pallas TPU kernel: fused clip+quantize+pairwise-mask for secure
aggregation.

The hot op of a secure FedAvg round boundary (D4) is, per protected
tensor: clip -> fixed-point quantize -> add n_clients pairwise PRG mask
streams. Unfused (secure/masking.py), that is one quantize pass plus a
fori_loop of full-tensor PRG generations — each a separate HBM
read/write. This kernel does the whole chain in ONE pass: the tensor is
read into VMEM once, the mask streams are generated in-register from a
counter-based hash PRG (two rounds of the murmur3 finalizer over the
global element index), and the masked int32 tensor is written once.

The PRG is an explicit integer hash rather than the TPU hardware PRNG
(`pltpu.prng_random_bits`) for a correctness reason: pairwise masks must
be bit-identical at both endpoints of a pair *and* reproducible by any
backend that joins the aggregation (CPU simulation, interpret mode,
different TPU generations). A counter-based hash makes the stream a pure
function of (pair seed, element index) — `masked_quantize_reference`
computes the identical values with plain jnp, and the tests pin them
against each other.

Mask cancellation: signs are antisymmetric per pair and addition wraps
mod 2^32 (int32 two's complement), exactly like secure/masking.py.

Status: integrated into `secure.make_secure_fedavg_round` behind the
explicit opt-in ``mask_impl="auto"``: pallas on TPU once the protected
buffer reaches `masking.MASK_PALLAS_MIN_ELEMS` (4.2M elements),
threefry below it and off-TPU. The round DEFAULT remains threefry
because the masks are a privacy primitive and this hash PRG is not
cryptographic (see make_secure_fedavg_round's threat-model note) —
"auto" buys throughput where that trade is acceptable.
The crossover is measured, not assumed
(`experiments/mask_crossover.jsonl`, sweep with dispatch amortized
inside one jit on a v5 lite chip): the fused pass never loses —
1.04x at 262k elements, 1.48x at 4.2M, 1.89x at VGG16's 14.7M, 2.48x
at 33.5M — but below the threshold the absolute win (~0.1 ms) is
noise while the round pays one kernel call per local client, and
threefry is also the cryptographically stronger PRG. (Round 3's
"threefry wins small" reading came from per-call timings dominated by
the tunneled runtime's ~10 ms dispatch; the in-jit sweep replaces it.)
Both impls aggregate bit-identically (tests/test_secure.py pins this).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_BLOCK_ROWS = 512  # 512x128 int32 = 256 KiB per VMEM buffer
_GOLDEN = 0x9E3779B1  # plain int: jnp constants would be captured by the kernel trace


def _fmix32(h):
    """murmur3 finalizer — a full-avalanche 32-bit mixer (public domain
    constants)."""
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _mask_stream(seed_u32, idx_u32):
    """The pairwise PRG: mask element = fmix32(fmix32(seed ^ idx*GOLDEN))."""
    return _fmix32(_fmix32(seed_u32 ^ (idx_u32 * jnp.uint32(_GOLDEN))))


def pair_seeds_and_signs(base_seed, my_id, n_clients: int, round_index=0):
    """Per-peer (seeds [n], signs [n]) for client `my_id`.

    seeds[j] is a pure function of (base_seed, round, {min(i,j),
    max(i,j)}) so both endpoints derive the same stream; signs[j] =
    sign(j - i) gives the antisymmetric cancellation. Plain jnp — callable
    inside shard_map with a traced my_id.
    """
    js = jnp.arange(n_clients, dtype=jnp.int32)
    my_id = jnp.asarray(my_id, jnp.int32)
    lo = jnp.minimum(js, my_id).astype(jnp.uint32)
    hi = jnp.maximum(js, my_id).astype(jnp.uint32)
    base = jnp.asarray(base_seed, jnp.uint32) + jnp.uint32(round_index) * jnp.uint32(_GOLDEN)
    seeds = _fmix32(_fmix32(base ^ (lo * jnp.uint32(_GOLDEN))) ^ (hi * jnp.uint32(0x85EBCA77)))
    signs = jnp.sign(js - my_id)
    return seeds, signs


def _kernel(seeds_ref, signs_ref, x_ref, out_ref, *, n_clients, scale,
            clip_abs, total_rows):
    block = pl.program_id(0)
    rows, lanes = x_ref.shape
    x = jnp.clip(x_ref[:], -clip_abs, clip_abs)
    acc = jnp.round(x * scale).astype(jnp.int32)
    row0 = block * rows
    idx = (jnp.uint32(row0) * jnp.uint32(lanes)
           + jax.lax.broadcasted_iota(jnp.uint32, (rows, lanes), 0)
           * jnp.uint32(lanes)
           + jax.lax.broadcasted_iota(jnp.uint32, (rows, lanes), 1))
    for j in range(n_clients):
        mask = _mask_stream(seeds_ref[j], idx)
        acc = acc + signs_ref[j] * jax.lax.bitcast_convert_type(
            mask, jnp.int32)
    out_ref[:] = acc


def fused_masked_quantize(x, seeds, signs, *, scale_bits: int,
                          clip_abs: float, interpret: bool = False):
    """Quantize `x` (any shape, fp) to int32 fixed point and add this
    client's total pairwise mask — one fused pass.

    `seeds`/`signs` come from `pair_seeds_and_signs`. Output has x's
    shape; the mask stream is indexed over the padded flat layout, so all
    clients must use identical tensor shapes (they do: model replicas).
    """
    n_clients = seeds.shape[0]
    orig_shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    rows = -(-n // _LANES)
    pad_rows = -(-rows // 8) * 8  # f32 tile: 8 sublanes
    padded = jnp.zeros((pad_rows * _LANES,), jnp.float32).at[:n].set(flat)
    grid_rows = min(_BLOCK_ROWS, pad_rows)
    n_blocks = -(-pad_rows // grid_rows)
    if pad_rows % grid_rows:
        extra = n_blocks * grid_rows - pad_rows
        padded = jnp.concatenate(
            [padded, jnp.zeros((extra * _LANES,), jnp.float32)])
        pad_rows = n_blocks * grid_rows
    x2 = padded.reshape(pad_rows, _LANES)

    kernel = functools.partial(
        _kernel, n_clients=n_clients, scale=float(2.0 ** scale_bits),
        clip_abs=float(clip_abs), total_rows=pad_rows)
    out = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((grid_rows, _LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((grid_rows, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((pad_rows, _LANES), jnp.int32),
        interpret=interpret,
    )(seeds.astype(jnp.uint32), signs.astype(jnp.int32), x2)
    return out.reshape(-1)[:n].reshape(orig_shape)


def masked_quantize_reference(x, seeds, signs, *, scale_bits: int,
                              clip_abs: float):
    """Bit-identical plain-jnp implementation of the kernel (the
    cross-backend contract: any participant computing this joins the same
    aggregation)."""
    orig_shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    q = jnp.round(jnp.clip(flat, -clip_abs, clip_abs)
                  * (2.0 ** scale_bits)).astype(jnp.int32)
    idx = jnp.arange(n, dtype=jnp.uint32)
    acc = q
    for j in range(seeds.shape[0]):
        mask = _mask_stream(seeds[j].astype(jnp.uint32), idx)
        acc = acc + signs[j].astype(jnp.int32) * jax.lax.bitcast_convert_type(
            mask, jnp.int32)
    return acc.reshape(orig_shape)
