from idc_models_tpu.ops.secure_masking_kernel import (  # noqa: F401
    fused_masked_quantize,
    masked_quantize_reference,
    pair_seeds_and_signs,
)
