"""Pallas TPU kernel: fused depthwise-conv + folded-BN + ReLU6 (ISSUE 16).

MobileNetV2's hot chains lower as three separate XLA ops — depthwise
conv, batchnorm, relu6 — each materializing the full activation tensor
in HBM between them. A depthwise conv does ~9 FLOPs per activation
byte (no channel contraction, nothing for the MXU to reduce), so every
unfused boundary roughly doubles the bytes per useful FLOP; the PR 14
MFU attribution (docs/BENCHMARKS.md) measured the whole train step at
arithmetic intensity 3.5 vs the v5e ridge of ~240 and named these
chains as the implicated lowering. This kernel keeps the activation
tile in VMEM across all three ops: one grid cell loads an image's
padded activation once, runs the kh*kw shifted multiply-accumulates
(the same taps formulation `core.depthwise_conv2d(impl="taps")` pins
against XLA's grouped lowering), applies the FOLDED batchnorm as one
scale/shift, clamps to [0, 6], and writes the output tile — HBM
traffic is x in + y out, nothing between.

BN folding happens OUTSIDE the kernel (and outside the custom_vjp), in
plain jnp, so it stays differentiable for free:

    mul = scale * rsqrt(var + eps)
    add = bias - mean * mul
    y   = relu6(dwconv(x) * mul + add)

which is exactly the inference / frozen-BN composition — the paths the
transfer-learning recipe runs (`bn_frozen_below` freezes every BN
below the fine-tune boundary, and phase-1 freezes all of them). In
unfrozen train mode BN needs batch statistics, so callers fall back to
the unfused chain there (models/mobilenet.py does this per-layer,
statically).

Grid/tiling: one grid cell per (image, channel tile). Spatial tiling
is deliberately NOT done — a 3x3 conv's spatial tiles overlap by a
halo, and Pallas BlockSpecs cannot express overlapping blocks, so the
per-cell block is the full padded image. Channels, by contrast, are
fully independent in a depthwise conv, so the channel axis is the free
tiling axis that bounds VMEM: `channel_tile` splits C when the full
image does not fit (it must divide C; `_pick_channel_tile` records the
sweep's choice — see experiments/fused_backbone.py). At the paper's
50x50 patches every activation fits untiled (largest: 25x25x96 f32 =
240 KB/image), which is the recorded default.

Gradients: `_fused` carries a custom_vjp whose backward differentiates
the pure-jnp reference at the saved inputs — the flash_block_kernel
pattern — so `depthwise_impl="fused"` trains (the depthwise kernels
above the fine-tune boundary still receive gradients even while their
BNs are frozen). The backward is ordinary XLA code and fuses fine; the
forward is where the unfused chain paid.

Testing contract: `interpret=True` runs the SAME kernel body under the
Pallas interpreter on CPU, so tier-1 parity tests exercise the real
code path, not a stand-in; `interpret=None` (the default) resolves to
the interpreter automatically off-TPU. XLA's `cost_analysis` cannot
see inside a Pallas custom call, so `depthwise_chain_cost` provides
the analytic FLOPs/bytes the profile verb merges into its ProgramCost
(observe/profile.py `augment_cost` / `register_cost`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# VMEM budget one image's padded activation + output may occupy before
# the kernel insists on channel tiling (v5e has 128 MB VMEM per core;
# staying well under leaves room for double-buffering).
VMEM_BUDGET_BYTES = 8 * 1024 * 1024

# Chosen by the experiments/fused_backbone.py sweep at the paper's
# shapes (50x50 patches, batch 8..4096): every MobileNetV2 activation
# fits VMEM whole, so the recorded default is "no channel tiling".
DEFAULT_CHANNEL_TILE = None


def fold_bn(scale, bias, mean, var, eps):
    """Fold inference-mode batchnorm into one (mul, add) affine pair:
    ``bn(y) = (y - mean) * rsqrt(var + eps) * scale + bias
            = y * mul + add``.
    Plain jnp on purpose — it runs outside the kernel (and outside the
    custom_vjp), so scale/bias gradients come from ordinary autodiff."""
    mul = scale * lax.rsqrt(var + eps)
    return mul, bias - mean * mul


def _same_pad(x, kh, kw, sh, sw):
    """TF-SAME padding (lo = total//2, hi = rest — matches XLA and the
    core.py taps impl) plus the padded/output spatial sizes."""
    _, h_in, w_in, _ = x.shape
    h_out, w_out = -(-h_in // sh), -(-w_in // sw)
    ph = max((h_out - 1) * sh + kh - h_in, 0)
    pw = max((w_out - 1) * sw + kw - w_in, 0)
    xp = jnp.pad(x, ((0, 0), (ph // 2, ph - ph // 2),
                     (pw // 2, pw - pw // 2), (0, 0)))
    return xp, h_out, w_out


def reference_impl(x, w, mul, add, *, stride=1, clamp6=True):
    """Pure-jnp mirror of the kernel: taps depthwise conv (TF-SAME),
    folded-BN affine, optional ReLU6. The parity target for the Pallas
    path and the function the custom_vjp backward differentiates."""
    kh, kw = int(w.shape[0]), int(w.shape[1])
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    xp, h_out, w_out = _same_pad(x, kh, kw, sh, sw)
    wf = w.reshape(kh, kw, -1)
    y = None
    for i in range(kh):
        for j in range(kw):
            xs = xp[:, i:i + (h_out - 1) * sh + 1:sh,
                    j:j + (w_out - 1) * sw + 1:sw, :]
            t = xs.astype(jnp.float32) * wf[i, j]
            y = t if y is None else y + t
    y = y * mul + add
    if clamp6:
        y = jnp.clip(y, 0.0, 6.0)
    return y.astype(x.dtype)


def _kernel(xp_ref, w_ref, mul_ref, add_ref, out_ref, *,
            kh, kw, sh, sw, h_out, w_out, clamp6):
    """One (image, channel-tile) cell: taps MAC + affine + clamp, all
    on the VMEM-resident tile."""
    x = xp_ref[...].astype(jnp.float32)          # (1, Hp, Wp, ct)
    ct = x.shape[-1]
    acc = None
    for i in range(kh):
        for j in range(kw):
            xs = lax.slice(x, (0, i, j, 0),
                           (1, i + (h_out - 1) * sh + 1,
                            j + (w_out - 1) * sw + 1, ct),
                           (1, sh, sw, 1))
            t = xs * w_ref[i * kw + j, :]
            acc = t if acc is None else acc + t
    y = acc * mul_ref[0] + add_ref[0]
    if clamp6:
        y = jnp.clip(y, 0.0, 6.0)
    out_ref[...] = y.astype(out_ref.dtype)


def _pick_channel_tile(h_p, w_p, h_out, w_out, c, itemsize,
                       channel_tile):
    """Resolve the channel-tile size: an explicit request must divide C;
    `None` means whole-C unless the per-cell VMEM footprint (padded
    input + output tile, f32 accumulate) busts the budget, in which
    case the largest budget-fitting divisor of C is chosen."""
    if channel_tile is not None:
        if c % channel_tile:
            raise ValueError(f"channel_tile {channel_tile} must divide "
                             f"channel count {c}")
        return channel_tile
    per_chan = (h_p * w_p + h_out * w_out) * max(itemsize, 4)
    if per_chan * c <= VMEM_BUDGET_BYTES:
        return c
    best = 1
    for d in range(1, c + 1):
        if c % d == 0 and per_chan * d <= VMEM_BUDGET_BYTES:
            best = d
    return best


def _pallas_impl(x, w, mul, add, *, stride, clamp6, interpret,
                 channel_tile):
    kh, kw = int(w.shape[0]), int(w.shape[1])
    sh, sw = stride
    n, _, _, c = x.shape
    xp, h_out, w_out = _same_pad(x, kh, kw, sh, sw)
    _, h_p, w_p, _ = xp.shape
    ct = _pick_channel_tile(h_p, w_p, h_out, w_out, c,
                            jnp.dtype(x.dtype).itemsize, channel_tile)
    wf = w.reshape(kh * kw, c).astype(jnp.float32)
    mul2 = mul.reshape(1, c).astype(jnp.float32)
    add2 = add.reshape(1, c).astype(jnp.float32)
    kern = functools.partial(_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                             h_out=h_out, w_out=w_out, clamp6=clamp6)
    return pl.pallas_call(
        kern,
        grid=(n, c // ct),
        in_specs=[
            pl.BlockSpec((1, h_p, w_p, ct), lambda i, j: (i, 0, 0, j)),
            pl.BlockSpec((kh * kw, ct), lambda i, j: (0, j)),
            pl.BlockSpec((1, ct), lambda i, j: (0, j)),
            pl.BlockSpec((1, ct), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, h_out, w_out, ct),
                               lambda i, j: (i, 0, 0, j)),
        out_shape=jax.ShapeDtypeStruct((n, h_out, w_out, c), x.dtype),
        interpret=interpret,
    )(xp, wf, mul2, add2)


@functools.lru_cache(maxsize=None)
def _make_fused(stride, clamp6, interpret, channel_tile):
    """custom_vjp closure over the static config: Pallas forward,
    backward = jax.vjp of the jnp reference at the saved inputs (the
    flash_block_kernel pattern — exact w.r.t. the reference math)."""

    @jax.custom_vjp
    def fused(x, w, mul, add):
        return _pallas_impl(x, w, mul, add, stride=stride,
                            clamp6=clamp6, interpret=interpret,
                            channel_tile=channel_tile)

    def fwd(x, w, mul, add):
        return fused(x, w, mul, add), (x, w, mul, add)

    def bwd(res, g):
        x, w, mul, add = res
        _, vjp = jax.vjp(
            lambda x_, w_, m_, a_: reference_impl(
                x_, w_, m_, a_, stride=stride, clamp6=clamp6),
            x, w, mul, add)
        return vjp(g)

    fused.defvjp(fwd, bwd)
    return fused


def default_interpret() -> bool:
    """The `interpret=None` resolution: real Mosaic lowering on TPU,
    the Pallas interpreter (same kernel body) everywhere else — the
    tier-1-on-CPU testing contract."""
    return jax.default_backend() != "tpu"


def fused_depthwise_affine(x, w, mul, add, *, stride=1, clamp6=True,
                           interpret=None, channel_tile=None):
    """Fused `clamp6(dwconv(x) * mul + add)` (TF-SAME padding).

    x: [N, H, W, C]; w: [kh, kw, 1, C] (the core.depthwise_conv2d param
    layout); mul/add: [C] folded-BN affine (identity: ones/zeros).
    Differentiable in all four array arguments via the reference-vjp
    backward.
    """
    if interpret is None:
        interpret = default_interpret()
    strides = (stride, stride) if isinstance(stride, int) else stride
    if channel_tile is None:
        channel_tile = DEFAULT_CHANNEL_TILE
    return _make_fused(tuple(strides), bool(clamp6), bool(interpret),
                       channel_tile)(x, w, mul, add)


def fused_depthwise_bn_relu6(x, w, scale, bias, mean, var, *, eps,
                             stride=1, interpret=None,
                             channel_tile=None):
    """The MobileNetV2 chain: depthwise conv -> inference-mode BN ->
    ReLU6, one kernel. `scale`/`bias` are BN params, `mean`/`var` the
    moving statistics — folding happens here, outside the kernel's
    custom_vjp, so their gradients flow through ordinary autodiff."""
    mul, add = fold_bn(scale, bias, mean, var, eps)
    return fused_depthwise_affine(x, w, mul, add, stride=stride,
                                  clamp6=True, interpret=interpret,
                                  channel_tile=channel_tile)


# ---------------------------------------------------------------------------
# analytic cost — XLA cost_analysis cannot see inside a Pallas call
# ---------------------------------------------------------------------------


def depthwise_call_cost(n, h_in, w_in, c, *, stride=1, kernel_size=3,
                        itemsize=4):
    """Analytic (flops, bytes_accessed) of ONE fused call: kh*kw MACs +
    the affine + the clamp per output element; HBM bytes are the padded
    input + output + the (tiny) weight/affine operands."""
    k = kernel_size
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    h_out, w_out = -(-h_in // sh), -(-w_in // sw)
    out_elems = n * h_out * w_out * c
    flops = float(out_elems * (2 * k * k + 3))
    h_p = (h_out - 1) * sh + k
    w_p = (w_out - 1) * sw + k
    bytes_accessed = float(
        (n * h_p * w_p * c + out_elems) * itemsize
        + (k * k * c + 2 * c) * 4)
    return flops, bytes_accessed


def depthwise_chain_cost(calls, *, itemsize=4):
    """Sum `depthwise_call_cost` over `calls` — an iterable of dicts of
    its keyword arguments (models/mobilenet.py `fused_call_shapes`
    produces the schedule). Returns (flops, bytes_accessed)."""
    flops = bytes_accessed = 0.0
    for call in calls:
        f, b = depthwise_call_cost(itemsize=itemsize, **call)
        flops += f
        bytes_accessed += b
    return flops, bytes_accessed
