"""Thin, well-tested wrappers over XLA collectives.

This module is the framework's entire "communication backend" — the
replacement for NCCL, which the reference uses implicitly through
`MirroredStrategy`'s default CrossDeviceOps (SURVEY.md D5; no explicit
collective code exists anywhere in the reference). On TPU these lower to
ICI ring reductions within a pod slice and DCN across hosts; the choice is
made by the XLA compiler at compile time, not by a runtime library.

All functions are meant to be called *inside* `shard_map`-ed (or otherwise
axis-bound) functions, where `axis_name` is in scope.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def psum(tree, axis_name: str):
    """Sum a pytree across an axis (gradient allreduce; mask cancellation)."""
    return lax.psum(tree, axis_name)


def pmean(tree, axis_name: str):
    """Mean a pytree across an axis (FedAvg unweighted aggregate)."""
    return lax.pmean(tree, axis_name)


def weighted_pmean(tree, weight, axis_name: str):
    """Example-weighted mean across an axis.

    The reference's TFF FedAvg is example-weighted while its hand-rolled
    secure server is an unweighted mean (quirk Q7, secure_fed_model.py:160-168);
    we expose the weighted form as the primitive and let callers pass
    weight=1 to recover the unweighted behavior.

    Failure-tolerance semantics: negative weights are treated as 0, and
    zero-weight members are excluded even if their values are non-finite
    (a crashed/diverged client would otherwise poison the aggregate
    through NaN * 0 == NaN). If EVERY member has weight 0 the result is
    a zero tree, not NaN — callers that must distinguish "no
    contributors" should check psum(weight) themselves (the FedAvg round
    keeps its previous state in that case).
    """
    return weighted_pmean_local(
        jax.tree.map(lambda x: jnp.asarray(x)[None], tree),
        jnp.asarray(weight, jnp.float32).reshape(1), axis_name)


def weighted_pmean_local(tree, weights, axis_name: str):
    """Weighted mean over members stacked on each leaf's LEADING axis and
    over the mesh axis — the k-clients-per-device round boundary
    (`weights` has shape [k], leaves [k, ...]). Same failure-tolerance
    semantics as `weighted_pmean`, of which this is the general form.
    """
    weights = jnp.maximum(jnp.asarray(weights, jnp.float32), 0.0)
    total = lax.psum(weights.sum(), axis_name)
    safe_total = jnp.maximum(total, jnp.float32(1e-30))

    def contrib(x):
        w = weights.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        masked = jnp.where(w > 0, x * w, jnp.zeros_like(x)).sum(axis=0)
        return lax.psum(masked, axis_name) / safe_total.astype(x.dtype)

    return jax.tree.map(contrib, tree)


def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = False):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name: str, perm):
    """Point-to-point permutation — the primitive behind ring schedules and
    pairwise-mask key agreement (secure aggregation)."""
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    # jax <= 0.4.x: psum of the python scalar 1 is evaluated at trace
    # time against the axis env and returns the CONCRETE size — the
    # canonical pre-axis_size idiom, safe to drive python-unrolled loops
    return lax.psum(1, axis_name)


def ring_perm(n: int, shift: int = 1) -> list[tuple[int, int]]:
    """Source->dest pairs for a ring shift of `shift` over n devices."""
    return [(i, (i + shift) % n) for i in range(n)]


def reduce_scatter(x, axis_name: str, *, scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                            tiled=True)


def ring_psum(x, axis_name: str):
    """All-reduce as an EXPLICIT bandwidth-optimal ring: a chunked
    reduce-scatter followed by an all-gather, each built from n-1
    neighbor `ppermute` shifts.

    `psum` compiles to this same schedule on a TPU ICI ring, so the
    normal hot path should just use `psum` and let XLA pick; this
    explicit form exists because it is the schedule under *user*
    control — the building block for programs that need to interleave
    per-hop compute with the transfers (ring/blockwise schedules over a
    sequence axis, e.g. ring attention, stage exactly this loop with the
    block compute fused between hops), which SURVEY.md §5 calls out as
    the future-facing reason this module exposes `ppermute`.

    Equal to `psum` up to summation order: bit-exact for integer dtypes
    (the secure-aggregation masks rely on int32 wrap-around, which is
    order-free), within fp tolerance for floats.

    Compile-time scaling: the 2(n-1) hops are unrolled in Python, so HLO
    size (and the dynamic-index `.at[].set` chain) grows linearly with
    ring size — fine for ICI-scale rings (n <= 64), deliberate for
    per-hop fusion control. A pod-of-pods ring would want the loop
    restructured as `lax.fori_loop` over rotating blocks; do that when
    such a ring becomes a real use case, not before.
    """
    n = int(axis_size(axis_name))
    if n == 1:
        return x
    me = lax.axis_index(axis_name)
    fwd = ring_perm(n)
    flat = x.reshape(-1)
    chunk = -(-flat.size // n)
    blocks = jnp.pad(flat, (0, chunk * n - flat.size)).reshape(n, chunk)

    # Reduce-scatter: after step s the carry holds s+2 devices' partial
    # sum; after n-1 steps device i owns the full sum of block (i+1)%n.
    carry = blocks[me]
    for s in range(n - 1):
        carry = lax.ppermute(carry, axis_name, fwd)
        carry = carry + blocks[jnp.mod(me - s - 1, n)]

    # All-gather: circulate the n reduced blocks back around the ring.
    out = jnp.zeros_like(blocks).at[jnp.mod(me + 1, n)].set(carry)
    for s in range(n - 1):
        carry = lax.ppermute(carry, axis_name, fwd)
        out = out.at[jnp.mod(me - s, n)].set(carry)
    return out.reshape(-1)[: flat.size].reshape(x.shape)
