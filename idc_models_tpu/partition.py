"""Rule-based GSPMD sharding: regex over named param paths -> PartitionSpec.

Before this layer, every subsystem hand-wired its own placement: the
train step pinned states replicated, tp.py carried a shape-based channel
rule, the serve engine replicated params next to its ring-sharded KV,
and the federated wave accumulators pinned ad-hoc shardings. One model
could not say "shard my attention weights over 'model' and my optimizer
moments with their params" in a single place — which is exactly what
FSDP / tensor-parallel LM configs need (ROADMAP item 2; the
`match_partition_rules` pattern of SNIPPETS.md [1]).

This module is THE resolution point (a static scan in
tests/test_static_robustness.py bans `NamedSharding(`/`PartitionSpec(`
construction outside the sharding layers):

- `PartitionRules` — ordered ``(regex, PartitionSpec)`` pairs, resolved
  against `jax.tree_util` key paths joined with "/" (e.g.
  ``params/block0/mha/wq``). FIRST match wins, so specific rules go
  before catch-alls. `re.search` semantics mean a rule written for a
  param path also matches the optimizer moments mirroring it
  (``opt_state/.../nu/block0/mha/wq``) — optimizer state shards with
  its param (FSDP) with zero extra rules.
- Specs are RIGHT-ALIGNED against each leaf's shape: ``P("model")`` on
  a [E, M] kernel shards M, on a [M] bias shards M — one rule covers a
  kernel and its bias. Missing leading dims are replicated.
- Mesh adaptation: axes absent from the mesh (or of size 1) are
  dropped, and a dim not divisible by its axis falls back to
  replication — one rule set serves every mesh, from a single-device
  serve ring to an ("data", "model", "seq") pod, degenerating to the
  pre-rules replicated layout where the axes don't exist.
- Teaching errors: a non-scalar leaf no rule matches raises (add a
  rule or the ``(r".*", P())`` catch-all); a rule that matches NO leaf
  raises too (a param rename silently killing a rule is the failure
  mode the golden param-path test freezes at CI time).
- `shard_tree` / `gather_tree` — the one place/unplace pair shared by
  train, federated, and serve.

Scalars (and 1-element leaves) always replicate, matching the
`match_partition_rules` reference pattern.
"""

from __future__ import annotations

import re
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from idc_models_tpu import mesh as meshlib

SEP = "/"


class PartitionError(ValueError):
    """A rules/tree mismatch with a teaching message."""


def _key_str(entry) -> str:
    """One key-path entry -> its bare name (no brackets/dots)."""
    for attr in ("key", "name", "idx"):
        v = getattr(entry, attr, None)
        if v is not None:
            return str(v)
    return str(entry)


def path_str(path) -> str:
    """A jax key path -> "a/b/0/c" (the name rules match against)."""
    return SEP.join(_key_str(k) for k in path)


def tree_paths(tree) -> list[tuple[str, object]]:
    """[(name, leaf)] for every leaf, names in "a/b/c" form."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(path_str(p), leaf) for p, leaf in leaves]


def _leaf_shape(leaf) -> tuple:
    shape = getattr(leaf, "shape", None)
    return tuple(shape) if shape is not None else np.shape(leaf)


def _axes_of(entry) -> tuple:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def _adapt(spec: P, shape: tuple, mesh: Mesh) -> P:
    """Fit a (right-aligned) spec onto a concrete shape and mesh: drop
    axes the mesh lacks (or holds at size 1) and fall back to
    replication on non-dividing dims. Trailing Nones are stripped so
    every surface spells one layout one way (the jit cache keys on
    spec EQUALITY — the engine's trailing-None-free discipline)."""
    entries = (None,) * (len(shape) - len(spec)) + tuple(spec)
    out = []
    for dim, entry in zip(shape, entries):
        kept = [a for a in _axes_of(entry)
                if a in mesh.axis_names and mesh.shape[a] > 1]
        n = int(np.prod([mesh.shape[a] for a in kept])) if kept else 1
        if not kept or dim % n:
            out.append(None)
        else:
            out.append(kept[0] if len(kept) == 1 else tuple(kept))
    while out and out[-1] is None:
        out.pop()
    return P(*out)


class PartitionRules:
    """Ordered ``(regex, PartitionSpec)`` pairs resolved against named
    param-tree paths — the whole sharding policy of a model in one
    object (see module docstring for matching/adaptation semantics)."""

    def __init__(self, rules: Sequence[tuple[str, P]]):
        if not rules:
            raise PartitionError(
                "PartitionRules needs at least one (regex, "
                "PartitionSpec) pair — for all-replicated use "
                "PartitionRules.replicated()")
        compiled = []
        for i, pair in enumerate(rules):
            if len(pair) != 2:
                raise PartitionError(
                    f"rule {i} must be a (regex, PartitionSpec) pair, "
                    f"got {pair!r}")
            pattern, spec = pair
            if not isinstance(spec, P):
                raise PartitionError(
                    f"rule {i} ({pattern!r}) maps to {spec!r} — the "
                    f"right side must be a jax.sharding.PartitionSpec")
            axes = [a for e in spec for a in _axes_of(e)]
            if len(axes) != len(set(axes)):
                raise PartitionError(
                    f"rule {i} ({pattern!r}) names a mesh axis twice "
                    f"in {spec} — a tensor dim pair cannot share one "
                    f"axis")
            try:
                rx = re.compile(pattern)
            except re.error as e:
                raise PartitionError(
                    f"rule {i} regex {pattern!r} does not compile: "
                    f"{e}") from e
            compiled.append((pattern, rx, spec))
        self._rules = tuple(compiled)

    @classmethod
    def replicated(cls) -> "PartitionRules":
        """The degenerate rule set: everything replicated — the layout
        every subsystem used before rules existed (bit-compatible)."""
        return cls(((r".*", P()),))

    @property
    def patterns(self) -> tuple[str, ...]:
        return tuple(pattern for pattern, _, _ in self._rules)

    def __repr__(self) -> str:
        body = ", ".join(f"({pattern!r}, {spec})"
                         for pattern, _, spec in self._rules)
        return f"PartitionRules(({body}))"

    def _match(self, name: str):
        for i, (_, rx, spec) in enumerate(self._rules):
            if rx.search(name) is not None:
                return i, spec
        return None, None

    def _resolve_leaf(self, name: str, shape):
        """(matched rule index | None, un-adapted spec) for one leaf —
        ONE regex scan per leaf. Scalars (and 1/0-element leaves)
        always replicate, matched or not; only a NON-scalar leaf no
        rule matches raises."""
        i, spec = self._match(name)
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return i, P()   # scalars, 1-element and ZERO-size leaves
        if i is None:
            raise PartitionError(self._unmatched_msg(name, shape))
        if len(spec) > len(shape):
            raise PartitionError(
                f"rule {self.patterns[i]!r} carries a rank-{len(spec)} "
                f"spec {spec} but matched the rank-{len(shape)} param "
                f"{name!r} (shape {tuple(shape)}) — specs right-align "
                f"against the leaf shape and may not exceed its rank; "
                f"write a more specific rule for this leaf")
        return i, spec

    def spec_for(self, name: str, shape) -> P:
        """The (un-adapted) spec for one named leaf: first matching
        rule wins; scalars/1-element leaves always replicate. `shape`
        is required — without it every leaf would read as a scalar
        and resolve replicated."""
        return self._resolve_leaf(name, shape)[1]

    def _unmatched_msg(self, name, shape) -> str:
        return (f"no partition rule matches param {name!r} (shape "
                f"{tuple(shape)}); rules tried, in order: "
                f"{list(self.patterns)}. Add a rule for it, or end "
                f"the rule set with the catch-all (r'.*', "
                f"PartitionSpec()) to replicate everything unmatched")

    def specs(self, tree, *, mesh: Mesh | None = None,
              check_dead: bool = True):
        """Pytree of PartitionSpec for `tree` — adapted to `mesh` when
        given (axis dropping + divisibility fallback), raw otherwise.
        With `check_dead`, a rule matching NO leaf raises: a dead rule
        means a param was renamed out from under it, and the sharding
        it described is silently gone."""
        live = set()
        names_seen = []

        def resolve(path, leaf):
            name = path_str(path)
            shape = _leaf_shape(leaf)
            i, spec = self._resolve_leaf(name, shape)
            if i is not None:
                live.add(i)
            names_seen.append(name)
            return _adapt(spec, shape, mesh) if mesh is not None else spec

        out = jax.tree_util.tree_map_with_path(resolve, tree)
        if check_dead and names_seen:
            dead = [self.patterns[i] for i in range(len(self._rules))
                    if i not in live]
            if dead:
                raise PartitionError(
                    f"dead partition rule(s) {dead}: they match none "
                    f"of the {len(names_seen)} leaves of this tree — "
                    f"a param rename has probably orphaned them "
                    f"(tests/test_partition.py freezes the golden "
                    f"param paths; update the rule or the model, "
                    f"or resolve with check_dead=False for a "
                    f"deliberately partial tree)")
        return out

    def shardings(self, mesh: Mesh, tree, *, check_dead: bool = True):
        """Pytree of NamedSharding over `mesh` — the jit
        in/out_shardings form of `specs`."""
        return jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            self.specs(tree, mesh=mesh, check_dead=check_dead),
            is_leaf=lambda x: isinstance(x, P))


def shard_tree(mesh: Mesh, rules: PartitionRules, tree, *,
               check_dead: bool = True):
    """Place a pytree on `mesh` under `rules` — THE shard half of the
    place/unplace pair every subsystem routes through. Multi-process
    safe (each host feeds only its addressable shards), and a leaf
    already under its resolved sharding is left untouched."""
    sh = rules.shardings(mesh, tree, check_dead=check_dead)
    return jax.tree.map(meshlib.put_with_sharding, tree, sh)


def gather_tree(mesh: Mesh, tree):
    """Re-place a (possibly sharded) pytree fully replicated on `mesh`
    — the gather half: the layout checkpointing, cross-mesh handoff
    (train -> serve), and host fetches expect. XLA inserts the
    all-gathers; already-replicated leaves are untouched."""
    rep = meshlib.replicated(mesh)
    return jax.tree.map(lambda a: meshlib.put_with_sharding(a, rep),
                        tree)
