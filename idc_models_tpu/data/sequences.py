"""Image -> token-sequence views: the bridge from the reference's image
pipeline (C1/C2, dist_model_tf_vgg.py:34-65) to the framework's
sequence-parallel attention workload.

The reference has no sequence models, so there is no reference recipe to
match; this is the smallest honest embedding of its own data domain into
the SP path: each decoded patch becomes a raster-order sequence of
square sub-patches, every token the flattened pixels of one sub-patch
(ViT-style patch embedding, minus the learned projection — that is the
model's `embed` layer). `patch_size=1` degenerates to the per-pixel
sequence (S*S tokens of the 3 channel values).
"""

from __future__ import annotations

import numpy as np


def patchify(images: np.ndarray, patch_size: int) -> np.ndarray:
    """[N, S, S, C] images -> [N, (S/p)^2, p*p*C] token sequences.

    Tokens are the p x p sub-patches in raster order; each token's
    features are its pixels flattened row-major with channels innermost.
    `S` must divide by `patch_size` (images are already square-resized
    by the loaders).
    """
    if patch_size < 1:
        raise ValueError(f"patch_size must be >= 1, got {patch_size}")
    images = np.asarray(images)
    if images.ndim != 4 or images.shape[1] != images.shape[2]:
        raise ValueError(f"expected [N, S, S, C] images, got "
                         f"{images.shape}")
    n, s, _, c = images.shape
    if s % patch_size:
        raise ValueError(f"image size {s} not divisible by patch_size "
                         f"{patch_size}")
    g = s // patch_size
    x = images.reshape(n, g, patch_size, g, patch_size, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)          # [N, gy, gx, p, p, C]
    return np.ascontiguousarray(
        x.reshape(n, g * g, patch_size * patch_size * c))


def sequence_shape(image_size: int, patch_size: int,
                   channels: int = 3) -> tuple[int, int]:
    """(seq_len, features) of `patchify` output for planning/validation."""
    if patch_size < 1:
        raise ValueError(f"patch_size must be >= 1, got {patch_size}")
    if image_size % patch_size:
        raise ValueError(f"image size {image_size} not divisible by "
                         f"patch_size {patch_size}")
    g = image_size // patch_size
    return g * g, patch_size * patch_size * channels
