"""Host→HBM input pipeline: batching, shuffling, and prefetch.

The TPU-native replacement for the reference's `prepare_for_training`
(cache → shuffle(1000) → batch → prefetch(AUTOTUNE), e.g.
dist_model_tf_vgg.py:47-65). Data lives in host RAM as numpy (the cache);
per-epoch order is a fresh seeded permutation (the shuffle); batches are
cut to a multiple of the mesh's data-axis size; and a background thread
keeps `prefetch` batches already transferred to device HBM with the right
NamedSharding (the prefetch) so the chips never wait on PCIe/host.
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Iterator

import jax
import numpy as np
from jax.sharding import Mesh

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data.idc import ArrayDataset


class _EpochSchedule:
    """The shared batching/shuffle/repeat schedule — the seeding contract
    ((seed, epoch) for pass 0, (seed, epoch, rep) for extra passes) lives
    only here, so `Loader` and `FileStream` stay bit-identical.

    - `shuffle`: new seeded permutation each epoch (epoch mixed into seed)
    - `drop_remainder`: required under data parallelism so every step's
      global batch divides the mesh; the reference gets this implicitly
      from fixed-size take/skip splits
    - `repeat`: passes over the dataset per epoch — the reference's
      CIFAR pipeline appends `.repeat(2)` after batching
      (dist_model_tf_dense.py:122-123), so each fit "epoch" sees the
      train set twice; with shuffle on, every pass gets a fresh
      permutation (tf.data reshuffles each iteration)

    Subclasses define `_num_examples()` and `_gather(idx) -> batch`.
    """

    def __init__(self, batch_size: int, *, shuffle: bool = True,
                 seed: int = 0, drop_remainder: bool = True,
                 repeat: int = 1):
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.repeat = repeat
        self._validate()

    def _validate(self) -> None:
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if self.repeat < 1:
            raise ValueError(f"repeat must be >= 1, got {self.repeat}")
        n = self._num_examples()
        if n < self.batch_size and self.drop_remainder:
            raise ValueError(
                f"dataset of {n} examples yields zero batches of "
                f"size {self.batch_size} with drop_remainder")

    def _num_examples(self) -> int:
        raise NotImplementedError

    def _gather(self, idx: np.ndarray):
        raise NotImplementedError

    def replace(self, **kw) -> "_EpochSchedule":
        """A copy with schedule knobs replaced (seed/repeat/...); used by
        `fit` to impose its per-phase schedule on caller-built loaders.
        Re-runs the constructor validation, so a bad knob fails as loudly
        here as at construction."""
        import copy

        new = copy.copy(self)
        for k, v in kw.items():
            if not hasattr(new, k):
                raise AttributeError(f"{type(self).__name__} has no {k!r}")
            setattr(new, k, v)
        new._validate()
        return new

    def __len__(self) -> int:
        n = self._num_examples()
        per_pass = (n // self.batch_size if self.drop_remainder
                    else -(-n // self.batch_size))
        return per_pass * self.repeat

    def _index_batches(self, epoch: int) -> Iterator[np.ndarray]:
        """The schedule itself: per-batch index arrays, deterministic in
        (seed, epoch) — the one place batching/shuffle/repeat order is
        defined (FileStream's multi-process decode re-walks it)."""
        n = self._num_examples()
        stop = (n // self.batch_size * self.batch_size
                if self.drop_remainder else n)
        for rep in range(self.repeat):
            if self.shuffle:
                # rep folded into the seed only for the extra passes keeps
                # the repeat=1 stream identical to what it always was
                key = (self.seed, epoch) if rep == 0 else (self.seed, epoch, rep)
                order = np.random.default_rng(key).permutation(n)
            else:
                order = np.arange(n)
            for i in range(0, stop, self.batch_size):
                yield order[i:i + self.batch_size]

    def epoch(self, epoch: int = 0) -> Iterator:
        for idx in self._index_batches(epoch):
            yield self._gather(idx)

    def __iter__(self):
        return self.epoch(0)


class Loader(_EpochSchedule):
    """Iterates (images, labels) numpy batches of a materialized
    ArrayDataset over epochs (see _EpochSchedule for the knobs)."""

    def __init__(self, ds: ArrayDataset, batch_size: int, **kw):
        self.ds = ds
        super().__init__(batch_size, **kw)

    def _num_examples(self) -> int:
        return len(self.ds)

    def _gather(self, idx):
        return self.ds.images[idx], self.ds.labels[idx]


class FileStream(_EpochSchedule):
    """Loader-shaped iterator that decodes image files per batch instead
    of materializing the dataset in host RAM.

    The scale path for C1/C2: `ArrayDataset` + `Loader` is the
    reference's `cache()` (entire dataset resident, fastest for the
    preset-sized subsets); `FileStream` is its streaming tf.data shape
    for directories that do not fit in memory — per-epoch seeded
    permutation of the FILE list, batches decoded on demand (native
    C++/libpng decoder when available, one persistent thread pool on the
    PIL fallback). Under `prefetch_to_mesh` the decode runs in the
    producer thread, overlapping device compute.

    Shares `Loader`'s schedule (`_EpochSchedule`) bit-for-bit: streaming
    a directory and training on its materialized ArrayDataset (same pair
    order) produce identical batch streams.
    """

    def __init__(self, pairs: list[tuple[str, int]], image_size: int,
                 batch_size: int, *, workers: int = 16,
                 backend: str = "auto", decode_workers: int = 0, **kw):
        if not pairs:
            raise ValueError("FileStream needs a non-empty file list")
        self.pairs = list(pairs)
        self.image_size = image_size
        self.workers = workers
        self.backend = backend
        self.decode_workers = decode_workers
        # lazy persistent pools, boxed so replace()'s shallow copies
        # share ONE pool instead of each leaking their own
        self._pool_box: list = [None]       # PIL thread pool
        self._proc_box: list = [None]       # decode worker processes
        super().__init__(batch_size, **kw)

    def _num_examples(self) -> int:
        return len(self.pairs)

    def _gather(self, idx):
        from idc_models_tpu.data.idc import decode_pairs

        batch = [self.pairs[j] for j in idx]
        labels = np.asarray([l for _, l in batch], np.int32)
        return decode_pairs(batch, self.image_size, workers=self.workers,
                            backend=self.backend,
                            pool=self._pil_pool), labels

    def _pil_pool(self):
        if self._pool_box[0] is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool_box[0] = ThreadPoolExecutor(max_workers=self.workers)
        return self._pool_box[0]

    def epoch(self, epoch: int = 0) -> Iterator:
        """With ``decode_workers`` > 0, whole batches fan out round-robin
        to N persistent worker PROCESSES (the tf.data C++ parallel-
        pipeline role at process granularity: each worker independently
        decodes full batches with the native/PIL path while the parent
        consumes earlier ones in order). The schedule is the shared
        `_index_batches`, and each batch is decoded by the SAME
        `decode_pairs` call a single-process stream would make, so the
        two streams are bit-identical — pinned by test. Workers hold no
        jax state (idc.py is numpy-only) and scale with host cores;
        BASELINE.md's decode-rate record (32.8k img/s/core) combines
        with this fan-out to cover the chip's ~88k img/s appetite at
        >=3 cores."""
        if not self.decode_workers:
            yield from super().epoch(epoch)
            return
        import itertools
        from collections import deque

        from idc_models_tpu.data import idc

        pool = self._proc_pool()
        # Bounded in-flight submission (submit-one/consume-one over a
        # 2N-deep window), NOT Pool.imap: imap's feeder drains the whole
        # epoch's task generator up front and buffers every decoded
        # batch until consumed — on a host where N workers outpace the
        # device that re-materializes the dataset --stream exists to
        # avoid. With the window, at most 2N decoded batches exist at
        # once, and an abandoned epoch leaves at most 2N stray tasks on
        # the shared pool.
        it = self._index_batches(epoch)
        inflight: deque = deque()

        def submit(n):
            for idx in itertools.islice(it, n):
                task = ([self.pairs[j] for j in idx], self.image_size,
                        self.backend, self.workers)
                inflight.append(
                    (idx, pool.apply_async(idc.decode_task, (task,))))

        submit(2 * self.decode_workers)
        while inflight:
            idx, fut = inflight.popleft()
            images = fut.get()
            labels = np.asarray([self.pairs[j][1] for j in idx], np.int32)
            yield images, labels
            submit(1)

    def _proc_pool(self):
        if self._proc_box[0] is None:
            import multiprocessing as mp

            # spawn, not fork: the parent holds live TPU-runtime and
            # prefetch threads that must not be duplicated into workers
            ctx = mp.get_context("spawn")
            self._proc_box[0] = ctx.Pool(
                self.decode_workers,
                initializer=_decode_worker_init)
        return self._proc_box[0]

    def close(self) -> None:
        """Shut the decode pools down (no-op if never created). Copies
        made by replace() share the same pools, so close the stream only
        when no copy is iterating; without close() the shared pools
        simply live until process exit."""
        pool, self._pool_box[0] = self._pool_box[0], None
        if pool is not None:
            pool.shutdown(wait=False)
        procs, self._proc_box[0] = self._proc_box[0], None
        if procs is not None:
            procs.terminate()
            procs.join()


def _decode_worker_init():
    """Decode workers never touch an accelerator: pin any jax that gets
    transitively imported to CPU before it can claim the chip."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"


def prefetch_to_mesh(batches: Iterator, mesh: Mesh, *, axis=meshlib.DATA_AXIS,
                     prefetch: int = 2) -> Iterator:
    """Background-thread device_put: yields batches already resident in HBM.

    Each incoming (images, labels) batch is placed with its leading axis
    sharded over `axis`. A bounded queue of `prefetch` in-flight transfers
    overlaps host decode/transfer with device compute — the AUTOTUNE
    prefetch of the reference, made explicit.
    """
    sh = meshlib.sharding(mesh, axis)
    q: queue.Queue = queue.Queue(maxsize=prefetch)
    stop = threading.Event()
    _END = object()

    def put(item) -> bool:
        # Bounded put that gives up when the consumer is gone — otherwise
        # an abandoned iterator would leave this thread blocked forever,
        # pinning `prefetch` HBM-resident batches.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in batches:
                if not put(jax.tree.map(
                        lambda a: meshlib.put_with_sharding(a, sh), batch)):
                    return
        except BaseException as e:  # surface errors to the consumer
            put(e)
            return
        put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def prefetch_eval_batches(ds: ArrayDataset, mesh: Mesh, batch_size: int, *,
                          steps: int | None = None) -> Iterator:
    """The deterministic full-coverage eval pipeline, shared by the
    Evaluator and the feature cache: batches of `ds` in order, final
    batch padded to divide the mesh, transfers overlapped with compute
    via `prefetch_to_mesh`. Yields (images_dev, labels_dev, size) where
    `size` is the batch's true row count — padding rows sit at the tail,
    so `out[:size]` drops them exactly."""
    axis = meshlib.batch_axis(mesh)
    # pad to the BATCH axis size — on a 2-D ("data", "model") mesh the
    # model axis replicates the batch, so padding to devices.size would
    # compute model-factor more dummy rows than sharding needs
    n_shards = mesh.shape[axis]
    loader = Loader(ds, batch_size, shuffle=False, drop_remainder=False)

    def padded():
        for i, (x, y) in enumerate(loader.epoch(0)):
            if steps is not None and i >= steps:
                break
            x, y, _ = pad_to_multiple(x, y, n_shards)
            yield x, y

    n_total = (len(ds) if steps is None
               else min(len(ds), steps * batch_size))
    for j, (x, y) in enumerate(prefetch_to_mesh(padded(), mesh, axis=axis)):
        yield x, y, min(batch_size, n_total - j * batch_size)


def pad_to_multiple(images: np.ndarray, labels: np.ndarray,
                    multiple: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad a final partial batch up to `multiple`, returning a validity mask.

    Used by eval loops that must see every example exactly once while still
    dividing the mesh (training uses drop_remainder instead).
    """
    n = len(images)
    pad = (-n) % multiple
    if pad == 0:
        return images, labels, np.ones(n, bool)
    images = np.concatenate([images, np.zeros((pad,) + images.shape[1:],
                                              images.dtype)])
    labels = np.concatenate([labels, np.zeros((pad,) + labels.shape[1:],
                                              labels.dtype)])
    mask = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
    return images, labels, mask
