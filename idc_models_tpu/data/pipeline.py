"""Host→HBM input pipeline: batching, shuffling, and prefetch.

The TPU-native replacement for the reference's `prepare_for_training`
(cache → shuffle(1000) → batch → prefetch(AUTOTUNE), e.g.
dist_model_tf_vgg.py:47-65). Data lives in host RAM as numpy (the cache);
per-epoch order is a fresh seeded permutation (the shuffle); batches are
cut to a multiple of the mesh's data-axis size; and a background thread
keeps `prefetch` batches already transferred to device HBM with the right
NamedSharding (the prefetch) so the chips never wait on PCIe/host.
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Iterator

import jax
import numpy as np
from jax.sharding import Mesh

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data.idc import ArrayDataset


class Loader:
    """Iterates (images, labels) numpy batches over epochs.

    - `shuffle`: new seeded permutation each epoch (epoch mixed into seed)
    - `drop_remainder`: required under data parallelism so every step's
      global batch divides the mesh; the reference gets this implicitly
      from fixed-size take/skip splits
    - `repeat`: passes over the dataset per epoch — the reference's
      CIFAR pipeline appends `.repeat(2)` after batching
      (dist_model_tf_dense.py:122-123), so each fit "epoch" sees the
      train set twice; with shuffle on, every pass gets a fresh
      permutation (tf.data reshuffles each iteration)
    """

    def __init__(self, ds: ArrayDataset, batch_size: int, *,
                 shuffle: bool = True, seed: int = 0,
                 drop_remainder: bool = True, repeat: int = 1):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if len(ds) < batch_size and drop_remainder:
            raise ValueError(
                f"dataset of {len(ds)} examples yields zero batches of "
                f"size {batch_size} with drop_remainder")
        if repeat < 1:
            raise ValueError(f"repeat must be >= 1, got {repeat}")
        self.ds = ds
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.repeat = repeat

    def __len__(self) -> int:
        n = len(self.ds)
        per_pass = (n // self.batch_size if self.drop_remainder
                    else -(-n // self.batch_size))
        return per_pass * self.repeat

    def epoch(self, epoch: int = 0) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        n = len(self.ds)
        stop = (n // self.batch_size * self.batch_size
                if self.drop_remainder else n)
        for rep in range(self.repeat):
            if self.shuffle:
                # rep folded into the seed only for the extra passes keeps
                # the repeat=1 stream identical to what it always was
                key = (self.seed, epoch) if rep == 0 else (self.seed, epoch, rep)
                order = np.random.default_rng(key).permutation(n)
            else:
                order = np.arange(n)
            for i in range(0, stop, self.batch_size):
                idx = order[i:i + self.batch_size]
                yield self.ds.images[idx], self.ds.labels[idx]

    def __iter__(self):
        return self.epoch(0)


def prefetch_to_mesh(batches: Iterator, mesh: Mesh, *, axis=meshlib.DATA_AXIS,
                     prefetch: int = 2) -> Iterator:
    """Background-thread device_put: yields batches already resident in HBM.

    Each incoming (images, labels) batch is placed with its leading axis
    sharded over `axis`. A bounded queue of `prefetch` in-flight transfers
    overlaps host decode/transfer with device compute — the AUTOTUNE
    prefetch of the reference, made explicit.
    """
    sh = meshlib.sharding(mesh, axis)
    q: queue.Queue = queue.Queue(maxsize=prefetch)
    stop = threading.Event()
    _END = object()

    def put(item) -> bool:
        # Bounded put that gives up when the consumer is gone — otherwise
        # an abandoned iterator would leave this thread blocked forever,
        # pinning `prefetch` HBM-resident batches.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in batches:
                if not put(jax.tree.map(
                        lambda a: meshlib.put_with_sharding(a, sh), batch)):
                    return
        except BaseException as e:  # surface errors to the consumer
            put(e)
            return
        put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def pad_to_multiple(images: np.ndarray, labels: np.ndarray,
                    multiple: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad a final partial batch up to `multiple`, returning a validity mask.

    Used by eval loops that must see every example exactly once while still
    dividing the mesh (training uses drop_remainder instead).
    """
    n = len(images)
    pad = (-n) % multiple
    if pad == 0:
        return images, labels, np.ones(n, bool)
    images = np.concatenate([images, np.zeros((pad,) + images.shape[1:],
                                              images.dtype)])
    labels = np.concatenate([labels, np.zeros((pad,) + labels.shape[1:],
                                              labels.dtype)])
    mask = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
    return images, labels, mask
