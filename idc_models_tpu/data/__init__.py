from idc_models_tpu.data import synthetic  # noqa: F401
