from idc_models_tpu.data import cifar10, idc, partition, pipeline, synthetic  # noqa: F401
from idc_models_tpu.data.idc import (  # noqa: F401
    ArrayDataset,
    load_directory,
    train_val_test_split,
)
from idc_models_tpu.data.pipeline import Loader, prefetch_to_mesh  # noqa: F401
