"""IDC directory-tree image loader.

Capability parity with the reference's C1/C4 pipeline (`get_label` /
`decode_img` / `process_path` / take-skip split, dist_model_tf_vgg.py:34-45,
105-110): a labeled dataset is built from `<root>/.../<label>/<file>.png`
where the label is the file's parent directory name ('0'/'1'), images are
decoded to float32 in [0,1] and resized.

Deliberate behavior fixes over the reference (SURVEY.md quirks):
- Q1: the reference's `list_files` reshuffles per iteration so its
  take/skip train/val/test split re-deals files every epoch — here the
  file list is sorted, shuffled once with a seed, and the split is
  materialized.
- Q2: the discarded `.shuffle()` no-op is simply not reproduced.

Decoding runs in a host-side thread pool (PNG decode releases the GIL in
zlib/PIL) — the framework's stand-in for tf.data's C++ runtime until the
native loader (idc_models_tpu.data.native) takes over.
"""

from __future__ import annotations

import dataclasses
import os
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import numpy as np


@dataclasses.dataclass(frozen=True)
class ArrayDataset:
    """An in-memory labeled image dataset (NHWC float32 in [0,1])."""

    images: np.ndarray
    labels: np.ndarray

    def __post_init__(self):
        assert len(self.images) == len(self.labels)

    def __len__(self) -> int:
        return len(self.images)

    def take(self, n: int) -> "ArrayDataset":
        return ArrayDataset(self.images[:n], self.labels[:n])

    def skip(self, n: int) -> "ArrayDataset":
        return ArrayDataset(self.images[n:], self.labels[n:])

    def shard(self, num_shards: int, index: int) -> "ArrayDataset":
        """Strided shard, matching tf.data `Dataset.shard` semantics
        (used for secure-fed clients, secure_fed_model.py:206-210)."""
        return ArrayDataset(self.images[index::num_shards],
                            self.labels[index::num_shards])

    def shuffled(self, seed: int) -> "ArrayDataset":
        perm = np.random.default_rng(seed).permutation(len(self))
        return ArrayDataset(self.images[perm], self.labels[perm])


def list_labeled_files(root: str | os.PathLike,
                       pattern: str = "*/*.png") -> list[tuple[str, int]]:
    """Sorted (path, label) pairs; label = parent directory name == '1'."""
    root = Path(root)
    files = sorted(root.glob(pattern))
    return [(str(f), int(f.parent.name == "1")) for f in files
            if f.parent.name in ("0", "1")]


def _decode_one(path: str, size: int) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        arr = np.asarray(im.convert("RGB"), np.float32) / 255.0
    if arr.shape[:2] != (size, size):
        arr = _resize_bilinear(arr, size)
    return arr


def _resize_bilinear(arr: np.ndarray, size: int) -> np.ndarray:
    """Naive bilinear with half-pixel centers — the semantics of the
    reference's `tf.image.resize` default (antialias=False,
    dist_model_tf_vgg.py:42) and numerically matching the native C++
    loader's resize (agreement ~1e-5, not bit-exact: the two use
    different fp evaluation orders and /255 placement), so backends are
    interchangeable for training. (PIL's BILINEAR antialiases on
    downscale and would diverge much further.)"""
    h, w = arr.shape[:2]
    fy = np.maximum((np.arange(size) + 0.5) * (h / size) - 0.5, 0.0)
    fx = np.maximum((np.arange(size) + 0.5) * (w / size) - 0.5, 0.0)
    y0 = fy.astype(np.int32)
    x0 = fx.astype(np.int32)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (fy - y0).astype(np.float32)[:, None, None]
    wx = (fx - x0).astype(np.float32)[None, :, None]
    top = arr[y0][:, x0] * (1 - wx) + arr[y0][:, x1] * wx
    bot = arr[y1][:, x0] * (1 - wx) + arr[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def load_directory(root: str | os.PathLike, *, image_size: int = 50,
                   limit: int | None = None, seed: int = 0,
                   workers: int = 16, backend: str = "auto") -> ArrayDataset:
    """Load the `<root>/<label>/*.png` tree into an ArrayDataset.

    The file list is deterministically shuffled with `seed` before an
    optional `limit` is applied (the reference's balanced_IDC_30k subset is
    a pre-balanced directory; `limit` supports the same "first N of a
    shuffled list" usage without per-epoch reshuffle leakage).

    `backend`: "native" (C++/libpng threaded decoder), "pil" (Python
    thread pool), or "auto" (native when buildable, else pil).
    """
    pairs = list_shuffled_pairs(root, seed=seed, limit=limit)
    labels = np.asarray([l for _, l in pairs], np.int32)
    return ArrayDataset(decode_pairs(pairs, image_size, workers=workers,
                                     backend=backend), labels)


def list_shuffled_pairs(root: str | os.PathLike, *, seed: int = 0,
                        limit: int | None = None) -> list[tuple[str, int]]:
    """The loaders' shared preamble: list the labeled tree, shuffle once
    with `seed`, apply the optional subset `limit`."""
    pairs = list_labeled_files(root)
    if not pairs:
        raise FileNotFoundError(f"no <label>/*.png files under {root}")
    order = np.random.default_rng(seed).permutation(len(pairs))
    pairs = [pairs[i] for i in order]
    return pairs[:limit] if limit is not None else pairs


def decode_pairs(pairs: list[tuple[str, int]], image_size: int, *,
                 workers: int = 16, backend: str = "auto",
                 pool=None) -> np.ndarray:
    """Decode (path, label) pairs to a float32 [n, s, s, 3] batch.

    The one decode entry point shared by the materializing loader and
    the streaming loader (`pipeline.FileStream`); `backend` as in
    `load_directory`. `pool` (a zero-arg callable returning a live
    executor) lets per-batch callers amortize thread-pool creation on
    the PIL fallback path.
    """
    if backend not in ("auto", "native", "pil"):
        raise ValueError(f"backend must be auto|native|pil, got {backend!r}")
    if not pairs:
        return np.zeros((0, image_size, image_size, 3), np.float32)
    if backend in ("auto", "native"):
        from idc_models_tpu.data import native

        if native.available():
            return native.decode_batch([p for p, _ in pairs], image_size,
                                       threads=workers)
        if backend == "native":
            raise RuntimeError(native.build_error())
    job = lambda p: _decode_one(p[0], image_size)
    if pool is not None:
        imgs = list(pool().map(job, pairs))
    else:
        with ThreadPoolExecutor(max_workers=workers) as ex:
            imgs = list(ex.map(job, pairs))
    return np.stack(imgs)


_TASK_POOL: list = [None, 0]  # [executor, max_workers] — per-process


def _task_pool(workers: int):
    """Process-local persistent thread pool for `decode_task`'s PIL
    fallback — without it each spawned decode worker would build and
    tear down a fresh ThreadPoolExecutor per batch, losing exactly the
    amortization the in-process `FileStream._gather` path has."""
    if _TASK_POOL[0] is None or _TASK_POOL[1] != workers:
        if _TASK_POOL[0] is not None:
            _TASK_POOL[0].shutdown(wait=False)
        _TASK_POOL[0] = ThreadPoolExecutor(max_workers=workers)
        _TASK_POOL[1] = workers
    return _TASK_POOL[0]


def decode_task(args):
    """Worker-process entry for `pipeline.FileStream`'s multi-process
    decode (one whole batch per task). Lives in this numpy-only module
    so spawn-started workers never import jax on the hot path."""
    pairs, image_size, backend, workers = args
    return decode_pairs(pairs, image_size, workers=workers,
                        backend=backend,
                        pool=lambda: _task_pool(workers))


def train_val_test_split(ds: ArrayDataset,
                         fractions: tuple[float, float, float] = (0.8, 0.1, 0.1),
                         *, seed: int | None = None,
                         ) -> tuple[ArrayDataset, ArrayDataset, ArrayDataset]:
    """Deterministic materialized split (fixes quirk Q1).

    If `seed` is given the dataset is shuffled first; the split sizes follow
    the reference's 80/10/10 take/skip scheme (dist_model_tf_vgg.py:10-13).
    """
    if seed is not None:
        ds = ds.shuffled(seed)
    n = len(ds)
    n_train = int(fractions[0] * n)
    n_val = int(fractions[1] * n)
    train = ds.take(n_train)
    val = ds.skip(n_train).take(n_val)
    test = ds.skip(n_train + n_val)
    return train, val, test
