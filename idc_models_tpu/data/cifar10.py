"""CIFAR-10 loader for the DenseNet preset (reference C3).

The reference pulls CIFAR-10 through tfds at runtime
(dist_model_tf_dense.py:120) and scales by /255. This environment has no
network egress, so resolution order is:

1. a local copy (numpy .npz, or the standard python-pickled batches under
   `cifar-10-batches-py/`) found beneath `root`;
2. a synthetic stand-in (clearly warned) so smoke runs and benches work
   anywhere.
"""

from __future__ import annotations

import pickle
import warnings
from pathlib import Path

import numpy as np

from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data import synthetic

NUM_CLASSES = 10


_SPLITS = ("train", "test")


def load_cifar10(root: str | None = None, *, split: str = "train",
                 synthetic_size: int = 2048,
                 seed: int = 0) -> ArrayDataset:
    if split not in _SPLITS:
        raise ValueError(f"split must be one of {_SPLITS}, got {split!r} "
                         "(carve validation out of 'train' with "
                         "train_val_test_split)")
    if root is not None:
        found = _find_local(Path(root), split)
        if found is not None:
            return found
    warnings.warn(
        "CIFAR-10 not found locally; using a synthetic stand-in "
        "(class-dependent mean shift). Pass root=<dir containing "
        "cifar-10-batches-py or cifar10.npz> for the real dataset.",
        stacklevel=2)
    # distinct deterministic seed per split so a synthetic "test" set never
    # silently evaluates on the synthetic training examples
    imgs, labels = synthetic.make_cifar_like(
        synthetic_size, seed=2 * seed + (1 if split == "test" else 0))
    return ArrayDataset(imgs, labels)


def _find_local(root: Path, split: str) -> ArrayDataset | None:
    npz = root / "cifar10.npz"
    if npz.exists():
        with np.load(npz) as z:
            x = z[f"x_{split}"].astype(np.float32) / 255.0
            y = z[f"y_{split}"].astype(np.int32).reshape(-1)
            return ArrayDataset(x, y)
    batches_dir = root / "cifar-10-batches-py"
    if batches_dir.exists():
        names = ([f"data_batch_{i}" for i in range(1, 6)]
                 if split == "train" else ["test_batch"])
        xs, ys = [], []
        for name in names:
            with open(batches_dir / name, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.append(np.asarray(d[b"labels"], np.int32))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return ArrayDataset(x.astype(np.float32) / 255.0, np.concatenate(ys))
    return None
