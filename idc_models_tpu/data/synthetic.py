"""Synthetic IDC-like data for tests, benchmarks, and smoke runs.

Generates 50x50 (or any size) RGB "patches" whose label is recoverable from
a simple statistic, so models can demonstrably learn — used everywhere the
real `<root>/<label>/*.png` tree (reference C1) is unavailable. Positive
patches get a brighter center blob (a cartoon of IDC nuclei density).
"""

from __future__ import annotations

import numpy as np


def make_idc_like(n: int, size: int = 50, *, seed: int = 0,
                  pos_fraction: float = 0.5) -> tuple[np.ndarray, np.ndarray]:
    """Returns (images [n,size,size,3] float32 in [0,1], labels [n] int32)."""
    rng = np.random.default_rng(seed)
    labels = (rng.random(n) < pos_fraction).astype(np.int32)
    imgs = rng.random((n, size, size, 3), dtype=np.float32) * 0.5
    yy, xx = np.mgrid[0:size, 0:size]
    c = (size - 1) / 2
    blob = np.exp(-(((yy - c) ** 2 + (xx - c) ** 2) / (2 * (size / 4) ** 2)))
    blob = blob[None, :, :, None].astype(np.float32)
    imgs = imgs + labels[:, None, None, None] * 0.4 * blob
    return np.clip(imgs, 0.0, 1.0), labels


def make_sequence_task(n: int, seq_len: int, features: int = 8, *,
                       seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Position-sensitive sequence task for the attention classifier:
    noise sequences with one marker spike on channel 0; label = whether
    the marker sits in the LATE half. GAP over raw inputs cannot solve
    it (the marker's value is position-independent) — the model must
    move positional information into the pooled features, which is
    exactly what attention + learned positions provide.

    Returns (x [n, seq_len, features] float32, labels [n] int32).
    """
    rng = np.random.default_rng(seed)
    x = rng.normal(0.0, 0.3, (n, seq_len, features)).astype(np.float32)
    pos = rng.integers(0, seq_len, n)
    x[np.arange(n), pos, 0] += 3.0
    labels = (pos >= seq_len // 2).astype(np.int32)
    return x, labels


def make_cifar_like(n: int, *, seed: int = 0,
                    num_classes: int = 10) -> tuple[np.ndarray, np.ndarray]:
    """32x32x3 images with class-dependent mean shift, labels in [0, C)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n).astype(np.int32)
    imgs = rng.random((n, 32, 32, 3), dtype=np.float32) * 0.6
    shift = (labels[:, None, None, None] / num_classes).astype(np.float32)
    imgs = np.clip(imgs + 0.4 * shift, 0.0, 1.0)
    return imgs, labels
