// Native IDC image loader: threaded PNG decode + bilinear resize.
//
// The reference's input pipeline rides tf.data's C++ runtime (PNG decode,
// resize, prefetch — dist_model_tf_vgg.py:34-65 via tf.io/tf.image). This
// is the framework's native equivalent: libpng decode fanned out over a
// std::thread pool, bilinear resize to the target patch size, float32
// [0,1] NHWC output written straight into a caller-provided (numpy)
// buffer. Exposed as a C ABI consumed through ctypes
// (idc_models_tpu/data/native/__init__.py) — no Python in the decode path,
// so the host CPU keeps TPU feed ahead of step time.
//
// Build: g++ -O3 -shared -fPIC loader.cpp -lpng -lz -lpthread
//        (see _build_cmd in __init__.py; rebuilt lazily when stale).

#include <png.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Decode one PNG to RGB8. Returns true on success; fills w/h and pixels.
bool decode_png_rgb(const char* path, std::vector<uint8_t>* pixels,
                    unsigned* width, unsigned* height) {
  png_image image;
  std::memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_file(&image, path)) return false;
  image.format = PNG_FORMAT_RGB;  // libpng converts gray/palette/alpha
  pixels->resize(PNG_IMAGE_SIZE(image));
  if (!png_image_finish_read(&image, nullptr, pixels->data(), 0, nullptr)) {
    png_image_free(&image);
    return false;
  }
  *width = image.width;
  *height = image.height;
  return true;
}

// Bilinear resize RGB8 (h,w) -> float32 [0,1] (size,size,3), matching
// PIL's BILINEAR (align_corners=false, half-pixel centers).
void resize_bilinear(const uint8_t* src, unsigned w, unsigned h,
                     int size, float* dst) {
  const float sx = static_cast<float>(w) / size;
  const float sy = static_cast<float>(h) / size;
  for (int oy = 0; oy < size; ++oy) {
    float fy = (oy + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    int y1 = y0 + 1 < static_cast<int>(h) ? y0 + 1 : h - 1;
    float wy = fy - y0;
    for (int ox = 0; ox < size; ++ox) {
      float fx = (ox + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      int x1 = x0 + 1 < static_cast<int>(w) ? x0 + 1 : w - 1;
      float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(y0 * w + x0) * 3 + c];
        float v01 = src[(y0 * w + x1) * 3 + c];
        float v10 = src[(y1 * w + x0) * 3 + c];
        float v11 = src[(y1 * w + x1) * 3 + c];
        float top = v00 + (v01 - v00) * wx;
        float bot = v10 + (v11 - v10) * wx;
        dst[(oy * size + ox) * 3 + c] = (top + (bot - top) * wy) / 255.0f;
      }
    }
  }
}

}  // namespace

extern "C" {

// Decode `n` PNG files to float32 [0,1] NHWC batches of (size,size,3).
// `out` must hold n*size*size*3 floats. Failed decodes leave their slot
// zeroed and are counted in the return value (0 == all succeeded).
// `status` (nullable) must hold n bytes; gets 1 per decoded file, 0 per
// failure, so the caller can name the failing paths.
int idc_decode_batch(const char** paths, int n, int size, float* out,
                     int n_threads, unsigned char* status) {
  if (n <= 0) return 0;
  if (n_threads <= 0) n_threads = std::thread::hardware_concurrency();
  if (n_threads > n) n_threads = n;
  std::atomic<int> next{0};
  std::atomic<int> failures{0};
  const size_t stride = static_cast<size_t>(size) * size * 3;

  auto worker = [&]() {
    std::vector<uint8_t> pixels;
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      unsigned w = 0, h = 0;
      float* dst = out + stride * i;
      if (!decode_png_rgb(paths[i], &pixels, &w, &h) || w == 0 || h == 0) {
        std::memset(dst, 0, stride * sizeof(float));
        if (status) status[i] = 0;
        failures.fetch_add(1);
        continue;
      }
      if (status) status[i] = 1;
      if (static_cast<int>(w) == size && static_cast<int>(h) == size) {
        for (size_t p = 0; p < stride; ++p) dst[p] = pixels[p] / 255.0f;
      } else {
        resize_bilinear(pixels.data(), w, h, size, dst);
      }
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return failures.load();
}

// ABI version so the Python side can detect stale binaries.
int idc_loader_abi_version() { return 2; }

}  // extern "C"
