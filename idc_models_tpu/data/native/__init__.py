"""ctypes binding for the native (C++/libpng) image loader.

Lazily builds `loader.cpp` into `_native_loader.so` beside this file the
first time it is needed (and whenever the source is newer), then exposes

    decode_batch(paths, size, threads=0) -> np.ndarray [n, size, size, 3]

`available()` reports whether the native path can be used; callers fall
back to the PIL thread pool (idc.py) when it cannot (no toolchain, no
libpng). The framework keeps the decode loop entirely outside Python —
the reference gets this from tf.data's C++ runtime (SURVEY.md §2c).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_DIR = Path(__file__).parent
_SRC = _DIR / "loader.cpp"
_SO = _DIR / "_native_loader.so"
_ABI = 2

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _build() -> None:
    """Compile to a per-process temp file and atomically rename into
    place — never truncate a .so another process may have mapped, and
    concurrent builders (e.g. multi-host workers sharing a checkout)
    cannot corrupt each other's half-written output."""
    tmp = _SO.with_name(f"{_SO.name}.{os.getpid()}.tmp")
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", str(_SRC),
             "-lpng", "-lz", "-lpthread", "-o", str(tmp)],
            check=True, capture_output=True, text=True)
        os.replace(tmp, _SO)
    finally:
        tmp.unlink(missing_ok=True)


def _open_checked() -> ctypes.CDLL:
    lib = ctypes.CDLL(str(_SO))
    try:
        abi = lib.idc_loader_abi_version()
    except AttributeError:
        _dlclose(lib)
        raise OSError("native loader predates the ABI-version export")
    if abi != _ABI:
        # dlclose before raising: dlopen caches by pathname, so a kept
        # handle would shadow the rebuilt binary on the retry
        _dlclose(lib)
        raise OSError(f"native loader ABI {abi} != expected {_ABI}")
    return lib


def _dlclose(lib: ctypes.CDLL) -> None:
    import _ctypes

    try:
        _ctypes.dlclose(lib._handle)
    except OSError:
        pass


def _load() -> ctypes.CDLL | None:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
                _build()
            try:
                lib = _open_checked()
            except (OSError, AttributeError):
                # a stale binary that escaped the mtime test (coarse
                # filesystem timestamps, copied checkouts, pre-ABI-export
                # builds raising AttributeError): rebuild from the source
                # sitting right next to it rather than giving up
                _build()
                lib = _open_checked()
            lib.idc_decode_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float), ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.idc_decode_batch.restype = ctypes.c_int
            _lib = lib
        except (OSError, subprocess.CalledProcessError, AttributeError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _build_error = f"native loader unavailable: {detail}"
        return _lib


def available() -> bool:
    return _load() is not None


def build_error() -> str | None:
    _load()
    return _build_error


def decode_batch(paths: list[str], size: int, *,
                 threads: int = 0, on_error: str = "raise") -> np.ndarray:
    """Decode PNGs to a float32 [n, size, size, 3] batch in [0, 1].

    `on_error="raise"` (default) raises ValueError naming the files that
    failed to decode — the same loud behavior as the PIL backend, so
    `backend="auto"` cannot silently train on zero images with real
    labels attached. `on_error="zero"` keeps the lenient mode (failed
    slots stay zero images, with a warning) for callers that opt in.
    """
    if on_error not in ("raise", "zero"):
        raise ValueError(f"on_error must be raise|zero, got {on_error!r}")
    lib = _load()
    if lib is None:
        raise RuntimeError(_build_error or "native loader unavailable")
    n = len(paths)
    out = np.empty((n, size, size, 3), np.float32)
    if n == 0:
        return out
    arr = (ctypes.c_char_p * n)(*[os.fsencode(p) for p in paths])
    status = np.empty(n, np.uint8)
    failures = lib.idc_decode_batch(
        arr, n, size, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        threads, status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if failures:
        bad = [paths[i] for i in np.flatnonzero(status == 0)]
        # even in lenient mode an entirely undecodable input must fail
        # loudly — an all-zero dataset with real labels is never useful
        if on_error == "raise" or failures >= n:
            shown = ", ".join(bad[:5])
            more = f" (+{len(bad) - 5} more)" if len(bad) > 5 else ""
            raise ValueError(
                f"{failures}/{n} files failed to decode: {shown}{more}")
        import warnings

        warnings.warn(f"{failures}/{n} files failed to decode; their "
                      f"slots are zero images", stacklevel=2)
    return out
