"""ctypes binding for the native (C++/libpng) image loader.

Lazily builds `loader.cpp` into `_native_loader.so` beside this file the
first time it is needed (and whenever the source is newer), then exposes

    decode_batch(paths, size, threads=0) -> np.ndarray [n, size, size, 3]

`available()` reports whether the native path can be used; callers fall
back to the PIL thread pool (idc.py) when it cannot (no toolchain, no
libpng). The framework keeps the decode loop entirely outside Python —
the reference gets this from tf.data's C++ runtime (SURVEY.md §2c).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_DIR = Path(__file__).parent
_SRC = _DIR / "loader.cpp"
_SO = _DIR / "_native_loader.so"
_ABI = 1

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _build_cmd() -> list[str]:
    return ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", str(_SRC),
            "-lpng", "-lz", "-lpthread", "-o", str(_SO)]


def _load() -> ctypes.CDLL | None:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
                subprocess.run(_build_cmd(), check=True, capture_output=True,
                               text=True)
            lib = ctypes.CDLL(str(_SO))
            if lib.idc_loader_abi_version() != _ABI:
                raise OSError("stale native loader ABI; rebuild")
            lib.idc_decode_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ]
            lib.idc_decode_batch.restype = ctypes.c_int
            _lib = lib
        except (OSError, subprocess.CalledProcessError, AttributeError) as e:
            # AttributeError: a stale .so predating the ABI-version export
            detail = getattr(e, "stderr", "") or str(e)
            _build_error = f"native loader unavailable: {detail}"
        return _lib


def available() -> bool:
    return _load() is not None


def build_error() -> str | None:
    _load()
    return _build_error


def decode_batch(paths: list[str], size: int, *,
                 threads: int = 0) -> np.ndarray:
    """Decode PNGs to a float32 [n, size, size, 3] batch in [0, 1].

    Failed files decode to zeros (matching the batch-robustness the
    tf.data pipeline gets from ignore_errors-style handling); a ValueError
    is raised instead if *every* file fails.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError(_build_error or "native loader unavailable")
    n = len(paths)
    out = np.empty((n, size, size, 3), np.float32)
    if n == 0:
        return out
    arr = (ctypes.c_char_p * n)(*[os.fsencode(p) for p in paths])
    failures = lib.idc_decode_batch(
        arr, n, size, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        threads)
    if failures >= n:
        raise ValueError(f"all {n} files failed to decode (first: {paths[0]})")
    if failures:
        import warnings

        warnings.warn(f"{failures}/{n} files failed to decode; their "
                      f"slots are zero images", stacklevel=2)
    return out
