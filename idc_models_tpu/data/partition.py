"""Federated client partitioning: IID / non-IID shards.

Capability parity with the reference's `get_data` + client sharding
(C9/C10): IID = globally shuffled examples cut into contiguous
equal-size client shards (fed_model.py:150-165); non-IID = all class-1
examples concatenated before class-0 so contiguous shards are label-skewed
(fed_model.py:161-165); secure-fed uses strided `shard(N, i)` instead
(secure_fed_model.py:206-210, available as `ArrayDataset.shard`).

Shards are materialized as a stacked [num_clients, client_size, ...] array
so the federated trainer can lay clients out along the "client" mesh axis
with one device_put — deterministic per client with no host round-trips
(SURVEY.md "hard parts": non-IID determinism).
"""

from __future__ import annotations

import numpy as np

from idc_models_tpu.data.idc import ArrayDataset


def partition_clients(ds: ArrayDataset, num_clients: int, *, iid: bool,
                      seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Returns (images [C, S, H, W, 3], labels [C, S]) client shards.

    S = len(ds) // num_clients; surplus examples are dropped (the
    reference's CLIENT_SIZE arithmetic, fed_model.py:58).
    """
    if num_clients <= 0:
        raise ValueError("num_clients must be positive")
    n = len(ds)
    client_size = n // num_clients
    if client_size == 0:
        raise ValueError(f"{n} examples cannot feed {num_clients} clients")
    if iid:
        order = np.random.default_rng(seed).permutation(n)
    else:
        # class-1 first, then class-0, each deterministically shuffled
        # within class — contiguous shards become label-skewed.
        rng = np.random.default_rng(seed)
        pos = np.flatnonzero(ds.labels == 1)
        neg = np.flatnonzero(ds.labels != 1)
        order = np.concatenate([rng.permutation(pos), rng.permutation(neg)])
    order = order[:client_size * num_clients]
    idx = order.reshape(num_clients, client_size)
    return ds.images[idx], ds.labels[idx]


def train_test_client_split(num_clients: int, test_fraction: float = 0.2,
                            *, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Split client *ids* into train/test populations (fed_model.py:47-49)."""
    ids = np.random.default_rng(seed).permutation(num_clients)
    n_test = max(1, int(round(test_fraction * num_clients)))
    if n_test >= num_clients:
        raise ValueError(
            f"test_fraction {test_fraction} leaves no training clients "
            f"out of {num_clients} — every round would be a no-op")
    return np.sort(ids[n_test:]), np.sort(ids[:n_test])


def pad_clients(images: np.ndarray, labels: np.ndarray, *weights: np.ndarray,
                multiple: int) -> tuple[np.ndarray, ...]:
    """Pad the client axis up to a multiple of the mesh size with
    weight-0 dummy clients (zero data). The round's failure-tolerant
    aggregation ignores zero-weight clients entirely, so padding lets
    any client count run on any device count (10 reference clients on an
    8-device mesh -> 16 shards, 2 per device, 6 of them inert).

    Every per-client weight vector travels through here together with
    the data (varargs), so no caller can pad them inconsistently.
    Returns (images, labels, *weights) padded to the same client count.
    """
    c = images.shape[0]
    pad = (-c) % multiple
    if pad == 0:
        return (images, labels) + tuple(
            np.asarray(w, np.float32) for w in weights)
    images = np.concatenate(
        [images, np.zeros((pad,) + images.shape[1:], images.dtype)])
    labels = np.concatenate(
        [labels, np.zeros((pad,) + labels.shape[1:], labels.dtype)])
    padded_w = tuple(
        np.concatenate([np.asarray(w, np.float32),
                        np.zeros((pad,), np.float32)]) for w in weights)
    return (images, labels) + padded_w
