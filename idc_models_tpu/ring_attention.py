"""Ring attention: exact attention over sequences sharded across devices.

Long-context sequence parallelism for this framework (SURVEY.md §5 names
the explicit ring schedule as the forward-looking reason `collectives`
exposes `ppermute`; the reference has no attention at all, so this is
beyond-parity capability, designed TPU-first):

- the sequence axis is sharded over a 1-D ``"seq"`` mesh
  (`mesh.seq_mesh`): every device holds the query block it owns for the
  whole computation plus ONE rotating key/value block;
- each of the n ring steps computes blockwise attention between the
  resident queries and the visiting K/V block, folded into a numerically
  stable online softmax (running max `m`, normalizer `l`, weighted
  accumulator `acc` — the flash-attention recurrence), then passes the
  K/V block to the next neighbor with a single `ppermute` hop riding ICI;
- per-device memory: q/k/v/acc are O(T/n), plus ONE [B,H,T/n,T/n] score
  tile alive per ring step on the default jnp block path (the blockwise
  tiling is across devices, not within a block). When local blocks grow
  long, pass ``block_impl="pallas"``: the fused flash kernel
  (`ops.flash_block_kernel`) keeps scores in VMEM — measured 1.41x at
  T/n=8k and 1.62x at 16k on a v5 lite chip. Either way a sequence n
  times longer than one device could hold attends exactly, with compute
  and communication overlapped by XLA's async collectives.

Causal throughput caveat: with the plain contiguous layout device i owns
queries that can see only blocks 0..i, yet every device executes all n
block steps in SPMD lockstep, so ~half the causal FLOPs land on fully
masked blocks (p == 0) and the ring's wall-clock is set by the last
device. The known fix is a striped ("zigzag") sequence layout — device i
holding stripes i and 2n-1-i balances visible work — kept as future work
and called out here so nobody sizes a causal run assuming 2x better.

The loop is a `lax.fori_loop`, so the traced program is O(1) in ring
size (one hop + one block-attention in the body; ring_psum's unrolled
form documents why that matters for compile time).  The result is
bit-for-bit independent of ring size in exact arithmetic and matches
single-device full attention to fp tolerance — pinned by tests,
including gradients (`jax.grad` flows through `ppermute` and
`fori_loop` natively).

Causal masking uses GLOBAL positions: device i's queries sit at offset
i*T_local, and after s rotations it is visiting the K/V block of device
(i - s) mod n, so the mask depends only on (axis_index, step) — no
position tensors are communicated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from idc_models_tpu import collectives
from idc_models_tpu import mesh as meshlib

shard_map = jax.shard_map


# Masked scores use a large finite negative instead of -inf: exp() of it
# is exactly 0.0 in f32 (no NaN-producing inf arithmetic on the backward
# pass), and the one pathological case — the FIRST visited block fully
# masked, making p momentarily exp(0)=1 — self-heals because the next
# unmasked block's corr = exp(_MASKED - real_max) = 0 wipes the bogus
# partial sums. Causal masking guarantees every query eventually sees an
# unmasked block (its own position).
_MASKED = -1e30


def _block_attend(q, k, v, m, l, acc, *, scale, mask=None):
    """One online-softmax update of (m, l, acc) with a visiting K/V block.

    q [B,Tq,H,D]; k,v [B,Tk,H,D]; m,l [B,H,Tq]; acc [B,Tq,H,D].
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, _MASKED)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = (acc * jnp.transpose(corr, (0, 2, 1))[..., None]
               + jnp.einsum("bhqk,bkhd->bqhd", p, v,
                            preferred_element_type=jnp.float32))
    return m_new, l_new, acc_new


def causal_block_mask(t_q, t_k, q_offset, k_offset):
    """[1, 1, t_q, t_k] bool: which (query, key) pairs are visible given
    the blocks' global start positions — THE causal convention, shared
    by the jnp ring body, the flash kernel's jnp reference, and (as an
    in-kernel iota copy, kept in sync by tests) the kernel itself."""
    q_pos = q_offset + jnp.arange(t_q)
    k_pos = k_offset + jnp.arange(t_k)
    return (q_pos[:, None] >= k_pos[None, :])[None, None]


def full_attention(q, k, v, *, causal: bool = False, scale: float | None
                   = None):
    """Single-device reference: softmax(q k^T / sqrt(d)) v, [B,T,H,D]."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        q.astype(jnp.float32), k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32))
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, *, axis: str = meshlib.SEQ_AXIS,
                        causal: bool = False, scale: float | None = None,
                        block_impl: str = "jnp"):
    """Build ``fn(q, k, v) -> out`` with q/k/v/out [B, T, H, D] sharded on
    T over `axis`; jitted, exact (not approximate) attention.

    ``block_impl``: ``"jnp"`` (default) computes each visiting block with
    plain jnp ops (XLA-fused, fine up to moderate local block lengths);
    ``"pallas"`` runs the fused flash kernel
    (`ops.flash_block_kernel`) — scores stay in VMEM, removing the
    per-step (T/n)^2 HBM score tensor; requires T/n a multiple of 128,
    interpret mode off-TPU, gradients via rematerialized backward.
    """
    if block_impl not in ("jnp", "pallas"):
        raise ValueError(f"unknown block_impl {block_impl!r}")
    n = mesh.shape[axis]

    def per_device(q, k, v):
        scale_ = scale if scale is not None else q.shape[-1] ** -0.5
        me = collectives.axis_index(axis)
        b, t_local, h, d = q.shape
        qf = q.astype(jnp.float32)
        m0 = jnp.full((b, h, t_local), _MASKED, jnp.float32)
        l0 = jnp.zeros((b, h, t_local), jnp.float32)
        acc0 = jnp.zeros((b, t_local, h, d), jnp.float32)
        perm = collectives.ring_perm(n)
        if block_impl == "pallas":
            from idc_models_tpu.ops import flash_block_kernel as fbk

            # interpret keys on the MESH's devices, not the process
            # default backend — a CPU-device mesh on a TPU-backed host
            # must interpret, not lower Mosaic for CPU
            interp = (mesh.devices.flat[0].platform
                      not in ("tpu", "axon"))
            flash_upd = fbk.make_flash_block_update(
                scale=scale_, causal=causal, interpret=interp)

        def body(s, carry):
            kc, vc, m, l, acc = carry
            # after s hops we hold the block of device (me - s) mod n
            kv_dev = jnp.mod(me - s, n)
            if block_impl == "pallas":
                # native dtypes straight through: bf16 q/k/v stay bf16
                # in HBM and over the ppermute hops; the kernel upcasts
                # per VMEM tile
                offsets = jnp.stack([me * t_local, kv_dev * t_local])
                m, l, acc = flash_upd(q, kc, vc, m, l, acc, offsets)
            else:
                mask = (causal_block_mask(t_local, t_local,
                                          me * t_local,
                                          kv_dev * t_local)
                        if causal else None)
                m, l, acc = _block_attend(qf, kc.astype(jnp.float32),
                                          vc.astype(jnp.float32), m, l,
                                          acc, scale=scale_, mask=mask)
            # one neighbor hop per step; the last hop returns the blocks
            # to their owners (harmless, keeps the loop body uniform)
            kc = collectives.ppermute(kc, axis, perm)
            vc = collectives.ppermute(vc, axis, perm)
            return kc, vc, m, l, acc

        _, _, m, l, acc = lax.fori_loop(0, n, body, (k, v, m0, l0, acc0))
        norm = jnp.transpose(l, (0, 2, 1))[..., None]
        return (acc / jnp.maximum(norm, 1e-37)).astype(q.dtype)

    spec = P(None, axis, None, None)
    mapped = shard_map(per_device, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return jax.jit(mapped)


def ring_attention(q, k, v, mesh: Mesh, *, axis: str = meshlib.SEQ_AXIS,
                   causal: bool = False, scale: float | None = None):
    """One-shot convenience wrapper around `make_ring_attention`.

    For hot loops build the function once with `make_ring_attention`
    (the jit cache keys on the python callable identity)."""
    fn = _cached_ring(mesh, axis, causal, scale)
    return fn(q, k, v)


@functools.lru_cache(maxsize=32)
def _cached_ring(mesh, axis, causal, scale):
    return make_ring_attention(mesh, axis=axis, causal=causal, scale=scale)
