"""Ring attention: exact attention over sequences sharded across devices.

Long-context sequence parallelism for this framework (SURVEY.md §5 names
the explicit ring schedule as the forward-looking reason `collectives`
exposes `ppermute`; the reference has no attention at all, so this is
beyond-parity capability, designed TPU-first):

- the sequence axis is sharded over a 1-D ``"seq"`` mesh
  (`mesh.seq_mesh`): every device holds the query block it owns for the
  whole computation plus ONE rotating key/value block;
- each of the n ring steps computes blockwise attention between the
  resident queries and the visiting K/V block, folded into a numerically
  stable online softmax (running max `m`, normalizer `l`, weighted
  accumulator `acc` — the flash-attention recurrence), then passes the
  K/V block to the next neighbor with a single `ppermute` hop riding ICI;
- per-device memory: q/k/v/acc are O(T/n), plus ONE [B,H,T/n,T/n] score
  tile alive per ring step on the default jnp block path (the blockwise
  tiling is across devices, not within a block). When local blocks grow
  long, pass ``block_impl="pallas"``: the fused flash kernel
  (`ops.flash_block_kernel`) keeps scores in VMEM — measured 1.41x at
  T/n=8k and 1.62x at 16k on a v5 lite chip. Either way a sequence n
  times longer than one device could hold attends exactly, with compute
  and communication overlapped by XLA's async collectives.

Causal layouts: with the plain contiguous layout device i owns queries
that can see only blocks 0..i, yet every device executes all n block
steps in SPMD lockstep, so ~half the causal FLOPs land on fully masked
blocks (p == 0) and the ring's wall-clock is set by the last device.
``layout="zigzag"`` fixes this: the sequence is split into 2n stripes
and device i holds stripes (i, 2n-1-i) — permute inputs with
`to_zigzag` and invert the output with `from_zigzag`. Under that layout
every device's causal schedule is IDENTICAL and dense: three
quarter-block attends on its own block (two stripe diagonals plus the
always-visible hi-vs-lo quarter; the lo-vs-hi quarter is provably empty
and never computed), then exactly two fully-visible half-attends per
ring hop. Total causal work drops from 4n quarter-blocks per device to
2n+1 — the ~2x the contiguous docstring used to concede. Measured on a
v5 lite chip (emulated ring-of-8 per-device schedule, pallas blocks,
`experiments/zigzag_bench.py`): 1.52x at t_local=4096, 1.74x at 8192,
1.76x at 16384 vs the contiguous schedule (ideal 4n/(2n+1) = 1.88x at
n=8); the executed-FLOP ratio is gated by an XLA-cost-analysis test.
Without `causal` the layout changes nothing (dense attention is
permutation-equivariant), so zigzag only matters for causal runs.

The loop is a `lax.fori_loop`, so the traced program is O(1) in ring
size (one hop + one block-attention in the body; ring_psum's unrolled
form documents why that matters for compile time).  The result is
bit-for-bit independent of ring size in exact arithmetic and matches
single-device full attention to fp tolerance — pinned by tests,
including gradients (`jax.grad` flows through `ppermute` and
`fori_loop` natively).

Causal masking uses GLOBAL positions: device i's queries sit at offset
i*T_local, and after s rotations it is visiting the K/V block of device
(i - s) mod n, so the mask depends only on (axis_index, step) — no
position tensors are communicated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from idc_models_tpu import collectives
from idc_models_tpu import mesh as meshlib

shard_map = jax.shard_map


# Masked scores use a large finite negative instead of -inf: exp() of it
# is exactly 0.0 in f32 (no NaN-producing inf arithmetic on the backward
# pass), and the one pathological case — the FIRST visited block fully
# masked, making p momentarily exp(0)=1 — self-heals because the next
# unmasked block's corr = exp(_MASKED - real_max) = 0 wipes the bogus
# partial sums. Causal masking guarantees every query eventually sees an
# unmasked block (its own position).
_MASKED = -1e30


def _block_attend(q, k, v, m, l, acc, *, scale, mask=None):
    """One online-softmax update of (m, l, acc) with a visiting K/V block.

    q [B,Tq,H,D]; k,v [B,Tk,H,D]; m,l [B,H,Tq]; acc [B,Tq,H,D].
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, _MASKED)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = (acc * jnp.transpose(corr, (0, 2, 1))[..., None]
               + jnp.einsum("bhqk,bkhd->bqhd", p, v,
                            preferred_element_type=jnp.float32))
    return m_new, l_new, acc_new


def causal_block_mask(t_q, t_k, q_offset, k_offset):
    """[1, 1, t_q, t_k] bool: which (query, key) pairs are visible given
    the blocks' global start positions — THE causal convention, shared
    by the jnp ring body, the flash kernel's jnp reference, and (as an
    in-kernel iota copy, kept in sync by tests) the kernel itself."""
    q_pos = q_offset + jnp.arange(t_q)
    k_pos = k_offset + jnp.arange(t_k)
    return (q_pos[:, None] >= k_pos[None, :])[None, None]


def zigzag_indices(t: int, n: int):
    """Global gather indices realizing the zigzag layout: the sequence is
    cut into 2n equal stripes and device i's contiguous shard becomes
    [stripe i, stripe 2n-1-i]. `t` must divide by 2n. Returns a numpy
    int array `p` with ``x_zig = x.take(p, axis=seq)``; the layout is an
    involution-free permutation whose inverse is `argsort(p)`
    (`from_zigzag`)."""
    import numpy as np

    if t % (2 * n):
        raise ValueError(f"sequence length {t} not divisible by 2*{n}")
    sw = t // (2 * n)
    stripes = np.arange(t).reshape(2 * n, sw)
    order = []
    for i in range(n):
        order += [i, 2 * n - 1 - i]
    return stripes[order].reshape(-1)


def to_zigzag(x, n: int, *, axis: int = 1):
    """Permute a sequence axis into the zigzag layout for an n-device
    ring (see `zigzag_indices`)."""
    return jnp.take(x, jnp.asarray(zigzag_indices(x.shape[axis], n)),
                    axis=axis)


def from_zigzag(x, n: int, *, axis: int = 1):
    """Inverse of `to_zigzag` — restore natural sequence order."""
    import numpy as np

    inv = np.argsort(zigzag_indices(x.shape[axis], n))
    return jnp.take(x, jnp.asarray(inv), axis=axis)


def full_attention(q, k, v, *, causal: bool = False, scale: float | None
                   = None):
    """Single-device reference: softmax(q k^T / sqrt(d)) v, [B,T,H,D]."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        q.astype(jnp.float32), k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32))
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, *, axis: str = meshlib.SEQ_AXIS,
                        causal: bool = False, scale: float | None = None,
                        block_impl: str = "jnp",
                        layout: str = "contiguous",
                        unroll: bool = False):
    """Build ``fn(q, k, v) -> out`` with q/k/v/out [B, T, H, D] sharded on
    T over `axis`; jitted, exact (not approximate) attention.

    ``block_impl``: ``"jnp"`` (default) computes each visiting block with
    plain jnp ops (XLA-fused, fine up to moderate local block lengths);
    ``"pallas"`` runs the fused flash kernel
    (`ops.flash_block_kernel`) — scores stay in VMEM, removing the
    per-step (T/n)^2 HBM score tensor; requires T/n a multiple of 128
    (256 under ``layout="zigzag"``, whose kernel calls operate on
    half-blocks), interpret mode off-TPU, gradients via rematerialized
    backward.

    ``layout``: how the global sequence maps to device shards.
    ``"contiguous"`` (default) is the identity; ``"zigzag"`` expects
    inputs pre-permuted with `to_zigzag(x, n)` and returns the output in
    the same zigzag order — for `causal` runs it executes the balanced
    schedule from the module docstring (~2x fewer FLOPs, every device
    identical work). Positions in the causal mask are always GLOBAL
    (natural-order) positions, so zigzag output equals
    `to_zigzag(full_attention(...))` exactly.

    ``unroll``: replace the `fori_loop` with a Python loop over the n
    ring steps. The traced program grows O(n), but XLA can then overlap
    step s+1's `ppermute` hop with step s's block compute (a while-loop
    body is a scheduling barrier between iterations) — worth it for
    ICI-scale rings; it is also what lets XLA cost analysis see the full
    schedule (the FLOP-ratio gate in tests uses it).
    """
    if block_impl not in ("jnp", "pallas"):
        raise ValueError(f"unknown block_impl {block_impl!r}")
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    n = mesh.shape[axis]

    def interp_mode():
        # interpret keys on the MESH's devices, not the process default
        # backend — a CPU-device mesh on a TPU-backed host must
        # interpret, not lower Mosaic for CPU
        return mesh.devices.flat[0].platform not in ("tpu", "axon")

    def run_steps(body, carry, start):
        if unroll:
            for s in range(start, n):
                carry = body(s, carry)
            return carry
        return lax.fori_loop(start, n, body, carry)

    def finalize(l, acc, dtype):
        norm = jnp.transpose(l, (0, 2, 1))[..., None]
        return (acc / jnp.maximum(norm, 1e-37)).astype(dtype)

    def per_device(q, k, v):
        scale_ = scale if scale is not None else q.shape[-1] ** -0.5
        me = collectives.axis_index(axis)
        b, t_local, h, d = q.shape
        qf = q.astype(jnp.float32)
        m0 = jnp.full((b, h, t_local), _MASKED, jnp.float32)
        l0 = jnp.zeros((b, h, t_local), jnp.float32)
        acc0 = jnp.zeros((b, t_local, h, d), jnp.float32)
        perm = collectives.ring_perm(n)
        if block_impl == "pallas":
            from idc_models_tpu.ops import flash_block_kernel as fbk

            flash_upd = fbk.make_flash_block_update(
                scale=scale_, causal=causal, interpret=interp_mode())

        def body(s, carry):
            kc, vc, m, l, acc = carry
            # after s hops we hold the block of device (me - s) mod n
            kv_dev = jnp.mod(me - s, n)
            if block_impl == "pallas":
                # native dtypes straight through: bf16 q/k/v stay bf16
                # in HBM and over the ppermute hops; the kernel upcasts
                # per VMEM tile
                offsets = jnp.stack([me * t_local, kv_dev * t_local])
                m, l, acc = flash_upd(q, kc, vc, m, l, acc, offsets)
            else:
                mask = (causal_block_mask(t_local, t_local,
                                          me * t_local,
                                          kv_dev * t_local)
                        if causal else None)
                m, l, acc = _block_attend(qf, kc.astype(jnp.float32),
                                          vc.astype(jnp.float32), m, l,
                                          acc, scale=scale_, mask=mask)
            # one neighbor hop per step; the last hop returns the blocks
            # to their owners (harmless, keeps the loop body uniform)
            kc = collectives.ppermute(kc, axis, perm)
            vc = collectives.ppermute(vc, axis, perm)
            return kc, vc, m, l, acc

        _, _, m, l, acc = run_steps(body, (k, v, m0, l0, acc0), 0)
        return finalize(l, acc, q.dtype)

    def per_device_zigzag(q, k, v):
        """Balanced causal schedule for the zigzag layout: the local block
        is [stripe me, stripe 2n-1-me]; per hop exactly two of the four
        stripe-pair quarters are (fully) visible, so both are computed
        dense and UNMASKED — all masking lives in the two step-0 stripe
        diagonals. Every device runs the identical 2n+1-quarter program,
        so no device waits on a longer peer."""
        scale_ = scale if scale is not None else q.shape[-1] ** -0.5
        me = collectives.axis_index(axis)
        b, t_local, h, d = q.shape
        if t_local % 2:
            raise ValueError(
                f"zigzag layout needs an even local block, got {t_local}")
        th = t_local // 2
        perm = collectives.ring_perm(n)
        if block_impl == "pallas":
            from idc_models_tpu.ops import flash_block_kernel as fbk

            if th % fbk.TILE_MIN:
                raise ValueError(
                    f"zigzag + pallas operates on half-blocks: t_local "
                    f"{t_local} gives quarters of {th}, need a multiple "
                    f"of {fbk.TILE_MIN} (t_local % 256 == 0)")
            flash_diag = fbk.make_flash_block_update(
                scale=scale_, causal=True, interpret=interp_mode())
            flash_full = fbk.make_flash_block_update(
                scale=scale_, causal=False, interpret=interp_mode())
            qq = q  # native dtype through the kernel (per-tile upcast)
        else:
            qq = q.astype(jnp.float32)
        q_lo, q_hi = qq[:, :th], qq[:, th:]
        lo_off = me * th                    # global start of stripe me
        hi_off = (2 * n - 1 - me) * th      # ... and of stripe 2n-1-me

        def quarter(m, l, acc, row0, qh, kh, vh, q_off, k_off, diag):
            """Fold one [th, th] quarter attend into carry rows
            [row0, row0+th); row0 may be a traced scalar (attend B picks
            its half at run time)."""
            ms = lax.dynamic_slice(m, (0, 0, row0), (b, h, th))
            ls = lax.dynamic_slice(l, (0, 0, row0), (b, h, th))
            accs = lax.dynamic_slice(acc, (0, row0, 0, 0), (b, th, h, d))
            if block_impl == "pallas":
                upd = flash_diag if diag else flash_full
                offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                                  jnp.asarray(k_off, jnp.int32)])
                ms, ls, accs = upd(qh, kh, vh, ms, ls, accs, offs)
            else:
                mask = (causal_block_mask(th, th, q_off, k_off)
                        if diag else None)
                ms, ls, accs = _block_attend(
                    qh, kh.astype(jnp.float32), vh.astype(jnp.float32),
                    ms, ls, accs, scale=scale_, mask=mask)
            return (lax.dynamic_update_slice(m, ms, (0, 0, row0)),
                    lax.dynamic_update_slice(l, ls, (0, 0, row0)),
                    lax.dynamic_update_slice(acc, accs, (0, row0, 0, 0)))

        m = jnp.full((b, h, t_local), _MASKED, jnp.float32)
        l = jnp.zeros((b, h, t_local), jnp.float32)
        acc = jnp.zeros((b, t_local, h, d), jnp.float32)

        # Step 0, own block: both stripe diagonals plus the always-
        # visible (hi queries, lo keys) quarter; (lo, hi) is provably
        # empty (lo stripe < n <= hi stripe) and never computed. Every
        # diagonal row sees its own position, so no row's first fold is
        # fully masked — the contiguous path's self-healing case cannot
        # even arise here.
        k_lo, k_hi = k[:, :th], k[:, th:]
        v_lo, v_hi = v[:, :th], v[:, th:]
        m, l, acc = quarter(m, l, acc, 0, q_lo, k_lo, v_lo,
                            lo_off, lo_off, True)
        m, l, acc = quarter(m, l, acc, th, q_hi, k_hi, v_hi,
                            hi_off, hi_off, True)
        m, l, acc = quarter(m, l, acc, th, q_hi, k_lo, v_lo,
                            hi_off, lo_off, False)

        def body(s, carry):
            kc, vc, m, l, acc = carry
            kc = collectives.ppermute(kc, axis, perm)
            vc = collectives.ppermute(vc, axis, perm)
            c = jnp.mod(me - s, n)          # owner of the visiting block
            kc_lo, kc_hi = kc[:, :th], kc[:, th:]
            vc_lo, vc_hi = vc[:, :th], vc[:, th:]
            c_lo = c * th
            c_hi = (2 * n - 1 - c) * th
            # A: hi queries vs visiting lo stripe — always fully visible
            # (hi stripe >= n > any lo stripe index).
            m, l, acc = quarter(m, l, acc, th, q_hi, kc_lo, vc_lo,
                                hi_off, c_lo, False)
            # B: exactly one of (lo q, lo k) / (hi q, hi k) is fully
            # visible — (lo, lo) iff c < me, else (hi, hi) since
            # 2n-1-c < 2n-1-me iff c > me; the other is fully masked and
            # skipped. Selected by value so the loop body stays uniform.
            cond = c < me
            qs = jnp.where(cond, q_lo, q_hi)
            ks = jnp.where(cond, kc_lo, kc_hi)
            vs = jnp.where(cond, vc_lo, vc_hi)
            row0 = jnp.where(cond, 0, th)
            qo = jnp.where(cond, lo_off, hi_off)
            ko = jnp.where(cond, c_lo, c_hi)
            m, l, acc = quarter(m, l, acc, row0, qs, ks, vs, qo, ko,
                                False)
            return kc, vc, m, l, acc

        _, _, m, l, acc = run_steps(body, (k, v, m, l, acc), 1)
        return finalize(l, acc, q.dtype)

    body_fn = per_device_zigzag if (layout == "zigzag" and causal) \
        else per_device
    spec = P(None, axis, None, None)
    mapped = shard_map(body_fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return jax.jit(mapped)


def ring_attention(q, k, v, mesh: Mesh, *, axis: str = meshlib.SEQ_AXIS,
                   causal: bool = False, scale: float | None = None,
                   block_impl: str = "jnp", layout: str = "contiguous",
                   unroll: bool = False):
    """One-shot convenience wrapper around `make_ring_attention` —
    every knob of the builder (the pallas fast path, the zigzag causal
    layout, unrolling) is reachable from here too.

    For hot loops build the function once with `make_ring_attention`
    (the jit cache keys on the python callable identity)."""
    fn = _cached_ring(mesh, axis, causal, scale, block_impl, layout,
                      unroll)
    return fn(q, k, v)


@functools.lru_cache(maxsize=32)
def _cached_ring(mesh, axis, causal, scale, block_impl="jnp",
                 layout="contiguous", unroll=False):
    return make_ring_attention(mesh, axis=axis, causal=causal, scale=scale,
                               block_impl=block_impl, layout=layout,
                               unroll=unroll)
