"""Ring attention: exact attention over sequences sharded across devices.

Long-context sequence parallelism for this framework (SURVEY.md §5 names
the explicit ring schedule as the forward-looking reason `collectives`
exposes `ppermute`; the reference has no attention at all, so this is
beyond-parity capability, designed TPU-first):

- the sequence axis is sharded over a 1-D ``"seq"`` mesh
  (`mesh.seq_mesh`): every device holds the query block it owns for the
  whole computation plus ONE rotating key/value block;
- each of the n ring steps computes blockwise attention between the
  resident queries and the visiting K/V block, folded into a numerically
  stable online softmax (running max `m`, normalizer `l`, weighted
  accumulator `acc` — the flash-attention recurrence), then passes the
  K/V block to the next neighbor with a single `ppermute` hop riding ICI;
- per-device memory: q/k/v/acc are O(T/n), plus ONE [B,H,T/n,T/n] score
  tile alive per ring step on the default jnp block path (the blockwise
  tiling is across devices, not within a block). When local blocks grow
  long, pass ``block_impl="pallas"``: the fused flash kernel
  (`ops.flash_block_kernel`) keeps scores in VMEM — measured 1.41x at
  T/n=8k and 1.62x at 16k on a v5 lite chip. Either way a sequence n
  times longer than one device could hold attends exactly.
  Comm/compute overlap within a step (the hop and the block attend read
  the same kc and are independent) is left to XLA's async collectives —
  an EXPECTATION from the dependence structure, not a measured result:
  a single-chip environment cannot time a real multi-hop ring, and no
  pod measurement exists yet. `unroll=True` additionally removes the
  while-loop barrier between steps (see `make_ring_attention`).

Causal layouts: with the plain contiguous layout device i owns queries
that can see only blocks 0..i, yet every device executes all n block
steps in SPMD lockstep, so ~half the causal FLOPs land on fully masked
blocks (p == 0) and the ring's wall-clock is set by the last device.
``layout="zigzag"`` fixes this: the sequence is split into 2n stripes
and device i holds stripes (i, 2n-1-i) — permute inputs with
`to_zigzag` and invert the output with `from_zigzag`. Under that layout
every device's causal schedule is IDENTICAL and dense: three
quarter-block attends on its own block (two stripe diagonals plus the
always-visible hi-vs-lo quarter; the lo-vs-hi quarter is provably empty
and never computed), then exactly two fully-visible half-attends per
ring hop. Total causal work drops from 4n quarter-blocks per device to
2n+1 — the ~2x the contiguous docstring used to concede. Measured on a
v5 lite chip (emulated ring-of-8 per-device schedule, pallas blocks,
`experiments/zigzag_bench.py`): 1.52x at t_local=4096, 1.74x at 8192,
1.76x at 16384 vs the contiguous schedule (ideal 4n/(2n+1) = 1.88x at
n=8); the executed-FLOP ratio is gated by an XLA-cost-analysis test.
Without `causal` the layout changes nothing (dense attention is
permutation-equivariant), so zigzag only matters for causal runs.

The loop is a `lax.fori_loop`, so the traced program is O(1) in ring
size (one hop + one block-attention in the body; ring_psum's unrolled
form documents why that matters for compile time).  The result is
bit-for-bit independent of ring size in exact arithmetic and matches
single-device full attention to fp tolerance — pinned by tests,
including gradients (`jax.grad` flows through `ppermute` and
`fori_loop` natively).

Causal masking uses GLOBAL positions: device i's queries sit at offset
i*T_local, and after s rotations it is visiting the K/V block of device
(i - s) mod n, so the mask depends only on (axis_index, step) — no
position tensors are communicated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from idc_models_tpu import collectives
from idc_models_tpu import mesh as meshlib

from idc_models_tpu.compat import shard_map


# Masked scores use a large finite negative instead of -inf: exp() of it
# is exactly 0.0 in f32 (no NaN-producing inf arithmetic on the backward
# pass), and the one pathological case — the FIRST visited block fully
# masked, making p momentarily exp(0)=1 — self-heals because the next
# unmasked block's corr = exp(_MASKED - real_max) = 0 wipes the bogus
# partial sums. Causal masking guarantees every query eventually sees an
# unmasked block (its own position).
_MASKED = -1e30


def _block_attend(q, k, v, m, l, acc, *, scale, mask=None):
    """One online-softmax update of (m, l, acc) with a visiting K/V block.

    q [B,Tq,H,D]; k,v [B,Tk,H,D]; m,l [B,H,Tq]; acc [B,Tq,H,D].
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, _MASKED)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = (acc * jnp.transpose(corr, (0, 2, 1))[..., None]
               + jnp.einsum("bhqk,bkhd->bqhd", p, v,
                            preferred_element_type=jnp.float32))
    return m_new, l_new, acc_new


def causal_block_mask(t_q, t_k, q_offset, k_offset):
    """[1, 1, t_q, t_k] bool: which (query, key) pairs are visible given
    the blocks' global start positions — THE causal convention, shared
    by the jnp ring body, the flash kernel's jnp reference, and (as an
    in-kernel iota copy, kept in sync by tests) the kernel itself."""
    q_pos = q_offset + jnp.arange(t_q)
    k_pos = k_offset + jnp.arange(t_k)
    return (q_pos[:, None] >= k_pos[None, :])[None, None]


def zigzag_indices(t: int, n: int):
    """Global gather indices realizing the zigzag layout: the sequence is
    cut into 2n equal stripes and device i's contiguous shard becomes
    [stripe i, stripe 2n-1-i]. `t` must divide by 2n. Returns a numpy
    int array `p` with ``x_zig = x.take(p, axis=seq)``; the layout is an
    involution-free permutation whose inverse is `argsort(p)`
    (`from_zigzag`)."""
    import numpy as np

    if t % (2 * n):
        raise ValueError(f"sequence length {t} not divisible by 2*{n}")
    sw = t // (2 * n)
    stripes = np.arange(t).reshape(2 * n, sw)
    order = []
    for i in range(n):
        order += [i, 2 * n - 1 - i]
    return stripes[order].reshape(-1)


def to_zigzag(x, n: int, *, axis: int = 1):
    """Permute a sequence axis into the zigzag layout for an n-device
    ring (see `zigzag_indices`)."""
    return jnp.take(x, jnp.asarray(zigzag_indices(x.shape[axis], n)),
                    axis=axis)


def from_zigzag(x, n: int, *, axis: int = 1):
    """Inverse of `to_zigzag` — restore natural sequence order."""
    import numpy as np

    inv = np.argsort(zigzag_indices(x.shape[axis], n))
    return jnp.take(x, jnp.asarray(inv), axis=axis)


def full_attention(q, k, v, *, causal: bool = False, scale: float | None
                   = None):
    """Single-device reference: softmax(q k^T / sqrt(d)) v, [B,T,H,D]."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        q.astype(jnp.float32), k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32))
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, *, axis: str = meshlib.SEQ_AXIS,
                        causal: bool = False, scale: float | None = None,
                        block_impl: str = "jnp",
                        layout: str = "contiguous",
                        unroll: bool = False):
    """Build ``fn(q, k, v) -> out`` with q/k/v/out [B, T, H, D] sharded on
    T over `axis`; jitted, exact (not approximate) attention.

    `mesh` may be multi-dimensional: the ring runs over `axis` and the
    batch dimension shards over every other mesh axis (e.g. a
    ("data", "seq") mesh from `mesh.data_seq_mesh` composes data
    parallelism with sequence parallelism — no resharding, one ring per
    data-mesh row).

    ``block_impl``: ``"jnp"`` (default) computes each visiting block with
    plain jnp ops (XLA-fused, fine up to moderate local block lengths);
    ``"pallas"`` runs the fused flash kernels
    (`ops.flash_block_kernel`) — scores stay in VMEM in BOTH
    directions: the forward ring folds blocks with the fused online-
    softmax kernel, and the whole per-device ring carries a custom_vjp
    whose backward is a second ring built on the blockwise flash
    backward (`make_flash_block_grads`: p recomputed per tile from the
    saved logsumexp; dk/dv accumulators ride the ring home). No
    [t_local, t_local] tensor exists in HBM forward or backward —
    asserted by a jaxpr test. Requires T/n a multiple of 128 (256 under
    ``layout="zigzag"``, whose kernel calls operate on half-blocks),
    interpret mode off-TPU.

    ``layout``: how the global sequence maps to device shards.
    ``"contiguous"`` (default) is the identity; ``"zigzag"`` expects
    inputs pre-permuted with `to_zigzag(x, n)` and returns the output in
    the same zigzag order — for `causal` runs it executes the balanced
    schedule from the module docstring (~2x fewer FLOPs, every device
    identical work). Positions in the causal mask are always GLOBAL
    (natural-order) positions, so zigzag output equals
    `to_zigzag(full_attention(...))` exactly.

    ``unroll``: replace the `fori_loop` with a Python loop over the n
    ring steps. The traced program grows O(n), but XLA can then overlap
    step s+1's `ppermute` hop with step s's block compute (a while-loop
    body is a scheduling barrier between iterations) — worth it for
    ICI-scale rings; it is also what lets XLA cost analysis see the full
    schedule (the FLOP-ratio gate in tests uses it).
    """
    if block_impl not in ("jnp", "pallas"):
        raise ValueError(f"unknown block_impl {block_impl!r}")
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    n = mesh.shape[axis]

    def interp_mode():
        # interpret keys on the MESH's devices, not the process default
        # backend — a CPU-device mesh on a TPU-backed host must
        # interpret, not lower Mosaic for CPU
        return mesh.devices.flat[0].platform not in ("tpu", "axon")

    def run_steps(body, carry, start):
        if unroll:
            for s in range(start, n):
                carry = body(s, carry)
            return carry
        return lax.fori_loop(start, n, body, carry)

    def finalize(l, acc, dtype):
        norm = jnp.transpose(l, (0, 2, 1))[..., None]
        return (acc / jnp.maximum(norm, 1e-37)).astype(dtype)

    def make_attend(scale_, use_pallas):
        """The one block-fold primitive both layouts walk their
        schedules with: ``attend(qh, kh, vh, m, l, acc, q_off, k_off,
        masked)`` folds one visiting block (or quarter) into the
        carry; `masked` applies causal masking by the two GLOBAL block
        offsets. jnp flavor: dense `_block_attend` (per-call f32
        upcast). pallas flavor: fused flash kernel, native dtypes in
        HBM, per-tile upcast."""
        if use_pallas:
            from idc_models_tpu.ops import flash_block_kernel as fbk

            upds = {masked: fbk.make_flash_block_update(
                        scale=scale_, causal=masked,
                        interpret=interp_mode())
                    for masked in (False, True)}

            def attend(qh, kh, vh, m, l, acc, q_off, k_off, masked):
                offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                                  jnp.asarray(k_off, jnp.int32)])
                return upds[masked](qh, kh, vh, m, l, acc, offs)
        else:
            def attend(qh, kh, vh, m, l, acc, q_off, k_off, masked):
                mask = (causal_block_mask(qh.shape[1], kh.shape[1],
                                          q_off, k_off)
                        if masked else None)
                return _block_attend(
                    qh.astype(jnp.float32), kh.astype(jnp.float32),
                    vh.astype(jnp.float32), m, l, acc, scale=scale_,
                    mask=mask)
        return attend

    def contiguous_fold(q, k, v, attend):
        """The contiguous ring walk: n lockstep steps, each folding the
        visiting full block then hopping it on (the last hop returns
        blocks to their owners — harmless, keeps the body uniform).
        Returns the raw (m, l, acc) carry so callers can keep L."""
        me = collectives.axis_index(axis)
        b, t_local, h, d = q.shape
        perm = collectives.ring_perm(n)
        m0 = jnp.full((b, h, t_local), _MASKED, jnp.float32)
        l0 = jnp.zeros((b, h, t_local), jnp.float32)
        acc0 = jnp.zeros((b, t_local, h, d), jnp.float32)

        def body(s, carry):
            kc, vc, m, l, acc = carry
            # after s hops we hold the block of device (me - s) mod n
            kv_dev = jnp.mod(me - s, n)
            m, l, acc = attend(q, kc, vc, m, l, acc, me * t_local,
                               kv_dev * t_local, causal)
            kc = collectives.ppermute(kc, axis, perm)
            vc = collectives.ppermute(vc, axis, perm)
            return kc, vc, m, l, acc

        _, _, m, l, acc = run_steps(body, (k, v, m0, l0, acc0), 0)
        return m, l, acc

    def zigzag_fold(q, k, v, attend):
        """The balanced causal schedule (one copy, walked by both block
        impls): the local block is [stripe me, stripe 2n-1-me]; per hop
        exactly two of the four stripe-pair quarters are (fully)
        visible, so both are computed dense and UNMASKED — all masking
        lives in the two step-0 stripe diagonals. Every device runs the
        identical 2n+1-quarter program, so no device waits on a longer
        peer. Returns the raw (m, l, acc) carry."""
        me = collectives.axis_index(axis)
        b, t_local, h, d = q.shape
        if t_local % 2:
            raise ValueError(
                f"zigzag layout needs an even local block, got {t_local}")
        th = t_local // 2
        perm = collectives.ring_perm(n)
        q_lo, q_hi = q[:, :th], q[:, th:]
        lo_off = me * th                    # global start of stripe me
        hi_off = (2 * n - 1 - me) * th      # ... and of stripe 2n-1-me

        def quarter(m, l, acc, row0, qh, kh, vh, q_off, k_off, diag):
            """Fold one [th, th] quarter attend into carry rows
            [row0, row0+th); row0 may be a traced scalar (attend B picks
            its half at run time)."""
            ms = lax.dynamic_slice(m, (0, 0, row0), (b, h, th))
            ls = lax.dynamic_slice(l, (0, 0, row0), (b, h, th))
            accs = lax.dynamic_slice(acc, (0, row0, 0, 0), (b, th, h, d))
            ms, ls, accs = attend(qh, kh, vh, ms, ls, accs, q_off,
                                  k_off, diag)
            return (lax.dynamic_update_slice(m, ms, (0, 0, row0)),
                    lax.dynamic_update_slice(l, ls, (0, 0, row0)),
                    lax.dynamic_update_slice(acc, accs, (0, row0, 0, 0)))

        m = jnp.full((b, h, t_local), _MASKED, jnp.float32)
        l = jnp.zeros((b, h, t_local), jnp.float32)
        acc = jnp.zeros((b, t_local, h, d), jnp.float32)

        # Step 0, own block: both stripe diagonals plus the always-
        # visible (hi queries, lo keys) quarter; (lo, hi) is provably
        # empty (lo stripe < n <= hi stripe) and never computed. Every
        # diagonal row sees its own position, so no row's first fold is
        # fully masked — the contiguous path's self-healing case cannot
        # even arise here.
        k_lo, k_hi = k[:, :th], k[:, th:]
        v_lo, v_hi = v[:, :th], v[:, th:]
        m, l, acc = quarter(m, l, acc, 0, q_lo, k_lo, v_lo,
                            lo_off, lo_off, True)
        m, l, acc = quarter(m, l, acc, th, q_hi, k_hi, v_hi,
                            hi_off, hi_off, True)
        m, l, acc = quarter(m, l, acc, th, q_hi, k_lo, v_lo,
                            hi_off, lo_off, False)

        def body(s, carry):
            kc, vc, m, l, acc = carry
            kc = collectives.ppermute(kc, axis, perm)
            vc = collectives.ppermute(vc, axis, perm)
            c = jnp.mod(me - s, n)          # owner of the visiting block
            kc_lo, kc_hi = kc[:, :th], kc[:, th:]
            vc_lo, vc_hi = vc[:, :th], vc[:, th:]
            c_lo = c * th
            c_hi = (2 * n - 1 - c) * th
            # A: hi queries vs visiting lo stripe — always fully visible
            # (hi stripe >= n > any lo stripe index).
            m, l, acc = quarter(m, l, acc, th, q_hi, kc_lo, vc_lo,
                                hi_off, c_lo, False)
            # B: exactly one of (lo q, lo k) / (hi q, hi k) is fully
            # visible — (lo, lo) iff c < me, else (hi, hi) since
            # 2n-1-c < 2n-1-me iff c > me; the other is fully masked and
            # skipped. Selected by value so the loop body stays uniform.
            cond = c < me
            qs = jnp.where(cond, q_lo, q_hi)
            ks = jnp.where(cond, kc_lo, kc_hi)
            vs = jnp.where(cond, vc_lo, vc_hi)
            row0 = jnp.where(cond, 0, th)
            qo = jnp.where(cond, lo_off, hi_off)
            ko = jnp.where(cond, c_lo, c_hi)
            m, l, acc = quarter(m, l, acc, row0, qs, ks, vs, qo, ko,
                                False)
            return kc, vc, m, l, acc

        _, _, m, l, acc = run_steps(body, (k, v, m, l, acc), 1)
        return m, l, acc

    def pallas_ring_vjp(fwd_loop, bwd_impl):
        """The ring-level custom_vjp scaffolding shared by both pallas
        layouts: forward runs `fwd_loop` (a fold returning the raw
        (m, l, acc) carry) and saves only (q, k, v, out, L); backward
        computes D = rowsum(dout*out) and hands off to the layout's
        `bwd_impl(q, k, v, dout, L, D)` backward ring. me/axis_index is
        taken INSIDE fwd/bwd (both run under the shard_map trace) —
        custom_vjp must not close over tracers."""

        @jax.custom_vjp
        def attn(q, k, v):
            _, l, acc = fwd_loop(q, k, v)
            return finalize(l, acc, q.dtype)

        def attn_fwd(q, k, v):
            m, l, acc = fwd_loop(q, k, v)
            out = finalize(l, acc, q.dtype)
            L = m + jnp.log(jnp.maximum(l, 1e-37))
            return out, (q, k, v, out, L)

        def attn_bwd(res, dout):
            q, k, v, out, L = res
            Dr = jnp.einsum("bqhd,bqhd->bhq", dout.astype(jnp.float32),
                            out.astype(jnp.float32))
            dq, dk, dv = bwd_impl(q, k, v, dout, L, Dr)
            return (dq.astype(q.dtype), dk.astype(k.dtype),
                    dv.astype(v.dtype))

        attn.defvjp(attn_fwd, attn_bwd)
        return attn

    def per_device(q, k, v):
        scale_ = scale if scale is not None else q.shape[-1] ** -0.5
        _, l, acc = contiguous_fold(q, k, v, make_attend(scale_, False))
        return finalize(l, acc, q.dtype)

    def per_device_pallas(q, k, v):
        """Contiguous pallas ring with a ring-level custom_vjp: the
        forward folds visiting blocks with the fused flash kernel
        (native dtypes in HBM, per-tile upcast) and saves only
        (q, k, v, out, L); the backward is a SECOND ring driving the
        blockwise flash backward kernels, with the dk/dv accumulators
        riding the ppermute hops back to their owners. Per-device
        memory stays O(t_local) in both directions."""
        from idc_models_tpu.ops import flash_block_kernel as fbk

        scale_ = scale if scale is not None else q.shape[-1] ** -0.5
        b, t_local, h, d = q.shape
        perm = collectives.ring_perm(n)
        attend = make_attend(scale_, True)
        gfn = fbk.make_flash_block_grads(
            scale=scale_, causal=causal, interpret=interp_mode())

        def offsets_for(me, s):
            return jnp.stack([me * t_local,
                              jnp.mod(me - s, n) * t_local])

        def fwd_loop(q, k, v):
            return contiguous_fold(q, k, v, attend)

        def bwd_ring(q, k, v, dout, L, Dr):
            me = collectives.axis_index(axis)

            def body(s, carry):
                kc, vc, dk, dv, dq = carry
                dqp, dkb, dvb = gfn(q, kc, vc, dout, L, Dr,
                                    offsets_for(me, s))
                dq = dq + dqp
                dk = dk + dkb
                dv = dv + dvb
                # dk/dv travel WITH their block; after the n-th hop the
                # fully-accumulated grads are back at the block's owner
                kc, vc, dk, dv = (collectives.ppermute(x, axis, perm)
                                  for x in (kc, vc, dk, dv))
                return kc, vc, dk, dv, dq

            zf = lambda x: jnp.zeros(x.shape, jnp.float32)
            _, _, dk, dv, dq = run_steps(
                body, (k, v, zf(k), zf(v), zf(q)), 0)
            return dq, dk, dv

        return pallas_ring_vjp(fwd_loop, bwd_ring)(q, k, v)

    def per_device_zigzag(q, k, v):
        scale_ = scale if scale is not None else q.shape[-1] ** -0.5
        _, l, acc = zigzag_fold(q, k, v, make_attend(scale_, False))
        return finalize(l, acc, q.dtype)

    def per_device_zigzag_pallas(q, k, v):
        """Zigzag schedule on the fused kernels, ring-level custom_vjp.

        Forward: the per_device_zigzag quarter schedule, each quarter a
        fused flash kernel call (diag quarters causal, hop quarters
        unmasked). Backward: the SAME schedule re-walked with the
        blockwise flash backward kernels — each quarter contributes a
        dq update at its query half and dk/dv updates at the visiting
        half, with dk/dv riding the hops; one trailing hop delivers the
        accumulators to their owners (the forward's n-1 hops leave them
        one device short)."""
        from idc_models_tpu.ops import flash_block_kernel as fbk

        scale_ = scale if scale is not None else q.shape[-1] ** -0.5
        b, t_local, h, d = q.shape
        if t_local % 2:
            raise ValueError(
                f"zigzag layout needs an even local block, got {t_local}")
        th = t_local // 2
        if th % fbk.TILE_MIN:
            raise ValueError(
                f"zigzag + pallas operates on half-blocks: t_local "
                f"{t_local} gives quarters of {th}, need a multiple "
                f"of {fbk.TILE_MIN} (t_local % 256 == 0)")
        perm = collectives.ring_perm(n)
        interp = interp_mode()
        attend = make_attend(scale_, True)
        g_diag = fbk.make_flash_block_grads(
            scale=scale_, causal=True, interpret=interp)
        g_full = fbk.make_flash_block_grads(
            scale=scale_, causal=False, interpret=interp)

        def stripe_offs(me):
            return me * th, (2 * n - 1 - me) * th

        def fwd_loop(q, k, v):
            return zigzag_fold(q, k, v, attend)

        def bwd_ring(q, k, v, dout, L, Dr):
            me = collectives.axis_index(axis)
            lo_off, hi_off = stripe_offs(me)

            def gquarter(dq, dk, dv, kc, vc, row0, krow0, q_off, k_off,
                         diag):
                """One quarter's grad contributions: rows [row0,
                row0+th) of q/dout/L/D against the [krow0, krow0+th)
                half of the visiting block."""
                qs = lax.dynamic_slice(q, (0, row0, 0, 0),
                                       (b, th, h, d))
                dos = lax.dynamic_slice(dout, (0, row0, 0, 0),
                                        (b, th, h, d))
                Ls = lax.dynamic_slice(L, (0, 0, row0), (b, h, th))
                Ds = lax.dynamic_slice(Dr, (0, 0, row0), (b, h, th))
                ks = lax.dynamic_slice(kc, (0, krow0, 0, 0),
                                       (b, th, h, d))
                vs = lax.dynamic_slice(vc, (0, krow0, 0, 0),
                                       (b, th, h, d))
                offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                                  jnp.asarray(k_off, jnp.int32)])
                gf = g_diag if diag else g_full
                dqp, dkb, dvb = gf(qs, ks, vs, dos, Ls, Ds, offs)
                dq = lax.dynamic_update_slice(
                    dq, lax.dynamic_slice(dq, (0, row0, 0, 0),
                                          (b, th, h, d)) + dqp,
                    (0, row0, 0, 0))
                dk = lax.dynamic_update_slice(
                    dk, lax.dynamic_slice(dk, (0, krow0, 0, 0),
                                          (b, th, h, d)) + dkb,
                    (0, krow0, 0, 0))
                dv = lax.dynamic_update_slice(
                    dv, lax.dynamic_slice(dv, (0, krow0, 0, 0),
                                          (b, th, h, d)) + dvb,
                    (0, krow0, 0, 0))
                return dq, dk, dv

            zf = lambda x: jnp.zeros(x.shape, jnp.float32)
            dq, dk, dv = zf(q), zf(k), zf(v)
            dq, dk, dv = gquarter(dq, dk, dv, k, v, 0, 0,
                                  lo_off, lo_off, True)
            dq, dk, dv = gquarter(dq, dk, dv, k, v, th, th,
                                  hi_off, hi_off, True)
            dq, dk, dv = gquarter(dq, dk, dv, k, v, th, 0,
                                  hi_off, lo_off, False)

            def body(s, carry):
                kc, vc, dk, dv, dq = carry
                kc, vc, dk, dv = (collectives.ppermute(x, axis, perm)
                                  for x in (kc, vc, dk, dv))
                c = jnp.mod(me - s, n)
                c_lo, c_hi = c * th, (2 * n - 1 - c) * th
                dq, dk, dv = gquarter(dq, dk, dv, kc, vc, th, 0,
                                      hi_off, c_lo, False)
                cond = c < me
                start = jnp.where(cond, 0, th)
                qo = jnp.where(cond, lo_off, hi_off)
                ko = jnp.where(cond, c_lo, c_hi)
                dq, dk, dv = gquarter(dq, dk, dv, kc, vc, start, start,
                                      qo, ko, False)
                return kc, vc, dk, dv, dq

            _, _, dk, dv, dq = run_steps(body, (k, v, dk, dv, dq), 1)
            # the forward's n-1 hops leave each accumulator one device
            # before its owner; one trailing hop delivers it
            dk = collectives.ppermute(dk, axis, perm)
            dv = collectives.ppermute(dv, axis, perm)
            return dq, dk, dv

        return pallas_ring_vjp(fwd_loop, bwd_ring)(q, k, v)

    if layout == "zigzag" and causal:
        body_fn = (per_device_zigzag_pallas if block_impl == "pallas"
                   else per_device_zigzag)
    else:
        body_fn = (per_device_pallas if block_impl == "pallas"
                   else per_device)
    # The ring runs over `axis`; every OTHER mesh axis shards the batch
    # dimension, so a 2-D ("data", "seq") mesh composes DP x SP without
    # resharding — each (data, seq) submesh row runs an independent ring
    # over its batch shard.
    spec = meshlib.batch_seq_spec(mesh, axis, trailing=2)
    mapped = shard_map(body_fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)

    def checked(q, k, v):
        # trace-time shape gate with the framework's message, instead of
        # letting an indivisible T fall into shard_map's generic
        # sharding error (the knob rejection matrix test pins this)
        t = q.shape[1]
        if t % n:
            raise ValueError(
                f"sequence length {t} not divisible by the ring size "
                f"{n} over mesh axis {axis!r}")
        return mapped(q, k, v)

    return jax.jit(checked)


def ring_attention(q, k, v, mesh: Mesh, *, axis: str = meshlib.SEQ_AXIS,
                   causal: bool = False, scale: float | None = None,
                   block_impl: str = "jnp", layout: str = "contiguous",
                   unroll: bool = False):
    """One-shot convenience wrapper around `make_ring_attention` —
    every knob of the builder (the pallas fast path, the zigzag causal
    layout, unrolling) is reachable from here too.

    For hot loops build the function once with `make_ring_attention`
    (the jit cache keys on the python callable identity)."""
    fn = _cached_ring(mesh, axis, causal, scale, block_impl, layout,
                      unroll)
    return fn(q, k, v)


@functools.lru_cache(maxsize=32)
def _cached_ring(mesh, axis, causal, scale, block_impl="jnp",
                 layout="contiguous", unroll=False):
    return make_ring_attention(mesh, axis=axis, causal=causal, scale=scale,
                               block_impl=block_impl, layout=layout,
                               unroll=unroll)
