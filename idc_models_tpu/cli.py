"""Command-line entry points — one subcommand per reference script.

Parity target (SURVEY.md C19): the reference's five scripts take
positional sys.argv (path; fed adds NUM_ROUNDS + iid|noniid; secure adds
NUM_ROUNDS + percent). Here: proper argparse with the presets from
`configs.py` as defaults and every hyperparameter overridable.

    python -m idc_models_tpu vgg --path runs/vgg --data-dir .../balanced_IDC_30k
    python -m idc_models_tpu fed --path runs/fed --rounds 10 --noniid
    python -m idc_models_tpu secure-fed --rounds 5 --percent 0.5

Data resolution: --data-dir (a `<label>/*.png` tree) if given, else
`<path>/data/balanced_IDC_30k` if present (the reference's layout,
dist_model_tf_vgg.py:105), else a synthetic stand-in sized by
--synthetic-examples so every preset smoke-runs anywhere. Virtual devices
for laptop/test runs come from --host-devices N (the TPU-pod stand-in).
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
from pathlib import Path

import numpy as np

# `serve --drafter` registry: choice name -> (module, class, story).
# Every class listed here MUST implement the models/draft.py contract
# (`propose(history) -> [k] int32 | None`) — a static scan
# (tests/test_static_robustness.py) imports each entry and asserts it,
# and asserts the argparse choices stay in lockstep with this table,
# so a drafter added to one place but not the other fails loudly.
SERVE_DRAFTERS = {
    "ngram": ("idc_models_tpu.models.draft", "NGramDrafter",
              "prompt-lookup over the slot's own stream; free, wins on "
              "repetitive/templated traffic, proposes nothing on fresh "
              "text"),
    "learned": ("idc_models_tpu.models.draft_lm", "DraftLM",
                "distilled draft LM (--draft-ckpt) with device-resident "
                "ring caches; one batched propose dispatch per cycle, "
                "wins on non-repetitive traffic"),
    "chained": ("idc_models_tpu.models.draft", "ChainedDrafter",
                "lookup-first / learned-fallback composition: the "
                "n-gram scan's free hits where streams repeat, the "
                "draft LM (--draft-ckpt) everywhere else"),
}


def main(argv: list[str] | None = None) -> int:
    ns = _parse(argv)
    if getattr(ns, "host_devices", 0):
        from idc_models_tpu import mesh as meshlib

        meshlib.force_cpu_pod(ns.host_devices)  # warns if ineffective
    runner = {"vgg": _run_dist, "mobile": _run_dist, "dense": _run_dist,
              "fed": _run_fed, "secure_fed": _run_secure,
              "attention": _run_attention, "lm": _run_lm,
              "serve": _run_serve, "serve_cluster": _run_serve_cluster,
              "stats": _run_stats, "profile": _run_profile,
              "convert_weights": _run_convert}[ns.preset_key]
    # --trace-out: ONE wiring point arms the runtime tracer for every
    # verb — the instrumented spans (serve scheduler cycles, federated
    # round attempts, train epochs/steps, Generator prefill/decode,
    # every legacy Timer) record only while this context is active and
    # export as Chrome trace-event JSON (Perfetto-loadable) on exit
    from idc_models_tpu.observe import tracing

    with tracing(chrome_path=getattr(ns, "trace_out", None)):
        runner(ns)
    return 0


def _parse(argv):
    p = argparse.ArgumentParser(prog="idc_models_tpu", description=__doc__)
    sub = p.add_subparsers(dest="preset_key", required=True)

    def common(sp):
        sp.add_argument("--path", default=None,
                        help="artifact root (plots under <path>/logs, "
                             "checkpoints under <path>/pretrained, jsonl "
                             "log) — the reference's argv[1]")
        sp.add_argument("--data-dir", default=None,
                        help="directory tree <label>/*.png")
        sp.add_argument("--synthetic-examples", type=int, default=512,
                        help="synthetic dataset size when no real data")
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument("--host-devices", type=int, default=0,
                        help="force N virtual CPU devices (TPU-pod "
                             "stand-in for local runs)")
        sp.add_argument("--batch-size", type=int, default=None)
        sp.add_argument("--lr", type=float, default=None)
        sp.add_argument("--profile-dir", default=None,
                        help="write a jax.profiler trace of the training "
                             "phase here (TensorBoard-viewable)")
        sp.add_argument("--trace-out", default=None,
                        help="write a Chrome trace-event JSON of the "
                             "run's host-side spans here (load it in "
                             "Perfetto / chrome://tracing; see "
                             "docs/OBSERVABILITY.md)")

    def pretrained_flag(sp):
        sp.add_argument("--pretrained-weights", default=None,
                        help="backbone weight artifact (.npz from "
                             "convert-weights, or a Keras .h5) — the "
                             "no-egress analogue of weights='imagenet' "
                             "(dist_model_tf_vgg.py:119)")

    for key in ("vgg", "mobile", "dense"):
        sp = sub.add_parser(key, help=f"{key} two-phase DP training")
        common(sp)
        pretrained_flag(sp)
        sp.add_argument("--epochs", type=int, default=None)
        sp.add_argument("--fine-tune-epochs", type=int, default=None)
        sp.add_argument("--fine-tune-at", type=int, default=None)
        sp.add_argument("--repeats", type=int, default=None,
                        help="dataset passes per epoch (the dense "
                             "preset's repeat(2))")
        sp.add_argument("--central-storage", action="store_true",
                        help="host-resident parameter store, broadcast "
                             "per step (the reference's use_mirror=False "
                             "CentralStorageStrategy toggle)")
        sp.add_argument("--cache-features", action="store_true",
                        help="fine-tune on cached frozen-backbone "
                             "activations (prefix computed once instead "
                             "of every step; numerically equivalent)")
        sp.add_argument("--resumable", action="store_true",
                        help="checkpoint the training loop after every "
                             "epoch under <path>/dist_ckpt and resume "
                             "from there on restart (requires --path)")
        sp.add_argument("--checkpoint-every", type=int, default=1,
                        help="with --resumable: epochs between loop "
                             "checkpoints (the final epoch always "
                             "saves; a blocking orbax save per short "
                             "epoch can dominate the epoch itself)")
        sp.add_argument("--stream", action="store_true",
                        help="decode training batches from disk on the "
                             "fly (datasets larger than host RAM) "
                             "instead of materializing the train split; "
                             "needs a real --data-dir IDC tree")
        sp.add_argument("--decode-workers", type=int, default=0,
                        help="with --stream: fan batch decoding out to "
                             "N worker processes (round-robin whole "
                             "batches; bit-identical stream, scales "
                             "with host cores)")
        sp.add_argument("--model-parallel", type=int, default=1,
                        help="shard weights channel-wise over a 'model' "
                             "mesh axis of this size (tensor parallelism "
                             "via GSPMD, tp.py); composes with data "
                             "parallelism over the remaining devices")

    sp = sub.add_parser("fed", help="federated averaging (FedAvg)")
    common(sp)
    pretrained_flag(sp)
    sp.add_argument("--rounds", type=int, default=None)
    sp.add_argument("--iid", dest="iid", action="store_true", default=None)
    sp.add_argument("--noniid", dest="iid", action="store_false")
    sp.add_argument("--num-clients", type=int, default=None)
    sp.add_argument("--local-epochs", type=int, default=None)
    sp.add_argument("--pretrain-epochs", type=int, default=None)
    sp.add_argument("--checkpoint-every", type=int, default=10,
                    help="save the federated server state every N rounds "
                         "(plus once at the end); a per-round blocking "
                         "orbax save would dominate the ~50 ms round")
    sp.add_argument("--aggregator", default="mean",
                    choices=("mean", "trimmed_mean", "median",
                             "norm_clip"),
                    help="round-boundary aggregation "
                         "(federated/robust.py): mean = example-"
                         "weighted FedAvg; trimmed_mean/median bound "
                         "Byzantine influence coordinate-wise; "
                         "norm_clip L2-clips each client's update")
    sp.add_argument("--trim", type=int, default=1,
                    help="clients trimmed per side with "
                         "--aggregator trimmed_mean (tolerates that "
                         "many Byzantine clients; needs > 2*trim "
                         "participants)")
    sp.add_argument("--clip-norm", type=float, default=10.0,
                    help="per-client update L2 bound with "
                         "--aggregator norm_clip")
    sp.add_argument("--faults", default=None,
                    help="fault-injection plan (faults.py), e.g. "
                         "'sign_flip:0-2:x1000,crash:5' — deterministic "
                         "per-round client faults applied before "
                         "aggregation, for resilience drills")
    sp.add_argument("--round-timeout", type=float, default=None,
                    help="per-round wall budget in seconds; a slower "
                         "round is discarded and retried with a "
                         "reseeded client subset (federated/driver.py)")
    sp.add_argument("--max-round-retries", type=int, default=2,
                    help="retries per failed round before the run "
                         "aborts with RoundFailure")
    sp.add_argument("--loss-spike-ratio", type=float, default=10.0,
                    help="divergence detector: a round whose train loss "
                         "exceeds this multiple of the last good "
                         "round's is rolled back (0 disables)")
    sp.add_argument("--population", type=int, default=0,
                    help="population mode: train over N VIRTUAL clients "
                         "(federated/population.py) whose shards derive "
                         "lazily from (seed, id) — memory is bounded by "
                         "the cohort, not N. 0 = classic materialized "
                         "mode. Skips the pretrain phase; --faults then "
                         "takes the population grammar "
                         "(kind:rounds[:param][@c<id>,...], fractions "
                         "like crash:2:0.1%)")
    sp.add_argument("--cohort", type=int, default=32,
                    help="clients sampled per round in population mode "
                         "(deterministic per (seed, round))")
    sp.add_argument("--cohort-wave", type=int, default=0,
                    help="streamed-aggregation wave size (must divide "
                         "the cohort; 0 = one wave per cohort). Server "
                         "memory is O(wave), constant in population "
                         "and cohort size")
    sp.add_argument("--weighted-sampling", action="store_true",
                    help="sample cohorts proportional to each virtual "
                         "client's (seeded) dataset-size weight instead "
                         "of uniformly")
    sp.add_argument("--client-examples", type=int, default=16,
                    help="examples per virtual client shard in "
                         "population mode")
    sp.add_argument("--async-buffer", type=int, default=0,
                    help="population mode: buffered-async FedAvg "
                         "(FedBuff) — client completions fill a buffer "
                         "of this size, each full buffer triggers one "
                         "staleness-weighted server update instead of "
                         "a round barrier. 0 = synchronous streamed "
                         "rounds")
    sp.add_argument("--staleness-decay", type=float, default=0.9,
                    help="async mode: per-version weight discount for "
                         "stale updates (weight x decay^staleness), in "
                         "(0, 1]; 1 = no discount")
    sp.add_argument("--model", default=None,
                    choices=("vgg16", "mobilenet_v2", "densenet201",
                             "small_cnn"),
                    help="population mode: override the preset model "
                         "(small_cnn = CPU-scale population drills; "
                         "classic mode keeps the preset's backbone)")
    sp.add_argument("--fault-delay-ms", type=float, default=0.0,
                    help="population mode: wall-clock delay per "
                         "straggler staleness unit (lag k completes "
                         "k x this late) — arms the sync round "
                         "BARRIER sleep and the async arrival lag, "
                         "so straggler drills are wall-clock-real; "
                         "0 = stale-params-only stragglers (sync) / "
                         "inert stragglers (async)")

    sp = sub.add_parser("secure-fed", aliases=["secure_fed"],
                        help="secure-aggregation FedAvg")
    common(sp)
    sp.add_argument("--rounds", type=int, default=None)
    sp.add_argument("--percent", type=float, default=None)
    sp.add_argument("--num-clients", type=int, default=None)
    sp.add_argument("--local-epochs", type=int, default=None)
    sp.add_argument("--paillier", action="store_true", default=None,
                    help="host-side Paillier parity mode instead of "
                             "pairwise masks")
    sp.add_argument("--mask-impl", default="threefry",
                    choices=("threefry", "pallas", "auto"),
                    help="PRG for the pairwise masks: XLA threefry "
                         "(default; cryptographic), the fused Pallas "
                         "hash-PRG kernel, or auto (pallas on TPU above "
                         "the measured crossover — see the threat-model "
                         "note in secure.make_secure_fedavg_round)")
    sp.add_argument("--async-buffer", type=int, default=0,
                    help="rejected: buffered-async aggregation cannot "
                         "compose with the pairwise-mask protocol (the "
                         "build explains why) — exists so the drill "
                         "teaches instead of silently ignoring the "
                         "flag")

    sp = sub.add_parser("attention",
                        help="sequence-parallel transformer classifier "
                             "(beyond-reference: ring attention as a "
                             "training workload)")
    common(sp)
    sp.add_argument("--seq-len", type=int, default=128)
    sp.add_argument("--features", type=int, default=8)
    sp.add_argument("--embed-dim", type=int, default=64)
    sp.add_argument("--num-heads", type=int, default=4)
    sp.add_argument("--mlp-dim", type=int, default=128)
    sp.add_argument("--num-blocks", type=int, default=2)
    sp.add_argument("--steps", type=int, default=300)
    sp.add_argument("--seq-parallel", type=int, default=0,
                    help="ring size over the 'seq' mesh axis; remaining "
                         "devices form the 'data' axis (0 = largest "
                         "power of two that divides the device count, "
                         "capped at 4)")
    sp.add_argument("--layout", choices=("contiguous", "zigzag"),
                    default="contiguous",
                    help="causal sequence layout (zigzag balances the "
                         "causal ring schedule, ~2x fewer FLOPs)")
    sp.add_argument("--block-impl", choices=("jnp", "pallas"),
                    default="jnp",
                    help="ring block engine (pallas keeps scores in "
                         "VMEM; needs t_local multiples of 128/256)")
    sp.add_argument("--remat", action="store_true",
                    help="jax.checkpoint each transformer block: the "
                         "backward recomputes block activations instead "
                         "of storing them (long-context memory lever)")
    sp.add_argument("--dropout", type=float, default=0.0,
                    help="residual dropout rate inside each block "
                         "(after attention and after the MLP)")
    sp.add_argument("--patch-size", type=int, default=5,
                    help="with --data-dir: each image becomes a raster "
                         "sequence of patch-size^2-pixel tokens "
                         "(data.sequences.patchify); 1 = per-pixel "
                         "sequence. --seq-len/--features are then "
                         "derived from the images, not the flags")
    sp.add_argument("--image-size", type=int, default=50,
                    help="with --data-dir: decode size of the IDC "
                         "patches (the reference's 50)")

    sp = sub.add_parser("lm",
                        help="causal LM through the ring: train "
                             "next-token on the counting task, then "
                             "greedy-generate via the ring-sharded "
                             "KV-cache decoder (beyond-reference)")
    common(sp)
    sp.add_argument("--vocab", type=int, default=16)
    sp.add_argument("--seq-len", type=int, default=64)
    sp.add_argument("--embed-dim", type=int, default=64)
    sp.add_argument("--num-heads", type=int, default=4)
    sp.add_argument("--mlp-dim", type=int, default=128)
    sp.add_argument("--num-blocks", type=int, default=2)
    sp.add_argument("--steps", type=int, default=200)
    sp.add_argument("--fsdp", type=int, default=0,
                    help="FSDP degree: shard params AND optimizer "
                         "state over a 'data' mesh axis of this size "
                         "(partition.py rules, registry rule set "
                         "'lm'); 0 = off (replicated state, the "
                         "historical layout)")
    sp.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel degree: shard the attention/"
                         "MLP/head weights over a 'model' mesh axis of "
                         "this size (Megatron orientation, "
                         "docs/SHARDING.md); composes with --fsdp on a "
                         "('data', 'model', 'seq') mesh; 0 = off")
    sp.add_argument("--seq-parallel", type=int, default=0,
                    help="ring size over the 'seq' mesh axis (0 = "
                         "largest dividing power of two, capped at 4)")
    sp.add_argument("--layout", choices=("contiguous", "zigzag"),
                    default="contiguous")
    sp.add_argument("--block-impl", choices=("jnp", "pallas"),
                    default="jnp")
    sp.add_argument("--remat", action="store_true")
    sp.add_argument("--dropout", type=float, default=0.0)
    sp.add_argument("--generate", type=int, default=12,
                    help="tokens to generate after training through "
                         "the KV-cache decoder (0 = skip); emitted in "
                         "ONE fused device dispatch (models/lm.py "
                         "Generator)")
    sp.add_argument("--temperature", type=float, default=0.0,
                    help="sampling temperature for --generate "
                         "(0 = greedy argmax, the default)")
    sp.add_argument("--top-k", type=int, default=0,
                    help="restrict sampling to the k most likely "
                         "tokens (0 = no restriction; needs "
                         "--temperature > 0)")

    sp = sub.add_parser("serve",
                        help="continuous-batching LM serving engine: "
                             "fixed decode slots, masked fused windows, "
                             "FIFO admission with backpressure "
                             "(serve/, beyond-reference)")
    sp.add_argument("--path", default=None,
                    help="artifact root (serving events stream to "
                         "<path>/logs/serve.jsonl)")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--host-devices", type=int, default=0,
                    help="force N virtual CPU devices (TPU stand-in)")
    sp.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler trace of the serve loop "
                         "here (TensorBoard-viewable)")
    sp.add_argument("--trace-out", default=None,
                    help="write a Chrome trace-event JSON of the serve "
                         "loop's spans (admission, prefill chunks, "
                         "decode windows, collects) here — "
                         "Perfetto-loadable")
    sp.add_argument("--vocab", type=int, default=16)
    sp.add_argument("--t-max", type=int, default=64,
                    help="cache capacity per slot (prompt + generation)")
    sp.add_argument("--embed-dim", type=int, default=32)
    sp.add_argument("--num-heads", type=int, default=2)
    sp.add_argument("--mlp-dim", type=int, default=64)
    sp.add_argument("--num-blocks", type=int, default=2)
    sp.add_argument("--seq-parallel", type=int, default=1,
                    help="ring size over the 'seq' mesh axis for the "
                         "serving mesh (caches shard over it)")
    sp.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel degree: serve with the "
                         "model's weights sharded over a 'model' mesh "
                         "axis of this size (partition.py rule set "
                         "'lm') while the KV caches keep their seq-"
                         "ring layout — params and KV shard "
                         "independently; 0 = off (replicated params)")
    sp.add_argument("--fsdp", type=int, default=0,
                    help="accepted for symmetry with the lm/profile "
                         "verbs but must stay 0 here: FSDP shards the "
                         "optimizer+param state over the batch axis at "
                         "TRAIN time; a serving engine holds no "
                         "optimizer state and prefills [1, P] batches "
                         "— use --tp for serving-side param sharding")
    sp.add_argument("--train-steps", type=int, default=0,
                    help="train the counting task this many steps "
                         "before serving (0 = serve from random init; "
                         "the engine exercises identically either way)")
    sp.add_argument("--slots", type=int, default=4,
                    help="concurrent decode slots")
    sp.add_argument("--window", type=int, default=8,
                    help="tokens per fused decode dispatch")
    sp.add_argument("--requests", type=int, default=16,
                    help="synthetic trace length (ignored with --trace)")
    sp.add_argument("--rate", type=float, default=50.0,
                    help="synthetic Poisson arrival rate, requests/s")
    sp.add_argument("--trace", default=None,
                    help="JSONL request trace to replay instead of the "
                         "synthetic Poisson one (serve.load_trace "
                         "format)")
    sp.add_argument("--realtime", action="store_true",
                    help="honor trace arrival times on the wall clock "
                         "(default: replay as fast as the engine "
                         "drains, order kept)")
    sp.add_argument("--temperature", type=float, default=0.0)
    sp.add_argument("--top-k", type=int, default=0)
    sp.add_argument("--eos", type=int, default=None,
                    help="stop token id (default: none — requests run "
                         "to their token budget)")
    sp.add_argument("--max-queue-depth", type=int, default=64,
                    help="admission-queue backpressure bound")
    sp.add_argument("--max-prefills-per-cycle", type=int, default=1,
                    help="prefill-vs-decode interleave cap per cycle")
    sp.add_argument("--prefill-chunk", type=int, default=0,
                    help="chunked prefill: admit prompts C tokens per "
                         "decode window instead of one monolithic "
                         "dispatch (0 = off; must divide --t-max). "
                         "Long prompts stop stalling in-flight decodes")
    sp.add_argument("--prefix-cache-mb", type=float, default=0.0,
                    help="radix prefix cache budget in MB (0 = off; "
                         "needs --prefill-chunk): requests sharing a "
                         "token prefix reuse chunk-boundary KV "
                         "snapshots instead of recomputing them")
    sp.add_argument("--kv-dtype", default="bf16",
                    choices=("bf16", "int8"),
                    help="ring-cache K/V storage: int8 halves HBM per "
                         "slot (per-(slot,head) scales, ~2x slots per "
                         "budget) at the cost of bounded logit drift — "
                         "leave bf16 when exact parity matters")
    sp.add_argument("--kv-page-size", type=int, default=0,
                    help="paged KV (0 = off, needs --kv-pages and "
                         "--prefill-chunk): replace the per-slot "
                         "[t_max] ring rows with fixed-size cache "
                         "pages + per-slot page tables, so HBM holds "
                         "tokens actually resident instead of every "
                         "slot's worst case. Must divide "
                         "--prefill-chunk (and t-max)")
    sp.add_argument("--kv-pages", type=int, default=0,
                    help="page-pool size for --kv-page-size: the HBM "
                         "budget in pages, shared by slots and prefix-"
                         "cache snapshots (pages*size must cover at "
                         "least one t-max request)")
    sp.add_argument("--kv-decode-reserve", type=int, default=0,
                    help="decode tokens PRE-reserved per admission on "
                         "the paged engine (0 = the full budget, "
                         "never exhausts mid-decode; smaller admits "
                         "optimistically and grows grants mid-decode, "
                         "quarantining honestly on exhaustion)")
    sp.add_argument("--spec-decode", action="store_true",
                    help="speculative decoding (models/draft.py + the "
                         "engine's fixed-k verify program): an n-gram "
                         "prompt-lookup drafter proposes --draft-k "
                         "continuation tokens per slot from the "
                         "slot's own stream, ONE batched verify "
                         "dispatch accepts the prefix the model "
                         "itself would have emitted (+ its own pick "
                         "at the first miss) — up to k+1 tokens per "
                         "dispatch on repetitive/templated traffic, "
                         "token-identical to plain decode")
    sp.add_argument("--draft-k", type=int, default=8,
                    help="draft tokens per slot per verify dispatch "
                         "(the verify program's ONE compiled shape)")
    sp.add_argument("--ngram-order", type=int, default=3,
                    help="longest trailing n-gram the prompt-lookup "
                         "drafter matches against the stream's "
                         "history (falls back to shorter n-grams "
                         "down to 1)")
    sp.add_argument("--drafter", choices=sorted(SERVE_DRAFTERS),
                    default="ngram",
                    help="which drafter proposes under --spec-decode: "
                         + "; ".join(f"'{name}' = {entry[2]}"
                                     for name, entry
                                     in sorted(SERVE_DRAFTERS.items())))
    sp.add_argument("--draft-ckpt", default=None, metavar="DIR",
                    help="distilled draft-LM checkpoint directory "
                         "(models/draft_lm.save_draft_lm: sharded "
                         "params + draft_config.json sidecar) — "
                         "required by --drafter learned/chained; the "
                         "restore re-resolves layout against the "
                         "serving mesh")
    sp.add_argument("--metrics-port", type=int, default=None,
                    help="serve GET /metrics (Prometheus text "
                         "exposition of the live registry) and GET "
                         "/healthz (last-tick age, queue depth, slot "
                         "occupancy) on 127.0.0.1:PORT for the run's "
                         "duration (0 = OS-assigned port, printed; "
                         "observe/exporter.py)")
    sp.add_argument("--serve-faults", default=None,
                    help="deterministic serve fault drill "
                         "(serve/faults.py), tick-indexed: e.g. "
                         "'nan_logits:3:0,stall:5-8:0.02,burst:2:16,"
                         "crash:40' — poisons/stalls/bursts/crashes "
                         "replay bit-identically; pair with "
                         "--max-retries and --journal to watch the "
                         "recovery paths work")
    sp.add_argument("--max-retries", type=int, default=0,
                    help="bounded re-admission for requests recovered "
                         "from a quarantined slot or a failed prefill "
                         "dispatch (0 = off; arming this also turns "
                         "on the per-cycle slot health checks)")
    sp.add_argument("--retry-backoff-ms", type=float, default=50.0,
                    help="base delay between retry attempts "
                         "(exponential: doubles per retry)")
    sp.add_argument("--journal", default=None,
                    help="request-journal WAL path (serve/journal.py): "
                         "accepted requests, per-tick progress, and "
                         "finishes; at startup any in-flight requests "
                         "a previous crashed run left in the file are "
                         "re-admitted through the normal path")
    sp.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent compile cache "
                         "(serve/compile_cache.py): AOT-serialized "
                         "decode/sample executables keyed on model "
                         "config + mesh + jaxlib version. First run "
                         "compiles and stores; later runs (and warm "
                         "replica spin-ups) deserialize instead of "
                         "recompiling")
    sp.add_argument("--brownout", action="store_true",
                    help="arm the staged degradation controller "
                         "(serve/brownout.py): when a declared SLO "
                         "burns or the queue passes the watermark, "
                         "pause prefix-cache writes -> clamp "
                         "max_new_tokens -> shed new submits (status "
                         "'shed'), restoring with hysteresis")
    sp.add_argument("--brownout-queue-high", type=int, default=None,
                    help="queue-depth escalation watermark for "
                         "--brownout (default: half --max-queue-depth)")
    sp.add_argument("--brownout-clamp-tokens", type=int, default=8,
                    help="the max_new_tokens bound brownout stage 2 "
                         "applies to new admissions")
    sp.add_argument("--brownout-dwell-ms", type=float, default=250.0,
                    help="minimum time between brownout escalations "
                         "(one stage per dwell while the signal "
                         "fires; lower it for fast drills)")
    sp.add_argument("--brownout-clear-ms", type=float, default=1000.0,
                    help="how long the signal must stay clear before "
                         "each one-stage restore (the hysteresis)")
    sp.add_argument("--slo-ttft-p95-ms", type=float, default=None,
                    help="declare a TTFT SLO: p95 of submit->first-"
                         "token <= this many ms, burn-rate-alerted "
                         "over sliding windows (observe/slo.py; "
                         "slo_alert events go to the run jsonl)")
    sp.add_argument("--slo-error-rate", type=float, default=None,
                    help="declare an error-rate SLO: at most this "
                         "fraction of requests may fail (rejected, "
                         "error, or deadline/timeout)")
    sp.add_argument("--slo-window-s", type=float, default=60.0,
                    help="the SLO engine's SHORT evaluation window in "
                         "seconds (the long window is 5x this)")
    sp.add_argument("--tenants", default=None,
                    help="multi-tenant serving (serve/tenancy.py): "
                         "comma-separated tenant names, first = the "
                         "default for untagged requests; the synthetic "
                         "Poisson trace tags arrivals round-robin. "
                         "Per-tenant quotas/SLOs isolate a flooding "
                         "tenant from its neighbors")
    sp.add_argument("--tenant-quota", action="append", default=None,
                    metavar="NAME=SLOTS[:QUEUED[:PAGES]]",
                    help="per-tenant admission quota (repeatable): "
                         "resident decode slots, queued requests, and "
                         "KV page budget — each an int >= 1 or '-' "
                         "for unlimited (e.g. acme=2:8:- caps acme at "
                         "2 slots and 8 queued). Needs --tenants")
    sp.add_argument("--tenant-slo-ttft-ms", action="append",
                    default=None, metavar="[NAME=]MS",
                    help="per-tenant TTFT p95 SLO in ms (repeatable): "
                         "NAME=MS for one tenant, a bare number for "
                         "every tenant. Burn-rate alerted per tenant "
                         "(ttft:<name>) and the tenant's own brownout "
                         "trigger — one tenant's flood sheds that "
                         "tenant only. Needs --tenants")
    sp.add_argument("--save-ckpt", default=None, metavar="DIR",
                    help="export the serving params as a sharded "
                         "checkpoint (checkpoint/sharded.py) before the "
                         "trace replays: each device writes only its "
                         "own shards, MANIFEST.json commits the save "
                         "atomically. Pair with --train-steps to mint "
                         "a --rollout candidate")
    sp.add_argument("--rollout", default=None, metavar="CKPT_DIR",
                    help="zero-downtime weight rollout "
                         "(checkpoint/rollout.py): mid-trace, restore "
                         "this sharded checkpoint against the SERVING "
                         "mesh + partition rules, canary "
                         "--canary-fraction of the traffic onto it, "
                         "compare error rate and TTFT p95 against the "
                         "live fleet-of-one, then promote (hot-swap "
                         "the live weights, zero recompile) or roll "
                         "back — no request is dropped or duplicated "
                         "either way")
    sp.add_argument("--canary-fraction", type=float, default=None,
                    help="traffic share routed to the --rollout canary "
                         "while it is open, in (0, 1] (tenant-affine: "
                         "whole tenants land on one side; default "
                         "0.25)")
    sp.add_argument("--canary-requests", type=int, default=None,
                    help="canary finishes required before the "
                         "promote/rollback verdict (default 4); a "
                         "trace that drains short of this ROLLS BACK "
                         "— insufficient evidence is not health")
    sp.add_argument("--rollout-at", type=float, default=None,
                    help="fraction of the trace submitted before the "
                         "rollout opens, in [0, 1) (default 0.25: the "
                         "live side banks baseline latency first)")
    sp.add_argument("--rollout-adapters", type=int, default=None,
                    metavar="RANK",
                    help="per-tenant adapter hot-swap drill, the cheap "
                         "first rung of a rollout: register rank-RANK "
                         "logit adapters for every tenant, serve the "
                         "trace, then swap a re-seeded bank in live — "
                         "no recompile, no dropped request. Needs "
                         "--tenants")

    sp = sub.add_parser(
        "serve-cluster", aliases=["serve_cluster"],
        help="disaggregated multi-replica serving (serve/cluster/): a "
             "router places requests on N engine replicas by health/"
             "load/page headroom/SLO burn, dedicated prefill replicas "
             "hand completed KV snapshots to decode replicas through "
             "the cluster prefix registry, and a killed replica's "
             "journaled requests migrate onto survivors")
    sp.add_argument("--path", default=None,
                    help="artifact root (cluster events stream to "
                         "<path>/logs/cluster.jsonl)")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--host-devices", type=int, default=0,
                    help="force N virtual CPU devices — each replica "
                         "takes its own device slice")
    sp.add_argument("--trace-out", default=None,
                    help="write a Chrome trace-event JSON of the "
                         "cluster's spans (placements, handoffs, "
                         "migrations, every replica's serve loop) "
                         "here")
    sp.add_argument("--vocab", type=int, default=16)
    sp.add_argument("--t-max", type=int, default=64)
    sp.add_argument("--embed-dim", type=int, default=32)
    sp.add_argument("--num-heads", type=int, default=2)
    sp.add_argument("--mlp-dim", type=int, default=64)
    sp.add_argument("--num-blocks", type=int, default=2)
    sp.add_argument("--replicas", type=int, default=2,
                    help="decode-capable replicas (each its own "
                         "engine on its own device slice)")
    sp.add_argument("--prefill-replicas", type=int, default=0,
                    help="dedicated prefill replicas: they never "
                         "decode — they drive chunked prefill and "
                         "publish boundary KV snapshots into the "
                         "cluster prefix registry for decode replicas "
                         "to adopt (needs --prefill-chunk and "
                         "--prefix-cache-mb)")
    sp.add_argument("--slots", type=int, default=4,
                    help="decode slots per replica")
    sp.add_argument("--window", type=int, default=8)
    sp.add_argument("--max-queue-depth", type=int, default=64,
                    help="per-replica admission-queue bound")
    sp.add_argument("--prefill-chunk", type=int, default=0,
                    help="chunked prefill (0 = off; must divide "
                         "--t-max) — required for prefill replicas "
                         "and the prefix registry")
    sp.add_argument("--prefix-cache-mb", type=float, default=0.0,
                    help="per-replica radix prefix cache budget in MB")
    sp.add_argument("--registry-mb", type=float, default=0.0,
                    help="cluster prefix-registry budget in MB (0 = "
                         "off): chunk-boundary snapshots published by "
                         "any replica, adopted by every other — a hot "
                         "system prompt is prefilled ONCE cluster-wide")
    sp.add_argument("--requests", type=int, default=16,
                    help="synthetic trace length (ignored with "
                         "--trace)")
    sp.add_argument("--rate", type=float, default=50.0,
                    help="synthetic Poisson arrival rate, requests/s")
    sp.add_argument("--trace", default=None,
                    help="JSONL request trace to replay "
                         "(serve.load_trace format)")
    sp.add_argument("--realtime", action="store_true",
                    help="honor trace arrival times on the wall clock")
    sp.add_argument("--eos", type=int, default=None)
    sp.add_argument("--temperature", type=float, default=0.0)
    sp.add_argument("--top-k", type=int, default=0)
    sp.add_argument("--journal-dir", default=None,
                    help="directory for per-replica journal WALs "
                         "(<dir>/journal-<replica>.jsonl) — required "
                         "for the kill drill's migration")
    sp.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent compile cache shared by every "
                         "replica (serve/compile_cache.py): the first "
                         "replica compiles and stores, the rest — and "
                         "any autoscaled spin-up — deserialize warm")
    sp.add_argument("--autoscale-max", type=int, default=None,
                    metavar="N",
                    help="arm the autoscaler "
                         "(serve/cluster/autoscaler.py): scale the "
                         "decode fleet between --replicas and N from "
                         "the replicas' own health documents (queue "
                         "depth, shedding, page headroom) with dwell "
                         "+ cooldown hysteresis; scale-down drains "
                         "the least-loaded replica and live-migrates "
                         "its in-flight slots onto survivors")
    sp.add_argument("--max-retries", type=int, default=2,
                    help="router-level re-placement bound per request "
                         "(migrations + hedges)")
    sp.add_argument("--hedge-after-ms", type=float, default=None,
                    help="duplicate a still-unfinished request onto a "
                         "second replica this long after placement "
                         "(first result wins; off by default)")
    sp.add_argument("--brownout-queue-high", type=int, default=None,
                    help="arm a per-replica brownout controller at "
                         "this queue-depth watermark (also the drain "
                         "mechanism: a draining replica jumps to its "
                         "shed stage)")
    sp.add_argument("--kill-replica", type=int, default=None,
                    help="failover drill: hard-kill replica INDEX "
                         "after --kill-after-steps router steps and "
                         "migrate its journaled requests onto the "
                         "survivors (needs --journal-dir)")
    sp.add_argument("--kill-after-steps", type=int, default=4,
                    help="router steps before the --kill-replica "
                         "drill fires")
    sp.add_argument("--drain-replica", type=int, default=None,
                    help="drain drill: gracefully drain replica INDEX "
                         "after --kill-after-steps router steps "
                         "(placement stops, in-flight work completes)")
    sp.add_argument("--metrics-port", type=int, default=None,
                    help="serve the FLEET observability surfaces on "
                         "127.0.0.1:PORT for the run's duration: GET "
                         "/metrics merges every replica's registry "
                         "into one replica-labeled exposition plus "
                         "fleet rollups, GET /healthz embeds every "
                         "replica's health document with autoscaler "
                         "and compile-cache state (0 = OS-assigned "
                         "port, printed; serve/cluster/telemetry.py)")
    sp.add_argument("--watchdog", action="store_true",
                    help="arm the cluster anomaly watchdogs "
                         "(speculative accept-rate collapse, per-"
                         "replica compile churn, migration-rate "
                         "spikes, canary-vs-baseline SLO divergence): "
                         "one detector pass per router step, each "
                         "firing emits a frozen cluster_anomaly jsonl "
                         "record and bumps cluster_anomalies_total")

    sp = sub.add_parser(
        "profile",
        help="performance attribution over a subsystem's hot loop "
             "(observe/profile.py): run N steps, report every compiled "
             "program's XLA cost/memory account, a compute-bound vs "
             "bandwidth-bound roofline verdict, device-wait vs "
             "host-gap step-time attribution, and the compile-churn "
             "watchdog's findings; writes frozen-schema "
             "profile_program/profile_step jsonl (rendered by `stats`)")
    sp.add_argument("--model", required=True,
                    choices=("vgg", "mobile", "dense", "small", "serve",
                             "lm"),
                    help="which hot loop to profile: a backbone's "
                         "fine-tune train step (vgg/mobile/dense, the "
                         "bench.py configurations; `small` is the tiny "
                         "CPU-smoke CNN), the continuous-batching "
                         "serve decode loop, or the LM train step "
                         "(`lm` — composes with --fsdp/--tp to "
                         "account the SHARDED step's per-device peak "
                         "HBM against the replicated figure)")
    sp.add_argument("--fsdp", type=int, default=0,
                    help="with --model lm: FSDP degree (params + "
                         "optimizer state shard over a 'data' axis of "
                         "this size; partition.py rule set 'lm'); the "
                         "epilogue reports per-device peak HBM from "
                         "XLA program accounting")
    sp.add_argument("--tp", type=int, default=0,
                    help="with --model lm: tensor-parallel degree "
                         "(weights shard over a 'model' axis); "
                         "composes with --fsdp")
    sp.add_argument("--steps", type=int, default=None,
                    help="measured steps/windows (default: 30 on an "
                         "accelerator, 4 on CPU)")
    sp.add_argument("--batch-size", type=int, default=None,
                    help="per-chip batch for the train loops (default: "
                         "the bench.py batch on an accelerator, 8 on "
                         "CPU — match bench to compare MFU)")
    sp.add_argument("--path", default=None,
                    help="artifact root (profile events stream to "
                         "<path>/logs/profile.jsonl)")
    sp.add_argument("--out", default=None,
                    help="explicit profile jsonl path (overrides "
                         "--path's default location)")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--host-devices", type=int, default=0,
                    help="force N virtual CPU devices (TPU stand-in)")
    sp.add_argument("--compile-limit", type=int, default=5,
                    help="compile-churn watchdog: flag any program "
                         "compiled more than this many times during "
                         "the run")
    sp.add_argument("--peak-tflops", type=float, default=None,
                    help="override/declare the backend's peak dense "
                         "bf16 TFLOP/s (required with --peak-gbps for "
                         "roofline verdicts on backends the table "
                         "does not know, e.g. CPU)")
    sp.add_argument("--peak-gbps", type=float, default=None,
                    help="override/declare the backend's peak memory "
                         "bandwidth in GB/s")
    sp.add_argument("--depthwise-impl", default="grouped",
                    choices=("grouped", "taps", "fused"),
                    help="with --model mobile: the depthwise lowering "
                         "(models/core.py depthwise_conv2d). 'fused' "
                         "runs the Pallas depthwise+BN+relu6 chain "
                         "(ops/fused_conv.py) and merges its analytic "
                         "FLOPs/bytes into the train.step account — "
                         "Pallas calls are opaque to XLA "
                         "cost_analysis, so without the merge the "
                         "roofline verdict would read from "
                         "under-counted zeros")
    sp.add_argument("--churn-drill", action="store_true",
                    help="end the run with a deliberately "
                         "shape-varying jitted loop so the "
                         "compile-churn watchdog demonstrably fires "
                         "(drill; a clean run stays silent)")
    sp.add_argument("--trace-out", default=None,
                    help="also export the run's spans as Chrome "
                         "trace-event JSON (Perfetto-loadable)")

    sp = sub.add_parser("stats",
                        help="offline summary of any run jsonl (train, "
                             "fed, or serve): per-event counts, "
                             "percentiles over every numeric field, "
                             "timer/span timing tables, and the last "
                             "metrics snapshot — no re-run needed")
    sp.add_argument("jsonl", nargs="+",
                    help="path(s) to run.jsonl / serve.jsonl / "
                         "exported span jsonl — several files (e.g. "
                         "every replica's log plus the router's) "
                         "merge into ONE summary, so --request "
                         "renders a cross-replica timeline")
    sp.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object instead "
                         "of the human table (includes the per-request "
                         "timeline table under 'requests')")
    sp.add_argument("--request", default=None, metavar="RID",
                    help="render ONE request's timeline (every serve_* "
                         "event and rid-stamped span for that id, "
                         "time-ordered) instead of the whole-run "
                         "summary")
    sp.add_argument("--top", type=int, default=15,
                    help="rows in the span self-time (exclusive-time) "
                         "table — the flame-style 'where does the "
                         "time go' answer from any span export")

    sp = sub.add_parser("convert-weights", aliases=["convert_weights"],
                        help="one-time offline conversion of a Keras "
                             "save_weights .h5 into the framework's .npz "
                             "pytree artifact")
    sp.add_argument("input", help="Keras .h5 weights file")
    sp.add_argument("output", help="destination .npz")
    sp.add_argument("--model", default=None,
                    choices=("vgg16", "mobilenet_v2", "densenet201"),
                    help="validate converted tensors against this "
                         "backbone's shapes")

    ns = p.parse_args(argv)
    ns.preset_key = ns.preset_key.replace("-", "_")
    return ns


def _apply_overrides(preset, ns, fields):
    kw = {}
    for f in fields:
        v = getattr(ns, f, None)
        if v is not None:
            kw[f] = v
    return dataclasses.replace(preset, **kw) if kw else preset


def _logger(ns):
    from idc_models_tpu.observe import JsonlLogger

    if ns.path is None:
        return None
    return JsonlLogger(Path(ns.path) / "logs" / "run.jsonl")


def _finish_logger(logger) -> None:
    """The shared tail of every logged run: append ONE metrics_snapshot
    record (the process-wide registry's counters/gauges/histograms —
    a NEW additive event type the `stats` verb renders) and close."""
    if not logger:
        return
    from idc_models_tpu.observe import REGISTRY

    REGISTRY.log_snapshot(logger)
    logger.close()


class _DrainRequested(Exception):
    """Raised from the SIGTERM handler to unwind the serve loop into
    the graceful-drain path (admissions stop, in-flight work
    finishes, the journal flushes)."""


def _arm_sigterm():
    """Install a SIGTERM handler that raises _DrainRequested in the
    main thread. Returns the previous handler so the caller can
    restore it, or None when installation is impossible (non-main
    thread — e.g. a test harness driving the verb from a worker)."""
    import signal

    def _handler(signum, frame):
        raise _DrainRequested()

    try:
        return signal.signal(signal.SIGTERM, _handler)
    except ValueError:
        return None


def _disarm_sigterm(prev) -> None:
    import signal

    if prev is None:
        return
    try:
        signal.signal(signal.SIGTERM, prev)
    except ValueError:
        pass


def _data_root(ns):
    """--data-dir > <path>/data/balanced_IDC_30k > None (synthetic)."""
    root = ns.data_dir
    if root is None and ns.path is not None:
        cand = Path(ns.path) / "data" / "balanced_IDC_30k"
        if cand.exists():
            root = cand
    return root


def _load_idc(ns, image_size, limit):
    from idc_models_tpu.data import synthetic
    from idc_models_tpu.data.idc import ArrayDataset, load_directory

    root = _data_root(ns)
    if root is not None:
        return load_directory(root, image_size=image_size, limit=limit,
                              seed=ns.seed)
    print(f"[idc_models_tpu] no IDC data found; using "
          f"{ns.synthetic_examples} synthetic {image_size}x{image_size} "
          f"patches", file=sys.stderr)
    imgs, labels = synthetic.make_idc_like(ns.synthetic_examples,
                                           size=image_size, seed=ns.seed)
    return ArrayDataset(imgs, labels)


def _streamed_idc_splits(ns, preset, global_batch):
    """80/10/10 split at the FILE level: train as a FileStream (decoded
    per batch), val/test materialized (they are small and eval needs
    ArrayDatasets)."""
    import numpy as np

    from idc_models_tpu.data.idc import (
        ArrayDataset, decode_pairs, list_shuffled_pairs,
    )
    from idc_models_tpu.data.pipeline import FileStream

    root = _data_root(ns)
    if root is None:
        return None
    pairs = list_shuffled_pairs(root, seed=ns.seed,
                                limit=preset.dataset_limit)
    n = len(pairs)
    n_tr, n_va = int(0.8 * n), int(0.1 * n)
    if n_tr < global_batch or n_va == 0 or n - n_tr - n_va == 0:
        sys.exit(f"--stream: {n} files are too few for an 80/10/10 split "
                 f"at global batch {global_batch}")
    train = FileStream(pairs[:n_tr], preset.image_size, global_batch,
                       seed=ns.seed, repeat=preset.repeats,
                       decode_workers=ns.decode_workers)

    def materialize(subset):
        labels = np.asarray([l for _, l in subset], np.int32)
        return ArrayDataset(decode_pairs(subset, preset.image_size), labels)

    val = materialize(pairs[n_tr:n_tr + n_va])
    test = materialize(pairs[n_tr + n_va:])
    return train, val, test


def _fetch_scalars(tree):
    """Fetch a pytree of device scalars in ONE host transfer.

    On the tunneled TPU runtime every individual device->host fetch is a
    ~50-90 ms synchronous round-trip, and `jax.device_get` of a metrics
    dict fetches leaf by leaf — six scalars cost ~0.5 s, 10x the round
    they describe. Stacking on device first makes the whole fetch one
    transfer (measured on the fed CLI: 1.08 -> ~0.2 s/round)."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    if _fetch_scalars._stack is None:
        import jax.numpy as jnp

        _fetch_scalars._stack = jax.jit(
            lambda ls: jnp.stack([jnp.float32(x).reshape(()) for x in ls]))
    vals = np.asarray(_fetch_scalars._stack(leaves))
    return jax.tree.unflatten(treedef, [float(v) for v in vals])


_fetch_scalars._stack = None


def _run_stats(ns):
    """Offline run-log rollup (observe/stats.py): works on any jsonl
    the framework writes — train/fed run.jsonl, serve.jsonl, or a
    tracer's exported span jsonl."""
    import json

    from idc_models_tpu.observe import (
        format_request_timeline, format_summary, summarize_jsonl,
    )

    paths = [Path(p) for p in ns.jsonl]
    for p in paths:
        if not p.exists():
            sys.exit(f"stats: no such file: {p}")
    summary = summarize_jsonl(paths[0] if len(paths) == 1 else paths)
    if ns.request is not None:
        # format_request_timeline owns the unknown-rid message (KeyError)
        # — rendering even on the --json path keeps one validation site
        try:
            text = format_request_timeline(summary, ns.request)
        except KeyError as e:
            sys.exit(f"stats: {e.args[0]}")
        if ns.json:
            print(json.dumps(
                {ns.request: summary["requests"][ns.request]}))
        else:
            print(text)
    elif ns.json:
        print(json.dumps(summary))
    else:
        if ns.top < 1:
            sys.exit(f"stats: --top {ns.top} must be >= 1")
        print(format_summary(summary, top=ns.top))


def _run_profile(ns):
    """Performance attribution over one subsystem's hot loop (ISSUE 9,
    observe/profile.py): program cost/memory accounting through the
    single `program_report` extraction point, a roofline verdict
    (compute-bound vs bandwidth-bound with achieved-fraction-of-roof
    numbers), device-wait vs host-gap step-time attribution from
    `device.sync`-bracketed spans, and the compile-churn watchdog's
    process-wide findings — printed human-readable and written as
    frozen-schema `profile_program`/`profile_step` jsonl events."""
    import json  # noqa: F401  (parity with sibling runners)

    import jax

    from idc_models_tpu.observe import JsonlLogger, REGISTRY, trace
    from idc_models_tpu.observe import profile as prof

    if ns.steps is not None and ns.steps < 1:
        sys.exit(f"profile: --steps {ns.steps} must be >= 1")
    if ns.batch_size is not None and ns.batch_size < 1:
        sys.exit(f"profile: --batch-size {ns.batch_size} must be >= 1")
    if ns.compile_limit < 1:
        sys.exit(f"profile: --compile-limit {ns.compile_limit} must "
                 f"be >= 1")
    if (ns.peak_tflops is None) != (ns.peak_gbps is None):
        sys.exit("profile: --peak-tflops and --peak-gbps declare the "
                 "two axes of one roofline — pass both or neither")
    if ns.fsdp < 0 or ns.tp < 0:
        sys.exit(f"profile: --fsdp/--tp must be >= 0 (0 = off), got "
                 f"{ns.fsdp}/{ns.tp}")
    if (ns.fsdp > 1 or ns.tp > 1) and ns.model != "lm":
        sys.exit(f"profile: --fsdp/--tp shard the LM's rule-based "
                 f"partition layout (--model lm); the {ns.model} "
                 f"model's default rules are replicated")
    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"
    if ns.peak_tflops is not None:
        try:
            prof.register_roof(dev.device_kind, ns.peak_tflops,
                               ns.peak_gbps)
        except ValueError as e:
            sys.exit(f"profile: {e}")
    wd = prof.arm_watchdog(limit=ns.compile_limit)
    # the main() --trace-out context may already have armed a tracer
    # (then the full run, warmups included, lands in the export); the
    # timeline below only consumes the measured region either way
    own = trace.get_tracer() is None
    prev = trace.set_tracer(trace.Tracer()) if own else None
    tr = trace.get_tracer()
    try:
        if ns.model == "serve":
            progs, mark = _profile_serve(ns, on_accel)
        elif ns.model == "lm":
            progs, mark = _profile_lm(ns, on_accel, dev)
        else:
            progs, mark = _profile_train_step(ns, on_accel, dev)
        if ns.churn_drill:
            _profile_churn_drill(ns.compile_limit)
        records = prof.records_since(tr, mark)
    finally:
        prof.disarm_watchdog()
        if own:
            trace.set_tracer(prev)

    timeline = prof.DeviceTimeline().consume(records)
    step_stats = timeline.report()
    print("programs (performance attribution):")
    recs = []
    for name, (cost, roofline, step_ms) in progs.items():
        rec = prof.program_record(cost, roofline, step_ms=step_ms,
                                  device_kind=dev.device_kind)
        recs.append(rec)
        print(prof.format_program(rec))
    print("step-time attribution (device-wait vs host-gap):")
    print(timeline.format_report(step_stats))
    rep = wd.report()
    line = (f"compiles: {rep['total_compiles']} observed, "
            f"{rep['compile_seconds_total']} s total")
    if rep["flagged"]:
        line += (f"; CHURN flagged: {', '.join(rep['flagged'])} "
                 f"(> {rep['limit']} compiles each — a shape/dtype is "
                 f"varying per call)")
    else:
        line += "; churn: none"
    print(line)

    out_path = ns.out or (Path(ns.path) / "logs" / "profile.jsonl"
                          if ns.path else None)
    if out_path:
        with JsonlLogger(out_path) as logger:
            for rec in recs:
                logger.log(event="profile_program", **rec)
            for loop, st in step_stats.items():
                logger.log(event="profile_step",
                           **prof.step_record(loop, st))
            REGISTRY.log_snapshot(logger)
        print(f"profile events written to {out_path}")


def _profile_train_step(ns, on_accel, dev):
    """Profile one backbone's fine-tune train step at the bench.py
    configuration (smoke scale on CPU). Two measured passes: a
    bench-methodology throughput window (k dispatches, ONE data-
    dependent fence — per-step fencing would wreck the MFU number on
    a tunneled runtime) for the roofline verdict, then a FENCED pass
    (one `device.sync` fetch per `profile.step`) for the device-wait
    vs host-gap split."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models import registry, small_cnn
    from idc_models_tpu.observe import profile as prof
    from idc_models_tpu.observe import trace
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, replicate,
        rmsprop, shard_batch,
    )
    from idc_models_tpu.train.losses import (
        binary_cross_entropy, sparse_categorical_cross_entropy,
    )

    from idc_models_tpu.configs import BENCH_TRAIN_CONFIGS

    if ns.model == "small":
        cfg = dict(model=None, image=10, outputs=1, ft=None,
                   lr=1e-3, batch=64)
    else:
        # the SAME table bench.py times against — the acceptance bar
        # is MFU agreement with bench's independently computed figure
        # (within 5%), so the two surfaces must share one config
        name = {"vgg": "vgg16", "mobile": "mobilenet_v2",
                "dense": "densenet201"}[ns.model]
        bc = BENCH_TRAIN_CONFIGS[name]
        cfg = dict(model=name, image=bc["image_size"],
                   outputs=bc["num_outputs"], ft=bc["fine_tune_at"],
                   lr=bc["lr"], batch=bc["batch_per_chip"])
    n_dev = len(jax.devices())
    batch = ns.batch_size or (cfg["batch"] if on_accel else 8)
    steps = ns.steps or (30 if on_accel else 4)
    total = batch * n_dev
    if cfg["model"] is None:
        model = small_cnn(cfg["image"], 3, cfg["outputs"])
        variables = model.init(jax.random.key(ns.seed))
        opt = rmsprop(cfg["lr"])
    else:
        spec = registry.get_model(cfg["model"])
        # BN-freeze only exists on the BN backbones (VGG has none)
        build_kw = ({"bn_frozen_below": cfg["ft"]}
                    if ns.model in ("mobile", "dense") else {})
        if ns.model == "mobile":
            build_kw["depthwise_impl"] = ns.depthwise_impl
        model = spec.build(cfg["outputs"], 3, **build_kw)
        variables = model.init(jax.random.key(ns.seed))
        opt = rmsprop(cfg["lr"],
                      trainable_mask=spec.fine_tune_mask(
                          variables.params, cfg["ft"]))
    loss_fn = (binary_cross_entropy if cfg["outputs"] == 1
               else sparse_categorical_cross_entropy)
    mesh = meshlib.data_mesh()
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, loss_fn,
                        compute_dtype=jnp.bfloat16), mesh)
    rng = np.random.default_rng(ns.seed)
    s = cfg["image"]
    imgs = rng.random((total, s, s, 3)).astype(np.float32)
    labels = rng.integers(0, max(cfg["outputs"], 2),
                          total).astype(np.int32)
    state = replicate(mesh, state)
    x, y = shard_batch(mesh, imgs, labels)
    with prof.compiling("train.step"):
        compiled = step.lower(state, x, y,
                              jax.random.key(ns.seed + 1)).compile()
    cost = prof.program_report(compiled, name="train.step")
    if ns.model == "mobile" and ns.depthwise_impl == "fused":
        # the fused depthwise chains run as Pallas custom calls, which
        # XLA's cost_analysis reports at zero — merge their analytic
        # account so the roofline verdict reads real intensity instead
        # of silently under-counted figures
        from idc_models_tpu.models import mobilenet
        from idc_models_tpu.ops import fused_conv

        k_flops, k_bytes = fused_conv.depthwise_chain_cost(
            mobilenet.fused_call_shapes(total, cfg["image"]))
        cost = prof.augment_cost(cost, flops=k_flops,
                                 bytes_accessed=k_bytes)
    cost = prof.register_cost("train.step", cost)
    digest = jax.jit(
        lambda st: jnp.sum(jax.tree.leaves(
            st.params)[0].astype(jnp.float32)))
    box = {"s": state, "k": jax.random.key(ns.seed + 1)}

    def one_step():
        box["k"], sub = jax.random.split(box["k"])
        box["s"], _ = compiled(box["s"], x, y, sub)

    def fence():
        return float(digest(box["s"]))

    one_step()
    one_step()
    fence()                                  # warm + fence
    mark = prof.trace_mark(trace.get_tracer())
    t0 = time.perf_counter()                 # throughput window
    for _ in range(steps):
        one_step()
    fence()
    step_s = (time.perf_counter() - t0) / steps
    for _ in range(steps):                   # fenced attribution pass
        with trace.span("profile.step"):
            one_step()
            with trace.span("device.sync"):
                fence()
    roofline = prof.roofline_verdict(cost, step_s, dev, n_dev=n_dev)
    pps = total / step_s / n_dev
    print(f"profile: train.step ({cfg['model'] or 'small_cnn'}, batch "
          f"{batch}/chip x {n_dev} device(s), {steps} steps)")
    print(f"  throughput {pps:.1f} patches/sec/chip, "
          f"{step_s * 1e3:.2f} ms/step")
    return {"train.step": (cost, roofline, step_s * 1e3)}, mark


def _profile_lm(ns, on_accel, dev):
    """Profile the LM train step — replicated or rule-sharded
    (--fsdp/--tp, partition.py): the acceptance surface for ROADMAP
    item 2, driveable from the command line. The epilogue's
    per-device peak-HBM line comes from XLA program accounting
    (memory_analysis reports the PER-DEVICE argument/temp footprint,
    so a sharded step's figure drops below the replicated one on the
    same config — capacity, not wall-clock, per the CPU measurement
    policy)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models import registry
    from idc_models_tpu.models.lm import attention_lm, next_token_loss
    from idc_models_tpu.observe import profile as prof
    from idc_models_tpu.observe import trace
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, rmsprop,
        shard_batch,
    )
    from idc_models_tpu.train.step import place_state

    if on_accel:
        vocab, e, mlp, heads, blocks, seq_len = 8192, 1024, 4096, 8, 4, 512
    else:
        vocab, e, mlp, heads, blocks, seq_len = 512, 128, 512, 4, 2, 64
    sharded = ns.fsdp > 1 or ns.tp > 1
    f, t = max(ns.fsdp, 1), max(ns.tp, 1)
    n_dev = len(jax.devices())
    if f * t > n_dev:
        sys.exit(f"profile: --fsdp {f} x --tp {t} needs {f * t} "
                 f"devices, have {n_dev} (use --host-devices)")
    mesh = meshlib.fsdp_tp_mesh(f, t, 1)
    rules = registry.get_partition_rules("lm") if sharded else None
    batch = ns.batch_size or (8 if on_accel else 4)
    if batch % f:
        sys.exit(f"profile: --batch-size {batch} must divide by "
                 f"--fsdp {f} (the batch shards over the same 'data' "
                 f"axis the params shard over)")
    steps = ns.steps or (30 if on_accel else 4)
    model = attention_lm(vocab, seq_len, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    opt = rmsprop(3e-3)
    variables = model.init(jax.random.key(ns.seed))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, next_token_loss), mesh,
        axis=meshlib.DATA_AXIS,
        state_shardings=(rules.shardings(mesh, state)
                         if rules is not None else None))
    state = place_state(mesh, state, rules=rules)
    rng = np.random.default_rng(ns.seed + 1)
    seqs = jnp.asarray((rng.integers(0, vocab, (batch, 1))
                        + np.arange(seq_len)) % vocab, jnp.int32)
    x = shard_batch(mesh, seqs, axis=meshlib.DATA_AXIS)
    with prof.compiling("train.step"):
        compiled = step.lower(state, x, x,
                              jax.random.key(ns.seed + 2)).compile()
    cost = prof.register_program("train.step", compiled)
    digest = jax.jit(lambda st: jnp.sum(
        st.params["embed"].astype(jnp.float32)))
    box = {"s": state, "k": jax.random.key(ns.seed + 2)}

    def one_step():
        box["k"], sub = jax.random.split(box["k"])
        box["s"], _ = compiled(box["s"], x, x, sub)

    def fence():
        return float(digest(box["s"]))

    one_step()
    one_step()
    fence()                                  # warm + fence
    mark = prof.trace_mark(trace.get_tracer())
    t0 = time.perf_counter()                 # throughput window
    for _ in range(steps):
        one_step()
    fence()
    step_s = (time.perf_counter() - t0) / steps
    for _ in range(steps):                   # fenced attribution pass
        with trace.span("profile.step"):
            one_step()
            with trace.span("device.sync"):
                fence()
    roofline = prof.roofline_verdict(cost, step_s, dev,
                                     n_dev=mesh.devices.size)
    layout = (f"fsdp={f}, tp={t} (rule set 'lm': params + optimizer "
              f"state sharded)" if sharded else "replicated")
    print(f"profile: train.step (lm {e}x{blocks}, vocab {vocab}, seq "
          f"{seq_len}, batch {batch} global, {steps} steps) — {layout}")
    print(f"  {step_s * 1e3:.2f} ms/step")
    if cost.peak_hbm_bytes is not None:
        # THE acceptance line: per-device resident footprint of the
        # compiled step (args + outputs + temps - donated aliases)
        print(f"  per-device peak HBM: "
              f"{cost.peak_hbm_bytes / 2**20:.2f} MiB over "
              f"{mesh.devices.size} device(s)")
    return {"train.step": (cost, roofline, step_s * 1e3)}, mark


def _profile_serve(ns, on_accel):
    """Profile the continuous-batching decode loop: slots saturated
    with long-budget requests, steady-state windows timed through the
    scheduler (collect's token fetch is the `device.sync` fence), the
    engine's compiled programs accounted via AOT accounting copies."""
    import time

    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.observe import profile as prof
    from idc_models_tpu.observe import trace
    from idc_models_tpu.serve import LMServer, Request

    if on_accel:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window = 2048, 8, 64
    else:
        vocab, e, heads, blocks, mlp = 32, 32, 2, 2, 64
        t_max, n_slots, window = 128, 4, 8
    dev = jax.devices()[0]
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(ns.seed)).params
    # the server's warmup compiles ~20 DISTINCT programs once each —
    # they stay in the unnamed bucket, which the churn detector
    # exempts for exactly this reason (one bucket of one-shot
    # compiles is not one program recompiling)
    from idc_models_tpu.models.draft_lm import (
        DraftLM, draft_config, draft_lm,
    )

    dcfg = draft_config(vocab, t_max)
    dparams = draft_lm(dcfg, mesh=mesh).init(
        jax.random.key(ns.seed + 1)).params

    class _NoDraft:
        # arms the engine's fixed-k verify program AND the drafter's
        # device state (via `learned`) so lm.verify and serve.propose
        # are both ACCOUNTED (cost/roofline), while never proposing —
        # the measured loop stays pure fused windows, so window_s
        # times exactly the program the serve.window verdict is
        # paired with
        learned = DraftLM(min(8, window), dparams, dcfg)

        def propose(self, history):
            return None

    server = LMServer(params, embed_dim=e, num_heads=heads,
                      num_blocks=blocks, t_max=t_max, n_slots=n_slots,
                      window=window, mesh=mesh,
                      cache_dtype=jnp.bfloat16,
                      spec_decode=True, draft_k=min(8, window),
                      drafter=_NoDraft())
    budget = t_max - 8
    for i in range(n_slots):
        server.submit(Request(id=f"p{i}", prompt=(1, 2, 3, 4),
                              max_new_tokens=budget))
    server.step()                            # admissions + first window
    server.step()                            # steady state
    costs = server.engine.program_costs(window)
    steps = ns.steps or max(budget // window - 4, 2)
    mark = prof.trace_mark(trace.get_tracer())
    t0 = time.perf_counter()
    n = 0
    for _ in range(steps):
        if server.scheduler.idle():
            break
        server.step()
        n += 1
    window_s = (time.perf_counter() - t0) / max(n, 1)
    server.close()
    # the PAGED twin at the same decode configuration: saturate, time
    # steady-state windows, and account serve.window_paged — so the
    # report shows the page-table gather indirection's cost NEXT TO
    # the contiguous serve.window figure (ISSUE 11)
    page_size = max(t_max // 16, 1)
    paged_server = LMServer(
        params, embed_dim=e, num_heads=heads, num_blocks=blocks,
        t_max=t_max, n_slots=n_slots, window=window, mesh=mesh,
        cache_dtype=jnp.bfloat16, prefill_chunk=page_size,
        kv_page_size=page_size,
        kv_pages=n_slots * (t_max // page_size))
    for i in range(n_slots):
        paged_server.submit(Request(id=f"g{i}", prompt=(1, 2, 3, 4),
                                    max_new_tokens=budget))
    for _ in range(n_slots + 2):   # chunked admissions settle (one
        paged_server.step()        # chunk dispatch per cycle)
    paged_costs = paged_server.engine.program_costs(window)
    t0 = time.perf_counter()
    np_ = 0
    for _ in range(steps):
        if paged_server.scheduler.idle():
            break
        paged_server.step()
        np_ += 1
    paged_window_s = (time.perf_counter() - t0) / max(np_, 1)
    paged_server.close()
    wcost = costs["serve.window"]
    roofline = prof.roofline_verdict(wcost, window_s, dev)
    progs = {"serve.window": (wcost, roofline, window_s * 1e3)}
    pw = paged_costs.pop("serve.window_paged")
    progs["serve.window_paged"] = (
        pw, prof.roofline_verdict(pw, paged_window_s, dev),
        paged_window_s * 1e3)
    for name, c in list(costs.items()) + list(paged_costs.items()):
        if name in progs:
            continue
        # untimed programs (admission prefill, the speculative verify)
        # still get an intensity-based compute-vs-bandwidth verdict —
        # achieved fractions need a measured step and stay None
        progs[name] = (c, prof.roofline_verdict(c, None, dev), None)
    print(f"profile: serve decode loop ({n_slots} slots x {window} "
          f"tokens/window, {n} measured windows)")
    print(f"  {window_s * 1e3:.2f} ms/window, "
          f"{n_slots * window / window_s:.1f} tokens/sec at full "
          f"occupancy")
    print(f"  paged: {paged_window_s * 1e3:.2f} ms/window "
          f"({np_} measured) — indirection overhead "
          f"{(paged_window_s / window_s - 1) * 100:+.1f}% vs "
          f"contiguous")
    return progs, mark


def _profile_churn_drill(limit: int) -> None:
    """The injected recompile loop: a jitted reduction called with a
    DIFFERENT shape every iteration, so the watchdog's churn detector
    demonstrably fires (`churn.drill` exceeds the limit) while a clean
    warm run stays silent."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu.observe import profile as prof

    f = jax.jit(lambda t: jnp.sum(t * 2.0))
    with prof.compiling("churn.drill"):
        for n in range(limit + 2):
            float(f(jnp.zeros((n + 1,), jnp.float32)))


def _run_convert(ns):
    """Keras .h5 -> framework .npz (SURVEY.md §7 'hard parts': one-time
    offline ImageNet weight conversion, no TF at runtime)."""
    import numpy as np

    from idc_models_tpu.models.pretrained import (
        _flatten, load_pretrained_file, save_npz,
    )

    params, state = load_pretrained_file(ns.input)
    if ns.model:
        import jax

        from idc_models_tpu.models import registry

        spec = registry.get_model(ns.model)

        # shapes only — no need to materialize a DenseNet-sized init
        def _init_shapes():
            v = spec.build(1, 3).init(jax.random.key(0))
            return {"params": v.params, "state": v.state}

        shapes = jax.eval_shape(_init_shapes)

        def check(loaded, target, what):
            flat_t = _flatten(target)
            mis = [k for k, v in _flatten(loaded).items()
                   if k not in flat_t
                   or tuple(np.shape(v)) != tuple(flat_t[k].shape)]
            n = len(_flatten(loaded)) - len(mis)
            print(f"validated {what} against {ns.model}: {n} tensors "
                  f"match, {len(mis)} mismatches")
            for m in mis[:10]:
                print(" ", m)
            return len(mis)

        bad = check(params, shapes["params"]["backbone"], "params")
        if state:
            bad += check(state, shapes["state"].get("backbone", {}),
                         "state")
        if bad:
            print(f"[idc_models_tpu] WARNING: {bad} tensors will not load "
                  f"into {ns.model}", file=sys.stderr)
    tree = {"params": params, "state": state} if state else {"params": params}
    save_npz(ns.output, tree)
    print(f"wrote {ns.output} ({len(params)} layers, "
          f"{len(_flatten(params))} tensors)")


def _run_dist(ns):
    import jax

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.configs import get_preset
    from idc_models_tpu.data.cifar10 import load_cifar10
    from idc_models_tpu.data.idc import train_val_test_split
    from idc_models_tpu.train import TwoPhaseConfig, evaluate, two_phase_fit

    if ns.resumable and ns.path is None:
        sys.exit("--resumable requires --path (checkpoints live under it)")
    if ns.checkpoint_every < 1:
        sys.exit(f"--checkpoint-every {ns.checkpoint_every} must be "
                 f">= 1: saving every 0 epochs is never, and never "
                 f"checkpointing is what --resumable exists to fix")
    if ns.checkpoint_every != 1 and not ns.resumable:
        sys.exit("--checkpoint-every needs --resumable: it paces the "
                 "resume checkpoints, and without --resumable none "
                 "are written")
    preset = _apply_overrides(
        get_preset(ns.preset_key), ns,
        ["batch_size", "lr", "epochs", "fine_tune_epochs", "fine_tune_at",
         "repeats"])
    if getattr(ns, "model_parallel", 1) > 1:
        if ns.central_storage:
            sys.exit("--central-storage broadcasts a host-resident "
                     "replica each step and cannot keep a model-sharded "
                     "layout; drop one of the two flags")
        from idc_models_tpu import tp

        try:
            mesh = tp.dp_tp_mesh(ns.model_parallel)
        except ValueError as e:
            sys.exit(str(e))
    else:
        mesh = meshlib.data_mesh()
    n_dev = mesh.shape.get(meshlib.DATA_AXIS, mesh.devices.size)
    global_batch = (preset.batch_size * n_dev if preset.per_replica_batch
                    else preset.batch_size)
    print(f"Number of devices: {mesh.devices.size}")

    # Synthetic fallback must yield at least one full global batch after
    # the train split, or the Loader rightly refuses to run.
    ns.synthetic_examples = max(ns.synthetic_examples, 2 * global_batch)
    streamed = None
    if ns.stream:
        if preset.dataset != "idc":
            sys.exit("--stream needs an IDC directory preset (vgg/mobile)")
        streamed = _streamed_idc_splits(ns, preset, global_batch)
        if streamed is None:
            print("[idc_models_tpu] --stream: no real data dir found; "
                  "falling back to the materialized synthetic path",
                  file=sys.stderr)
    if streamed is not None:
        train, val, test = streamed
    elif preset.dataset == "cifar10":
        ds = load_cifar10(ns.path, split="train",
                          synthetic_size=ns.synthetic_examples, seed=ns.seed)
        test = load_cifar10(ns.path, split="test",
                            synthetic_size=max(ns.synthetic_examples // 5, 64),
                            seed=ns.seed)
        train, val, _ = train_val_test_split(ds, (0.9, 0.1, 0.0),
                                             seed=ns.seed)
    else:
        ds = _load_idc(ns, preset.image_size, preset.dataset_limit)
        train, val, test = train_val_test_split(ds, seed=ns.seed)

    from idc_models_tpu.observe import profile_trace

    logger = _logger(ns)
    with profile_trace(ns.profile_dir):
        result = two_phase_fit(
            preset.model, preset.num_outputs, train, val, mesh,
            TwoPhaseConfig(lr=preset.lr, epochs=preset.epochs,
                           fine_tune_epochs=preset.fine_tune_epochs,
                           batch_size=global_batch,
                           fine_tune_at=preset.fine_tune_at,
                           repeats=preset.repeats, seed=ns.seed,
                           central_storage=ns.central_storage,
                           cache_features=ns.cache_features),
            pretrained_weights=ns.pretrained_weights,
            artifact_path=ns.path,
            checkpoint_dir=(str(Path(ns.path) / "dist_ckpt")
                            if ns.resumable and ns.path else None),
            checkpoint_every=ns.checkpoint_every,
            logger=logger)
    test_metrics = evaluate(result.model, result.state, test,
                            _loss_for(preset.num_outputs), mesh,
                            batch_size=global_batch,
                            with_auroc=preset.num_outputs == 1)
    print("test:", " ".join(f"{k}={v:.4f}" for k, v in test_metrics.items()))
    if logger:
        logger.log(event="test", **test_metrics)
    _finish_logger(logger)


def _loss_for(num_outputs):
    from idc_models_tpu.train.losses import (
        binary_cross_entropy, sparse_categorical_cross_entropy,
    )

    return (binary_cross_entropy if num_outputs == 1
            else sparse_categorical_cross_entropy)


def _run_attention(ns):
    """Beyond-reference workload: the ring-attention transformer
    classifier over a ("data", "seq") mesh — sequence parallelism from
    the command line, under the same step/eval/logging machinery as
    every other preset. Trains on the position-sensitive synthetic
    sequence task, or — with --data-dir — on the reference's own IDC
    patch tree (C1/C2), each image embedded as a raster token sequence
    (data.sequences.patchify; see docs/LONG_CONTEXT.md)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.data import synthetic
    from idc_models_tpu.data.idc import ArrayDataset, train_val_test_split
    from idc_models_tpu.data.sequences import patchify, sequence_shape
    from idc_models_tpu.models.attention import attention_classifier
    from idc_models_tpu.observe import Timer, profile_trace
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, replicate,
        rmsprop, shard_batch,
    )
    from idc_models_tpu.train.loop import Evaluator
    from idc_models_tpu.train.losses import binary_cross_entropy

    if not 0.0 <= ns.dropout < 1.0:
        sys.exit(f"--dropout {ns.dropout} must be in [0, 1)")
    # explicit --data-dir ONLY (not _data_root's <path>/data fallback):
    # real data overrides --seq-len/--features with the derived patch
    # sequence shape, so an artifact dir that happens to contain the
    # IDC tree must not silently turn a long-context synthetic run into
    # a 100-token IDC run
    root = ns.data_dir
    seq_len, features = ns.seq_len, ns.features
    if root is not None:
        try:
            seq_len, features = sequence_shape(ns.image_size,
                                               ns.patch_size)
        except ValueError as e:
            sys.exit(f"--patch-size: {e}")
    n_dev = len(jax.devices())
    # auto ring size: the largest power of two that DIVIDES the device
    # count (capped at 4), so the default never aborts on e.g. 6 devices
    n_seq = ns.seq_parallel or max(
        p for p in (4, 2, 1) if n_dev % p == 0)
    if n_seq < 1 or n_dev % n_seq:
        sys.exit(f"--seq-parallel {n_seq} must be a positive divisor "
                 f"of the device count ({n_dev})")
    stripes = 2 * n_seq if ns.layout == "zigzag" else n_seq
    what = ("--seq-len" if root is None
            else f"the {seq_len}-token patch sequence "
                 f"({ns.image_size}x{ns.image_size} images at "
                 f"--patch-size {ns.patch_size})")
    if seq_len % stripes:
        sys.exit(f"{what} = {seq_len} must divide into {stripes} "
                 f"equal stripes for --layout {ns.layout} at ring "
                 f"size {n_seq}")
    mesh = meshlib.data_seq_mesh(n_seq)
    print(f"Number of devices: {mesh.devices.size} "
          f"(data={mesh.shape[meshlib.DATA_AXIS]}, seq={n_seq})")

    model = attention_classifier(
        seq_len, features, embed_dim=ns.embed_dim,
        num_heads=ns.num_heads, mlp_dim=ns.mlp_dim,
        num_blocks=ns.num_blocks, num_outputs=1, mesh=mesh, causal=True,
        layout=ns.layout, block_impl=ns.block_impl, remat=ns.remat,
        dropout_rate=ns.dropout)
    batch = ns.batch_size or 64
    lr = ns.lr if ns.lr is not None else 1e-3
    if root is not None:
        # the reference's data domain through the SP path: decode the
        # labeled tree (C1), deterministic 80/10/10 split (C4), then
        # tokenize each patch
        ds = _load_idc(ns, ns.image_size, None)
        train_ds, val_ds, _ = train_val_test_split(ds, seed=ns.seed)
        x, y = patchify(train_ds.images, ns.patch_size), train_ds.labels
        vx, vy = patchify(val_ds.images, ns.patch_size), val_ds.labels
        print(f"IDC patch sequences: {len(x)} train / {len(vx)} val, "
              f"{seq_len} tokens x {features} features per patch")
    else:
        n_train = max(ns.synthetic_examples, 4 * batch)
        x, y = synthetic.make_sequence_task(n_train, seq_len, features,
                                            seed=ns.seed)
        vx, vy = synthetic.make_sequence_task(max(n_train // 4, batch),
                                              seq_len, features,
                                              seed=ns.seed + 1)

    opt = rmsprop(lr)
    variables = model.init(jax.random.key(ns.seed))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, binary_cross_entropy), mesh,
        axis=meshlib.DATA_AXIS)
    state = replicate(mesh, state)
    logger = _logger(ns)
    key = jax.random.key(ns.seed + 1)
    sel_rng = np.random.default_rng(ns.seed + 2)
    with Timer("Attention training", logger=logger), \
            profile_trace(ns.profile_dir):
        for i in range(ns.steps):
            sel = sel_rng.integers(0, len(x), batch)
            key, sub = jax.random.split(key)
            state, m = step(state, *shard_batch(mesh, x[sel], y[sel],
                                                axis=meshlib.DATA_AXIS),
                            sub)
            if i % 50 == 0 or i == ns.steps - 1:
                m = _fetch_scalars(m)
                print(f"step {i}, loss={float(m['loss']):.4f}, "
                      f"accuracy={float(m['accuracy']):.4f}")
                if logger:
                    logger.log(event="step", step=i,
                               loss=float(m["loss"]),
                               accuracy=float(m["accuracy"]))
    ev = Evaluator(model, binary_cross_entropy, mesh, batch_size=batch,
                   with_auroc=True)
    vm = ev(state, ArrayDataset(vx, vy))
    print("val:", " ".join(f"{k}={v:.4f}" for k, v in vm.items()))
    if logger:
        logger.log(event="val", **vm)
    _finish_logger(logger)


def _run_lm(ns):
    """Beyond-reference workload: the decoder-only LM trained through
    sequence-parallel ring attention on the counting task
    (next = (tok+1) % vocab), then served through the ring-sharded
    KV-cache decoder — train and generate from one parameter tree
    (models/lm.py, docs/LONG_CONTEXT.md)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm, next_token_loss
    from idc_models_tpu.observe import Timer, profile_trace
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, rmsprop,
        shard_batch,
    )
    from idc_models_tpu.train.step import place_state

    if not 0.0 <= ns.dropout < 1.0:
        sys.exit(f"--dropout {ns.dropout} must be in [0, 1)")
    if ns.fsdp < 0 or ns.tp < 0:
        sys.exit(f"--fsdp/--tp must be >= 0 (0 = off), got "
                 f"{ns.fsdp}/{ns.tp}")
    n_dev = len(jax.devices())
    sharded = ns.fsdp > 1 or ns.tp > 1
    if sharded:
        # rule-sharded mesh (partition.py): FSDP over "data", TP over
        # "model", the ring over "seq"; --seq-parallel defaults to 1
        # here (the three axes share the device budget)
        f, t = max(ns.fsdp, 1), max(ns.tp, 1)
        n_seq = ns.seq_parallel or 1
        if f * t * n_seq > n_dev:
            sys.exit(f"--fsdp {f} x --tp {t} x --seq-parallel {n_seq} "
                     f"needs {f * t * n_seq} devices, have {n_dev} "
                     f"(use --host-devices to grow the virtual pod)")
        batch = ns.batch_size or 32
        if batch % f:
            sys.exit(f"--batch-size {batch} must divide by --fsdp {f} "
                     f"(the batch shards over the same 'data' axis the "
                     f"params shard over)")
        mesh = meshlib.fsdp_tp_mesh(f, t, n_seq)
    else:
        n_seq = ns.seq_parallel or max(
            p for p in (4, 2, 1) if n_dev % p == 0)
        if n_seq < 1 or n_dev % n_seq:
            sys.exit(f"--seq-parallel {n_seq} must be a positive "
                     f"divisor of the device count ({n_dev})")
        mesh = meshlib.data_seq_mesh(n_seq)
    stripes = 2 * n_seq if ns.layout == "zigzag" else n_seq
    if ns.seq_len % stripes:
        sys.exit(f"--seq-len {ns.seq_len} must divide into {stripes} "
                 f"equal stripes for --layout {ns.layout} at ring "
                 f"size {n_seq}")
    rules = None
    if sharded:
        from idc_models_tpu.models import registry

        rules = registry.get_partition_rules("lm")
        print(f"Number of devices: {mesh.devices.size} "
              f"(fsdp={mesh.shape[meshlib.DATA_AXIS]}, "
              f"tp={mesh.shape[meshlib.MODEL_AXIS]}, seq={n_seq}; "
              f"params + optimizer state sharded by rule set 'lm')")
    else:
        print(f"Number of devices: {mesh.devices.size} "
              f"(data={mesh.shape[meshlib.DATA_AXIS]}, seq={n_seq})")

    model = attention_lm(
        ns.vocab, ns.seq_len, embed_dim=ns.embed_dim,
        num_heads=ns.num_heads, mlp_dim=ns.mlp_dim,
        num_blocks=ns.num_blocks, mesh=mesh, layout=ns.layout,
        block_impl=ns.block_impl, remat=ns.remat,
        dropout_rate=ns.dropout)
    batch = ns.batch_size or 32
    lr = ns.lr if ns.lr is not None else 3e-3
    opt = rmsprop(lr)
    variables = model.init(jax.random.key(ns.seed))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, next_token_loss), mesh,
        axis=meshlib.DATA_AXIS,
        state_shardings=(rules.shardings(mesh, state)
                         if rules is not None else None))
    state = place_state(mesh, state, rules=rules)
    logger = _logger(ns)
    rng = np.random.default_rng(ns.seed + 1)
    key = jax.random.key(ns.seed + 2)
    with Timer("LM training", logger=logger), \
            profile_trace(ns.profile_dir):
        for i in range(ns.steps):
            starts = rng.integers(0, ns.vocab, (batch, 1))
            seqs = jnp.asarray((starts + np.arange(ns.seq_len))
                               % ns.vocab, jnp.int32)
            bx = shard_batch(mesh, seqs, axis=meshlib.DATA_AXIS)
            key, sub = jax.random.split(key)
            state, m = step(state, bx, bx, sub)
            if i % 50 == 0 or i == ns.steps - 1:
                m = _fetch_scalars(m)
                print(f"step {i}, loss={float(m['loss']):.4f}, "
                      f"next-token accuracy={float(m['accuracy']):.4f}")
                if logger:
                    logger.log(event="step", step=i,
                               loss=float(m["loss"]),
                               accuracy=float(m["accuracy"]))
    n_gen = min(ns.generate, ns.seq_len - 3)
    if ns.generate > 0 and n_gen >= 1:
        import time as _time

        from idc_models_tpu.models.lm import Generator

        if ns.temperature < 0.0:
            sys.exit(f"--temperature {ns.temperature} must be >= 0")
        if ns.top_k < 0:
            sys.exit(f"--top-k {ns.top_k} must be >= 0 (0 = no "
                     f"restriction)")
        if ns.top_k > 0 and ns.temperature == 0.0:
            print("[idc_models_tpu] --top-k has no effect at "
                  "--temperature 0 (greedy argmax already picks the "
                  "top-1 token)", file=sys.stderr)
        # the serving object compiles prefill + the fused scan decode
        # once; repeated requests against it perform zero recompilation
        gen = Generator(jax.device_get(state.params),
                        embed_dim=ns.embed_dim, num_heads=ns.num_heads,
                        num_blocks=ns.num_blocks, t_max=ns.seq_len,
                        cache_dtype=jnp.float32,
                        temperature=ns.temperature,
                        top_k=ns.top_k or None)
        prompt = jnp.asarray(
            [[i % ns.vocab for i in range(3)]], jnp.int32)
        key = (jax.random.key(ns.seed + 3) if ns.temperature > 0.0
               else None)
        out = gen(prompt, n_gen, rng=key)         # compile + generate
        t0 = _time.perf_counter()
        out = gen(prompt, n_gen, rng=key)         # compiled: 2 dispatches
        toks = out.tolist()[0]                    # fetch fences the timer
        dt = _time.perf_counter() - t0
        want = [i % ns.vocab for i in range(3 + n_gen)]
        ok = toks == want
        verdict = ("matches" if ok else "does NOT match"
                   ) if ns.temperature == 0.0 else "sampled against"
        print(f"generate: {toks[:3]} -> {toks[3:]} ({verdict} the "
              f"counting pattern; {n_gen} tokens end-to-end in "
              f"{dt * 1e3:.1f} ms, one prefill + one fused decode "
              f"dispatch)")
        if logger:
            # generate_ms_per_token is END-TO-END (prefill dispatch +
            # fused decode + host fetch) / tokens — NOT the same metric
            # as bench.py's decode_ms_per_token (pure decode window)
            logger.log(event="generate", tokens=toks, matches=ok,
                       generate_ms_per_token=dt * 1e3 / n_gen)
    _finish_logger(logger)


def _run_serve(ns):
    """Beyond-reference workload: the continuous-batching serving
    engine (serve/) over an `attention_lm` parameter tree — fixed decode
    slots, masked fused windows, FIFO admission with backpressure —
    replaying a request trace (JSONL or synthetic Poisson arrivals) and
    reporting throughput/TTFT/occupancy (docs/LONG_CONTEXT.md)."""
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm, next_token_loss
    from idc_models_tpu.observe import JsonlLogger, Timer, profile_trace
    from idc_models_tpu.serve import LMServer, load_trace, poisson_trace

    n_dev = len(jax.devices())
    if ns.seq_parallel < 1 or n_dev < ns.seq_parallel:
        sys.exit(f"--seq-parallel {ns.seq_parallel} needs at least that "
                 f"many devices ({n_dev} available)")
    if ns.t_max % ns.seq_parallel:
        sys.exit(f"--t-max {ns.t_max} must divide by --seq-parallel "
                 f"{ns.seq_parallel}")
    if ns.fsdp not in (0, 1):
        sys.exit(f"--fsdp {ns.fsdp}: FSDP shards the optimizer+param "
                 f"state over the batch axis at TRAIN time; a serving "
                 f"engine holds no optimizer state and prefills [1, P] "
                 f"batches — use --tp for serving-side param sharding")
    if ns.tp < 0:
        sys.exit(f"--tp {ns.tp} must be >= 0 (0 = off)")
    if ns.tp > 1 and ns.tp * ns.seq_parallel > n_dev:
        sys.exit(f"--tp {ns.tp} x --seq-parallel {ns.seq_parallel} "
                 f"needs {ns.tp * ns.seq_parallel} devices, have "
                 f"{n_dev} (use --host-devices to grow the virtual "
                 f"pod)")
    if ns.temperature < 0.0:
        sys.exit(f"--temperature {ns.temperature} must be >= 0")
    # fail fast — BEFORE any --train-steps pre-training runs
    if ns.prefill_chunk and (ns.prefill_chunk < 1
                             or ns.t_max % ns.prefill_chunk):
        sys.exit(f"--prefill-chunk {ns.prefill_chunk} must be >= 1 and "
                 f"divide --t-max {ns.t_max}")
    if ns.prefix_cache_mb > 0 and not ns.prefill_chunk:
        sys.exit("--prefix-cache-mb needs --prefill-chunk (snapshots "
                 "live on chunk boundaries)")
    if bool(ns.kv_page_size) != bool(ns.kv_pages):
        sys.exit("paged KV needs BOTH --kv-page-size and --kv-pages "
                 "(or neither for the contiguous per-slot rows)")
    if ns.kv_page_size:
        if not ns.prefill_chunk:
            sys.exit("--kv-page-size needs --prefill-chunk: prompts "
                     "stream straight into pool pages chunk by chunk")
        if ns.kv_page_size < 1 or ns.t_max % ns.kv_page_size:
            sys.exit(f"--kv-page-size {ns.kv_page_size} must be >= 1 "
                     f"and divide --t-max {ns.t_max}")
        if ns.prefill_chunk % ns.kv_page_size:
            sys.exit(f"--prefill-chunk {ns.prefill_chunk} must be a "
                     f"multiple of --kv-page-size {ns.kv_page_size} "
                     f"(chunk boundaries must land on the page grid)")
        if ns.kv_pages * ns.kv_page_size < ns.t_max:
            sys.exit(f"--kv-pages {ns.kv_pages} x --kv-page-size "
                     f"{ns.kv_page_size} < --t-max {ns.t_max}: one "
                     f"full-length request could never be admitted")
    if ns.kv_decode_reserve and not ns.kv_page_size:
        sys.exit("--kv-decode-reserve needs paged KV "
                 "(--kv-page-size/--kv-pages)")
    if ns.kv_decode_reserve < 0:
        sys.exit(f"--kv-decode-reserve {ns.kv_decode_reserve} must be "
                 f">= 0 (0 = reserve the full budget)")
    if ns.spec_decode and not 1 <= ns.draft_k <= ns.t_max - 2:
        sys.exit(f"--draft-k {ns.draft_k} must be in [1, t_max - 2] "
                 f"(a verify needs room for k drafts + the bonus "
                 f"token inside the {ns.t_max}-slot cache)")
    if ns.spec_decode and ns.ngram_order < 1:
        sys.exit(f"--ngram-order {ns.ngram_order} must be >= 1")
    if ns.drafter != "ngram" and not ns.spec_decode:
        sys.exit(f"--drafter {ns.drafter} without --spec-decode: the "
                 f"drafter only runs inside the speculative loop (its "
                 f"proposals feed the engine's fixed-k verify "
                 f"program) — add --spec-decode")
    if ns.drafter in ("learned", "chained") and not ns.draft_ckpt:
        sys.exit(f"--drafter {ns.drafter} needs --draft-ckpt DIR: the "
                 f"learned drafter is a distilled draft LM restored "
                 f"from a models/draft_lm.save_draft_lm checkpoint "
                 f"(params + draft_config.json sidecar); distill one "
                 f"with models/draft_lm.distill_draft_lm, or use "
                 f"--drafter ngram which needs no model")
    if ns.draft_ckpt and ns.drafter == "ngram":
        sys.exit(f"--draft-ckpt without a learned drafter: the n-gram "
                 f"drafter loads no model, so the checkpoint would be "
                 f"silently ignored — pass --drafter learned (or "
                 f"chained) to use it")
    if ns.slo_ttft_p95_ms is not None and ns.slo_ttft_p95_ms <= 0:
        sys.exit(f"--slo-ttft-p95-ms {ns.slo_ttft_p95_ms} must be > 0")
    if (ns.slo_error_rate is not None
            and not 0.0 < ns.slo_error_rate < 1.0):
        sys.exit(f"--slo-error-rate {ns.slo_error_rate} must be a "
                 f"fraction in (0, 1)")
    if ns.slo_window_s <= 0:
        sys.exit(f"--slo-window-s {ns.slo_window_s} must be > 0")
    if ns.metrics_port is not None and not 0 <= ns.metrics_port <= 65535:
        sys.exit(f"--metrics-port {ns.metrics_port} must be in "
                 f"[0, 65535] (0 = OS-assigned)")
    if ns.max_retries < 0:
        sys.exit(f"--max-retries {ns.max_retries} must be >= 0")
    if ns.retry_backoff_ms < 0:
        sys.exit(f"--retry-backoff-ms {ns.retry_backoff_ms} must be "
                 f">= 0")
    if (ns.brownout_queue_high is not None
            and ns.brownout_queue_high < 1):
        sys.exit(f"--brownout-queue-high {ns.brownout_queue_high} "
                 f"must be >= 1")
    if ns.brownout_clamp_tokens < 1:
        sys.exit(f"--brownout-clamp-tokens {ns.brownout_clamp_tokens} "
                 f"must be >= 1")
    if ns.brownout_dwell_ms < 0 or ns.brownout_clear_ms < 0:
        sys.exit(f"--brownout-dwell-ms/--brownout-clear-ms must be "
                 f">= 0, got {ns.brownout_dwell_ms}/"
                 f"{ns.brownout_clear_ms}")
    ns.tenant_list, ns.tenant_quotas, ns.tenant_slos = (
        _parse_tenant_flags(ns))
    # rollout flags fail fast too — a bad canary fraction discovered
    # AFTER --train-steps pre-training wastes the whole warmup
    if ns.rollout is None:
        for flag, val in (("--canary-fraction", ns.canary_fraction),
                          ("--canary-requests", ns.canary_requests),
                          ("--rollout-at", ns.rollout_at)):
            if val is not None:
                sys.exit(f"{flag} needs --rollout: it tunes the canary "
                         f"stage of a weight rollout, and without a "
                         f"candidate checkpoint there is no rollout to "
                         f"tune")
    else:
        if ns.canary_fraction is None:
            ns.canary_fraction = 0.25
        if ns.canary_requests is None:
            ns.canary_requests = 4
        if ns.rollout_at is None:
            ns.rollout_at = 0.25
        if not 0.0 < ns.canary_fraction <= 1.0:
            sys.exit(f"--canary-fraction {ns.canary_fraction} must be "
                     f"in (0, 1]: a zero (or negative) fraction "
                     f"starves the canary of evidence forever, and "
                     f"promoting without evidence is not a rollout")
        if ns.canary_requests < 1:
            sys.exit(f"--canary-requests {ns.canary_requests} must be "
                     f">= 1: the verdict needs at least one canary "
                     f"finish to compare")
        if not 0.0 <= ns.rollout_at < 1.0:
            sys.exit(f"--rollout-at {ns.rollout_at} must be in [0, 1): "
                     f"at 1.0 or past it the trace drains before the "
                     f"rollout ever opens")
        from idc_models_tpu.checkpoint import (
            CheckpointError, checkpoint_info,
        )

        try:
            checkpoint_info(ns.rollout)
        except CheckpointError as e:
            sys.exit(f"--rollout: {e}")
    if ns.rollout_adapters is not None:
        if ns.tenant_list is None:
            sys.exit("--rollout-adapters needs --tenants: an adapter "
                     "rollout hot-swaps PER-TENANT logit deltas, and a "
                     "tenant-less server has no adapter bank to swap")
        if ns.rollout_adapters < 1:
            sys.exit(f"--rollout-adapters {ns.rollout_adapters} must "
                     f"be >= 1 (it is the adapter rank r in the "
                     f"[V, r] x [r, V] factors)")
    ns.serve_fault_plan = None
    if ns.serve_faults:
        from idc_models_tpu.serve import parse_serve_fault_spec

        try:
            ns.serve_fault_plan = parse_serve_fault_spec(
                ns.serve_faults, seed=ns.seed)
        except ValueError as e:
            sys.exit(f"--serve-faults: {e}")
    serve_rules = None
    if ns.tp > 1:
        # tensor-parallel serving (partition.py): weights shard over
        # "model", the KV ring keeps "seq" — independent axes
        from idc_models_tpu.models import registry as model_registry

        serve_rules = model_registry.get_partition_rules("lm")
        mesh = meshlib.fsdp_tp_mesh(1, ns.tp, ns.seq_parallel)
        print(f"serving mesh: tp={ns.tp} x seq={ns.seq_parallel} "
              f"(params sharded by rule set 'lm'; KV on the seq ring)")
    else:
        mesh = meshlib.seq_mesh(ns.seq_parallel)
    # the model trains through the SAME ring the serving mesh uses —
    # omitting mesh here would silently train single-device full
    # attention ([B, H, t_max, t_max] scores) at exactly the sizes
    # --seq-parallel exists for
    model = attention_lm(ns.vocab, ns.t_max, embed_dim=ns.embed_dim,
                         num_heads=ns.num_heads, mlp_dim=ns.mlp_dim,
                         num_blocks=ns.num_blocks,
                         mesh=mesh if ns.seq_parallel > 1 else None)
    params = model.init(jax.random.key(ns.seed)).params
    if ns.train_steps > 0:
        from idc_models_tpu.train import (
            TrainState, make_train_step, rmsprop,
        )

        opt = rmsprop(3e-3)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           model_state={}, opt_state=opt.init(params))
        step = jax.jit(make_train_step(model, opt, next_token_loss))
        rng = np.random.default_rng(ns.seed + 1)
        key = jax.random.key(ns.seed + 2)
        with Timer("Serve pre-training"):
            for _ in range(ns.train_steps):
                starts = rng.integers(0, ns.vocab, (16, 1))
                seqs = jnp.asarray(
                    (starts + np.arange(ns.t_max)) % ns.vocab, jnp.int32)
                key, sub = jax.random.split(key)
                state, m = step(state, seqs, seqs, sub)
            print(f"pre-trained {ns.train_steps} steps, "
                  f"loss={float(m['loss']):.4f}")
        params = jax.device_get(state.params)

    logger = (JsonlLogger(Path(ns.path) / "logs" / "serve.jsonl")
              if ns.path else None)
    # live exposition (observe/exporter.py): armed BEFORE the server's
    # warmup compiles so a scraper sees the process from startup, torn
    # down with the run (the finally below)
    exporter = None
    if ns.metrics_port is not None:
        from idc_models_tpu.observe import MetricsExporter

        try:
            exporter = MetricsExporter(port=ns.metrics_port).start()
        except OSError as e:
            sys.exit(f"serve: cannot bind --metrics-port "
                     f"{ns.metrics_port}: {e}")
        print(f"metrics: {exporter.url}/metrics  healthz: "
              f"{exporter.url}/healthz")
    try:
        _serve_body(ns, mesh, params, logger, serve_rules)
    finally:
        if exporter is not None:
            exporter.close()


def _parse_tenant_flags(ns):
    """Validate the serve verb's tenancy flags into (names, {name:
    TenantQuota}, {name: ttft_ms}) — every bad spelling is a usage
    error that TEACHES the grammar, the CLI's established discipline."""
    quota_grammar = ("--tenant-quota grammar: NAME=SLOTS[:QUEUED"
                     "[:PAGES]], each an int >= 1 or '-' (unlimited), "
                     "e.g. acme=2:8:- ; NAME must be in --tenants")
    slo_grammar = ("--tenant-slo-ttft-ms grammar: NAME=MS for one "
                   "tenant or a bare MS > 0 for every tenant, e.g. "
                   "acme=250 ; NAME must be in --tenants")
    if ns.tenants is None:
        if ns.tenant_quota:
            sys.exit("--tenant-quota needs --tenants: quotas bound "
                     "REGISTERED tenants")
        if ns.tenant_slo_ttft_ms:
            sys.exit("--tenant-slo-ttft-ms needs --tenants: SLOs "
                     "attach to REGISTERED tenants")
        return None, {}, {}
    names = [t.strip() for t in ns.tenants.split(",")]
    if any(not t for t in names):
        sys.exit(f"--tenants {ns.tenants!r}: empty tenant name "
                 f"(comma-separated non-empty names, first = default)")
    if len(set(names)) != len(names):
        sys.exit(f"--tenants {ns.tenants!r}: duplicate tenant name — "
                 f"tenant names are identities")
    from idc_models_tpu.serve import TenantQuota

    def bound(tok, spec):
        if tok == "-":
            return None
        try:
            v = int(tok)
        except ValueError:
            sys.exit(f"--tenant-quota {spec!r}: {tok!r} is not an int "
                     f"or '-'. {quota_grammar}")
        if v < 1:
            sys.exit(f"--tenant-quota {spec!r}: bounds must be >= 1 "
                     f"(a 0 quota would admit nothing ever). "
                     f"{quota_grammar}")
        return v

    quotas = {}
    for spec in ns.tenant_quota or ():
        name, eq, rest = spec.partition("=")
        parts = rest.split(":") if rest else []
        if not eq or not name or not 1 <= len(parts) <= 3:
            sys.exit(f"--tenant-quota {spec!r}: malformed. "
                     f"{quota_grammar}")
        if name not in names:
            sys.exit(f"--tenant-quota {spec!r}: unknown tenant "
                     f"{name!r} (registered: {names}). {quota_grammar}")
        if name in quotas:
            sys.exit(f"--tenant-quota {spec!r}: tenant {name!r} "
                     f"already has a quota")
        parts += ["-"] * (3 - len(parts))
        quotas[name] = TenantQuota(
            max_resident_slots=bound(parts[0], spec),
            max_queued=bound(parts[1], spec),
            kv_page_budget=bound(parts[2], spec))
    slos = {}
    for spec in ns.tenant_slo_ttft_ms or ():
        name, eq, rest = spec.partition("=")
        if not eq:
            name, rest = None, spec
        try:
            ms = float(rest)
        except ValueError:
            sys.exit(f"--tenant-slo-ttft-ms {spec!r}: {rest!r} is not "
                     f"a number. {slo_grammar}")
        if ms <= 0:
            sys.exit(f"--tenant-slo-ttft-ms {spec!r}: must be > 0. "
                     f"{slo_grammar}")
        targets = [name] if name is not None else names
        for t in targets:
            if t not in names:
                sys.exit(f"--tenant-slo-ttft-ms {spec!r}: unknown "
                         f"tenant {t!r} (registered: {names}). "
                         f"{slo_grammar}")
            if t in slos:
                sys.exit(f"--tenant-slo-ttft-ms {spec!r}: tenant "
                         f"{t!r} already has a TTFT SLO")
            slos[t] = ms
    return names, quotas, slos


def _synth_adapters(names, vocab, rank, seed):
    """Deterministic rank-r logit-adapter factors per tenant ([V, r] /
    [r, V] float32) for the --rollout-adapters drill — small enough
    that the hot-swap mechanics, not the math, are the thing under
    test."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return {name: (rng.normal(0.0, 0.01, (vocab, rank))
                   .astype(np.float32),
                   rng.normal(0.0, 0.01, (rank, vocab))
                   .astype(np.float32))
            for name in names}


def _serve_body(ns, mesh, params, logger, rules=None) -> None:
    import json

    import jax.numpy as jnp
    import numpy as np

    from idc_models_tpu.observe import Timer, profile_trace
    from idc_models_tpu.serve import LMServer, load_trace, poisson_trace

    # declared SLOs (observe/slo.py): the serving metrics hooks feed
    # them and evaluate burn rates once per scheduler cycle; slo_alert
    # records stream to the same serve.jsonl
    slo = None
    slos = []
    if ns.slo_ttft_p95_ms is not None:
        from idc_models_tpu.observe import SLO

        slos.append(SLO.latency("ttft",
                                threshold_s=ns.slo_ttft_p95_ms / 1e3))
    if ns.slo_error_rate is not None:
        from idc_models_tpu.observe import SLO

        slos.append(SLO.rate("error_rate", budget=ns.slo_error_rate))
    if slos:
        from idc_models_tpu.observe import SLOEngine

        slo = SLOEngine(slos, short_window_s=ns.slo_window_s,
                        long_window_s=5.0 * ns.slo_window_s,
                        logger=logger)
    # resilience wiring (serve/faults, scheduler RetryPolicy,
    # serve/journal, serve/brownout — docs/ROBUSTNESS.md "Serving
    # resilience"): all default-off, armed by their flags
    retry = None
    if ns.max_retries > 0:
        from idc_models_tpu.serve import RetryPolicy

        retry = RetryPolicy(max_retries=ns.max_retries,
                            backoff_s=ns.retry_backoff_ms / 1e3)
    brownout = None
    if ns.brownout:
        from idc_models_tpu.serve import BrownoutController

        queue_high = (ns.brownout_queue_high
                      or max(ns.max_queue_depth // 2, 2))
        brownout = BrownoutController(
            slo=slo, queue_high=queue_high,
            clamp_tokens=ns.brownout_clamp_tokens,
            escalate_dwell_s=ns.brownout_dwell_ms / 1e3,
            clear_after_s=ns.brownout_clear_ms / 1e3, logger=logger)
    # multi-tenant serving (serve/tenancy.py, ISSUE 14): register the
    # tenant set with its quotas + per-tenant TTFT SLOs and build the
    # runtime against the serve knobs' windows/dwells. CLI tenants
    # carry no trained adapters (the synthetic model has none to
    # load) unless --rollout-adapters arms synthetic ones for the
    # hot-swap drill; quota/SLO/brownout isolation is the full drill
    # surface — docs/MULTITENANCY.md shows the adapter path in code.
    tenancy = None
    if ns.tenant_list:
        from idc_models_tpu.serve import TenantRegistry

        reg = TenantRegistry()
        # --rollout-adapters arms the bank at build time (rank is a
        # compiled shape): every tenant gets a deterministic rank-r
        # adapter the post-trace hot-swap then replaces live
        adapters = (_synth_adapters(ns.tenant_list, ns.vocab,
                                    ns.rollout_adapters, ns.seed)
                    if ns.rollout_adapters else {})
        for name in ns.tenant_list:
            reg.register(name, adapter=adapters.get(name),
                         quota=ns.tenant_quotas.get(name),
                         slo_ttft_p95_ms=ns.tenant_slos.get(name))
        tenancy = reg.build(
            vocab=ns.vocab, logger=logger,
            slo_short_window_s=ns.slo_window_s,
            brownout_dwell_s=ns.brownout_dwell_ms / 1e3,
            brownout_clear_s=ns.brownout_clear_ms / 1e3,
            brownout_clamp_tokens=ns.brownout_clamp_tokens)
    # count the journal's in-flight leftovers BEFORE the server opens
    # it for appending: these are the requests a previous crashed run
    # accepted but never finished
    n_pending = 0
    if ns.journal and Path(ns.journal).exists():
        from idc_models_tpu.serve import pending_requests

        n_pending = len(pending_requests(ns.journal))
    compile_cache = None
    if ns.compile_cache:
        from idc_models_tpu.serve import CompileCache

        compile_cache = CompileCache(ns.compile_cache, logger=logger)
    # --drafter learned/chained: restore the distilled draft LM through
    # the sharded-checkpoint path (layout re-resolved against THIS
    # mesh) and hand the drafter to the server; 'ngram' stays None so
    # LMServer builds its default prompt-lookup drafter from
    # --ngram-order. Vocab is checked HERE, at load time, because the
    # engine's own teaching error fires only after params land on
    # device — an operator typo should die before that.
    drafter = None
    draft_rules = None
    if ns.spec_decode and ns.drafter != "ngram":
        from idc_models_tpu.models.draft_lm import DraftLM, load_draft_lm
        from idc_models_tpu.models.registry import DRAFT_LM_RULES

        draft_rules = DRAFT_LM_RULES if rules is not None else None
        dparams, dcfg = load_draft_lm(ns.draft_ckpt, mesh=mesh,
                                      rules=draft_rules)
        if dcfg["vocab_size"] != ns.vocab:
            sys.exit(f"--draft-ckpt {ns.draft_ckpt} was distilled "
                     f"against a {dcfg['vocab_size']}-token vocab but "
                     f"this target serves --vocab {ns.vocab}: drafter "
                     f"and target must share one tokenizer (the verify "
                     f"program compares token IDS) — re-distill the "
                     f"drafter against this target "
                     f"(models/draft_lm.distill_draft_lm)")
        learned = DraftLM(ns.draft_k, dparams, dcfg)
        if ns.drafter == "chained":
            from idc_models_tpu.models.draft import (
                ChainedDrafter, NGramDrafter,
            )

            drafter = ChainedDrafter(
                NGramDrafter(ns.draft_k, order=ns.ngram_order), learned)
        else:
            drafter = learned
    server = LMServer(
        params, embed_dim=ns.embed_dim, num_heads=ns.num_heads,
        num_blocks=ns.num_blocks, t_max=ns.t_max, n_slots=ns.slots,
        window=ns.window, mesh=mesh, cache_dtype=jnp.float32,
        temperature=ns.temperature, top_k=ns.top_k or None,
        eos_id=ns.eos, max_queue_depth=ns.max_queue_depth,
        max_prefills_per_cycle=ns.max_prefills_per_cycle, logger=logger,
        prefill_chunk=ns.prefill_chunk or None,
        prefix_cache_mb=ns.prefix_cache_mb,
        kv_dtype=("int8" if ns.kv_dtype == "int8" else None), slo=slo,
        retry=retry, fault_plan=ns.serve_fault_plan,
        journal=ns.journal, brownout=brownout,
        spec_decode=ns.spec_decode, draft_k=ns.draft_k,
        draft_order=ns.ngram_order, drafter=drafter,
        draft_partition_rules=draft_rules,
        kv_page_size=ns.kv_page_size or None,
        kv_pages=ns.kv_pages or None,
        kv_decode_reserve=ns.kv_decode_reserve or None,
        tenancy=tenancy, partition_rules=rules,
        compile_cache=compile_cache)
    if n_pending:
        readmitted = server.resubmit_pending(ns.journal)
        line = (f"journal: re-admitted {len(readmitted)} in-flight "
                f"request(s) from a previous run")
        refused = n_pending - len(readmitted)
        if refused:
            # backpressure refusals leave no finish record, so the WAL
            # still holds them — an honest count beats claiming full
            # recovery, and a rerun picks up the remainder
            line += (f"; {refused} refused by backpressure — raise "
                     f"--max-queue-depth and rerun with the same "
                     f"--journal to recover them")
        print(line)
    if ns.save_ckpt:
        # each device writes only its own shards; the manifest is the
        # atomic completion contract (checkpoint/sharded.py). With
        # --train-steps this mints a --rollout candidate in one run.
        from idc_models_tpu.checkpoint import save_sharded

        manifest = save_sharded(ns.save_ckpt, server.engine._params,
                                step=ns.train_steps, logger=logger).wait()
        print(f"checkpoint: wrote {manifest['n_shards']} shard(s) / "
              f"{len(manifest['leaves'])} leaves to {ns.save_ckpt}")
    if ns.trace:
        trace = load_trace(ns.trace)
    else:
        trace = poisson_trace(
            ns.requests, rate_per_s=ns.rate, vocab=ns.vocab,
            t_max=ns.t_max, eos_id=ns.eos,
            prompt_lens=(2, max(ns.t_max // 4, 2)),
            budgets=(2, max(ns.t_max // 4, 2)), seed=ns.seed,
            sampled=ns.temperature > 0.0, tenants=ns.tenant_list)
    print(f"serving {len(trace)} requests on {ns.slots} slots "
          f"(window {ns.window}, t_max {ns.t_max}, ring "
          f"{ns.seq_parallel})")
    from idc_models_tpu.serve import InjectedEngineCrash

    crashed = None
    drained = False
    rollout_ctl = None
    prev_sigterm = _arm_sigterm()
    try:
        with Timer("Serving trace", logger=logger), \
                profile_trace(ns.profile_dir):
            try:
                if ns.rollout:
                    from idc_models_tpu.checkpoint import (
                        run_with_rollout,
                    )

                    results, rollout_ctl = run_with_rollout(
                        server, trace, ns.rollout,
                        start_after=ns.rollout_at,
                        realtime=ns.realtime,
                        canary_fraction=ns.canary_fraction,
                        canary_requests=ns.canary_requests,
                        logger=logger)
                else:
                    results = server.run(trace, realtime=ns.realtime)
            except InjectedEngineCrash as e:
                # the drill's hard death: the failure cleanup already
                # finalized every in-flight request as an error Result
                # — salvage them, report honestly, and point at the
                # recovery
                crashed = e
                results = server.results()
            except _DrainRequested:
                # SIGTERM: stop admitting, finish what's running, let
                # the journal's finish records land — the honest
                # graceful-shutdown contract
                drained = True
                server.scheduler.begin_drain()
                server.drain()
                results = server.results()
    finally:
        _disarm_sigterm(prev_sigterm)
    if drained:
        print("SIGTERM: drained gracefully — admissions stopped, "
              "in-flight requests finished, journal flushed"
              + (f" ({ns.journal})" if ns.journal else ""))
    if crashed is not None:
        hint = (f"; rerun with --journal {ns.journal} to recover the "
                f"in-flight requests" if ns.journal else
                "; arm --journal to make this recoverable")
        print(f"engine crashed mid-run (injected): {crashed}{hint}")
    n_ok = sum(r.status == "ok" for r in results)
    summary = server.summary()
    print(f"served: ok={n_ok} timeout={summary['serve_timed_out']} "
          f"rejected={summary['serve_rejected']} "
          f"tokens={summary['serve_tokens']}")
    # TTFT decomposed so an operator can tell queueing from compute:
    # p95 TTFT = queue wait (add slots / shed load) + prefill compute
    # (shrink prompts, chunk smaller, warm the prefix cache). Absent
    # when nothing emitted a first token (all expired/rejected).
    if summary.get("serve_ttft_ms_p95") is not None:
        print(f"ttft p95 {summary['serve_ttft_ms_p95']} ms = queue-wait "
              f"{summary['serve_queue_wait_ms_p95']} ms + prefill "
              f"{summary['serve_prefill_ms_p95']} ms (p95s)")
    if summary.get("serve_prefix_hit_rate") is not None:
        print(f"prefix cache: hit rate "
              f"{summary['serve_prefix_hit_rate']} "
              f"({summary['serve_prefix_hits']} hits, "
              f"{summary['serve_prefix_evictions']} evictions, "
              f"{summary['serve_prefix_bytes']} bytes)")
    if summary.get("serve_compile_cache") is not None:
        cc = summary["serve_compile_cache"]
        print(f"compile cache: {cc['hits']} hit(s) "
              f"({cc['deserialize_s']:.3f}s deserializing), "
              f"{cc['misses']} miss(es) -> {cc['stores']} store(s) "
              f"({cc['compile_s']:.3f}s compiling), "
              f"{cc['evicted_corrupt']} corrupt eviction(s)")
    if ns.kv_page_size:
        # what paging actually bought: peak pool occupancy vs the
        # capacity the same HBM would hold as contiguous per-slot
        # rows, and the tokens-per-HBM-byte the claim is stated in
        print(f"paged kv: {summary['serve_kv_pages_used_peak']}/"
              f"{summary['serve_kv_pages_total']} pages peak "
              f"(page {ns.kv_page_size} tokens), resident peak "
              f"{summary['serve_kv_resident_tokens_peak']} tokens / "
              f"{summary['serve_kv_resident_bytes_peak']} bytes "
              f"(tokens/HBM-byte "
              f"{summary['serve_kv_tokens_per_hbm_byte']}), "
              f"exhaustion backpressure "
              f"{summary['serve_page_exhaustions']}")
    if ns.spec_decode:
        # what speculation actually bought: accept rate over drafted
        # tokens and emitted tokens per slot per verify (1.0 would
        # mean plain decode did just as well)
        line = (f"speculative ({ns.drafter}): "
                f"drafted={summary['serve_spec_drafted']} "
                f"accepted={summary['serve_spec_accepted']} "
                f"accept_rate={summary['serve_spec_accept_rate']} "
                f"tokens/dispatch="
                f"{summary['serve_spec_tokens_per_dispatch']} "
                f"({summary['serve_spec_verify_dispatches']} verify + "
                f"{summary['serve_decode_dispatches'] - summary['serve_spec_verify_dispatches']}"
                f" window dispatches)")
        if summary.get("serve_spec_propose_s") is not None:
            # the overhead speculation pays before any win: host+device
            # seconds spent PROPOSING (the bench states it as a % of
            # window time — serve_spec_nonrep_draft_overhead_pct)
            line += f" propose_s={summary['serve_spec_propose_s']}"
        print(line)
    if slo is not None:
        names = sorted({a["slo"] for a in slo.alerts})
        print(f"slo: {len(slo.alerts)} alert(s)"
              + (f" ({', '.join(names)})" if names else ""))
    if rollout_ctl is not None:
        # the verdict an operator acts on: terminal stage, how much
        # canary evidence backed it, and the rollback reason if any
        line = (f"rollout: {rollout_ctl.stage} after "
                f"{rollout_ctl.canary_finishes} canary finish(es)")
        if rollout_ctl.reason:
            line += f" — {rollout_ctl.reason}"
        print(line)
    if ns.rollout_adapters and crashed is None:
        # the cheap first rung, live: replace the whole bank with
        # re-seeded factors — same compiled shapes, zero recompile
        fresh = _synth_adapters(ns.tenant_list, ns.vocab,
                                ns.rollout_adapters, ns.seed + 1)
        server.swap_adapters(
            np.stack([fresh[n][0] for n in ns.tenant_list]),
            np.stack([fresh[n][1] for n in ns.tenant_list]))
        print(f"adapter rollout: hot-swapped rank-"
              f"{ns.rollout_adapters} adapters for "
              f"{len(ns.tenant_list)} tenant(s), zero recompile")
    if tenancy is not None:
        # what isolation actually did, one line per tenant: volume,
        # tail latency, sheds/quota refusals, the tenant's own
        # brownout high-water stage, and whether its TTFT alert fired
        for name, ts in summary["serve_tenants"].items():
            bc = tenancy.brownouts.get(name)
            alerts = (len([a for a in tenancy.slo.alerts
                           if a["slo"] == f"ttft:{name}"])
                      if tenancy.slo is not None else 0)
            print(f"tenant {name}: requests={ts['requests']} "
                  f"tokens={ts['tokens']} "
                  f"ttft_p95={ts['ttft_ms_p95']}ms "
                  f"shed={ts['shed']} "
                  f"quota_rejected={ts['quota_rejections']} "
                  f"brownout_max_stage="
                  f"{bc.max_stage_seen if bc is not None else 0} "
                  f"slo_alerts={alerts}")
    # resilience epilogue: what the armed machinery actually did —
    # faults fired, quarantines, retries, brownout sheds/clamps
    if (ns.serve_fault_plan is not None or retry is not None
            or brownout is not None or summary["serve_slot_faults"]):
        line = (f"resilience: injected={summary['serve_faults_injected']}"
                f" slot_faults={summary['serve_slot_faults']}"
                f" retries={summary['serve_retries']}"
                f" shed={summary['serve_shed']}"
                f" clamped={summary['serve_clamped']}")
        if brownout is not None:
            line += (f" brownout_max_stage={brownout.max_stage_seen}"
                     f" (stage {brownout.stage} at exit)")
        print(line)
    print("serve summary:", json.dumps(summary))
    if logger:
        logger.log(event="serve_summary", **summary)
    server.close()
    _finish_logger(logger)


def _run_serve_cluster(ns):
    """Disaggregated multi-replica serving (serve/cluster/, ISSUE 12):
    a router tier over N engine replicas — SLO/health-aware placement,
    prefill/decode separation over the cluster prefix registry, drain,
    and journal-backed failover (docs/LONG_CONTEXT.md "Disaggregated
    serving")."""
    import json

    import jax
    import jax.numpy as jnp

    from idc_models_tpu.observe import JsonlLogger, Timer
    from idc_models_tpu.serve import (
        PrefixRegistry, RetryPolicy, Router, build_replica, load_trace,
        poisson_trace,
    )

    if ns.replicas < 1:
        sys.exit(f"--replicas {ns.replicas} must be >= 1")
    if ns.prefill_replicas < 0:
        sys.exit(f"--prefill-replicas {ns.prefill_replicas} must be "
                 f">= 0")
    if ns.prefill_chunk and (ns.prefill_chunk < 1
                             or ns.t_max % ns.prefill_chunk):
        sys.exit(f"--prefill-chunk {ns.prefill_chunk} must be >= 1 "
                 f"and divide --t-max {ns.t_max}")
    if ns.prefix_cache_mb > 0 and not ns.prefill_chunk:
        sys.exit("--prefix-cache-mb needs --prefill-chunk")
    if ns.registry_mb > 0 and not ns.prefix_cache_mb:
        sys.exit("--registry-mb needs --prefix-cache-mb (replicas "
                 "adopt registry snapshots through their local cache)")
    if ns.prefill_replicas and not ns.registry_mb:
        sys.exit("--prefill-replicas needs --registry-mb: the handoff "
                 "artifact travels through the cluster prefix registry")
    if ns.max_retries < 0:
        sys.exit(f"--max-retries {ns.max_retries} must be >= 0")
    if ns.hedge_after_ms is not None and ns.hedge_after_ms <= 0:
        sys.exit(f"--hedge-after-ms {ns.hedge_after_ms} must be > 0")
    n_fleet = ns.replicas + ns.prefill_replicas
    for flag, idx in (("--kill-replica", ns.kill_replica),
                      ("--drain-replica", ns.drain_replica)):
        if idx is not None and not 0 <= idx < n_fleet:
            sys.exit(f"{flag} {idx} outside the fleet [0, {n_fleet})")
    if ns.kill_replica is not None and not ns.journal_dir:
        sys.exit("--kill-replica needs --journal-dir: migration "
                 "replays the dead replica's journal WAL")
    if ns.kill_after_steps < 0:
        sys.exit(f"--kill-after-steps {ns.kill_after_steps} must be "
                 f">= 0")
    if ns.autoscale_max is not None and ns.autoscale_max < ns.replicas:
        sys.exit(f"--autoscale-max {ns.autoscale_max} must be >= "
                 f"--replicas {ns.replicas} (it is the fleet ceiling)")
    if ns.metrics_port is not None and not 0 <= ns.metrics_port <= 65535:
        sys.exit(f"--metrics-port {ns.metrics_port} must be in "
                 f"[0, 65535] (0 = OS-assigned)")

    logger = (JsonlLogger(Path(ns.path) / "logs" / "cluster.jsonl")
              if ns.path else None)
    model_kw = dict(embed_dim=ns.embed_dim, num_heads=ns.num_heads,
                    num_blocks=ns.num_blocks, t_max=ns.t_max)
    from idc_models_tpu.models.lm import attention_lm

    model = attention_lm(ns.vocab, ns.t_max, embed_dim=ns.embed_dim,
                         num_heads=ns.num_heads, mlp_dim=ns.mlp_dim,
                         num_blocks=ns.num_blocks)
    params = model.init(jax.random.key(ns.seed)).params

    registry = (PrefixRegistry(ns.prefill_chunk,
                               int(ns.registry_mb * 1024 * 1024),
                               logger=logger)
                if ns.registry_mb > 0 else None)
    # always a policy: --max-retries 0 means ZERO re-placements (a
    # valid, strict budget), never "unbounded"
    retry = RetryPolicy(max_retries=ns.max_retries)
    compile_cache = None
    if ns.compile_cache:
        from idc_models_tpu.serve import CompileCache

        compile_cache = CompileCache(ns.compile_cache, logger=logger)
    devices = jax.devices()

    def _build(i, rid, role):
        return build_replica(
            params, replica_id=rid, role=role,
            device=devices[i % len(devices)],
            n_slots=ns.slots, window=ns.window,
            prefill_chunk=ns.prefill_chunk or None,
            prefix_cache_mb=ns.prefix_cache_mb,
            shared_prefix=registry,
            journal_path=(
                str(Path(ns.journal_dir) / f"journal-{rid}.jsonl")
                if ns.journal_dir else None),
            retry=retry,
            brownout_queue_high=ns.brownout_queue_high,
            max_queue_depth=ns.max_queue_depth,
            temperature=ns.temperature, top_k=ns.top_k or None,
            eos_id=ns.eos, cache_dtype=jnp.float32,
            compile_cache=compile_cache,
            logger=logger, **model_kw)

    replicas = []
    with Timer("Cluster build", logger=logger):
        for i in range(n_fleet):
            role = "prefill" if i >= ns.replicas else "mixed"
            replicas.append(_build(i, f"r{i}", role))
    autoscaler = None
    replica_factory = None
    if ns.autoscale_max is not None:
        from idc_models_tpu.serve import AutoscaleConfig, Autoscaler

        autoscaler = Autoscaler(
            AutoscaleConfig(min_replicas=ns.replicas,
                            max_replicas=ns.autoscale_max),
            logger=logger)
        # a spun-up replica inherits the fleet's build kwargs — and
        # the shared compile cache, so it deserializes warm instead
        # of recompiling
        auto_ordinal = [n_fleet]

        def replica_factory(rid):
            i = auto_ordinal[0]
            auto_ordinal[0] += 1
            return _build(i, rid, "mixed")

    router = Router(
        replicas, retry=retry,
        hedge_after_s=(None if ns.hedge_after_ms is None
                       else ns.hedge_after_ms / 1e3),
        prefix_registry=registry, logger=logger,
        autoscaler=autoscaler, replica_factory=replica_factory)
    # fleet observability (ISSUE 20, serve/cluster/telemetry.py):
    # merged replica-labeled /metrics + fleet /healthz, armed BEFORE
    # the trace so a scraper sees the fleet from its first placement
    exporter = None
    if ns.metrics_port is not None:
        from idc_models_tpu.observe import MetricsExporter
        from idc_models_tpu.serve import ClusterTelemetry

        telemetry = ClusterTelemetry(router,
                                     compile_cache=compile_cache)
        try:
            exporter = MetricsExporter(
                router.registry, port=ns.metrics_port,
                cluster=telemetry).start()
        except OSError as e:
            sys.exit(f"serve-cluster: cannot bind --metrics-port "
                     f"{ns.metrics_port}: {e}")
        print(f"fleet metrics: {exporter.url}/metrics  healthz: "
              f"{exporter.url}/healthz")
    if ns.watchdog:
        from idc_models_tpu.serve import ClusterWatchdog

        router.watchdog = ClusterWatchdog(router, logger=logger)
    if ns.trace:
        trace = load_trace(ns.trace)
    else:
        trace = poisson_trace(
            ns.requests, rate_per_s=ns.rate, vocab=ns.vocab,
            t_max=ns.t_max, eos_id=ns.eos,
            prompt_lens=(2, max(ns.t_max // 4, 2)),
            budgets=(2, max(ns.t_max // 4, 2)), seed=ns.seed,
            sampled=ns.temperature > 0.0)
    print(f"cluster: {ns.replicas} decode replica(s) + "
          f"{ns.prefill_replicas} prefill replica(s), {ns.slots} "
          f"slots each (window {ns.window}, t_max {ns.t_max}); "
          f"serving {len(trace)} requests")
    drill_at = (ns.kill_after_steps
                if (ns.kill_replica is not None
                    or ns.drain_replica is not None) else None)
    drained_on_signal = False
    prev_sigterm = _arm_sigterm()
    try:
        with Timer("Serving trace (cluster)", logger=logger):
            try:
                if drill_at is None:
                    results = router.run(trace, realtime=ns.realtime)
                else:
                    # drill mode: burst-submit (re-offering on
                    # backpressure — a refused submit leaves no Result
                    # and must not be silently dropped), step to the
                    # drill point, fire it, then drain —
                    # deterministic and journal-backed
                    steps = 0
                    for _, req in sorted(trace, key=lambda tr: tr[0]):
                        while not router.submit(req):
                            shed = router.poll(req.id)
                            if shed is not None and shed.status == "shed":
                                break   # terminal answer, not a race
                            router.step()
                            steps += 1
                    for _ in range(max(drill_at - steps, 0)):
                        router.step()
                    if ns.drain_replica is not None:
                        router.drain_replica(f"r{ns.drain_replica}")
                        print(f"drained replica r{ns.drain_replica}")
                    if ns.kill_replica is not None:
                        migrated = router.kill_replica(
                            f"r{ns.kill_replica}")
                        print(f"killed replica r{ns.kill_replica}: "
                              f"{len(migrated)} journaled request(s) "
                              f"migrated onto the survivors")
                    router.drain()
                    results = router.results()
            except _DrainRequested:
                # SIGTERM: every live replica stops admitting, the
                # router steps the fleet until in-flight work lands,
                # and each WAL carries its finish records
                drained_on_signal = True
                for rep in router.replicas:
                    if rep.state == "live":
                        rep.drain()
                router.drain()
                results = router.results()
    finally:
        _disarm_sigterm(prev_sigterm)
        if exporter is not None:
            exporter.close()
    if drained_on_signal:
        print("SIGTERM: cluster drained gracefully — admissions "
              "stopped, in-flight requests finished on every live "
              "replica, journals flushed")
    n_ok = sum(r.status == "ok" for r in results)
    summary = router.summary()
    print(f"served: ok={n_ok} "
          f"timed_out={summary['cluster_timed_out']} "
          f"rejected={summary['cluster_rejected']} "
          f"shed={summary['cluster_shed']} "
          f"tokens={summary['cluster_tokens']}")
    if summary.get("cluster_ttft_ms_p95") is not None:
        print(f"ttft p95 {summary['cluster_ttft_ms_p95']} ms "
              f"(pooled across replicas)")
    print(f"placements: {summary['cluster_placements']}  "
          f"migrations={summary['cluster_migrations']} "
          f"slot_migrations={summary['cluster_slot_migrations']} "
          f"handoffs={summary['cluster_handoffs']} "
          f"hedges={summary['cluster_hedges']}  replicas "
          f"live={summary['cluster_replicas_live']} "
          f"draining={summary['cluster_replicas_draining']} "
          f"dead={summary['cluster_replicas_dead']}")
    if autoscaler is not None:
        ups = sum(1 for d in autoscaler.decisions
                  if d["action"] == "up")
        downs = sum(1 for d in autoscaler.decisions
                    if d["action"] == "down")
        print(f"autoscaler: {ups} scale-up(s), {downs} "
              f"scale-down(s), fleet "
              f"{summary['cluster_replicas_live']} live at exit "
              f"(bounds [{ns.replicas}, {ns.autoscale_max}])")
    if compile_cache is not None:
        cs = compile_cache.summary()
        print(f"compile cache: {cs['hits']} hit(s) "
              f"({cs['deserialize_s']:.3f}s deserializing), "
              f"{cs['misses']} miss(es) -> {cs['stores']} store(s) "
              f"({cs['compile_s']:.3f}s compiling)")
    if registry is not None:
        print(f"prefix registry: {summary['cluster_prefix_hits']} "
              f"hit(s), {summary['cluster_prefix_published']} "
              f"published, {summary['cluster_prefix_bytes']} bytes")
    if router.watchdog is not None:
        kinds = sorted({a["kind"]
                        for a in router.watchdog.anomalies})
        print(f"watchdog: {len(router.watchdog.anomalies)} "
              f"anomaly(ies)"
              + (f" ({', '.join(kinds)})" if kinds else ""))
    print("cluster summary:", json.dumps(summary))
    if logger:
        logger.log(event="cluster_summary", **summary)
    router.close()
    _finish_logger(logger)


def _run_fed_population(ns):
    """Population-scale federated mode: virtual clients + cohort
    sampling + streamed (or async buffered) aggregation — ROADMAP
    item 4's millions-of-users story at the CLI surface."""
    import jax

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.configs import get_preset
    from idc_models_tpu import faults as faults_lib
    from idc_models_tpu.federated import (
        ClientPopulation, CohortSampler, DriverConfig, RoundFailure,
        initialize_server, make_async_round, make_federated_eval,
        make_population_round, run_rounds,
    )
    from idc_models_tpu.federated import robust
    from idc_models_tpu.models import registry
    from idc_models_tpu.observe import Timer, profile_trace
    from idc_models_tpu.train import rmsprop

    preset = _apply_overrides(
        get_preset("fed"), ns, ["batch_size", "lr", "rounds",
                                "local_epochs"])
    n_pop = int(ns.population)
    cohort = int(ns.cohort)
    if cohort < 1:
        sys.exit(f"--cohort must be >= 1, got {cohort}")
    if cohort > n_pop:
        sys.exit(f"--cohort {cohort} exceeds --population {n_pop}: a "
                 f"round cannot sample more clients than the "
                 f"population holds")
    wave = int(ns.cohort_wave) or cohort
    use_async = int(ns.async_buffer) != 0
    if use_async and ns.async_buffer < 0:
        sys.exit(f"--async-buffer must be >= 1 (0 disables async "
                 f"mode), got {ns.async_buffer}")
    if use_async and int(ns.cohort_wave):
        sys.exit("--cohort-wave only applies to synchronous streamed "
                 "rounds; the async server buffers by --async-buffer "
                 "instead (drop one of the two flags)")
    decay = float(ns.staleness_decay)
    if not 0.0 < decay <= 1.0:
        sys.exit(f"--staleness-decay must be in (0, 1], got {decay} "
                 f"(1 = no discount; smaller discounts staler "
                 f"updates harder)")
    n_dev = len(jax.devices())
    mesh = meshlib.client_mesh(meshlib.largest_dividing_mesh(wave,
                                                             n_dev))
    model_name = getattr(ns, "model", None) or preset.model
    image_size = 10 if model_name == "small_cnn" else preset.image_size
    s = int(ns.client_examples)
    if s < 1:
        sys.exit(f"--client-examples must be >= 1, got {s} (each "
                 f"virtual client's shard size)")
    weight_range = (0.5 * s, 1.5 * s) if ns.weighted_sampling else \
        (float(s), float(s))
    population = ClientPopulation(
        n_pop, examples_per_client=s, image_size=image_size,
        seed=ns.seed, weight_range=weight_range)
    sampler = CohortSampler(population, cohort, seed=ns.seed,
                            weighted=ns.weighted_sampling)
    logger = _logger(ns)
    delay_ms = float(getattr(ns, "fault_delay_ms", 0.0))
    if delay_ms < 0:
        sys.exit(f"--fault-delay-ms must be >= 0, got {delay_ms}")
    plan = None
    if getattr(ns, "faults", None):
        try:
            plan = faults_lib.parse_population_fault_spec(
                ns.faults, n_pop, seed=ns.seed,
                delay_unit_s=delay_ms / 1000.0)
        except ValueError as e:
            sys.exit(str(e))
        print(f"[idc_models_tpu] injecting faults: {plan}",
              file=sys.stderr)
        if (use_async and delay_ms == 0.0
                and plan.max_staleness > 0):
            # without a wall delay a straggler never arrives late, and
            # async staleness IS lateness — say so instead of letting
            # the drill silently run fault-free
            print("[idc_models_tpu] straggler faults are INERT in "
                  "async mode without --fault-delay-ms: buffered "
                  "staleness comes from late arrival, and the plan's "
                  "stragglers arrive on time", file=sys.stderr)

    spec = registry.get_model(model_name)
    model = spec.build(preset.num_outputs, 3)
    loss_fn = _loss_for(preset.num_outputs)
    opt = rmsprop(preset.lr / 10.0)
    server = initialize_server(model, jax.random.key(ns.seed))
    server_ckpt = Path(ns.path) / "fed_server" if ns.path else None
    resumed = False
    from idc_models_tpu.train import checkpoint_exists, restore_checkpoint

    if server_ckpt is not None and checkpoint_exists(server_ckpt):
        server = restore_checkpoint(server_ckpt, jax.device_get(server))
        print(f"resuming federated training from round "
              f"{int(server.round)}")
        resumed = int(server.round) > 0
    if not use_async:
        # the streamed wave program wants the server replicated over
        # the client mesh; the async server is host-driven and keeps
        # default placement
        server = jax.device_put(server, meshlib.replicated(mesh))
    # separate resume high-water marks per event: fed_cohort is written
    # INSIDE round_fn while the `round` record lands after eval, so a
    # crash in between leaves them unequal — one shared max would
    # suppress the missing record's re-log forever
    logged_through = -1          # `round` records (and round_health)
    cohort_through = -1          # fed_cohort records (builder-owned)
    if resumed and logger is not None and logger.path.exists():
        import json as _json

        for line in logger.path.read_text().splitlines():
            try:
                rec = _json.loads(line)
            except ValueError:
                continue
            if rec.get("event") == "round":
                logged_through = max(logged_through, int(rec["round"]))
            elif rec.get("event") == "fed_cohort":
                cohort_through = max(cohort_through, int(rec["round"]))

    agg_name = getattr(ns, "aggregator", "mean")
    agg_kw = ({"trim": ns.trim} if agg_name == "trimmed_mean" else
              {"max_norm": ns.clip_norm} if agg_name == "norm_clip"
              else {})
    try:
        agg = robust.get_aggregator(agg_name, **agg_kw)
        if use_async:
            round_fn = make_async_round(
                model, opt, loss_fn, population, sampler,
                buffer_size=int(ns.async_buffer),
                staleness_decay=decay,
                local_epochs=preset.local_epochs,
                batch_size=preset.batch_size, aggregator=agg,
                faults=plan, seed=ns.seed, logger=logger,
                log_from_round=cohort_through)
            participant_ids_fn = lambda r: round_fn.last_participants
        else:
            round_fn = make_population_round(
                model, opt, loss_fn, mesh, population, sampler,
                wave_size=wave, local_epochs=preset.local_epochs,
                batch_size=preset.batch_size, aggregator=agg,
                faults=plan, barrier_sleep=delay_ms > 0,
                logger=logger, log_from_round=cohort_through)
            participant_ids_fn = lambda r: sampler.cohort(r)
    except ValueError as e:
        sys.exit(str(e))

    # held-out eval cohort: a fixed seeded draw, materialized once —
    # O(wave) like everything else in this mode
    eval_sampler = CohortSampler(population, wave, seed=ns.seed + 4242)
    eval_imgs, eval_labels, eval_w = population.materialize(
        eval_sampler.cohort(0))
    cshard = meshlib.sharding(mesh, meshlib.CLIENT_AXIS)
    eval_imgs = jax.device_put(eval_imgs, cshard)
    eval_labels = jax.device_put(eval_labels, cshard)
    eval_fn = make_federated_eval(model, loss_fn, mesh)

    def eval_round(sv):
        em = _fetch_scalars(eval_fn(sv, eval_imgs, eval_labels, eval_w))
        return {"test_loss": float(em["loss"]),
                "test_acc": float(em["accuracy"])}

    print("round, train_loss, train_acc, test_loss, test_acc")
    totals = {"updates": 0, "staleness_sum": 0.0, "participants": 0}

    def print_round(entry):
        print(f"{entry['round']}, {entry['loss']:.4f}, "
              f"{entry['accuracy']:.4f}, {entry['test_loss']:.4f}, "
              f"{entry['test_acc']:.4f}")
        totals["updates"] += int(entry.get("updates", 0))
        totals["staleness_sum"] += (float(entry.get("staleness_mean",
                                                    0.0))
                                    * int(entry.get("participants", 0)))
        totals["participants"] += int(entry.get("participants", 0))
        if logger and entry["round"] > logged_through:
            logger.log(event="round", round=entry["round"],
                       train_loss=entry["loss"],
                       train_acc=entry["accuracy"],
                       test_loss=entry["test_loss"],
                       test_acc=entry["test_acc"],
                       clients_dropped=int(
                           entry.get("clients_dropped", 0)))

    spike = getattr(ns, "loss_spike_ratio", 10.0)
    if spike is not None and spike != 0 and spike <= 1:
        sys.exit(f"--loss-spike-ratio {spike} must be > 1 (0 disables "
                 f"the detector)")
    config = DriverConfig(
        rounds=preset.rounds,
        timeout_s=getattr(ns, "round_timeout", None),
        max_attempts=1 + max(int(getattr(ns, "max_round_retries", 2)),
                             0),
        loss_spike_ratio=spike if spike and spike > 1 else None,
        checkpoint_path=server_ckpt,
        checkpoint_every=max(int(getattr(ns, "checkpoint_every", 10)),
                             1))
    try:
        with Timer("Federated training", logger=logger), \
                profile_trace(ns.profile_dir):
            result = run_rounds(
                round_fn, server, None, None,
                np.ones((cohort,), np.float32), config=config,
                seed=ns.seed + 1, eval_fn=eval_round,
                on_round=print_round, logger=logger, verbose=True,
                log_from_round=logged_through,
                log_round_records=False, fault_plan=plan,
                participant_ids_fn=participant_ids_fn)
    except RoundFailure as e:
        sys.exit(f"[idc_models_tpu] federated training aborted: {e}")
    mode = "weighted" if ns.weighted_sampling else "uniform"
    decomp = (f" in {cohort // wave} wave(s) of {wave}; memory "
              f"bounded by the wave, not the population" if not
              use_async else "; memory bounded by the in-flight pool, "
              "not the population")
    print(f"population: {n_pop} virtual clients, cohort {cohort} "
          f"({mode}){decomp}")
    if use_async:
        mean_st = (totals["staleness_sum"] / totals["participants"]
                   if totals["participants"] else 0.0)
        print(f"async buffer: K={int(ns.async_buffer)}, staleness "
              f"decay {decay}, {totals['updates']} buffered update(s),"
              f" mean staleness {mean_st:.2f}")
    retried = [e for e in result.events if e["status"] != "ok"]
    if retried:
        print(f"[idc_models_tpu] {len(retried)} round attempt(s) "
              f"failed and were healed (rollback/reseed); see "
              f"round_health events", file=sys.stderr)
    _finish_logger(logger)


def _run_fed(ns):
    import jax

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.configs import get_preset
    from idc_models_tpu.data.partition import (
        pad_clients, partition_clients, train_test_client_split,
    )
    from idc_models_tpu import faults as faults_lib
    from idc_models_tpu.federated import (
        DriverConfig, RoundFailure, initialize_server, make_fedavg_round,
        make_federated_eval, run_rounds, seed_server_with,
    )
    from idc_models_tpu.models import registry
    from idc_models_tpu.observe import Timer, profile_trace
    from idc_models_tpu.train import (
        TwoPhaseConfig, checkpoint_exists, restore_checkpoint,
        rmsprop, save_checkpoint, two_phase_fit,
    )

    if ns.checkpoint_every < 1:
        sys.exit(f"--checkpoint-every {ns.checkpoint_every} must be "
                 f">= 1: saving every 0 rounds is never, and a crash "
                 f"then replays the whole run")
    if getattr(ns, "population", 0):
        return _run_fed_population(ns)
    preset = _apply_overrides(
        get_preset("fed"), ns,
        ["batch_size", "lr", "rounds", "iid", "num_clients", "local_epochs",
         "pretrain_epochs"])
    n_dev = len(jax.devices())
    # client count is independent of chip count: k = ceil(C/D) clients
    # train per device (vmapped), padded with weight-0 dummies
    n_clients = preset.num_clients
    ds = _load_idc(ns, preset.image_size, preset.dataset_limit)
    logger = _logger(ns)

    # Pretrain (C8): checkpoint-gated two-phase VGG16 on the pooled data.
    spec = registry.get_model(preset.model)
    mesh_dp = meshlib.data_mesh()
    from idc_models_tpu.data.idc import train_val_test_split

    train, val, _ = train_val_test_split(ds, seed=ns.seed)
    ckpt = (Path(ns.path) / "pretrained" / "cp.ckpt" if ns.path else None)
    model = spec.build(preset.num_outputs, 3)
    if ckpt is not None and checkpoint_exists(ckpt):
        variables = model.init(jax.random.key(ns.seed))
        target = {"params": variables.params, "state": variables.state}
        restored = restore_checkpoint(ckpt, target)
        params, model_state = restored["params"], restored["state"]
        print(f"restored pretrained weights from {ckpt}")
        if ns.pretrained_weights:
            print(f"[idc_models_tpu] --pretrained-weights ignored: "
                  f"checkpoint {ckpt} takes precedence (delete it to "
                  f"re-pretrain from the artifact)", file=sys.stderr)
    else:
        result = two_phase_fit(
            preset.model, preset.num_outputs, train, val, mesh_dp,
            TwoPhaseConfig(lr=preset.lr, epochs=preset.pretrain_epochs,
                           fine_tune_epochs=0,
                           batch_size=preset.batch_size,
                           fine_tune_at=preset.fine_tune_at, seed=ns.seed),
            pretrained_weights=ns.pretrained_weights,
            artifact_path=ns.path, logger=logger)
        params, model_state = result.state.params, result.state.model_state
        if ckpt is not None:
            save_checkpoint(ckpt, {"params": jax.device_get(params),
                                   "state": jax.device_get(model_state)})

    # Federate: clients fine-tune above fine_tune_at at lr/10
    # (fed_model.py:140-147,208).
    mesh = meshlib.client_mesh(min(n_clients, n_dev))
    n_mesh = mesh.devices.size
    imgs, labels = partition_clients(ds, n_clients, iid=bool(preset.iid),
                                     seed=ns.seed)
    n_per_client = imgs.shape[1]
    train_ids, test_ids = train_test_client_split(
        n_clients, preset.test_client_fraction, seed=ns.seed)
    # train clients carry weight = examples; test clients weight 0; pad
    # the client axis to the mesh with inert weight-0 dummies
    w_train = np.zeros((n_clients,), np.float32)
    w_train[train_ids] = n_per_client
    w_test = np.zeros((n_clients,), np.float32)
    w_test[test_ids] = n_per_client
    imgs, labels, w_train, w_test = pad_clients(imgs, labels, w_train,
                                                w_test, multiple=n_mesh)
    # upload the stacked client shards to HBM once — not once per round
    cshard = meshlib.sharding(mesh, meshlib.CLIENT_AXIS)
    imgs = jax.device_put(imgs, cshard)
    labels = jax.device_put(labels, cshard)
    opt = rmsprop(preset.lr / 10.0,
                  trainable_mask=spec.fine_tune_mask(params,
                                                     preset.fine_tune_at))
    server = seed_server_with(
        initialize_server(model, jax.random.key(ns.seed)),
        params, model_state)
    # Round-loop checkpoint/resume: the reference checkpoints only the
    # pretrainer (SURVEY.md §5); here the federated loop resumes too.
    server_ckpt = Path(ns.path) / "fed_server" if ns.path else None
    resumed = False
    if server_ckpt is not None and checkpoint_exists(server_ckpt):
        server = restore_checkpoint(server_ckpt, jax.device_get(server))
        print(f"resuming federated training from round {int(server.round)}")
        resumed = int(server.round) > 0
    # restored/pretrained arrays may live on a single device; the round
    # program wants them replicated over the client mesh
    server = jax.device_put(server, meshlib.replicated(mesh))
    plan = None
    if getattr(ns, "faults", None):
        plan = faults_lib.parse_fault_spec(ns.faults, n_clients)
        print(f"[idc_models_tpu] injecting faults: {plan}",
              file=sys.stderr)
    from idc_models_tpu.federated import robust

    agg_name = getattr(ns, "aggregator", "mean")
    agg_kw = ({"trim": ns.trim} if agg_name == "trimmed_mean" else
              {"max_norm": ns.clip_norm} if agg_name == "norm_clip" else {})
    round_fn = make_fedavg_round(
        model, opt, _loss_for(preset.num_outputs), mesh,
        local_epochs=preset.local_epochs, batch_size=preset.batch_size,
        aggregator=robust.get_aggregator(agg_name, **agg_kw), faults=plan)
    eval_fn = make_federated_eval(model, _loss_for(preset.num_outputs), mesh)
    print("round, train_loss, train_acc, test_loss, test_acc")
    every = max(int(getattr(ns, "checkpoint_every", 10)), 1)
    # A resume from an every-N checkpoint deterministically replays the
    # rounds after the last save (same fold_in(round) rng). Replayed
    # rounds print again (this process really runs them) but must NOT
    # append duplicate records to the append-only run.jsonl — consumers
    # aggregating by event=round would double-count them. Only an ACTUAL
    # resume replays rounds: a fresh run pointed at a reused --log-dir
    # must log every round, not inherit the old file's high-water mark.
    logged_through = -1
    if resumed and logger is not None and logger.path.exists():
        import json as _json

        for line in logger.path.read_text().splitlines():
            try:
                rec = _json.loads(line)
            except ValueError:
                continue
            if rec.get("event") == "round":
                logged_through = max(logged_through, int(rec["round"]))
    def eval_round(sv):
        # ONE host fetch for every metric: on a tunneled runtime each
        # individual scalar fetch is a full ~50-90 ms sync round-trip,
        # which at six per round costs 10x the 46 ms round itself
        em = _fetch_scalars(eval_fn(sv, imgs, labels, w_test))
        return {"test_loss": float(em["loss"]),
                "test_acc": float(em["accuracy"])}

    def print_round(entry):
        print(f"{entry['round']}, {entry['loss']:.4f}, "
              f"{entry['accuracy']:.4f}, {entry['test_loss']:.4f}, "
              f"{entry['test_acc']:.4f}")
        # the CLI owns the `round` jsonl records (driver logs only
        # round_health) so the historical field names — train_loss/
        # train_acc, consumed by existing run.jsonl tooling — survive
        # the move to the driver
        if entry.get("trim_degenerate"):
            print(f"[idc_models_tpu] round {entry['round']}: trimmed "
                  f"mean had NO kept band (live clients <= 2*trim) — "
                  f"the server state was left UNCHANGED this round; "
                  f"lower --trim or enroll more clients",
                  file=sys.stderr)
        if logger and entry["round"] > logged_through:
            logger.log(event="round", round=entry["round"],
                       train_loss=entry["loss"],
                       train_acc=entry["accuracy"],
                       test_loss=entry["test_loss"],
                       test_acc=entry["test_acc"],
                       clients_dropped=int(
                           entry.get("clients_dropped", 0)))

    spike = getattr(ns, "loss_spike_ratio", 10.0)
    if spike is not None and spike != 0 and spike <= 1:
        # only the documented 0 disables; negatives and (0, 1] are
        # configuration mistakes that must not silently turn the
        # divergence detector off
        sys.exit(f"--loss-spike-ratio {spike} must be > 1 (a round is "
                 f"rolled back when its loss exceeds ratio x the last "
                 f"good loss; 0 disables the detector)")
    config = DriverConfig(
        rounds=preset.rounds,
        timeout_s=getattr(ns, "round_timeout", None),
        max_attempts=1 + max(int(getattr(ns, "max_round_retries", 2)), 0),
        loss_spike_ratio=spike if spike and spike > 1 else None,
        checkpoint_path=server_ckpt, checkpoint_every=every)
    # the self-healing driver (federated/driver.py) owns the round loop:
    # per-round wall budget, reseeded-subset retry, divergence rollback,
    # periodic checkpoints, and round_health jsonl events
    try:
        with Timer("Federated training", logger=logger), \
                profile_trace(ns.profile_dir):
            result = run_rounds(
                round_fn, server, imgs, labels, w_train, config=config,
                seed=ns.seed + 1, eval_fn=eval_round,
                on_round=print_round, logger=logger, verbose=True,
                log_from_round=logged_through, log_round_records=False,
                fault_plan=plan)
    except RoundFailure as e:
        sys.exit(f"[idc_models_tpu] federated training aborted: {e}")
    server = result.server
    for entry in result.history:
        dropped = int(entry.get("clients_dropped", 0))
        if dropped:
            print(f"[idc_models_tpu] round {entry['round']}: dropped "
                  f"{dropped} client(s) with non-finite updates from "
                  f"the aggregate", file=sys.stderr)
    retried = [e for e in result.events if e["status"] != "ok"]
    if retried:
        print(f"[idc_models_tpu] {len(retried)} round attempt(s) "
              f"failed and were healed (rollback/reseed); see "
              f"round_health events", file=sys.stderr)
    _finish_logger(logger)


def _run_secure(ns):
    import jax

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.configs import get_preset
    from idc_models_tpu.data.idc import ArrayDataset
    from idc_models_tpu.models import registry
    from idc_models_tpu.observe import Timer
    from idc_models_tpu.train import Evaluator, rmsprop
    from idc_models_tpu.federated import initialize_server
    from idc_models_tpu.secure import make_secure_fedavg_round

    if getattr(ns, "async_buffer", 0):
        # rejected at BUILD, with the protocol reason — not silently
        # ignored, not a bare argparse error
        from idc_models_tpu.federated import ensure_async_compatible

        try:
            ensure_async_compatible(secure=True)
        except ValueError as e:
            sys.exit(str(e))
    preset = _apply_overrides(
        get_preset("secure_fed"), ns,
        ["batch_size", "lr", "rounds", "percent", "num_clients",
         "local_epochs", "paillier"])
    n_dev = len(jax.devices())
    # full mesh for any client count: non-dividing counts are padded
    # inside the round with mask-participating dummy clients (forced-zero
    # updates, divisor = real count), so every device works
    n_clients = preset.num_clients
    n_mesh = min(n_clients, n_dev)
    ds = _load_idc(ns, preset.image_size, None)
    # take/skip split sized by the preset (24000/6000 in the reference,
    # secure_fed_model.py:219-220), scaled down when the dataset is smaller
    n_client_total = min(preset.client_examples, int(len(ds) * 0.8))
    client_ds = ds.take(n_client_total)
    test_ds = ds.skip(n_client_total).take(preset.test_examples)
    logger = _logger(ns)

    spec = registry.get_model(preset.model)
    model = spec.build(preset.num_outputs, 3)
    loss_fn = _loss_for(preset.num_outputs)
    opt = rmsprop(preset.lr)

    if preset.paillier:
        if getattr(ns, "mask_impl", "threefry") != "threefry":
            print("[idc_models_tpu] --mask-impl has no effect with "
                  "--paillier (host-side Paillier path)", file=sys.stderr)
        _run_secure_paillier(preset, n_clients, client_ds, test_ds, model,
                             opt, loss_fn, logger, ns)
        _finish_logger(logger)
        return

    # strided shard per client (secure_fed_model.py:206-210), stacked for
    # the client mesh
    shards = [client_ds.shard(n_clients, i) for i in range(n_clients)]
    size = min(len(s) for s in shards)
    imgs = np.stack([s.images[:size] for s in shards])
    labels = np.stack([s.labels[:size] for s in shards])

    mesh = meshlib.client_mesh(n_mesh)
    # pad non-dividing client counts to the mesh ONCE (the padded slots
    # become mask-participating dummies inside the round — n_real keeps
    # the divisor honest), then upload the stacked shards to HBM once —
    # never re-pad/re-upload per round
    pad = -n_clients % n_mesh
    if pad:
        imgs = np.concatenate(
            [imgs, np.zeros((pad,) + imgs.shape[1:], imgs.dtype)])
        labels = np.concatenate(
            [labels, np.zeros((pad,) + labels.shape[1:], labels.dtype)])
    cshard = meshlib.sharding(mesh, meshlib.CLIENT_AXIS)
    imgs = jax.device_put(imgs, cshard)
    labels = jax.device_put(labels, cshard)
    server = initialize_server(model, jax.random.key(ns.seed))
    round_fn = make_secure_fedavg_round(
        model, opt, loss_fn, mesh, percent=preset.percent,
        local_epochs=preset.local_epochs, batch_size=preset.batch_size,
        mask_impl=getattr(ns, "mask_impl", "threefry"))
    evaluator = Evaluator(model, loss_fn, mesh, batch_size=preset.batch_size,
                          with_auroc=True)
    from idc_models_tpu.observe import profile_trace

    key = jax.random.key(ns.seed + 1)
    with Timer("Secure fed model", logger=logger), \
            profile_trace(ns.profile_dir):
        for r in range(preset.rounds):
            key, sub = jax.random.split(key)
            server, tm = round_fn(server, imgs, labels, sub,
                                  n_real=n_clients)
            from idc_models_tpu.train import TrainState

            eval_state = TrainState(step=server.round, params=server.params,
                                    model_state=server.model_state,
                                    opt_state=None)
            em = evaluator(eval_state, test_ds)
            # one host fetch for the round metrics (see _fetch_scalars);
            # em is already host floats — Evaluator fetches internally
            tm = _fetch_scalars(tm)
            print(f"round {r}: train_loss={float(tm['loss']):.4f} "
                  f"test_loss={em['loss']:.4f} acc={em['accuracy']:.4f} "
                  f"auroc={em['auroc']:.4f}")
            recovered = int(tm.get("clients_recovered", 0))
            if recovered:
                print(f"[idc_models_tpu] round {r}: {recovered} "
                      f"client(s) diverged; their updates were replaced "
                      f"with the incoming global weights", file=sys.stderr)
            if logger:
                logger.log(event="round", round=r, train_loss=tm["loss"],
                           clients_recovered=recovered,
                           **{f"test_{k}": v for k, v in em.items()})
    _finish_logger(logger)


def _run_secure_paillier(preset, n_clients, client_ds, test_ds, model, opt,
                         loss_fn, logger, ns):
    from idc_models_tpu.observe import Timer
    from idc_models_tpu.secure.fedavg import PaillierClient, PaillierServer
    from idc_models_tpu.secure.paillier import generate_paillier_keypair

    pub, priv = generate_paillier_keypair(512)
    clients = []
    for i in range(n_clients):
        shard = client_ds.shard(n_clients, i)
        clients.append(PaillierClient(
            model, opt, loss_fn, shard.images, shard.labels, i,
            preset.percent, pub, priv, local_epochs=preset.local_epochs,
            batch_size=preset.batch_size, seed=ns.seed))
    with Timer("Secure fed model", logger=logger):
        for r in range(preset.rounds):
            packages = []
            for c in clients:
                with Timer(f"Client {c.client_id} training"):
                    pkg, _ = c.client_fit()
                packages.append(pkg)
            agg = PaillierServer.aggregate(packages)
            for c in clients:
                c.client_update(agg)
            m = clients[0].evaluate(test_ds.images, test_ds.labels, loss_fn)
            print(f"round {r}: " + " ".join(f"{k}={v:.4f}"
                                            for k, v in m.items()))
            if logger:
                logger.log(event="round", round=r, **m)


if __name__ == "__main__":
    sys.exit(main())
