"""MobileNetV2 backbone + transfer-learning head.

Capability parity with the reference's mobile preset
(dist_model_tf_mobile.py:119-129): MobileNetV2 (alpha=1.0) without top,
GlobalAveragePooling2D, Dense(1) logits head, fine_tune_at=100
(dist_model_tf_mobile.py:146).

The architecture follows keras.applications MobileNetV2: stem conv(32,s2)
-> 17 inverted-residual blocks (expansion 6 except the first) -> conv(1280)
with BN(eps=1e-3, momentum=0.999) + ReLU6 throughout and residual adds on
stride-1 same-width blocks. Total params (incl. BN moving stats) =
2,257,984, matching Keras include_top=False.

`KERAS_LAYER_INDEX` reproduces Keras' flat layer numbering (ZeroPadding and
Add layers included) so the reference's `fine_tune_at` — an index into
`base_model.layers` — selects the same parameters here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from idc_models_tpu.models import core

# (expansion t, out channels c, stride s) per block, keras order
_BLOCKS = (
    [(1, 16, 1)]
    + [(6, 24, 2), (6, 24, 1)]
    + [(6, 32, 2), (6, 32, 1), (6, 32, 1)]
    + [(6, 64, 2), (6, 64, 1), (6, 64, 1), (6, 64, 1)]
    + [(6, 96, 1), (6, 96, 1), (6, 96, 1)]
    + [(6, 160, 2), (6, 160, 1), (6, 160, 1)]
    + [(6, 320, 1)]
)

KERAS_LAYER_INDEX: dict[str, int] = {}


def _build_index():
    """Replicate Keras MobileNetV2's layer ordering: param groups get the
    index of their conv/BN layer; activations/pads/adds only advance it."""
    i = 0
    idx = {}

    def layer(name=None):
        nonlocal i
        if name is not None:
            idx[name] = i
        i += 1

    layer()                      # InputLayer
    layer("Conv1")
    layer("bn_Conv1")
    layer()                      # Conv1_relu
    # block 0 (expanded_conv): no expand conv
    layer("expanded_conv_depthwise")
    layer("expanded_conv_depthwise_BN")
    layer()                      # relu
    layer("expanded_conv_project")
    layer("expanded_conv_project_BN")
    c_in = 16
    for b, (t, c, s) in enumerate(_BLOCKS[1:], start=1):
        layer(f"block_{b}_expand")
        layer(f"block_{b}_expand_BN")
        layer()                  # expand_relu
        if s == 2:
            layer()              # ZeroPadding2D
        layer(f"block_{b}_depthwise")
        layer(f"block_{b}_depthwise_BN")
        layer()                  # depthwise_relu
        layer(f"block_{b}_project")
        layer(f"block_{b}_project_BN")
        if s == 1 and c == c_in:
            layer()              # Add
        c_in = c
    layer("Conv_1")
    layer("Conv_1_bn")
    layer()                      # out_relu
    return idx


KERAS_LAYER_INDEX = _build_index()

_BN = dict(momentum=0.999, eps=1e-3)

FREEZE_ALL = 10**9  # bn_frozen_below value freezing every BN layer


def mobilenet_v2_backbone(in_channels: int = 3, *,
                          bn_frozen_below: int = 0) -> core.Module:
    """Returns the backbone module; params keyed by Keras layer names.

    `bn_frozen_below`: BN layers with Keras index < this run in permanent
    inference mode (Keras `trainable=False` semantics) — pass FREEZE_ALL
    for the head-only phase and the phase-2 `fine_tune_at` for fine-tuning,
    mirroring the masks.
    """
    specs: list[tuple[str, core.Module]] = []

    def add(m: core.Module):
        specs.append((m.name, m))

    def _bn(c, name):
        frozen = KERAS_LAYER_INDEX[name] < bn_frozen_below
        return core.batch_norm(c, name=name, frozen=frozen, **_BN)

    add(core.conv2d(in_channels, 32, 3, stride=2, use_bias=False, name="Conv1"))
    add(_bn(32, "bn_Conv1"))
    add(core.depthwise_conv2d(32, 3, use_bias=False,
                              name="expanded_conv_depthwise"))
    add(_bn(32, "expanded_conv_depthwise_BN"))
    add(core.conv2d(32, 16, 1, use_bias=False, name="expanded_conv_project"))
    add(_bn(16, "expanded_conv_project_BN"))
    c_in = 16
    blocks = []
    for b, (t, c, s) in enumerate(_BLOCKS[1:], start=1):
        hidden = t * c_in
        add(core.conv2d(c_in, hidden, 1, use_bias=False, name=f"block_{b}_expand"))
        add(_bn(hidden, f"block_{b}_expand_BN"))
        add(core.depthwise_conv2d(hidden, 3, stride=s, use_bias=False,
                                  name=f"block_{b}_depthwise"))
        add(_bn(hidden, f"block_{b}_depthwise_BN"))
        add(core.conv2d(hidden, c, 1, use_bias=False, name=f"block_{b}_project"))
        add(_bn(c, f"block_{b}_project_BN"))
        blocks.append((b, t, c, s, c_in))
        c_in = c
    add(core.conv2d(320, 1280, 1, use_bias=False, name="Conv_1"))
    add(_bn(1280, "Conv_1_bn"))
    modules = dict(specs)

    def init(rng):
        rngs = jax.random.split(rng, len(specs))
        params, state = {}, {}
        for (name, m), r in zip(specs, rngs):
            v = m.init(r)
            if v.params:
                params[name] = v.params
            if v.state:
                state[name] = v.state
        return core.Variables(params, state)

    def apply(params, state, x, *, train=False, rng=None):
        new_state = dict(state)

        def run(name, h):
            m = modules[name]
            y, s2 = m.apply(params.get(name, {}), state.get(name, {}), h,
                            train=train, rng=None)
            if name in state:
                new_state[name] = s2
            return y

        h = run("Conv1", x)
        h = jnp.minimum(jax.nn.relu(run("bn_Conv1", h)), 6.0)
        h = run("expanded_conv_depthwise", h)
        h = jnp.minimum(jax.nn.relu(run("expanded_conv_depthwise_BN", h)), 6.0)
        h = run("expanded_conv_project", h)
        h = run("expanded_conv_project_BN", h)
        for b, t, c, s, ci in blocks:
            inp = h
            h = run(f"block_{b}_expand", h)
            h = jnp.minimum(jax.nn.relu(run(f"block_{b}_expand_BN", h)), 6.0)
            h = run(f"block_{b}_depthwise", h)
            h = jnp.minimum(jax.nn.relu(run(f"block_{b}_depthwise_BN", h)), 6.0)
            h = run(f"block_{b}_project", h)
            h = run(f"block_{b}_project_BN", h)
            if s == 1 and c == ci:
                h = h + inp
        h = run("Conv_1", h)
        h = jnp.minimum(jax.nn.relu(run("Conv_1_bn", h)), 6.0)
        return h, new_state

    # layer_names in Keras creation order (_build_index inserts names in
    # ascending Keras-index order) so secure percent-selection follows
    # get_weights() order for this backbone too (secure_fed_model.py:115-121)
    return core.Module(init, apply, "mobilenet_v2",
                       layer_names=tuple(KERAS_LAYER_INDEX))


def mobilenet_v2(num_outputs: int = 1, in_channels: int = 3, *,
                 bn_frozen_below: int = 0) -> core.Module:
    backbone = mobilenet_v2_backbone(in_channels,
                                     bn_frozen_below=bn_frozen_below)
    return core.classifier(backbone, 1280, num_outputs,
                           name="mobilenet_v2_classifier")


head_only_mask = core.head_only_mask


def fine_tune_mask(params, fine_tune_at: int = 100):
    """Unfreeze backbone layers with Keras index >= fine_tune_at
    (dist_model_tf_mobile.py:146 uses 100, which lands inside block 11)."""
    return core.keras_fine_tune_mask(params, KERAS_LAYER_INDEX, fine_tune_at)
