"""MobileNetV2 backbone + transfer-learning head.

Capability parity with the reference's mobile preset
(dist_model_tf_mobile.py:119-129): MobileNetV2 (alpha=1.0) without top,
GlobalAveragePooling2D, Dense(1) logits head, fine_tune_at=100
(dist_model_tf_mobile.py:146).

The architecture follows keras.applications MobileNetV2: stem conv(32,s2)
-> 17 inverted-residual blocks (expansion 6 except the first) -> conv(1280)
with BN(eps=1e-3, momentum=0.999) + ReLU6 throughout and residual adds on
stride-1 same-width blocks. Total params (incl. BN moving stats) =
2,257,984, matching Keras include_top=False.

`KERAS_LAYER_INDEX` reproduces Keras' flat layer numbering (ZeroPadding and
Add layers included) so the reference's `fine_tune_at` — an index into
`base_model.layers` — selects the same parameters here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from idc_models_tpu.models import core
from idc_models_tpu.ops import fused_conv

# (expansion t, out channels c, stride s) per block, keras order
_BLOCKS = (
    [(1, 16, 1)]
    + [(6, 24, 2), (6, 24, 1)]
    + [(6, 32, 2), (6, 32, 1), (6, 32, 1)]
    + [(6, 64, 2), (6, 64, 1), (6, 64, 1), (6, 64, 1)]
    + [(6, 96, 1), (6, 96, 1), (6, 96, 1)]
    + [(6, 160, 2), (6, 160, 1), (6, 160, 1)]
    + [(6, 320, 1)]
)

KERAS_LAYER_INDEX: dict[str, int] = {}


def _build_index():
    """Replicate Keras MobileNetV2's layer ordering: param groups get the
    index of their conv/BN layer; activations/pads/adds only advance it."""
    i = 0
    idx = {}

    def layer(name=None):
        nonlocal i
        if name is not None:
            idx[name] = i
        i += 1

    layer()                      # InputLayer
    layer("Conv1")
    layer("bn_Conv1")
    layer()                      # Conv1_relu
    # block 0 (expanded_conv): no expand conv
    layer("expanded_conv_depthwise")
    layer("expanded_conv_depthwise_BN")
    layer()                      # relu
    layer("expanded_conv_project")
    layer("expanded_conv_project_BN")
    c_in = 16
    for b, (t, c, s) in enumerate(_BLOCKS[1:], start=1):
        layer(f"block_{b}_expand")
        layer(f"block_{b}_expand_BN")
        layer()                  # expand_relu
        if s == 2:
            layer()              # ZeroPadding2D
        layer(f"block_{b}_depthwise")
        layer(f"block_{b}_depthwise_BN")
        layer()                  # depthwise_relu
        layer(f"block_{b}_project")
        layer(f"block_{b}_project_BN")
        if s == 1 and c == c_in:
            layer()              # Add
        c_in = c
    layer("Conv_1")
    layer("Conv_1_bn")
    layer()                      # out_relu
    return idx


KERAS_LAYER_INDEX = _build_index()

_BN = dict(momentum=0.999, eps=1e-3)

FREEZE_ALL = 10**9  # bn_frozen_below value freezing every BN layer


def _units(in_channels: int, bn_frozen_below: int,
           depthwise_impl: str = "grouped"):
    """The backbone as a list of topology units — unit 0 = stem (Conv1 +
    block 0), units 1..16 = inverted-residual blocks, unit 17 = the
    Conv_1 top. Each unit is (param_names, apply_fn(run, h) -> h) where
    `run` applies a named leaf layer. Units are the split granularity for
    the frozen-backbone feature cache: every unit is a pure function of
    its input, so any unit boundary is a valid cache point (the residual
    add lives entirely inside its block's unit)."""
    specs: list[tuple[str, core.Module]] = []

    def _bn(c, name):
        frozen = KERAS_LAYER_INDEX[name] < bn_frozen_below
        return core.batch_norm(c, name=name, frozen=frozen, **_BN)

    def reg(m: core.Module) -> str:
        specs.append((m.name, m))
        return m.name

    def relu6(h):
        return jnp.minimum(jax.nn.relu(h), 6.0)

    def dw_chain(run, h, dw_name, bn_name, *, stride):
        """The depthwise-conv -> BN -> relu6 chain. With
        depthwise_impl="fused" and the BN in inference mode (frozen —
        a BUILD-time constant — or eval), the whole chain runs as one
        Pallas kernel on the BN-folded affine (ops/fused_conv.py),
        reading params/stats through `run`'s attribute views; both
        layers' states are provably untouched there (frozen/eval BN
        returns state as-is), so bypassing `run` is state-identical.
        Unfrozen train mode needs batch statistics, so it keeps the
        unfused per-layer composition — as does every other impl."""
        frozen = KERAS_LAYER_INDEX[bn_name] < bn_frozen_below
        if depthwise_impl == "fused" and (frozen or not run.train):
            p_bn = run.params[bn_name]
            s_bn = run.state[bn_name]
            return fused_conv.fused_depthwise_bn_relu6(
                h, run.params[dw_name]["kernel"].astype(h.dtype),
                p_bn["scale"], p_bn["bias"], s_bn["mean"], s_bn["var"],
                eps=_BN["eps"], stride=stride)
        return relu6(run(bn_name, run(dw_name, h)))

    units: list[tuple[list[str], object]] = []

    stem_names = [
        reg(core.conv2d(in_channels, 32, 3, stride=2, use_bias=False,
                        name="Conv1")),
        reg(_bn(32, "bn_Conv1")),
        reg(core.depthwise_conv2d(32, 3, use_bias=False,
                                  impl=depthwise_impl,
                                  name="expanded_conv_depthwise")),
        reg(_bn(32, "expanded_conv_depthwise_BN")),
        reg(core.conv2d(32, 16, 1, use_bias=False,
                        name="expanded_conv_project")),
        reg(_bn(16, "expanded_conv_project_BN")),
    ]

    def stem(run, x):
        h = relu6(run("bn_Conv1", run("Conv1", x)))
        h = dw_chain(run, h, "expanded_conv_depthwise",
                     "expanded_conv_depthwise_BN", stride=1)
        return run("expanded_conv_project_BN",
                   run("expanded_conv_project", h))

    units.append((stem_names, stem))

    c_in = 16
    for b, (t, c, s) in enumerate(_BLOCKS[1:], start=1):
        hidden = t * c_in
        names = [
            reg(core.conv2d(c_in, hidden, 1, use_bias=False,
                            name=f"block_{b}_expand")),
            reg(_bn(hidden, f"block_{b}_expand_BN")),
            reg(core.depthwise_conv2d(hidden, 3, stride=s, use_bias=False,
                                      impl=depthwise_impl,
                                      name=f"block_{b}_depthwise")),
            reg(_bn(hidden, f"block_{b}_depthwise_BN")),
            reg(core.conv2d(hidden, c, 1, use_bias=False,
                            name=f"block_{b}_project")),
            reg(_bn(c, f"block_{b}_project_BN")),
        ]

        def block(run, h, *, b=b, s=s, residual=(s == 1 and c == c_in)):
            inp = h
            h = relu6(run(f"block_{b}_expand_BN", run(f"block_{b}_expand", h)))
            h = dw_chain(run, h, f"block_{b}_depthwise",
                         f"block_{b}_depthwise_BN", stride=s)
            h = run(f"block_{b}_project_BN", run(f"block_{b}_project", h))
            return h + inp if residual else h

        units.append((names, block))
        c_in = c

    top_names = [
        reg(core.conv2d(320, 1280, 1, use_bias=False, name="Conv_1")),
        reg(_bn(1280, "Conv_1_bn")),
    ]
    units.append((top_names, lambda run, h: relu6(run("Conv_1_bn",
                                                      run("Conv_1", h)))))
    return units, dict(specs)


def fused_call_shapes(batch: int, size: int) -> list[dict]:
    """The fused depthwise chain's call schedule at an input resolution:
    one dict of `ops.fused_conv.depthwise_call_cost` kwargs per
    depthwise layer (stem + 16 blocks), tracking the spatial walk
    (stride-2 stem conv, then each stride-2 depthwise halves again).
    XLA's cost_analysis cannot see inside the Pallas calls, so
    `profile --model mobile --depthwise-impl fused` sums these into
    its ProgramCost (cli.py via observe.profile.augment_cost)."""
    h = -(-size // 2)                      # after the stride-2 stem conv
    calls = [dict(n=batch, h_in=h, w_in=h, c=32, stride=1)]
    c_in = 16
    for t, c, s in _BLOCKS[1:]:
        calls.append(dict(n=batch, h_in=h, w_in=h, c=t * c_in, stride=s))
        if s == 2:
            h = -(-h // 2)
        c_in = c
    return calls


def mobilenet_v2_backbone(in_channels: int = 3, *,
                          bn_frozen_below: int = 0,
                          depthwise_impl: str = "grouped") -> core.Module:
    """Returns the backbone module; params keyed by Keras layer names.

    `bn_frozen_below`: BN layers with Keras index < this run in permanent
    inference mode (Keras `trainable=False` semantics) — pass FREEZE_ALL
    for the head-only phase and the phase-2 `fine_tune_at` for fine-tuning,
    mirroring the masks.

    The returned Module carries a `splitter` (unit granularity: stem, 16
    blocks, top) so the frozen-backbone feature cache works despite the
    residual topology; the split lands on the last unit edge where every
    earlier layer has Keras index < fine_tune_at.
    """
    units, modules = _units(in_channels, bn_frozen_below, depthwise_impl)
    # layer_names in Keras creation order (_build_index inserts names in
    # ascending Keras-index order) so secure percent-selection follows
    # get_weights() order for this backbone too (secure_fed_model.py:115-121)
    sec = core.unit_backbone(units, modules, "mobilenet_v2",
                             KERAS_LAYER_INDEX)
    assert sec.layer_names == tuple(KERAS_LAYER_INDEX)
    return sec


def mobilenet_v2(num_outputs: int = 1, in_channels: int = 3, *,
                 bn_frozen_below: int = 0,
                 depthwise_impl: str = "grouped") -> core.Module:
    backbone = mobilenet_v2_backbone(in_channels,
                                     bn_frozen_below=bn_frozen_below,
                                     depthwise_impl=depthwise_impl)
    return core.classifier(backbone, 1280, num_outputs,
                           name="mobilenet_v2_classifier")


head_only_mask = core.head_only_mask


def fine_tune_mask(params, fine_tune_at: int = 100):
    """Unfreeze backbone layers with Keras index >= fine_tune_at
    (dist_model_tf_mobile.py:146 uses 100, which lands inside block 11)."""
    return core.keras_fine_tune_mask(params, KERAS_LAYER_INDEX, fine_tune_at)
