"""Explicit-pytree neural-network layer library (the Keras replacement).

Every layer is a `Module`: a pair of pure functions

    init(rng)                         -> Variables{"params", "state"}
    apply(params, state, x, train, rng) -> (y, new_state)

Parameters and mutable state (BatchNorm moving statistics) are plain nested
dicts of jnp arrays — ordinary pytrees that `jit`, `grad`, `shard_map`,
optax, and orbax all consume directly. There is no module instance holding
tensors, so "clone the model per graph context" (the reference's
fed_model.py:196-205 contortion) is just... reusing the pytree.

Layout is NHWC with HWIO conv kernels — the layout XLA:TPU prefers for
feeding the MXU. Initializers match Keras defaults (glorot_uniform kernels,
zero biases) so parity runs start from the same distribution family as the
reference models (e.g. secure_fed_model.py:84-98).

Trainability is expressed as a boolean pytree mask consumed by
`train.state.freeze_where` (see `trainability_mask`) instead of the
reference's freeze/recompile dance (quirk Q6, dist_model_tf_vgg.py:141-154).
"""

from __future__ import annotations

import dataclasses
import math
from collections.abc import Callable, Sequence
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

Params = Any  # nested dict pytree of jnp arrays
State = Any


@dataclasses.dataclass(frozen=True)
class Variables:
    params: Params
    state: State


@dataclasses.dataclass(frozen=True)
class Module:
    """A pure init/apply pair. `name` is used as the pytree key in Sequential.

    `layer_names` records the model's layer order (the order Keras
    `get_weights()` would enumerate) for composites built by `sequential` /
    `classifier`; consumers that need ordered-tensor semantics (the secure
    `percent`-of-tensors knob) use it instead of jax's alphabetical
    flatten order.
    """

    init: Callable[[jax.Array], Variables]
    apply: Callable[..., tuple[jax.Array, State]]
    name: str = "module"
    layer_names: tuple[str, ...] = ()
    # (param_key, child Module) pairs for composites built by `sequential`
    # / `classifier`; lets consumers re-compose sub-programs (e.g. the
    # frozen-backbone feature cache splits a backbone at fine_tune_at).
    # Empty for leaf layers and hand-rolled composites.
    children: tuple[tuple[str, "Module"], ...] = ()
    # Optional model-provided split for backbones whose topology is not a
    # plain sequential (residual adds, dense concats): called with a
    # Keras fine_tune_at index, returns (prefix, suffix) Modules sharing
    # the parent's flat param keys — each section's layer_names lists the
    # param keys it consumes — or None when no frozen prefix exists.
    splitter: Callable[[int], tuple["Module", "Module"] | None] | None = None


def _split(rng, n):
    return jax.random.split(rng, n)


# ---------------------------------------------------------------------------
# initializers (Keras-default parity)
# ---------------------------------------------------------------------------

def glorot_uniform(rng, shape, fan_in, fan_out, dtype=jnp.float32):
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def he_normal(rng, shape, fan_in, dtype=jnp.float32):
    std = math.sqrt(2.0 / fan_in)
    return jax.random.normal(rng, shape, dtype) * std


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------

def dense(features_in: int, features_out: int, *, use_bias: bool = True,
          name: str = "dense") -> Module:
    def init(rng):
        k = glorot_uniform(rng, (features_in, features_out),
                           features_in, features_out)
        p = {"kernel": k}
        if use_bias:
            p["bias"] = jnp.zeros((features_out,))
        return Variables(p, {})

    def apply(params, state, x, *, train=False, rng=None):
        y = x @ params["kernel"]
        if use_bias:
            y = y + params["bias"]
        return y, state

    return Module(init, apply, name)


def conv2d(features_in: int, features_out: int, kernel_size: int | tuple = 3,
           *, stride: int | tuple = 1,
           padding: str | tuple = "SAME",
           use_bias: bool = True, name: str = "conv") -> Module:
    """2-D convolution. `padding` is "SAME"/"VALID" or explicit
    ((lo_h, hi_h), (lo_w, hi_w)) pairs — the explicit form is needed where
    Keras uses symmetric ZeroPadding2D + valid conv (e.g. the DenseNet
    stem), which lax SAME (asymmetric lo<=hi split) does not reproduce."""
    kh, kw = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
              else kernel_size)
    strides = (stride, stride) if isinstance(stride, int) else stride

    def init(rng):
        fan_in = kh * kw * features_in
        fan_out = kh * kw * features_out
        k = glorot_uniform(rng, (kh, kw, features_in, features_out),
                           fan_in, fan_out)
        p = {"kernel": k}
        if use_bias:
            p["bias"] = jnp.zeros((features_out,))
        return Variables(p, {})

    pad = padding if isinstance(padding, str) else [tuple(p) for p in padding]
    # MXU input-tile fill: a 3-channel contraction (the RGB stem conv,
    # contraction depth kh*kw*3) under-fills the systolic array; zero-
    # padding input AND kernel to 4 channels measured +4% whole-step
    # throughput on TPU v5e (experiments/mfu_matrix.jsonl: pad4 vs base)
    # with identical output — the padded taps contribute exact zeros, and
    # params keep their Keras-parity (kh, kw, 3, out) shape.
    pad_c = 4 - features_in if 0 < features_in < 4 else 0

    def apply(params, state, x, *, train=False, rng=None):
        k = params["kernel"].astype(x.dtype)
        if pad_c:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, pad_c)))
            k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_c), (0, 0)))
        y = lax.conv_general_dilated(
            x, k, strides, pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if use_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state

    return Module(init, apply, name)


def depthwise_conv2d(features: int, kernel_size: int | tuple = 3, *,
                     stride: int | tuple = 1, padding: str = "SAME",
                     use_bias: bool = False, impl: str = "grouped",
                     name: str = "dwconv") -> Module:
    """Depthwise conv (MobileNetV2 building block).

    `impl` picks the lowering, same math either way (equality pinned by
    tests/test_core_layers.py):

    - "grouped": `lax.conv_general_dilated` with
      feature_group_count=features — XLA's native depthwise path.
    - "taps": explicit kh*kw shifted elementwise multiply-accumulates.
      A depthwise conv has no channel contraction, so there is nothing
      for the MXU's systolic array to reduce — this formulation hands
      XLA the pure-VPU form directly: kh*kw strided slices of one
      padded copy of x, fused into one elementwise loop. Measured
      (experiments/backbone_mfu.jsonl, MobileNetV2 fine-tune on TPU
      v5e): the native grouped lowering WINS — 234k vs 138k patches/s
      at batch 2048 — so "grouped" stays the default and "taps" remains
      as the measured ablation that closed the question.
    - "fused": the Pallas kernel (ops/fused_conv.py) — the taps math
      computed on a VMEM-resident tile (interpreted off-TPU, so the
      same code path runs in tier-1 on CPU). Standalone it runs with an
      identity affine; its point is the cross-LAYER fusion
      models/mobilenet.py drives through it (depthwise+BN+relu6 in one
      kernel, see unit_backbone's `run` attributes). Stays opt-in until
      the perf gate holds on TPU (ISSUE 16 acceptance).
    """
    kh, kw = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
              else kernel_size)
    strides = (stride, stride) if isinstance(stride, int) else stride
    if impl not in ("grouped", "taps", "fused"):
        raise ValueError(f"impl must be grouped|taps|fused, got {impl!r}")
    if impl in ("taps", "fused") and padding != "SAME":
        raise ValueError(f"impl={impl!r} implements SAME padding only")

    def init(rng):
        fan_in = kh * kw
        k = glorot_uniform(rng, (kh, kw, 1, features), fan_in, fan_in)
        p = {"kernel": k}
        if use_bias:
            p["bias"] = jnp.zeros((features,))
        return Variables(p, {})

    def apply(params, state, x, *, train=False, rng=None):
        w = params["kernel"].astype(x.dtype)
        if impl == "fused":
            from idc_models_tpu.ops import fused_conv

            ones = jnp.ones((features,), jnp.float32)
            add = (params["bias"].astype(jnp.float32) if use_bias
                   else jnp.zeros((features,), jnp.float32))
            y = fused_conv.fused_depthwise_affine(
                x, w, ones, add, stride=strides, clamp6=False)
            return y, state
        if impl == "taps":
            sh, sw = strides
            _, h_in, w_in, _ = x.shape
            h_out, w_out = -(-h_in // sh), -(-w_in // sw)
            # TF-SAME split: lo = total//2, hi = rest (matches XLA)
            ph = max((h_out - 1) * sh + kh - h_in, 0)
            pw = max((w_out - 1) * sw + kw - w_in, 0)
            xp = jnp.pad(x, ((0, 0), (ph // 2, ph - ph // 2),
                             (pw // 2, pw - pw // 2), (0, 0)))
            y = None
            for i in range(kh):
                for j in range(kw):
                    xs = xp[:, i:i + (h_out - 1) * sh + 1:sh,
                            j:j + (w_out - 1) * sw + 1:sw, :]
                    t = xs * w[i, j, 0]
                    y = t if y is None else y + t
        else:
            y = lax.conv_general_dilated(
                x, w, strides, padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=features)
        if use_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state

    return Module(init, apply, name)


def batch_norm(features: int, *, momentum: float = 0.99, eps: float = 1e-3,
               axis_name: str | None = None, frozen: bool = False,
               name: str = "bn") -> Module:
    """BatchNorm with explicit moving statistics.

    In train mode, batch statistics are computed over the local batch; if
    `axis_name` is given (when running under shard_map) they are averaged
    cross-replica with `lax.pmean`, making global-batch statistics explicit —
    the decision the reference leaves implicit to Keras (SURVEY.md §7 "hard
    parts": BN under freeze/fine-tune). In eval mode the stored moving
    stats are used.

    `frozen=True` reproduces Keras' `trainable=False` BN semantics: the
    layer always runs in inference mode (moving stats, no updates) even
    when the model is applied with train=True — required so a frozen
    pretrained backbone's function does not drift under a training head.
    """

    def init(rng):
        p = {"scale": jnp.ones((features,)), "bias": jnp.zeros((features,))}
        s = {"mean": jnp.zeros((features,)), "var": jnp.ones((features,))}
        return Variables(p, s)

    def apply(params, state, x, *, train=False, rng=None):
        if train and not frozen:
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(x.astype(jnp.float32), axes)
            second = jnp.mean(jnp.square(x.astype(jnp.float32)), axes)
            if axis_name is not None:
                # Average the raw moments, not per-shard variances: global
                # var must come from global moments or it is underestimated
                # whenever shard means differ (e.g. non-IID client shards).
                mean = lax.pmean(mean, axis_name)
                second = lax.pmean(second, axis_name)
            var = second - mean**2
            new_state = {
                "mean": momentum * state["mean"] + (1 - momentum) * mean,
                "var": momentum * state["var"] + (1 - momentum) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + eps) * params["scale"]
        y = (x.astype(jnp.float32) - mean) * inv + params["bias"]
        return y.astype(x.dtype), new_state

    return Module(init, apply, name)


def layer_norm(features: int, *, eps: float = 1e-6,
               name: str = "ln") -> Module:
    """LayerNorm over the trailing feature axis (Keras
    LayerNormalization defaults: scale+bias, trailing-axis stats).
    Unlike batch_norm it carries no cross-replica state, so it is the
    normalization of choice for sequence models running under
    sequence-sharded meshes (ring_attention): every position normalizes
    itself."""

    def init(rng):
        return Variables({"scale": jnp.ones((features,)),
                          "bias": jnp.zeros((features,))}, {})

    def apply(params, state, x, *, train=False, rng=None):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + eps)
        y = y * params["scale"] + params["bias"]
        return y.astype(x.dtype), state

    return Module(init, apply, name)


def relu(name: str = "relu") -> Module:
    return _stateless(lambda x: jax.nn.relu(x), name)


def relu6(name: str = "relu6") -> Module:
    return _stateless(lambda x: jnp.minimum(jax.nn.relu(x), 6.0), name)


def _stateless(fn, name):
    def init(rng):
        return Variables({}, {})

    def apply(params, state, x, *, train=False, rng=None):
        return fn(x), state

    return Module(init, apply, name)


def max_pool(window: int = 2, stride: int | None = None, *,
             padding: str = "VALID", name: str = "maxpool") -> Module:
    stride = window if stride is None else stride

    def apply_fn(x):
        return lax.reduce_window(
            x, -jnp.inf, lax.max,
            (1, window, window, 1), (1, stride, stride, 1), padding)

    return _stateless(apply_fn, name)


def avg_pool(window: int = 2, stride: int | None = None, *,
             padding: str = "VALID", name: str = "avgpool") -> Module:
    stride = window if stride is None else stride

    def apply_fn(x):
        dims = (1, window, window, 1)
        strides = (1, stride, stride, 1)
        s = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
        if padding == "VALID":
            return s / (window * window)
        # SAME: divide by the count of real (non-padded) elements per
        # window, matching Keras AveragePooling2D edge behavior.
        ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
        count = lax.reduce_window(ones, 0.0, lax.add, dims, strides, padding)
        return s / count

    return _stateless(apply_fn, name)


def global_avg_pool(name: str = "gap") -> Module:
    """GlobalAveragePooling2D — the head junction in every reference model
    (e.g. dist_model_tf_vgg.py:125-129)."""
    return _stateless(lambda x: jnp.mean(x, axis=(1, 2)), name)


def flatten(name: str = "flatten") -> Module:
    return _stateless(lambda x: x.reshape(x.shape[0], -1), name)


def dropout(rate: float, name: str = "dropout") -> Module:
    if not 0.0 <= rate < 1.0:
        raise ValueError(
            f"dropout rate must be in [0, 1), got {rate} — negative "
            f"rates silently rescale activations and rate >= 1 zeroes "
            f"the branch entirely")

    def init(rng):
        return Variables({}, {})

    def apply(params, state, x, *, train=False, rng=None):
        if not train or rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError(f"dropout({name}) needs an rng in train mode")
        keep = 1.0 - rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype), state

    return Module(init, apply, name)


# ---------------------------------------------------------------------------
# composition
# ---------------------------------------------------------------------------

def _keyed_sequential(keys: list[str], layers: list[Module],
                      name: str) -> Module:
    """The one sequential-composition body: params/state are dicts under
    the given per-layer keys. Shared by `sequential` (which derives fresh
    unique keys) and `subsequence` (which KEEPS a parent's keys)."""

    def init(rng):
        rngs = _split(rng, len(layers))
        params, state = {}, {}
        for key, m, r in zip(keys, layers, rngs):
            v = m.init(r)
            if v.params:
                params[key] = v.params
            if v.state:
                state[key] = v.state
        return Variables(params, state)

    def apply(params, state, x, *, train=False, rng=None):
        new_state = dict(state)
        rngs = _split(rng, len(layers)) if rng is not None else [None] * len(layers)
        for key, m, r in zip(keys, layers, rngs):
            p = params.get(key, {})
            s = state.get(key, {})
            x, s2 = m.apply(p, s, x, train=train, rng=r)
            if key in state:
                new_state[key] = s2
        return x, new_state

    return Module(init, apply, name, layer_names=tuple(keys),
                  children=tuple(zip(keys, layers)))


def sequential(layers: Sequence[Module], name: str = "sequential") -> Module:
    """Compose modules; params/state are dicts keyed by unique layer names."""
    keys: list[str] = []
    used: set[str] = set()
    for m in layers:
        n = m.name
        i = 0
        while n in used:
            n = f"{m.name}_{i}"
            i += 1
        used.add(n)
        keys.append(n)
    return _keyed_sequential(keys, list(layers), name)


def subsequence(seq: Module, keys_subset: Sequence[str],
                name: str | None = None) -> Module:
    """A sequential over a contiguous run of `seq`'s children, KEEPING the
    parent's param keys (so the sub-module consumes/produces the matching
    subtree of the parent's params/state directly). `keys_subset` must be
    a contiguous in-order slice of the parent's child keys (possibly
    empty: the identity module) — anything else would silently compute a
    different function than the parent."""
    parent_keys = [k for k, _ in seq.children]
    if not parent_keys:
        raise ValueError(f"{seq.name} has no children to slice")
    keys = list(keys_subset)
    if keys:
        try:
            start = parent_keys.index(keys[0])
        except ValueError:
            raise KeyError(f"{seq.name} has no child {keys[0]!r}")
        if parent_keys[start:start + len(keys)] != keys:
            raise ValueError(
                f"keys_subset must be a contiguous in-order run of "
                f"{seq.name}'s children; got {keys}")
    child_map = dict(seq.children)
    default = (f"{seq.name}[{keys[0]}:{keys[-1]}]" if keys
               else f"{seq.name}[empty]")
    return _keyed_sequential(keys, [child_map[k] for k in keys],
                             name or default)


def split_sequential(seq: Module, at_key: str) -> tuple[Module, Module]:
    """Split a sequential composite into (prefix, suffix) at `at_key`
    (the suffix starts with `at_key`). Param/state keys are preserved, so
    `suffix.apply(subset_of_params, ...)` composes with
    `prefix.apply(...)` to reproduce `seq.apply` exactly."""
    keys = [k for k, _ in seq.children]
    if at_key not in keys:
        raise KeyError(f"{seq.name} has no child {at_key!r}; have {keys}")
    i = keys.index(at_key)
    return (subsequence(seq, keys[:i], name=f"{seq.name}[:{at_key}]"),
            subsequence(seq, keys[i:], name=f"{seq.name}[{at_key}:]"))


def unit_backbone(units: Sequence[tuple[list[str], Callable]],
                  modules: dict[str, Module], name: str,
                  layer_index: dict[str, int]) -> Module:
    """Compose a backbone from topology *units* over a FLAT param/state
    namespace (Keras layer names), with a fine-tune splitter at unit
    granularity.

    `units` is a list of (param_names, apply_fn) where `apply_fn(run, h)`
    threads the activation through the unit's layers via
    `run(layer_name, h)`. A unit must be a pure function of its input
    activation — residual adds / dense concats live entirely inside one
    unit — so every unit edge is a valid frozen-prefix cache point. The
    returned Module's `splitter(fine_tune_at)` cuts at the first unit
    containing a layer with Keras index >= fine_tune_at (indices are
    monotone in creation order, so everything before it is frozen).

    `run` exposes the section's traced trees as attributes —
    `run.params`, `run.state`, `run.train` — so a unit may implement a
    lowering that SPANS layer boundaries (e.g. mobilenet's fused
    depthwise+BN+relu6 Pallas chain, which needs the BN layer's
    params/stats alongside the conv kernel) while the param/state
    namespace stays flat per-layer (pretrained loading, masks, and
    summary never see the fusion). A unit taking that path must be
    value-equivalent to the per-layer `run` composition and may only
    bypass `run` for layers whose state it provably leaves unchanged
    (frozen/eval BN returns its state untouched).
    """

    def section(lo: int, hi: int, sec_name: str, splitter=None) -> Module:
        names = [n for ns, _ in units[lo:hi] for n in ns]

        def init(rng):
            rngs = _split(rng, len(names))
            params, state = {}, {}
            for n, r in zip(names, rngs):
                v = modules[n].init(r)
                if v.params:
                    params[n] = v.params
                if v.state:
                    state[n] = v.state
            return Variables(params, state)

        def apply(params, state, x, *, train=False, rng=None):
            new_state = dict(state)

            def run(n, h):
                y, s2 = modules[n].apply(params.get(n, {}),
                                         state.get(n, {}), h,
                                         train=train, rng=None)
                if n in state:
                    new_state[n] = s2
                return y

            run.params, run.state, run.train = params, state, train
            for _, unit_fn in units[lo:hi]:
                x = unit_fn(run, x)
            return x, new_state

        return Module(init, apply, sec_name, layer_names=tuple(names),
                      splitter=splitter)

    def boundary_unit(fine_tune_at: int):
        for k, (names, _) in enumerate(units):
            if any(layer_index[n] >= fine_tune_at for n in names):
                return k if k > 0 else None
        return len(units)  # nothing live: cache everything

    def split(fine_tune_at: int):
        k = boundary_unit(fine_tune_at)
        if k is None:
            return None
        return (section(0, k, f"{name}[:{k}]"),
                section(k, len(units), f"{name}[{k}:]"))

    return section(0, len(units), name, splitter=split)


def classifier(backbone: Module, feature_dim: int, num_outputs: int,
               name: str | None = None) -> Module:
    """Backbone + GlobalAveragePooling + Dense head — the model shape every
    reference workload shares (SURVEY.md §3.5, e.g. dist_model_tf_vgg.py:
    125-129). Params = {"backbone": ..., "head": ...}.
    """
    head = dense(feature_dim, num_outputs, name="head")

    def init(rng):
        r1, r2 = _split(rng, 2)
        bb = backbone.init(r1)
        hd = head.init(r2)
        return Variables({"backbone": bb.params, "head": hd.params},
                         {"backbone": bb.state})

    def apply(params, state, x, *, train=False, rng=None):
        h, bb_state = backbone.apply(params["backbone"],
                                     state.get("backbone", {}), x,
                                     train=train, rng=rng)
        h = h.mean(axis=(1, 2))  # GlobalAveragePooling2D
        y, _ = head.apply(params["head"], {}, h, train=train)
        return y, {"backbone": bb_state}

    # Propagate the backbone's internal layer order as dotted paths so
    # ordered-tensor consumers (secure `percent` selection) see the true
    # get_weights()-style enumeration, not just the two top-level keys.
    bb_names = (tuple(f"backbone.{n}" for n in backbone.layer_names)
                if backbone.layer_names else ("backbone",))
    return Module(init, apply, name or f"{backbone.name}_classifier",
                  layer_names=bb_names + ("head",),
                  children=(("backbone", backbone), ("head", head)))


# ---------------------------------------------------------------------------
# trainability masks (replaces Keras freeze/recompile — quirk Q6)
# ---------------------------------------------------------------------------

def trainability_mask(params: Params,
                      predicate: Callable[[tuple[str, ...]], bool]):
    """Boolean pytree over `params`: True where trainable.

    `predicate` receives the path as a tuple of dict keys, e.g.
    ("backbone", "conv1", "kernel"). Feed the result to
    `train.state.freeze_where(optimizer, mask)` so frozen parameters
    receive zero updates — the explicit form of the reference's
    `base_model.trainable=False` + recompile (dist_model_tf_vgg.py:122,
    141-154). (Do NOT use bare `optax.masked`: it passes raw gradients
    through False leaves instead of zeroing them.)
    """
    return jax.tree_util.tree_map_with_path(
        lambda path, _: predicate(tuple(p.key for p in path)), params)


def count_params(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def summary(module: Module, variables: Variables | None = None, *,
            trainable_mask=None) -> str:
    """A Keras-`model.summary()`-style table: one row per layer (in
    `layer_names` order when the module records it, flat param-tree
    order otherwise) with parameter shapes and counts, plus the
    trainable/non-trainable totals when a mask is given.

    The explicit-pytree analogue of the inspection surface Keras users
    lean on (`Sequential.summary()`); purely host-side.
    """
    if variables is None:
        # abstract init: shapes/sizes without allocating a real model
        # (Variables itself is not a pytree, so trace to a (p, s) pair)
        p, s = jax.eval_shape(
            lambda rng: (lambda v: (v.params, v.state))(module.init(rng)),
            jax.random.key(0))
        variables = Variables(p, s)

    def leaf_rows(tree, mask):
        rows: dict[str, list] = {}  # layer -> [n_params, shapes, n_trainable]
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        mask_leaves = (jax.tree.leaves(mask) if mask is not None
                       else [True] * len(flat))
        for (path, leaf), trainable in zip(flat, mask_leaves,
                                           strict=True):
            keys = tuple(p.key for p in path)
            layer, var = ".".join(keys[:-1]) or keys[-1], keys[-1]
            row = rows.setdefault(layer, [0, [], 0])
            row[0] += leaf.size
            row[1].append(f"{var}{list(leaf.shape)}")
            row[2] += leaf.size if trainable else 0
        return rows

    rows = leaf_rows(variables.params, trainable_mask)
    state_rows = leaf_rows(variables.state, None)
    order = list(rows)
    if module.layer_names:
        ranked = {n: i for i, n in enumerate(module.layer_names)}
        order.sort(key=lambda l: ranked.get(l, len(ranked)))

    name_w = max([len(l) for l in order + list(state_rows)] + [5]) + 2
    lines = [f"Model: {module.name}",
             f"{'Layer':<{name_w}}{'Params':>10}  Variables"]
    total = trainable = 0
    for layer in order:
        n, shapes, n_train = rows[layer]
        total += n
        trainable += n_train
        suffix = ("" if trainable_mask is None or n_train == n
                  else "  (frozen)" if n_train == 0
                  else f"  ({n_train:,} trainable)")
        lines.append(f"{layer:<{name_w}}{n:>10,}  "
                     f"{', '.join(shapes)}{suffix}")
    state_total = 0
    for layer, (n, shapes, _) in state_rows.items():
        state_total += n
        lines.append(f"{layer:<{name_w}}{n:>10,}  "
                     f"{', '.join(shapes)}  (state)")
    lines.append(f"Total params: {total:,}")
    if trainable_mask is not None:
        lines.append(f"Trainable params: {trainable:,}")
        lines.append(f"Non-trainable params: {total - trainable:,}")
    if state_total:
        lines.append(f"State (BN statistics): {state_total:,}")
    return "\n".join(lines)


def head_only_mask(params: Params):
    """Phase-1 transfer-learning mask: only the "head" subtree trains."""
    return trainability_mask(params, lambda p: p[0] == "head")


def keras_fine_tune_mask(params: Params, index_map: dict[str, int],
                         fine_tune_at: int):
    """Phase-2 mask: head + backbone layers whose Keras layer index (from
    the model's KERAS_LAYER_INDEX map) is >= fine_tune_at — the exact
    semantics of the reference's `for layer in model.layers[:fine_tune_at]:
    layer.trainable = False` (dist_model_tf_vgg.py:144-147)."""

    def pred(path):
        if path[0] == "head":
            return True
        return index_map.get(path[1], -1) >= fine_tune_at

    return trainability_mask(params, pred)
