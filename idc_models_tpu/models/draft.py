"""Token drafters for speculative decoding: cheap host-side proposal
of the next k tokens of a slot's stream, verified (and corrected) by
the target model's batched verify program (models/lm.py verify forward,
serve/engine.py verify dispatch).

The drafter contract is deliberately tiny so a small draft LM can slot
in later:

    drafter.propose(history) -> np.ndarray [k] int32, or None

`history` is the slot's ENTIRE token stream so far — prompt plus every
emitted token — as a 1-D int array; the return is exactly `k` proposed
continuation tokens, or None when the drafter has nothing worth
verifying. A proposal is never trusted: the verify program accepts only
the prefix the target model itself would have emitted (greedy argmax,
or the seeded sample, per position), so a BAD drafter costs acceptance
rate, never correctness — any `propose` implementation is sound.

`NGramDrafter` is prompt-lookup / n-gram drafting (Saxena 2023;
PLD in vLLM): find the most recent earlier occurrence of the stream's
trailing n-gram and propose the tokens that followed it. No second
model, no device work — ideal for the repetitive, templated traffic
(shared system prompts, retrieval echoes, code) where the continuation
usually HAS appeared before. On adversarially random streams it simply
stops proposing (None) and serving falls back to the plain fused
window (docs/LONG_CONTEXT.md owns the when-it-loses story).

`ChainedDrafter` composes drafters first-hit-wins per slot — the
production policy is lookup-first/learned-fallback: the n-gram scan's
free hits on templated streams, the learned draft LM
(models/draft_lm.DraftLM) everywhere the lookup goes quiet. Because
every member honors the same contract, the chain does too — the
verify program makes ANY composition sound.
"""

from __future__ import annotations

import numpy as np


class NGramDrafter:
    """Longest-suffix n-gram lookup over the slot's own stream.

    For n from `order` down to `min_order`, find the LAST position
    before the end where the stream's trailing n tokens occurred, and
    propose the `k` tokens that followed that occurrence (recency wins
    because templated streams drift: the latest occurrence is the best
    predictor of what follows now). A match whose continuation runs
    past the end of the history pads by repeating the final history
    token — padding is verified like any other draft token, so it
    costs only acceptance. Returns None when no n-gram down to
    `min_order` recurs (nothing to verify beats verifying noise).

    `lookback` bounds the scan to the stream's most recent N tokens —
    the drafting pass runs on the serving host's critical path once
    per scheduler cycle per slot, so it must stay O(lookback), not
    O(stream). Recency preference makes the truncation cheap: a match
    only reachable beyond the lookback costs acceptance rate, never
    correctness. None scans everything."""

    def __init__(self, k: int, *, order: int = 3, min_order: int = 1,
                 lookback: int | None = 512):
        if k < 1:
            raise ValueError(f"need k >= 1 draft tokens, got {k}")
        if not 1 <= min_order <= order:
            raise ValueError(f"need 1 <= min_order <= order, got "
                             f"min_order {min_order}, order {order}")
        if lookback is not None and lookback < order + 1:
            raise ValueError(f"lookback {lookback} cannot even hold "
                             f"one order-{order} match")
        self.k = int(k)
        self.order = int(order)
        self.min_order = int(min_order)
        self.lookback = None if lookback is None else int(lookback)

    def propose(self, history) -> np.ndarray | None:
        h = np.asarray(history, np.int64).ravel()
        if self.lookback is not None and h.shape[0] > self.lookback:
            h = h[-self.lookback:]
        length = h.shape[0]
        for n in range(min(self.order, length - 1), self.min_order - 1,
                       -1):
            suffix = h[length - n:]
            # every window over h[:L-1] starts at i <= L-1-n < L-n, so
            # the suffix's self-match at L-n (whose "continuation" is
            # the future being drafted) is excluded by the slice
            windows = np.lib.stride_tricks.sliding_window_view(
                h[:length - 1], n)
            hits = np.flatnonzero((windows == suffix).all(axis=1))
            if not hits.size:
                continue
            i = int(hits[-1])
            cont = h[i + n:i + n + self.k]
            if not cont.size:
                continue
            if cont.shape[0] < self.k:
                cont = np.concatenate([
                    cont, np.full(self.k - cont.shape[0], h[-1],
                                  np.int64)])
            return cont.astype(np.int32)
        return None


class ChainedDrafter:
    """First-hit-wins composition of drafters, one proposal per slot.

    Per slot, members are consulted IN ORDER and the first non-None
    proposal wins — put the free drafter first (lookup-first /
    learned-fallback: `ChainedDrafter(NGramDrafter(k), DraftLM(...))`)
    so the expensive member only answers where the cheap one went
    quiet. All members must agree on `k` (the verify program has ONE
    fixed draft shape), and at most one member may be engine-backed
    (`uses_engine`): the engine hosts one set of drafter ring caches,
    and the chain keeps the one-propose-dispatch-per-cycle budget.

    The batched path calls the engine-backed member's
    `propose_batched` exactly ONCE per cycle regardless of how many
    slots the earlier members already covered — the dispatch is what
    drains the drafter's pending-token backlog into its ring caches,
    so skipping it on lookup-hit cycles would let the drafter's state
    fall behind the streams it must draft next cycle."""

    def __init__(self, *drafters):
        if len(drafters) < 2:
            raise ValueError(
                f"ChainedDrafter needs at least 2 drafters to chain, "
                f"got {len(drafters)} — use the drafter directly")
        ks = sorted({int(d.k) for d in drafters})
        if len(ks) != 1:
            raise ValueError(
                f"chained drafters disagree on k {ks}: the verify "
                f"program has one fixed [n_slots, draft_k] draft "
                f"shape, so every member must propose the same k")
        backed = [d for d in drafters
                  if getattr(d, "uses_engine", False)]
        if len(backed) > 1:
            raise ValueError(
                f"chain has {len(backed)} engine-backed drafters "
                f"({', '.join(type(d).__name__ for d in backed)}); "
                f"the engine hosts ONE set of drafter ring caches — "
                f"chain at most one models/draft_lm.DraftLM")
        self.drafters = tuple(drafters)
        self.k = ks[0]

    @property
    def learned(self):
        """The engine-backed member's model handle (None without one)
        — serve/api.py arms the engine's drafter state from this."""
        for d in self.drafters:
            if getattr(d, "uses_engine", False):
                return d.learned
        return None

    def propose(self, history) -> np.ndarray | None:
        """Host-side chain walk: first member with a proposal wins
        (the engine-backed member answers through its own host-side
        rollout here)."""
        for d in self.drafters:
            got = d.propose(history)
            if got is not None:
                return got
        return None

    def propose_batched(self, engine, slots, hists) -> dict:
        """Per-slot chain resolution over ONE batched learned dispatch
        (when a learned member is chained) plus the host members'
        scans."""
        learned_rows = {}
        for d in self.drafters:
            if getattr(d, "uses_engine", False):
                learned_rows = d.propose_batched(engine, slots, hists)
                break
        out = {}
        for s, h in zip(slots, hists):
            got = None
            for d in self.drafters:
                got = (learned_rows.get(s)
                       if getattr(d, "uses_engine", False)
                       else d.propose(h))
                if got is not None:
                    break
            out[s] = got
        return out
