"""VGG16 backbone + transfer-learning head.

Capability parity with the reference's flagship model
(dist_model_tf_vgg.py:119-129, fed_model.py:113-123): VGG16 without top,
GlobalAveragePooling2D, Dense(1) logits head. 14,714,688 backbone params
(matches keras.applications VGG16 include_top=False).

Freezing follows the reference's two phases: phase 1 trains the head only
(backbone frozen, dist_model_tf_vgg.py:122); phase 2 unfreezes layers with
Keras index >= fine_tune_at=15 (dist_model_tf_vgg.py:146) — i.e. block 5's
convolutions. Here that is an explicit optax mask from `fine_tune_mask`,
keyed by the same Keras layer indices (see KERAS_LAYER_INDEX).
"""

from __future__ import annotations

from idc_models_tpu.models import core

# (block, filters, convs-per-block) — VGG16 topology
_CFG = [(1, 64, 2), (2, 128, 2), (3, 256, 3), (4, 512, 3), (5, 512, 3)]

# Keras layer index of every parameterized backbone layer, matching
# keras.applications.VGG16(include_top=False).layers (index 0 = InputLayer,
# pools occupy indices too). Used to translate the reference's
# `fine_tune_at` layer numbers into param-group masks.
KERAS_LAYER_INDEX: dict[str, int] = {}
_i = 1
for _b, _f, _n in _CFG:
    for _c in range(1, _n + 1):
        KERAS_LAYER_INDEX[f"block{_b}_conv{_c}"] = _i
        _i += 1
    _i += 1  # the block's pooling layer


def vgg16_backbone(in_channels: int = 3) -> core.Module:
    layers: list[core.Module] = []
    c_in = in_channels
    for block, filters, n_convs in _CFG:
        for conv in range(1, n_convs + 1):
            layers.append(core.conv2d(c_in, filters, 3,
                                      name=f"block{block}_conv{conv}"))
            layers.append(core.relu(name=f"block{block}_relu{conv}"))
            c_in = filters
        layers.append(core.max_pool(2, name=f"block{block}_pool"))
    return core.sequential(layers, name="vgg16")


def vgg16(num_outputs: int = 1, in_channels: int = 3) -> core.Module:
    """Backbone + GAP + Dense head; params = {"backbone": ..., "head": ...}."""
    return core.classifier(vgg16_backbone(in_channels), 512, num_outputs,
                           name="vgg16_classifier")


head_only_mask = core.head_only_mask


def fine_tune_mask(params, fine_tune_at: int = 15):
    """Phase-2 mask: head + backbone layers with Keras index >= fine_tune_at."""
    return core.keras_fine_tune_mask(params, KERAS_LAYER_INDEX, fine_tune_at)
