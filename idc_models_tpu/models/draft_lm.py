"""Learned draft model for speculative decoding (ROADMAP item 2).

The PR 10 n-gram drafter only proposes when a trailing n-gram recurs,
so speculation is inert on fresh text. This module supplies the
learned alternative — a tiny `attention_lm` student (same tokenizer /
vocab as the target, ~2 blocks) distilled from the target's own
logits — plus the glue that carries it from `train/loop.py` all the
way to the serve stack:

- `draft_config` / `draft_lm`: the student architecture, a scaled-down
  models/lm.py `attention_lm`. Same param-tree schema as the target,
  so the drafter rides the registry partition rules ("draft_lm") and
  the sharded checkpoint path unchanged.
- `greedy_streams`: the target's own greedy continuations of a prompt
  batch — the distillation corpus ("the target's sampled streams").
- `distill_kl_loss` / `distill_draft_lm`: per-position KL against the
  teacher's logits, trained through the EXISTING `train/loop.fit`
  machinery (epoch loop, checkpoint-resume, history) so the
  train→serve handoff is exercised end to end.
- `save_draft_lm` / `load_draft_lm`: sharded-checkpoint save/restore
  (checkpoint/sharded.py — atomic manifest, cross-mesh restore) with
  a `draft_config.json` sidecar so a restore knows the architecture
  without the caller carrying it out of band.
- `DraftLM`: the serve-side drafter. It satisfies the models/draft.py
  host contract (`propose(history) -> [k] int32 | None`) with a
  fixed-shape jitted forward (one compile per instance, any history),
  and additionally flags `uses_engine=True` so the scheduler routes
  proposals through `SlotEngine.propose_all()` — ONE batched device
  dispatch per cycle for ALL running slots against the drafter's own
  ring KV caches — instead of per-slot host calls.

A draft model is never trusted: the target's verify program accepts
only the prefix the target itself would have emitted, so a bad student
costs acceptance rate, never correctness (models/draft.py owns that
contract).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.models.lm import attention_lm

CONFIG_NAME = "draft_config.json"

# architecture knobs a draft_config carries (beyond vocab/seq); the
# defaults are the "tiny student" the distillation recipe targets —
# ~2 blocks, a fraction of the target's width
_ARCH_DEFAULTS = {
    "embed_dim": 32,
    "num_heads": 2,
    "mlp_dim": 64,
    "num_blocks": 2,
}


def draft_config(vocab_size: int, seq_len: int, **overrides) -> dict:
    """Normalized draft-model architecture dict (the sidecar schema).

    `seq_len` sizes the position table: it must cover the longest
    training stream AND the serving engine's `t_max` (the engine
    validates the latter with a teaching error at construction).
    """
    unknown = set(overrides) - set(_ARCH_DEFAULTS)
    if unknown:
        raise ValueError(
            f"unknown draft_config overrides {sorted(unknown)}; valid "
            f"keys: {sorted(_ARCH_DEFAULTS)}")
    cfg = {"vocab_size": int(vocab_size), "seq_len": int(seq_len)}
    for key, default in _ARCH_DEFAULTS.items():
        cfg[key] = int(overrides.get(key, default))
    if cfg["embed_dim"] % cfg["num_heads"]:
        raise ValueError(
            f"draft embed_dim {cfg['embed_dim']} must divide by "
            f"num_heads {cfg['num_heads']}")
    return cfg


def draft_lm(config: dict, *, mesh=None, block_impl: str = "jnp"):
    """Build the student Module from a `draft_config` dict."""
    return attention_lm(
        config["vocab_size"], config["seq_len"],
        embed_dim=config["embed_dim"], num_heads=config["num_heads"],
        mlp_dim=config["mlp_dim"], num_blocks=config["num_blocks"],
        mesh=mesh, block_impl=block_impl)


def greedy_streams(model, variables, prompts, total_len: int) -> np.ndarray:
    """The target's own greedy continuations: extend each prompt row to
    `total_len` tokens with the target's argmax picks. This is the
    distillation corpus — the student learns the target's behavior on
    the target's OWN stream distribution, which is exactly what it will
    be asked to draft at serve time."""
    prompts = np.asarray(prompts, np.int32)
    n, p_len = prompts.shape
    if not 1 <= p_len < total_len:
        raise ValueError(f"need 1 <= prompt len < total_len, got "
                         f"prompt {p_len}, total_len {total_len}")
    toks = np.zeros((n, total_len), np.int32)
    toks[:, :p_len] = prompts
    fwd = jax.jit(lambda p, s, t: model.apply(p, s, t, train=False)[0])
    for t in range(p_len, total_len):
        logits = fwd(variables.params, variables.state, toks)
        toks[:, t] = np.asarray(jnp.argmax(logits[:, t - 1, :], -1),
                                np.int32)
    return toks


def teacher_logits(model, variables, streams, *,
                   batch_size: int = 32) -> np.ndarray:
    """The teacher's full-sequence logits [N, T, V] float32 — the soft
    labels the KL loss distills against."""
    streams = np.asarray(streams, np.int32)
    fwd = jax.jit(lambda p, s, t: model.apply(p, s, t, train=False)[0])
    out = []
    for i in range(0, len(streams), batch_size):
        chunk = streams[i:i + batch_size]
        live = len(chunk)
        if live < batch_size:       # pad the ragged tail: one jit entry
            chunk = np.concatenate(
                [chunk, np.repeat(chunk[-1:], batch_size - live, 0)])
        logits = np.asarray(fwd(variables.params, variables.state,
                                chunk), np.float32)
        out.append(logits[:live])
    return np.concatenate(out, axis=0)


def distill_kl_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean per-position KL(teacher ‖ student). `labels` are the
    teacher's raw logits [B, T, V] (an ArrayDataset's labels field);
    both distributions are formed in float32. Unshifted: teacher and
    student logits at position t both predict token t+1, so the
    positions already align."""
    t = jax.nn.log_softmax(labels.astype(jnp.float32), axis=-1)
    s = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.mean(jnp.sum(jnp.exp(t) * (t - s), axis=-1))


def distill_draft_lm(target_model, target_variables, streams, *,
                     config: dict, mesh, epochs: int = 4,
                     batch_size: int = 8, lr: float = 1e-2,
                     seed: int = 0, rules=None,
                     checkpoint_dir: str | None = None, logger=None,
                     verbose: bool = False):
    """The distillation recipe, through the standard train stack.

    Computes the teacher's logits over `streams` (int32 [N, T] token
    streams — use `greedy_streams` to sample them from the target),
    then runs `train/loop.fit` on the student with `distill_kl_loss`
    and the reference RMSprop — the same epoch loop, checkpoint-resume
    and history plumbing every other model here trains through, so the
    train→serve handoff is exercised end to end.

    Returns `(student_model, TrainState, history)`; persist with
    `save_draft_lm(path, jax.device_get(state.params), config=config)`.
    """
    # lazy: keeps models.* import-light (train pulls in the loader /
    # observe stacks)
    from idc_models_tpu.data.idc import ArrayDataset
    from idc_models_tpu.train.loop import fit
    from idc_models_tpu.train.state import TrainState, rmsprop

    streams = np.asarray(streams, np.int32)
    if streams.ndim != 2:
        raise ValueError(f"streams must be [N, T] int tokens, got "
                         f"shape {streams.shape}")
    if streams.shape[1] > config["seq_len"]:
        raise ValueError(
            f"stream length {streams.shape[1]} exceeds the draft "
            f"position table seq_len={config['seq_len']}; raise "
            f"seq_len in draft_config (it must also cover the serving "
            f"engine's t_max)")
    labels = teacher_logits(target_model, target_variables, streams,
                            batch_size=batch_size)
    model = draft_lm(config, mesh=mesh)
    variables = model.init(jax.random.PRNGKey(seed))
    opt = rmsprop(lr)
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    ds = ArrayDataset(streams, labels)
    state, history = fit(model, opt, distill_kl_loss, state, ds, None,
                         mesh, epochs=epochs, batch_size=batch_size,
                         seed=seed, logger=logger, verbose=verbose,
                         checkpoint_dir=checkpoint_dir, rules=rules)
    return model, state, history


def save_draft_lm(path, params, *, config: dict, step=None):
    """Save a distilled drafter: the param tree through the sharded
    checkpoint path (atomic manifest, per-shard writes) plus the
    `draft_config.json` architecture sidecar, committed atomically by
    the same writer the manifest uses."""
    from idc_models_tpu.checkpoint import save_sharded
    from idc_models_tpu.checkpoint.sharded import _commit_json

    for p, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        if np.asarray(leaf).dtype == object:
            raise ValueError(
                f"save_draft_lm got a non-array leaf at "
                f"{jax.tree_util.keystr(p)} ({type(leaf).__name__}): "
                f"pass the PARAM tree — distill_draft_lm returns "
                f"(model, state, history), so save "
                f"jax.device_get(state.params), not the model")
    doc = draft_config(config["vocab_size"], config["seq_len"],
                       **{k: config[k] for k in _ARCH_DEFAULTS
                          if k in config})
    handle = save_sharded(str(path), params, step=step)
    from pathlib import Path

    _commit_json(Path(path), CONFIG_NAME, doc)
    return handle


def load_draft_lm(path, *, mesh=None, rules=None):
    """Restore `(params, config)` from a `save_draft_lm` directory.

    `mesh` + `rules` re-resolve the layout against the TARGET mesh
    (checkpoint/sharded.py): a drafter saved under FSDP rules restores
    bit-identically onto a TP mesh or a different device count. With a
    mesh but no rules, the registry's "draft_lm" rule set (the one the
    serving engine places drafter params with) is used.
    """
    from idc_models_tpu.checkpoint import restore_sharded

    if mesh is not None and rules is None:
        from idc_models_tpu.models.registry import DRAFT_LM_RULES

        rules = DRAFT_LM_RULES

    cfg_path = os.path.join(str(path), CONFIG_NAME)
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(
            f"{cfg_path}: missing the {CONFIG_NAME} sidecar, so this "
            f"is not a draft-LM checkpoint (a bare sharded tree has "
            f"no architecture record); save with "
            f"models/draft_lm.save_draft_lm")
    with open(cfg_path) as f:
        raw = json.load(f)
    config = draft_config(raw["vocab_size"], raw["seq_len"],
                          **{k: raw[k] for k in _ARCH_DEFAULTS
                             if k in raw})
    params = restore_sharded(str(path), mesh=mesh, rules=rules)
    return params, config


class DraftLM:
    """Learned drafter over a distilled draft-LM checkpoint.

    Satisfies the models/draft.py contract with a host-side greedy
    rollout (`propose`), and flags `uses_engine=True` so the serving
    scheduler instead batches proposals for ALL running slots through
    `SlotEngine.propose_all()` — one jitted device dispatch per cycle
    against the drafter's own per-slot ring KV caches. The host path
    stays for engines without drafter state (and for bit-identity
    tests across checkpoint restores).

    `adapters=(u [T, V, r], v [T, r, V])` optionally stacks per-tenant
    low-rank drafter heads; the engine applies them with the traced-tid
    gather (models/lm.py `make_adapter_head_hook`), so mixed-tenant
    batches stay one dispatch.
    """

    uses_engine = True

    def __init__(self, k: int, params, config: dict, *, adapters=None):
        if k < 1:
            raise ValueError(f"need k >= 1 draft tokens, got {k}")
        self.k = int(k)
        self.params = params
        self.config = draft_config(config["vocab_size"],
                                   config["seq_len"],
                                   **{key: config[key]
                                      for key in _ARCH_DEFAULTS
                                      if key in config})
        vocab = int(params["embed"].shape[0])
        if vocab != self.config["vocab_size"]:
            raise ValueError(
                f"draft params embed a {vocab}-token vocab but the "
                f"config says {self.config['vocab_size']}; the sidecar "
                f"and the tree disagree — re-save with save_draft_lm")
        if adapters is not None:
            u, v = adapters
            if (u.ndim != 3 or v.ndim != 3 or u.shape[0] != v.shape[0]
                    or u.shape[1] != vocab or v.shape[2] != vocab
                    or u.shape[2] != v.shape[1]):
                raise ValueError(
                    f"drafter adapters must be u [T, V, r] / v [T, r, V] "
                    f"with V={vocab}, got u {getattr(u, 'shape', None)} "
                    f"v {getattr(v, 'shape', None)}")
        self.adapters = adapters
        self._fwd = None

    @property
    def learned(self) -> "DraftLM":
        """The engine-backed member (serve/api.py arms the engine's
        drafter state from this)."""
        return self

    @property
    def vocab_size(self) -> int:
        return self.config["vocab_size"]

    def _forward(self):
        if self._fwd is None:
            model = draft_lm(self.config)

            def pick(params, toks, last):
                logits, _ = model.apply(params, {}, toks, train=False)
                return jnp.argmax(logits[0, last, :], -1)

            self._fwd = jax.jit(pick)
        return self._fwd

    def propose(self, history) -> np.ndarray | None:
        """Host-side greedy rollout of k tokens. Fixed shapes — the
        window is always [1, seq_len] and `last` is a traced index —
        so any history length hits ONE compiled program."""
        h = np.asarray(history, np.int32).ravel()
        if h.size == 0:
            return None
        seq = self.config["seq_len"]
        fwd = self._forward()
        toks = np.zeros(seq, np.int32)
        tail = h[-seq:]
        n = tail.size
        toks[:n] = tail
        out = np.empty(self.k, np.int32)
        for j in range(self.k):
            nxt = int(fwd(self.params, toks[None], n - 1))
            out[j] = nxt
            if n < seq:
                toks[n] = nxt
                n += 1
            else:                       # slide the window by one
                toks[:-1] = toks[1:]
                toks[-1] = nxt
        return out

    def propose_batched(self, engine, slots, hists) -> dict:
        """One `SlotEngine.propose_all()` dispatch covering every
        running slot; rows come back per requested slot (None where
        the drafter had no valid context)."""
        res = engine.propose_all()
        if res is None:
            return {s: None for s in slots}
        drafts, valid = res
        return {s: (np.asarray(drafts[s], np.int32) if valid[s]
                    else None) for s in slots}
