"""Ring-attention sequence classifier — SP as a TRAINING capability.

The reference has no attention models at all (its models are the CNN
backbones of SURVEY.md §3.5), so this module is beyond-parity: it
exists to prove the framework's sequence parallelism is a first-class
training path, not a standalone library demo. The classifier is the
smallest honest transformer — token embed + learned positions, pre-LN
blocks whose self-attention runs through `make_ring_attention` over a
mesh's "seq" axis, GAP over positions, dense head — built from the same
explicit-pytree `core.Module` contract as every CNN here, so the
existing train step, optimizer, freeze machinery
(`core.head_only_mask`), checkpointing, and eval loop drive it
unchanged (gated by tests/test_attention_model.py's golden-learning
test on a ("data", "seq") 2-D mesh).

Mesh composition: pass the SAME mesh the train step runs on. The batch
dimension shards over every non-"seq" axis and each data-mesh row runs
an independent ring (ring_attention.py); with `mesh=None` the model
falls back to single-device `full_attention` — identical function,
pinned by a test — so the model also runs un-meshed (e.g. export or
CPU debugging).

Zigzag: with ``layout="zigzag"`` the model permutes the embedded
sequence into the balanced causal layout ONCE after adding positions
and never permutes back — LayerNorm/MLP are per-position, the causal
masks use global natural-order positions internally, and the final GAP
is permutation-invariant, so the only cost of the ~2x-faster causal
schedule is one gather at the bottom of the network.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models import core
from idc_models_tpu.ring_attention import (
    full_attention, make_ring_attention, to_zigzag, zigzag_indices,
)


def residual_sharding(mesh: Mesh, axis: str = meshlib.SEQ_AXIS):
    """The [B, T, E] residual-stream sharding on `mesh` — the same
    layout the ring op forces at its shard_map boundary
    (`mesh.batch_seq_sharding`, one construction site for all SP
    surfaces)."""
    return meshlib.batch_seq_sharding(mesh, axis, trailing=1)


def _seq_pin(mesh: Mesh | None, axis: str = meshlib.SEQ_AXIS):
    """Constraint pinning [B, T, E] activations to `residual_sharding`.

    Without this, nothing stops GSPMD from replicating the LN/MLP/embed
    activations BETWEEN ring calls over "seq" — the long-context memory
    claim (docs/LONG_CONTEXT.md) would then hold for the attention op
    but not the model. Gated by tests/test_attention_model.py::
    test_residual_stream_stays_seq_sharded, which fails if any full-T
    activation survives in the partitioned module."""
    if mesh is None:
        return lambda h: h
    sh = residual_sharding(mesh, axis)
    return lambda h: jax.lax.with_sharding_constraint(h, sh)


def multi_head_attention(embed_dim: int, num_heads: int, *,
                         mesh: Mesh | None = None,
                         axis: str = meshlib.SEQ_AXIS,
                         causal: bool = True,
                         block_impl: str = "jnp",
                         layout: str = "contiguous",
                         name: str = "mha") -> core.Module:
    """Multi-head self-attention [B, T, E] -> [B, T, E]; the attention
    itself is a sequence-parallel ring over `mesh`'s `axis` (or
    single-device full attention when mesh is None)."""
    if embed_dim % num_heads:
        raise ValueError(f"embed_dim {embed_dim} not divisible by "
                         f"num_heads {num_heads}")
    head_dim = embed_dim // num_heads
    if mesh is None:
        attn = lambda q, k, v: full_attention(q, k, v, causal=causal)
    else:
        if axis not in mesh.axis_names:
            raise ValueError(
                f"mesh {mesh.axis_names} has no {axis!r} axis for the "
                f"attention ring — build one with mesh.data_seq_mesh / "
                f"mesh.seq_mesh, or pass mesh=None for single-device "
                f"full attention")
        attn = make_ring_attention(mesh, axis=axis, causal=causal,
                                   block_impl=block_impl, layout=layout)

    def init(rng):
        ks = jax.random.split(rng, 4)
        proj = lambda r: core.glorot_uniform(
            r, (embed_dim, embed_dim), embed_dim, embed_dim)
        return core.Variables(
            {"wq": proj(ks[0]), "wk": proj(ks[1]), "wv": proj(ks[2]),
             "wo": proj(ks[3]), "bo": jnp.zeros((embed_dim,))}, {})

    def apply(params, state, x, *, train=False, rng=None):
        b, t, _ = x.shape
        split = lambda y: y.reshape(b, t, num_heads, head_dim)
        q = split(x @ params["wq"].astype(x.dtype))
        k = split(x @ params["wk"].astype(x.dtype))
        v = split(x @ params["wv"].astype(x.dtype))
        o = attn(q, k, v).reshape(b, t, embed_dim)
        return (o @ params["wo"].astype(x.dtype)
                + params["bo"].astype(x.dtype)), state

    return core.Module(init, apply, name)


def transformer_block(embed_dim: int, num_heads: int, mlp_dim: int, *,
                      mesh: Mesh | None = None, causal: bool = True,
                      block_impl: str = "jnp",
                      layout: str = "contiguous",
                      dropout_rate: float = 0.0,
                      name: str = "block") -> core.Module:
    """Pre-LN transformer block: x + drop(MHA(LN(x))), then
    + drop(MLP(LN(.))) — residual dropout in the two standard places
    (attention-probability dropout would have to live inside the flash
    kernels and is deliberately not offered)."""
    ln1 = core.layer_norm(embed_dim, name="ln1")
    ln2 = core.layer_norm(embed_dim, name="ln2")
    mha = multi_head_attention(embed_dim, num_heads, mesh=mesh,
                               causal=causal, block_impl=block_impl,
                               layout=layout)
    fc1 = core.dense(embed_dim, mlp_dim, name="fc1")
    fc2 = core.dense(mlp_dim, embed_dim, name="fc2")
    drop = core.dropout(dropout_rate)
    parts = (("ln1", ln1), ("mha", mha), ("ln2", ln2), ("fc1", fc1),
             ("fc2", fc2))

    def init(rng):
        rngs = jax.random.split(rng, len(parts))
        return core.Variables(
            {k: m.init(r).params for (k, m), r in zip(parts, rngs)}, {})

    def apply(params, state, x, *, train=False, rng=None):
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        h, _ = ln1.apply(params["ln1"], {}, x, train=train)
        h, _ = mha.apply(params["mha"], {}, h, train=train)
        h, _ = drop.apply({}, {}, h, train=train, rng=r1)
        x = x + h
        h, _ = ln2.apply(params["ln2"], {}, x, train=train)
        h, _ = fc1.apply(params["fc1"], {}, h, train=train)
        h = jax.nn.gelu(h)
        h, _ = fc2.apply(params["fc2"], {}, h, train=train)
        h, _ = drop.apply({}, {}, h, train=train, rng=r2)
        return x + h, state

    return core.Module(init, apply, name, children=parts)


def attention_classifier(seq_len: int, features_in: int, *,
                         embed_dim: int = 64, num_heads: int = 4,
                         mlp_dim: int = 128, num_blocks: int = 2,
                         num_outputs: int = 1,
                         mesh: Mesh | None = None,
                         causal: bool = True,
                         block_impl: str = "jnp",
                         layout: str = "contiguous",
                         dropout_rate: float = 0.0,
                         remat: bool = False) -> core.Module:
    """Sequence classifier over [B, T, F] inputs: dense embed + learned
    positions -> `num_blocks` ring-attention transformer blocks -> GAP
    over positions -> dense head. Inputs are always NATURAL order; the
    zigzag permutation (if any) is internal (see module docstring).

    ``remat=True`` wraps each transformer block in `jax.checkpoint`:
    the backward recomputes block activations instead of storing them,
    so residual memory is O(num_blocks) block BOUNDARIES rather than
    every intermediate — the standard long-context lever, composing
    with the flash kernels' own VMEM-resident scores (identical values
    and gradients, pinned by test)."""
    embed = core.dense(features_in, embed_dim, name="embed")
    blocks = [transformer_block(embed_dim, num_heads, mlp_dim, mesh=mesh,
                                causal=causal, block_impl=block_impl,
                                layout=layout,
                                dropout_rate=dropout_rate,
                                name=f"block{i}")
              for i in range(num_blocks)]
    ln_f = core.layer_norm(embed_dim, name="ln_f")
    head = core.dense(embed_dim, num_outputs, name="head")
    n_ring = mesh.shape[meshlib.SEQ_AXIS] if mesh is not None else 1
    zig = layout == "zigzag" and causal

    def init(rng):
        rngs = jax.random.split(rng, num_blocks + 4)
        params = {"embed": embed.init(rngs[0]).params,
                  "pos": 0.02 * jax.random.normal(
                      rngs[1], (seq_len, embed_dim))}
        for i, (blk, r) in enumerate(zip(blocks, rngs[2:2 + num_blocks])):
            params[f"block{i}"] = blk.init(r).params
        params["ln_f"] = ln_f.init(rngs[-2]).params
        params["head"] = head.init(rngs[-1]).params
        return core.Variables(params, {})

    pin = _seq_pin(mesh)

    def apply(params, state, x, *, train=False, rng=None):
        pos = params["pos"]
        if zig:
            # Permute the INPUT (and positions to match) rather than the
            # embedded stream: embed is per-position so the result is
            # identical, but the gather then touches only input-scale
            # [B, T, F] / param-scale [T, E] tensors — no full-length
            # [B, T, E] activation ever materializes, which keeps the
            # residual stream seq-sharded end to end (see _seq_pin).
            x = to_zigzag(x, n_ring)
            pos = jnp.take(pos, zigzag_indices(pos.shape[0], n_ring),
                           axis=0)
        h, _ = embed.apply(params["embed"], {}, x, train=train)
        h = pin(h + pos.astype(h.dtype))
        rngs = (jax.random.split(rng, num_blocks) if rng is not None
                else [None] * num_blocks)
        for i, blk in enumerate(blocks):
            def run_block(p, h, _blk=blk, _r=rngs[i]):
                return _blk.apply(p, {}, h, train=train, rng=_r)[0]

            if remat:
                run_block = jax.checkpoint(run_block)
            h = pin(run_block(params[f"block{i}"], h))
        h, _ = ln_f.apply(params["ln_f"], {}, h, train=train)
        pooled = jnp.mean(h, axis=1)   # GAP — permutation-invariant
        y, _ = head.apply(params["head"], {}, pooled, train=train)
        return y, state

    names = (("embed", "pos")
             + tuple(f"block{i}" for i in range(num_blocks))
             + ("ln_f", "head"))
    return core.Module(init, apply, "attention_classifier",
                       layer_names=names,
                       children=tuple((f"block{i}", b)
                                      for i, b in enumerate(blocks)))
