"""The small custom CNN used by the secure-federated workload.

Capability parity with the reference's `create_model`
(secure_fed_model.py:84-98): Conv2D(32, 3x3, stride 2, relu) -> MaxPool(2x2)
-> Dropout(0.25) -> Flatten -> Dense(8, relu) -> Dropout(0.5) -> Dense(1)
for 10x10x3 inputs, binary logits.
"""

from __future__ import annotations

from idc_models_tpu.models import core


def small_cnn(input_size: int = 10, channels: int = 3,
              num_outputs: int = 1) -> core.Module:
    # stride-2 SAME conv: 10x10 -> 5x5; maxpool 2x2 VALID: 5x5 -> 2x2
    conv_out = (input_size + 1) // 2
    pooled = conv_out // 2
    flat = pooled * pooled * 32
    return core.sequential(
        [
            core.conv2d(channels, 32, 3, stride=2, padding="SAME", name="conv1"),
            core.relu(),
            core.max_pool(2, name="pool1"),
            core.dropout(0.25, name="drop1"),
            core.flatten(),
            core.dense(flat, 8, name="fc1"),
            core.relu(name="relu_1"),
            core.dropout(0.5, name="drop2"),
            core.dense(8, num_outputs, name="head"),
        ],
        name="small_cnn",
    )
