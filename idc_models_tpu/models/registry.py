"""Model registry: name -> (builder, head-only mask, fine-tune mask).

Gives the CLI/configs one lookup for the reference's model zoo
(keras.applications in the reference; SURVEY.md C5/C6).
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable

from idc_models_tpu.models import densenet, mobilenet, small_cnn as small_cnn_mod, vgg
from idc_models_tpu.models.core import Module


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    build: Callable[..., Module]          # (num_outputs, in_channels) -> Module
    head_only_mask: Callable              # params -> bool pytree
    fine_tune_mask: Callable              # (params, fine_tune_at) -> bool pytree
    default_fine_tune_at: int
    feature_dim: int
    # Keras layer index per parameterized backbone layer (the zoo's
    # KERAS_LAYER_INDEX); consumers: fine-tune boundary lookups such as
    # the frozen-prefix feature cache. None for models without one.
    layer_index: dict[str, int] | None = None


def _always_trainable(params, fine_tune_at=0):
    import jax

    return jax.tree.map(lambda _: True, params)


REGISTRY: dict[str, ModelSpec] = {
    "vgg16": ModelSpec(vgg.vgg16, vgg.head_only_mask, vgg.fine_tune_mask,
                       default_fine_tune_at=15, feature_dim=512,
                       layer_index=vgg.KERAS_LAYER_INDEX),
    "mobilenet_v2": ModelSpec(mobilenet.mobilenet_v2,
                              mobilenet.head_only_mask,
                              mobilenet.fine_tune_mask,
                              default_fine_tune_at=100, feature_dim=1280,
                              layer_index=mobilenet.KERAS_LAYER_INDEX),
    "densenet201": ModelSpec(densenet.densenet201, densenet.head_only_mask,
                             densenet.fine_tune_mask,
                             default_fine_tune_at=150, feature_dim=1920,
                             layer_index=densenet.KERAS_LAYER_INDEX),
    "small_cnn": ModelSpec(
        lambda num_outputs=1, in_channels=3: small_cnn_mod.small_cnn(
            10, in_channels, num_outputs),
        _always_trainable, _always_trainable,
        default_fine_tune_at=0, feature_dim=8),
}


def get_model(name: str) -> ModelSpec:
    if name not in REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(REGISTRY)}")
    return REGISTRY[name]
