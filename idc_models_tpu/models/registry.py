"""Model registry: name -> (builder, head-only mask, fine-tune mask,
partition rules).

Gives the CLI/configs one lookup for the reference's model zoo
(keras.applications in the reference; SURVEY.md C5/C6), and — since the
rule-based sharding layer (partition.py, ISSUE 15) — each model's
DEFAULT partition-rule set: the regex->PartitionSpec policy train,
federated, and serve all resolve placement through.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable

from jax.sharding import PartitionSpec as P

from idc_models_tpu import mesh as meshlib, partition
from idc_models_tpu.models import densenet, mobilenet, small_cnn as small_cnn_mod, vgg
from idc_models_tpu.models.core import Module

# The classifier zoo replicates by default — DP alone is fastest at the
# reference's 50x50 scale (tp.py docstring), and replicated rules are
# bit-compatible with the pre-rules layout.
REPLICATED_RULES = partition.PartitionRules.replicated()

_D, _M = meshlib.DATA_AXIS, meshlib.MODEL_AXIS

# The decoder-only LM (models/lm.py attention_lm): FSDP over "data"
# (params AND the rmsprop moments mirroring them — re.search matches
# the nu/... suffix paths), tensor parallelism over "model" in the
# Megatron orientation (qkv/fc1/head column-parallel, wo/fc2
# row-parallel), biases riding their kernel's output sharding. On a
# mesh without one of the axes the rules degrade to the other; on a
# seq-only serve mesh they degrade to replicated. Order matters: first
# match wins, the catch-all replicates LN scales/biases and the rest.
# docs/SHARDING.md walks every rule.
_LM_RULE_PAIRS = (
    (r"mha/w[qkv]$", P(_D, _M)),       # [E, E] column-parallel
    (r"mha/wo$", P(_M, _D)),           # [E, E] row-parallel
    (r"fc1/kernel$", P(_D, _M)),       # [E, mlp] column-parallel
    (r"fc1/bias$", P(_M)),             # [mlp] rides fc1's out shard
    (r"fc2/kernel$", P(_M, _D)),       # [mlp, E] row-parallel
    (r"head/kernel$", P(_D, _M)),      # [E, vocab] column-parallel
    (r"head/bias$", P(_M)),            # [vocab] rides the head shard
    (r"embed$", P(None, _D)),          # [vocab, E] FSDP on E
    (r"pos$", P(None, _D)),            # [T, E] FSDP on E
    (r".*", P()),                      # LN scale/bias, bo, fc2/bias,
    #                                    step counter: replicated
)
LM_RULES = partition.PartitionRules(_LM_RULE_PAIRS)

# The learned drafter (models/draft_lm.py) is a scaled-down
# attention_lm — same param-tree schema — so the same regex policy
# applies verbatim. It still gets its OWN named rule set: the drafter's
# placement is tuned independently of the target's (a 2-block student
# rarely wants the target's TP split; swapping its rules must not
# perturb the target), and serve/engine.py + the draft-LM checkpoint
# path resolve through this name.
DRAFT_LM_RULES = partition.PartitionRules(_LM_RULE_PAIRS)

# name -> default rule set; "lm" serves attention_lm trees (train AND
# serve resolve through it), "draft_lm" the learned drafter,
# classifier names alias their ModelSpec's rules so both lookups agree.
PARTITION_RULES: dict[str, partition.PartitionRules] = {
    "replicated": REPLICATED_RULES,
    "lm": LM_RULES,
    "draft_lm": DRAFT_LM_RULES,
}


def get_partition_rules(name: str) -> partition.PartitionRules:
    """Default partition rules for a registered model (or the "lm" /
    "replicated" rule-set names)."""
    if name in PARTITION_RULES:
        return PARTITION_RULES[name]
    if name in REGISTRY:
        return REGISTRY[name].partition_rules
    raise KeyError(
        f"no partition rules for {name!r}; have "
        f"{sorted(set(PARTITION_RULES) | set(REGISTRY))}")


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    build: Callable[..., Module]          # (num_outputs, in_channels) -> Module
    head_only_mask: Callable              # params -> bool pytree
    fine_tune_mask: Callable              # (params, fine_tune_at) -> bool pytree
    default_fine_tune_at: int
    feature_dim: int
    # Keras layer index per parameterized backbone layer (the zoo's
    # KERAS_LAYER_INDEX); consumers: fine-tune boundary lookups such as
    # the frozen-prefix feature cache. None for models without one.
    layer_index: dict[str, int] | None = None
    # the model's default regex->PartitionSpec policy (partition.py);
    # replicated for the zoo — see LM_RULES for a sharded example
    partition_rules: partition.PartitionRules = REPLICATED_RULES


def _always_trainable(params, fine_tune_at=0):
    import jax

    return jax.tree.map(lambda _: True, params)


REGISTRY: dict[str, ModelSpec] = {
    "vgg16": ModelSpec(vgg.vgg16, vgg.head_only_mask, vgg.fine_tune_mask,
                       default_fine_tune_at=15, feature_dim=512,
                       layer_index=vgg.KERAS_LAYER_INDEX),
    "mobilenet_v2": ModelSpec(mobilenet.mobilenet_v2,
                              mobilenet.head_only_mask,
                              mobilenet.fine_tune_mask,
                              default_fine_tune_at=100, feature_dim=1280,
                              layer_index=mobilenet.KERAS_LAYER_INDEX),
    "densenet201": ModelSpec(densenet.densenet201, densenet.head_only_mask,
                             densenet.fine_tune_mask,
                             default_fine_tune_at=150, feature_dim=1920,
                             layer_index=densenet.KERAS_LAYER_INDEX),
    "small_cnn": ModelSpec(
        lambda num_outputs=1, in_channels=3: small_cnn_mod.small_cnn(
            10, in_channels, num_outputs),
        _always_trainable, _always_trainable,
        default_fine_tune_at=0, feature_dim=8),
}


def get_model(name: str) -> ModelSpec:
    if name not in REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(REGISTRY)}")
    return REGISTRY[name]


# ISSUE 16: the one place defining what "fused backbone" means per
# model, so bench.py (bench_backbone_fused), the profile verb, and
# experiments/fused_backbone.py build the same variants. For
# mobilenet the fused Pallas depthwise chain is OPT-IN (default
# "grouped" until the TPU perf gate holds — ISSUE 16 acceptance);
# for densenet the concat-free packed blocks ARE the default (parity
# is bit-exact, pinned on CPU), so its "unfused" baseline opts back
# into the concat reference.
FUSED_BUILD_KWARGS: dict[str, dict] = {
    "mobilenet_v2": {"depthwise_impl": "fused"},
    "densenet201": {"block_impl": "packed"},
}
UNFUSED_BUILD_KWARGS: dict[str, dict] = {
    "mobilenet_v2": {"depthwise_impl": "grouped"},
    "densenet201": {"block_impl": "concat"},
}
