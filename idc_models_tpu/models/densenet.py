"""DenseNet201 backbone + transfer-learning head.

Capability parity with the reference's dense preset
(dist_model_tf_dense.py:131-141): DenseNet201 without top, GAP, Dense(10)
softmax-logits head for CIFAR-10, fine_tune_at=150
(dist_model_tf_dense.py:158).

Architecture follows keras.applications DenseNet201: stem conv(64,7x7,s2)
-> maxpool -> dense blocks [6,12,48,32] (growth 32; each layer is
BN-ReLU-conv1x1(128) -> BN-ReLU-conv3x3(32) -> concat) with 0.5-compression
transitions, final BN+ReLU. All convs bias-free; BN eps=1.001e-5. Total
params (incl. BN moving stats) = 18,321,984, matching Keras
include_top=False.

Dense blocks are CONCAT-FREE by default (`block_impl="packed"`, ISSUE
16): the literal `concat(h, f(h))` re-reads and re-writes the whole
growing feature map at every layer — the PR 14 MFU attribution measured
2.3 GB moved for 4.7 GFLOP, arithmetic intensity 2.0 against the v5e
ridge of ~240 — so instead the block's full [N, H, W, C_final] buffer
is allocated ONCE at the block's first layer and each layer
`dynamic_update_slice`s its 32-channel output into the next free
channel range, reading its input as a static slice of the buffer.
Channel layout ([input, y_1, y_2, ...]) is exactly the iterated-concat
layout, and every conv/BN sees bit-identical inputs, so pretrained
weight loading, golden outputs, and param counts are unchanged —
pinned by tests/test_fused_conv.py against `block_impl="concat"`, the
reference implementation kept for that parity test (and allowlisted as
such by the test_static_robustness concat ban).

`KERAS_LAYER_INDEX` reproduces Keras' flat layer numbering so the
reference's `fine_tune_at=150` (an index into `base_model.layers`, landing
inside conv4_block2) selects the same parameters here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from idc_models_tpu.models import core

_BLOCKS = [6, 12, 48, 32]
_GROWTH = 32
_BN = dict(eps=1.001e-5, momentum=0.99)

KERAS_LAYER_INDEX: dict[str, int] = {}


def _build_index():
    i = 0
    idx = {}

    def layer(name=None):
        nonlocal i
        if name is not None:
            idx[name] = i
        i += 1

    layer()                       # InputLayer
    layer()                       # ZeroPadding2D
    layer("conv1_conv")
    layer("conv1_bn")
    layer()                       # conv1_relu
    layer()                       # ZeroPadding2D
    layer()                       # pool1
    for stage, n_layers in enumerate(_BLOCKS, start=2):
        for l in range(1, n_layers + 1):
            p = f"conv{stage}_block{l}"
            layer(f"{p}_0_bn")
            layer()               # 0_relu
            layer(f"{p}_1_conv")
            layer(f"{p}_1_bn")
            layer()               # 1_relu
            layer(f"{p}_2_conv")
            layer()               # concat
        if stage < 5:
            layer(f"pool{stage}_bn")
            layer()               # pool relu
            layer(f"pool{stage}_conv")
            layer()               # avgpool
    layer("bn")
    layer()                       # relu
    return idx


KERAS_LAYER_INDEX = _build_index()


FREEZE_ALL = 10**9


def _units(in_channels: int, bn_frozen_below: int,
           block_impl: str = "packed"):
    """The backbone as topology units (stem, one unit per dense layer,
    one per transition, final BN) over the flat Keras-layer-name params:
    a dense layer is `h -> concat(h, f(h))` semantically — a pure
    function of its input — so every unit edge is a valid split point
    for the frozen-backbone feature cache despite the dense topology.
    Module-level (like mobilenet._units) so per-stage attribution
    microbenches (experiments/backbone_mfu.py) can build stage
    sub-models from unit ranges.

    `block_impl` picks the dense-block data movement, same values
    either way:

    - "packed" (default): the block's [N, H, W, C_final] buffer is
      allocated once at the block's first layer; each layer reads the
      static slice [:, :, :, :c_in] and dynamic_update_slices its
      32-channel output at c_in. Between the block's unit edges the
      activation carries C_final channels with the not-yet-written
      tail zero-filled — downstream layers never read it, and by the
      last layer the buffer is exactly full, so transitions and split
      points see the ordinary fully-valid tensor. (A mid-block split
      caches the partially-filled buffer; prefix-then-suffix
      composition stays bit-exact since each layer touches only its
      static channel ranges.)
    - "concat": the literal `concat(h, f(h))` — the parity reference
      the packed path is pinned bit-close against
      (tests/test_fused_conv.py) and the bench_backbone_fused
      baseline. Not for production use: it re-materializes the whole
      growing feature map every layer.
    """
    if block_impl not in ("packed", "concat"):
        raise ValueError(
            f"block_impl must be packed|concat, got {block_impl!r}")
    specs: list[tuple[str, core.Module]] = []

    def reg(m) -> str:
        specs.append((m.name, m))
        return m.name

    def bn(c, name):
        frozen = KERAS_LAYER_INDEX[name] < bn_frozen_below
        return core.batch_norm(c, name=name, frozen=frozen, **_BN)

    units: list[tuple[list[str], object]] = []

    # Keras stem: ZeroPadding2D((3,3)) + valid 7x7/2 conv, then
    # ZeroPadding2D((1,1)) + valid 3x3/2 pool — symmetric padding, which
    # lax SAME (lo<=hi asymmetric) would shift by one pixel.
    stem_names = [
        reg(core.conv2d(in_channels, 64, 7, stride=2, use_bias=False,
                        padding=((3, 3), (3, 3)), name="conv1_conv")),
        reg(bn(64, "conv1_bn")),
    ]

    def stem(run, x):
        h = jax.nn.relu(run("conv1_bn", run("conv1_conv", x)))
        return jax.lax.reduce_window(h, -jnp.inf, jax.lax.max,
                                     (1, 3, 3, 1), (1, 2, 2, 1),
                                     [(0, 0), (1, 1), (1, 1), (0, 0)])

    units.append((stem_names, stem))

    def bottleneck(run, x, *, p):
        """One dense layer's BN-relu-conv1x1-BN-relu-conv3x3 trunk —
        shared by both block impls; they differ only in how its
        32-channel output joins the feature map."""
        y = jax.nn.relu(run(f"{p}_0_bn", x))
        y = run(f"{p}_1_conv", y)
        y = jax.nn.relu(run(f"{p}_1_bn", y))
        return run(f"{p}_2_conv", y)

    c = 64
    for stage, n_layers in enumerate(_BLOCKS, start=2):
        for l in range(1, n_layers + 1):
            p = f"conv{stage}_block{l}"
            names = [
                reg(bn(c + (l - 1) * _GROWTH, f"{p}_0_bn")),
                reg(core.conv2d(c + (l - 1) * _GROWTH, 4 * _GROWTH, 1,
                                use_bias=False, name=f"{p}_1_conv")),
                reg(bn(4 * _GROWTH, f"{p}_1_bn")),
                reg(core.conv2d(4 * _GROWTH, _GROWTH, 3, use_bias=False,
                                name=f"{p}_2_conv")),
            ]

            def dense_layer_packed(run, h, *, p=p,
                                   c_in=c + (l - 1) * _GROWTH,
                                   c_final=c + n_layers * _GROWTH,
                                   first=(l == 1)):
                # all channel offsets are static, so reads/writes lower
                # to in-place slices instead of whole-map concat copies
                if first:
                    buf = jnp.zeros(h.shape[:3] + (c_final,), h.dtype)
                    buf = jax.lax.dynamic_update_slice_in_dim(
                        buf, h, 0, axis=3)
                else:
                    buf = h
                y = bottleneck(
                    run, jax.lax.slice_in_dim(buf, 0, c_in, axis=3), p=p)
                return jax.lax.dynamic_update_slice_in_dim(
                    buf, y.astype(buf.dtype), c_in, axis=3)

            def dense_layer_concat(run, h, *, p=p):
                # parity reference ONLY (test_static_robustness bans
                # concatenate in this file outside this function)
                return jnp.concatenate([h, bottleneck(run, h, p=p)],
                                       axis=-1)

            units.append((names, dense_layer_packed
                          if block_impl == "packed"
                          else dense_layer_concat))
        c = c + n_layers * _GROWTH
        if stage < 5:
            names = [
                reg(bn(c, f"pool{stage}_bn")),
                reg(core.conv2d(c, c // 2, 1, use_bias=False,
                                name=f"pool{stage}_conv")),
            ]

            def transition(run, h, *, stage=stage):
                h = jax.nn.relu(run(f"pool{stage}_bn", h))
                h = run(f"pool{stage}_conv", h)
                return jax.lax.reduce_window(h, 0.0, jax.lax.add,
                                             (1, 2, 2, 1), (1, 2, 2, 1),
                                             "VALID") / 4.0

            units.append((names, transition))
            c = c // 2
    units.append(([reg(bn(c, "bn"))],
                  lambda run, h: jax.nn.relu(run("bn", h))))
    return units, dict(specs)


def densenet201_backbone(in_channels: int = 3, *,
                         bn_frozen_below: int = 0,
                         block_impl: str = "packed") -> core.Module:
    """`bn_frozen_below`: BN layers with Keras index < this run in
    permanent inference mode (Keras trainable=False semantics).
    `block_impl`: dense-block data movement — "packed" (concat-free
    default) or "concat" (the parity-reference copy chain); see
    `_units`."""
    units, modules = _units(in_channels, bn_frozen_below, block_impl)
    # layer_names in Keras creation order (see mobilenet.py) so secure
    # percent-selection keeps get_weights() order for this backbone
    sec = core.unit_backbone(units, modules, "densenet201",
                             KERAS_LAYER_INDEX)
    assert sec.layer_names == tuple(KERAS_LAYER_INDEX)
    return sec


DENSENET201_FEATURES = 1920


def densenet201(num_outputs: int = 10, in_channels: int = 3, *,
                bn_frozen_below: int = 0,
                block_impl: str = "packed") -> core.Module:
    backbone = densenet201_backbone(in_channels,
                                    bn_frozen_below=bn_frozen_below,
                                    block_impl=block_impl)
    return core.classifier(backbone, DENSENET201_FEATURES, num_outputs,
                           name="densenet201_classifier")


head_only_mask = core.head_only_mask


def fine_tune_mask(params, fine_tune_at: int = 150):
    return core.keras_fine_tune_mask(params, KERAS_LAYER_INDEX, fine_tune_at)
