"""Pretrained-weight import: Keras h5 / npz checkpoints -> explicit pytrees.

The reference downloads ImageNet weights through keras.applications at
runtime (dist_model_tf_vgg.py:119). This environment has no network egress,
so the framework takes weights from local artifacts instead:

- ``load_npz`` / ``save_npz``: the framework's own flat "path/to/leaf" npz
  pytree format (also used by unit tests and the offline conversion).
- ``load_keras_h5``: one-time offline conversion from a Keras
  `.h5` weights file (as produced by `model.save_weights`), mapping Keras
  layer names onto this package's identical param-group names. Conv kernels
  are already HWIO in Keras h5, so no transposition is needed; only
  depthwise kernels need their (kh, kw, in, 1) -> (kh, kw, 1, in) swap.

If no weight file is available, models start from the standard random
initialization and `maybe_load_pretrained` says so — capability parity
degrades gracefully rather than failing.
"""

from __future__ import annotations

import warnings
from pathlib import Path

import jax
import numpy as np


def _flatten(tree, prefix=()):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, prefix + (k,)))
    else:
        out["/".join(prefix)] = tree
    return out


def _unflatten(flat: dict[str, np.ndarray]):
    tree: dict = {}
    for path, v in flat.items():
        node = tree
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def save_npz(path: str | Path, tree) -> None:
    flat = {k: np.asarray(v) for k, v in _flatten(tree).items()}
    np.savez(path, **flat)


def load_npz(path: str | Path):
    with np.load(path) as z:
        return _unflatten({k: z[k] for k in z.files})


def merge_pretrained(params, loaded, *, strict: bool = False):
    """Graft `loaded` leaves onto `params` where paths+shapes match.

    Returns (merged, n_loaded, mismatches). With strict=True any shape
    mismatch or missing path raises.
    """
    flat_p = _flatten(params)
    flat_l = _flatten(loaded)
    merged = dict(flat_p)
    mismatches = []
    n = 0
    for k, v in flat_l.items():
        if k not in flat_p:
            mismatches.append(f"unexpected: {k}")
            continue
        if tuple(np.shape(v)) != tuple(np.shape(flat_p[k])):
            mismatches.append(
                f"shape {k}: {np.shape(v)} vs {np.shape(flat_p[k])}")
            continue
        merged[k] = np.asarray(v, dtype=np.asarray(flat_p[k]).dtype)
        n += 1
    if strict and (mismatches or n < len(flat_p)):
        raise ValueError(f"pretrained merge failed: {mismatches[:10]}, "
                         f"loaded {n}/{len(flat_p)}")
    return _unflatten(merged), n, mismatches


_KERAS_SUFFIX = {
    "kernel:0": "kernel",
    # Keras DepthwiseConv2D names its variable depthwise_kernel:0 (the
    # real keras.applications MobileNetV2 h5 layout), stored (kh, kw, C, 1)
    "depthwise_kernel:0": "kernel",
    "bias:0": "bias",
    "gamma:0": "scale", "beta:0": "bias",
    "moving_mean:0": "mean", "moving_variance:0": "var",
}


def load_keras_h5(path: str | Path):
    """Read a Keras `save_weights` h5 into (params_flat, state_flat) trees
    keyed by Keras layer name — the same names this package's backbones use."""
    import h5py  # optional; only needed for offline conversion

    params: dict = {}
    state: dict = {}
    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        for layer in root:
            g = root[layer]
            for w in g.attrs.get("weight_names", []):
                name = w.decode() if isinstance(w, bytes) else w
                arr = np.asarray(g[name])
                suffix = name.split("/")[-1]
                key = _KERAS_SUFFIX.get(suffix)
                if key is None:
                    continue
                layer_name = name.split("/")[-2]
                if key == "kernel" and (suffix == "depthwise_kernel:0"
                                        or "depthwise" in layer_name):
                    arr = np.transpose(arr, (0, 1, 3, 2))
                dest = state if suffix.startswith("moving") else params
                dest.setdefault(layer_name, {})[key] = arr
    return params, state


def load_pretrained_file(path: str | Path):
    """Load a weight artifact -> (params_tree, state_tree).

    ``.h5``/``.hdf5`` are read as Keras `save_weights` files (the layout
    keras.applications downloads for weights='imagenet',
    dist_model_tf_vgg.py:119); anything else is the framework's flat npz
    pytree, either params-only or the {"params": ..., "state": ...}
    wrapper written by `convert-weights`.
    """
    p = Path(path)
    if p.suffix.lower() in (".h5", ".hdf5"):
        return load_keras_h5(p)
    loaded = load_npz(p)
    if loaded and set(loaded) <= {"params", "state"}:
        return loaded.get("params", {}), loaded.get("state", {})
    return loaded, {}


def maybe_load_pretrained(params, weights_path: str | Path | None, *,
                          state=None, subtree: str = "backbone"):
    """Merge a weight artifact into `params[subtree]` (and, for BN-bearing
    backbones, moving stats into `state[subtree]`) if it exists.

    Accepts .npz (framework format) or Keras .h5. Returns
    ``(params, state)`` possibly updated; warns (not fails) when the
    artifact is absent — the no-egress analogue of the reference's
    weights='imagenet' download.
    """
    if weights_path is None:
        return params, state
    p = Path(weights_path)
    if not p.exists():
        warnings.warn(f"pretrained weights {p} not found; using random "
                      f"initialization", stacklevel=2)
        return params, state
    loaded_p, loaded_s = load_pretrained_file(p)

    def graft(tree, loaded, what):
        if tree is None or not loaded:
            return tree, 0
        target = tree[subtree] if subtree else tree
        merged, n, mis = merge_pretrained(target, loaded)
        if mis:
            warnings.warn(f"pretrained {what} merge: {len(mis)} mismatches "
                          f"(first: {mis[:3]})", stacklevel=2)
        if not subtree:
            return merged, n
        out = dict(tree)
        out[subtree] = merged
        return out, n

    params, n_p = graft(params, loaded_p, "params")
    state, n_s = graft(state, loaded_s, "state")
    if n_p + n_s == 0:
        warnings.warn(f"pretrained weights {p}: no tensors matched — "
                      f"continuing from random initialization", stacklevel=2)
    else:
        print(f"loaded pretrained weights from {p} "
              f"({n_p} param tensors, {n_s} state tensors)")
    return params, state
