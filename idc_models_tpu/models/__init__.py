from idc_models_tpu.models import core  # noqa: F401
from idc_models_tpu.models.small_cnn import small_cnn  # noqa: F401
