from idc_models_tpu.models import (  # noqa: F401
    attention, core, densenet, mobilenet, registry, vgg,
)
from idc_models_tpu.models.attention import attention_classifier  # noqa: F401
from idc_models_tpu.models.densenet import densenet201  # noqa: F401
from idc_models_tpu.models.mobilenet import mobilenet_v2  # noqa: F401
from idc_models_tpu.models.registry import REGISTRY, get_model  # noqa: F401
from idc_models_tpu.models.small_cnn import small_cnn  # noqa: F401
from idc_models_tpu.models.vgg import vgg16  # noqa: F401
