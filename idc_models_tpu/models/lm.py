"""Causal language model over the ring: train long contexts, then SERVE
them — the model-level composition of `ring_attention` (training) and
`ring_decode` (KV-cache inference) sharing one parameter tree.

The reference has no sequence models at all (its models are the CNN
backbones, SURVEY.md §3.5), so this is beyond-parity: it exists to
close the loop the round-5 pieces opened. `attention_lm` is the
smallest honest decoder-only LM — token embedding + learned positions,
the SAME pre-LN ring-attention blocks as the classifier
(`models/attention.py::transformer_block`), final LN, per-position
vocab head — and the serving side drives the SAME parameters:
`make_lm_decoder` exposes single-token KV-cache steps (per block,
project this token's q/k/v, fold against the block's ring-sharded
cache (`ring_decode`), residual + MLP — exactly the block forward
restricted to one position) plus a ring prefill, and `Generator` is
the compiled serving object: one ring-sharded prefill dispatch over
the prompt (O(P/n) per device, same `make_ring_attention` as
training) and ONE fused `lax.scan` dispatch emitting all requested
tokens with the caches donated through the loop — compiled once per
decode configuration, process-wide, zero recompilation on reuse.

Incremental == full: teacher-forcing the decoder over a sequence
reproduces the training-path logits at every position to fp tolerance
(tests/test_lm.py gates it on the 2-D mesh, non-power-of-2 rings, and
both block engines' training weights). Because the zigzag layout is an
internal training-schedule permutation that does not change the
function (gated in test_zigzag.py), weights trained under
``layout="zigzag"`` decode identically through this (natural-order)
path — layout is a training knob, not a serving constraint.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models import core
from idc_models_tpu.models.attention import _seq_pin, transformer_block
from idc_models_tpu.observe import trace
from idc_models_tpu.ring_decode import (
    cache_sharding, init_cache, make_chunk_ring_decode, make_ring_decode,
)


def attention_lm(vocab_size: int, seq_len: int, *,
                 embed_dim: int = 64, num_heads: int = 4,
                 mlp_dim: int = 128, num_blocks: int = 2,
                 mesh: Mesh | None = None,
                 block_impl: str = "jnp",
                 layout: str = "contiguous",
                 dropout_rate: float = 0.0,
                 remat: bool = False) -> core.Module:
    """Decoder-only LM: int32 tokens [B, T] -> logits [B, T, vocab].

    Causal by construction; `layout`/`block_impl`/`remat`/`mesh` behave
    exactly as on `attention_classifier` (the blocks are shared). The
    zigzag permutation, when used, moves the TOKEN ids and positions
    before embedding (per-position embed commutes with it) and the
    output logits are permuted back — training-path logits are always
    in natural order, so the loss/labels need no layout awareness."""
    from idc_models_tpu.ring_attention import from_zigzag, to_zigzag

    blocks = [transformer_block(embed_dim, num_heads, mlp_dim, mesh=mesh,
                                causal=True, block_impl=block_impl,
                                layout=layout,
                                dropout_rate=dropout_rate,
                                name=f"block{i}")
              for i in range(num_blocks)]
    ln_f = core.layer_norm(embed_dim, name="ln_f")
    head = core.dense(embed_dim, vocab_size, name="head")
    n_ring = mesh.shape[meshlib.SEQ_AXIS] if mesh is not None else 1
    zig = layout == "zigzag"
    pin = _seq_pin(mesh)

    def init(rng):
        rngs = jax.random.split(rng, num_blocks + 4)
        params = {
            "embed": 0.02 * jax.random.normal(
                rngs[0], (vocab_size, embed_dim)),
            "pos": 0.02 * jax.random.normal(rngs[1],
                                            (seq_len, embed_dim)),
        }
        for i, (blk, r) in enumerate(zip(blocks, rngs[2:2 + num_blocks])):
            params[f"block{i}"] = blk.init(r).params
        params["ln_f"] = ln_f.init(rngs[-2]).params
        params["head"] = head.init(rngs[-1]).params
        return core.Variables(params, {})

    def apply(params, state, tokens, *, train=False, rng=None):
        # the shared train step casts inputs to its compute dtype;
        # token ids must come back to int before the table gather
        tokens = tokens.astype(jnp.int32)
        pos = params["pos"]
        if zig:
            tokens = to_zigzag(tokens, n_ring)
            pos = to_zigzag(pos[None], n_ring)[0]
        h = jnp.take(params["embed"], tokens, axis=0) + pos
        h = pin(h)
        rngs = (jax.random.split(rng, num_blocks) if rng is not None
                else [None] * num_blocks)
        for i, blk in enumerate(blocks):
            def run_block(p, h, _blk=blk, _r=rngs[i]):
                return _blk.apply(p, {}, h, train=train, rng=_r)[0]

            if remat:
                run_block = jax.checkpoint(run_block)
            h = pin(run_block(params[f"block{i}"], h))
        h, _ = ln_f.apply(params["ln_f"], {}, h, train=train)
        logits, _ = head.apply(params["head"], {}, h, train=train)
        if zig:
            logits = from_zigzag(logits, n_ring)
        return logits, state

    names = (("embed", "pos")
             + tuple(f"block{i}" for i in range(num_blocks))
             + ("ln_f", "head"))
    return core.Module(init, apply, "attention_lm", layer_names=names,
                       children=tuple((f"block{i}", b)
                                      for i, b in enumerate(blocks)))


def next_token_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean cross-entropy of logits[:, :-1] predicting tokens[:, 1:] —
    the standard shifted LM objective, usable as the train step's
    loss_fn with the raw token batch as labels."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
    tgt = tokens[:, 1:]
    ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


class _ServeConfig(NamedTuple):
    """Everything that shapes the compiled serving programs — and
    NOTHING that doesn't (parameters are explicit arguments, prompt
    length and step count are jit shape keys). Hashable, so one config
    maps to one compiled program set for the life of the process."""
    mesh: Mesh
    embed_dim: int
    num_heads: int
    num_blocks: int
    t_max: int
    cache_dtype: object          # np.dtype (normalized, hashable)
    block_impl: str
    temperature: float
    top_k: int | None


def _place_params(params, mesh, rules=None):
    """Bind a parameter tree to the SERVING mesh: replicated by
    default, or under partition `rules` (regex->PartitionSpec,
    models/registry.py) — the tensor-parallel serving path, where
    params shard over "model" while activations and the KV ring keep
    their own (independent) axes.

    Host (numpy) trees are fine to pass in — e.g. a checkpoint straight
    from device_get/restore — and so are device trees living on a
    DIFFERENT topology (a training state replicated over the full pod,
    served on a sub-mesh): the serving programs pin activations to the
    serving mesh, so the parameters must live there too, not wherever
    training left them."""
    if rules is not None:
        # raw leaves straight into their SHARDED placements — an
        # asarray pass first would commit every param whole to one
        # device, transiently needing the replicated footprint the
        # rules path exists to avoid (put_with_sharding takes host
        # arrays directly)
        from idc_models_tpu import partition

        return partition.shard_tree(mesh, rules, params)
    sh = meshlib.replicated(mesh)
    return jax.tree.map(
        lambda a: meshlib.put_with_sharding(jnp.asarray(a), sh), params)


class _ServeFns(NamedTuple):
    init_caches: object
    step: object          # (params, caches, tok, pos) -> (logits, caches)
    prefill: object       # (params, tokens) -> (logits, caches)
    decode_loop: object   # (params, caches, logits, rng, offsets)
    #                       -> (tokens, logits, caches)
    prefill_chunk: object  # (params, caches, tokens, start, p_end)
    #                        -> (logits, caches)


def _serve_config(params, *, embed_dim, num_heads, num_blocks, t_max,
                  mesh, cache_dtype, block_impl="jnp",
                  temperature=0.0, top_k=None) -> _ServeConfig:
    if embed_dim % num_heads:
        raise ValueError(f"embed_dim {embed_dim} not divisible by "
                         f"num_heads {num_heads}")
    if params["pos"].shape[0] < t_max:
        raise ValueError(
            f"cache t_max {t_max} exceeds the trained position table "
            f"({params['pos'].shape[0]}) — positions past it have no "
            f"embedding")
    mesh = mesh if mesh is not None else meshlib.seq_mesh(1)
    n = mesh.shape[meshlib.SEQ_AXIS]
    if t_max % n:
        raise ValueError(f"t_max {t_max} not divisible by the ring size "
                         f"{n} over mesh axis {meshlib.SEQ_AXIS!r}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    return _ServeConfig(mesh, embed_dim, num_heads, num_blocks, t_max,
                        jnp.dtype(cache_dtype), block_impl,
                        float(temperature), top_k)


def _check_prompt(tokens, t_max: int):
    """The one prompt contract for every prefill entry point: non-empty
    int32 [B, P] with P <= t_max."""
    tokens = jnp.asarray(tokens, jnp.int32)
    if tokens.ndim != 2 or tokens.shape[1] < 1:
        raise ValueError(f"prefill expects non-empty [B, P] tokens, "
                         f"got shape {tokens.shape}")
    if tokens.shape[1] > t_max:
        raise ValueError(f"prompt length {tokens.shape[1]} exceeds "
                         f"t_max {t_max}")
    return tokens


def prefill_bucket(p_len: int, t_max: int, n_ring: int) -> int:
    """The padded prompt length the prefill program actually runs at:
    the smallest `n_ring * 2**k` >= p_len, capped at t_max.

    Prompt length is a jit SHAPE key — an engine admitting arbitrary
    user prompt lengths would otherwise compile a fresh prefill per
    length. Bucketing maps every length onto O(log(t_max)) compiled
    shapes, and because the true length rides through the program as a
    TRACED scalar (see `_serving_fns`), two prompts in the same bucket
    share one executable bit-for-bit."""
    if not 1 <= p_len <= t_max:
        raise ValueError(f"prompt length {p_len} outside [1, {t_max}]")
    b = n_ring
    while b < p_len:
        b *= 2
    return min(b, t_max)


def prefill_buckets(t_max: int, n_ring: int) -> tuple[int, ...]:
    """Every bucket `prefill_bucket` can return — the complete compile
    set a serving engine warms up (O(log(t_max / n_ring)) shapes)."""
    out, b = [], n_ring
    while b < t_max:
        out.append(b)
        b *= 2
    out.append(t_max)
    return tuple(out)


def check_prefill_chunk(chunk: int, t_max: int) -> int:
    """The one chunk-length contract: chunks tile the cache exactly, so
    chunk k always starts at k*chunk and never hangs past t_max (the
    ragged FINAL chunk is handled by the traced true length, not by a
    different shape — one compiled chunk program serves every prompt)."""
    chunk = int(chunk)
    if not 1 <= chunk <= t_max:
        raise ValueError(f"prefill_chunk {chunk} outside [1, {t_max}]")
    if t_max % chunk:
        raise ValueError(f"prefill_chunk {chunk} must divide t_max "
                         f"{t_max} so chunk boundaries tile the cache")
    return chunk


def _pad_prompt(tokens, t_max: int, n_ring: int):
    """[B, P] -> ([B, bucket] zero-padded, true length P). Pad tokens
    embed position >= P but are masked out of the cache and, causally,
    cannot influence any real position's logits."""
    p_len = tokens.shape[1]
    bucket = prefill_bucket(p_len, t_max, n_ring)
    if bucket != p_len:
        tokens = jnp.pad(tokens, ((0, 0), (0, bucket - p_len)))
    return tokens, p_len


def _make_pick(cfg: _ServeConfig):
    """The sampling rule for one decode config: greedy argmax at
    temperature 0, else temperature softmax optionally restricted to the
    top_k most likely tokens. Module-level so the serving ENGINE
    (serve/engine.py) applies the exact same math per slot — bit parity
    with a serial `Generator` hinges on sharing this definition."""
    def pick(logits, key):
        lg = logits.astype(jnp.float32)
        if cfg.top_k is not None and cfg.top_k < lg.shape[-1]:
            kth = jax.lax.top_k(lg, cfg.top_k)[0][:, -1]
            lg = jnp.where(lg >= kth[:, None], lg, -jnp.inf)
        if cfg.temperature == 0.0:
            return jnp.argmax(lg, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, lg / cfg.temperature,
                                      axis=-1).astype(jnp.int32)

    return pick


def _project_qkv(cfg: _ServeConfig, ln, p, h, seq_shape: tuple):
    """Pre-LN q/k/v projection of one block — THE single definition
    shared by the one-token decode forward (seq_shape=(1,)), the chunk
    prefill (seq_shape=(C,)), and the monolithic ring prefill
    (seq_shape=(P',)). A dtype/bias/reshape fix lands in every path at
    once or not at all — the bit-parity contracts between them hinge on
    this sharing."""
    b = h.shape[0]
    head_dim = cfg.embed_dim // cfg.num_heads
    a, _ = ln.apply(p["ln1"], {}, h)
    split = lambda y: y.reshape(b, *seq_shape, cfg.num_heads, head_dim)
    q = split(a @ p["mha"]["wq"].astype(a.dtype))
    k = split(a @ p["mha"]["wk"].astype(a.dtype))
    v = split(a @ p["mha"]["wv"].astype(a.dtype))
    return q, k, v


def _attn_residual(p, h, o):
    """Out-projection + residual, one definition for every path."""
    return h + (o @ p["mha"]["wo"].astype(o.dtype)
                + p["mha"]["bo"].astype(o.dtype))


def _mlp_residual(ln, p, h):
    """Pre-LN MLP + residual, one definition for every path."""
    a, _ = ln.apply(p["ln2"], {}, h)
    m = jax.nn.gelu(a @ p["fc1"]["kernel"] + p["fc1"]["bias"])
    return h + (m @ p["fc2"]["kernel"] + p["fc2"]["bias"])


def _final_logits(ln, params, h):
    """Final LN + vocab head, one definition for every path."""
    h, _ = ln.apply(params["ln_f"], {}, h)
    return h @ params["head"]["kernel"] + params["head"]["bias"]


def make_adapter_head_hook(u, v, tslot):
    """The per-tenant ADAPTER-DELTA forward hook (serve/tenancy.py) —
    the one definition the fused window AND verify programs apply at
    sampling time.

    `u [T, V, r]` / `v [T, r, V]` stack every tenant's low-rank
    logit-space adapter factors; `tslot [S]` (int32, traced VALUES not
    shapes — tenant arrival patterns compile nothing) names each
    slot's tenant. The returned hook maps base logits to effective
    pick logits:

        eff[s] = logits[s] + (logits[s] @ u[tslot[s]]) @ v[tslot[s]]

    i.e. an effective head ``W (I + U_t V_t)`` per tenant. Because the
    delta is a pure function of the BASE logits, all stored state —
    prefill outputs, the engine's per-slot logits rows, prefix-cache
    boundary snapshots — stays tenant-agnostic and shareable; only
    the token PICK sees the tenant's head. Adapter-less tenants hold
    zero rows, so their delta is exactly zero and they decode the
    base model through the same gathered program. Accepts logits of
    shape [S, V] (the window's per-step rows) or [S, K+1, V] (the
    verify's candidate distributions) — the gather broadcasts over
    any middle axes. An adapter that must touch attention/MLP
    projections cannot take this form; that is the full-checkpoint-
    per-tenant boundary (docs/MULTITENANCY.md)."""
    ug = jnp.take(u, tslot, axis=0)          # [S, V, r]
    vg = jnp.take(v, tslot, axis=0)          # [S, r, V]

    def hook(logits):
        z = jnp.einsum("s...v,svr->s...r", logits.astype(u.dtype), ug)
        d = jnp.einsum("s...r,srv->s...v", z, vg)
        return logits + d.astype(logits.dtype)

    return hook


def _token_forward(cfg: _ServeConfig, ln, params, caches, tok, pos, fold):
    """One token per row through every block — the single definition of
    the decode-time forward: embed (+position), then per block
    [pre-LN -> q/k/v projection of THIS token -> cache fold ->
    out-projection residual -> pre-LN MLP residual], final LN, vocab
    head. `pos` may be a scalar (serial decode: every row at the same
    position) or an int32 [B] vector (the serving engine's per-slot
    positions) — the position-table gather broadcasts either way.
    `fold(block_idx, kc, vc, q, k, v) -> (o, kc, vc)` supplies the
    cache fold, so the serial scalar-pos path and the engine's masked
    per-row path share every other op bit-for-bit. The fold contract
    is deliberately cache-layout-agnostic: the PAGED engine passes
    per-block (k_pool, v_pool) pairs and a page-table-indirect fold
    (`ring_decode.make_paged_batched_ring_decode`, with the table
    closed over) through the same signature — which is why paged token
    streams are bit-identical to contiguous ones on a 1-device mesh:
    everything outside the fold IS this one definition."""
    b = tok.shape[0]
    h = (jnp.take(params["embed"], tok, axis=0)
         + params["pos"][pos])                          # [B, E]
    new_caches = []
    for i in range(cfg.num_blocks):
        p = params[f"block{i}"]
        kc, vc = caches[i]
        q, k, v = _project_qkv(cfg, ln, p, h, (1,))
        o, kc, vc = fold(i, kc, vc, q, k, v)
        h = _attn_residual(p, h, o.reshape(b, cfg.embed_dim))
        h = _mlp_residual(ln, p, h)
        new_caches.append((kc, vc))
    logits = _final_logits(ln, params, h)
    return logits, tuple(new_caches)


def _chunk_batch_forward(cfg: _ServeConfig, ln, params, caches, toks,
                         pos, fold):
    """C tokens per row through every block — `_token_forward` WIDENED
    to C positions with PER-ROW start positions: the model half of the
    speculative verify program. Row b's tokens occupy global positions
    [pos[b], pos[b] + C); embedding gathers each row's slice of the
    position table, then per block [pre-LN -> q/k/v projection of the
    C tokens -> chunk cache fold -> out-projection residual -> pre-LN
    MLP residual], final LN, vocab head at EVERY position (the verify
    needs all C next-token distributions, not just the last).
    `fold(block_idx, kc, vc, q, k, v) -> (o [B,C,H,D], kc, vc)`
    supplies the cache fold (the batched chunk fold — contiguous or
    page-table-indirect, with liveness and positions closed over by
    the caller), so this shares every other op with
    `_token_forward`/`chunk_body` bit-for-bit — the speculative parity
    contract, paged and contiguous alike, hinges on that sharing."""
    b, c = toks.shape
    idx = jnp.clip(pos[:, None] + jnp.arange(c, dtype=jnp.int32),
                   0, params["pos"].shape[0] - 1)
    h = jnp.take(params["embed"], toks, axis=0) + params["pos"][idx]
    new_caches = []
    for i in range(cfg.num_blocks):
        p = params[f"block{i}"]
        kc, vc = caches[i]
        q, k, v = _project_qkv(cfg, ln, p, h, (c,))
        o, kc, vc = fold(i, kc, vc, q, k, v)
        h = _attn_residual(p, h, o.reshape(b, c, cfg.embed_dim))
        h = _mlp_residual(ln, p, h)
        new_caches.append((kc, vc))
    logits = _final_logits(ln, params, h)                # [B, C, V]
    return logits, tuple(new_caches)


@functools.lru_cache(maxsize=16)
def _serving_fns(cfg: _ServeConfig) -> _ServeFns:
    """The compile-once serving programs for one decode configuration.

    Every program takes the parameter tree as an EXPLICIT argument
    instead of closing over it, so the jitted executables — cached here
    by config and inside jax.jit by shape — are shared across
    `Generator` instances and repeated `generate` calls: a second
    request with the same config and shapes performs zero XLA
    recompilation (ADVICE round 5; gated by
    tests/test_lm.py::test_generator_reuses_compilation)."""
    from idc_models_tpu.ring_attention import make_ring_attention

    mesh, t_max = cfg.mesh, cfg.t_max
    head_dim = cfg.embed_dim // cfg.num_heads
    n_ring = mesh.shape[meshlib.SEQ_AXIS]
    # un-jitted decode fold: it is traced INTO the jitted step and the
    # fused scan below, whose top-level jit owns donation
    decode = make_ring_decode(mesh, jit=False)
    ring = make_ring_attention(mesh, causal=True,
                               block_impl=cfg.block_impl)
    ln = core.layer_norm(cfg.embed_dim)
    pin = _seq_pin(mesh)

    def init_caches(batch: int):
        return tuple(init_cache(mesh, batch, t_max, cfg.num_heads,
                                head_dim, dtype=cfg.cache_dtype)
                     for _ in range(cfg.num_blocks))

    def step_body(params, caches, tok, pos):
        return _token_forward(
            cfg, ln, params, caches, tok, pos,
            lambda _i, kc, vc, q, k, v: decode(kc, vc, q, k, v, pos))

    # one dispatch per token for callers driving single steps: without
    # this, every token pays ~15 eager host-side op dispatches per
    # block around the cache fold — on the tunneled runtime that is
    # ~ms each, swamping the 0.15-0.35 ms device floor the decode bench
    # measures. Caches are donated (a serving loop only ever holds the
    # returned ones).
    step = jax.jit(step_body, donate_argnums=(1,))

    def prefill_body(params, tokens, p_len):
        # the prompt runs through the SAME ring the model trained with:
        # per device a [P/n, P/n]-tiled causal fold instead of a
        # replicated [B, H, P, P] score tensor — prefill keeps the
        # O(T/n) property the ring cache exists for. `tokens` arrives
        # padded to a prefill BUCKET (`prefill_bucket`: n_ring * 2**k,
        # capped at t_max) and `p_len` — the TRUE prompt length — is a
        # traced scalar, so every prompt length in a bucket runs the
        # same executable: prompt length stops being a compile key.
        # Causality makes the padding exact (pad positions cannot
        # influence real ones) and the pad K/V is masked out of the
        # cache below.
        b, p_pad = tokens.shape
        h = (jnp.take(params["embed"], tokens, axis=0)
             + params["pos"][:p_pad])                    # [B, P', E]
        h = pin(h)
        kvs = []
        for i in range(cfg.num_blocks):
            p = params[f"block{i}"]
            q, k, v = _project_qkv(cfg, ln, p, h, (p_pad,))
            o = ring(q, k, v)
            o = o.reshape(b, p_pad, cfg.embed_dim)
            h = pin(_attn_residual(p, h, o))
            h = pin(_mlp_residual(ln, p, h))
            kvs.append((k, v))
        # last REAL position's activations — p_len is traced, so this is
        # a dynamic gather, not a static index
        h_last = lax.dynamic_slice_in_dim(h, p_len - 1, 1, axis=1)[:, 0]
        logits = _final_logits(ln, params, h_last)
        sh = cache_sharding(mesh)
        keep = (jnp.arange(p_pad) < p_len)[None, :, None, None]

        def to_cache(x):                 # K/V -> fresh ring cache slot
            # zero pad positions (traced mask): decode's visibility
            # masking relies on slots past the prompt staying zero
            x = jnp.where(keep, x, 0).astype(cfg.cache_dtype)
            x = jnp.pad(x, ((0, 0), (0, t_max - p_pad), (0, 0), (0, 0)))
            return lax.with_sharding_constraint(x, sh)

        return logits, tuple((to_cache(k), to_cache(v)) for k, v in kvs)

    prefill = jax.jit(prefill_body)

    chunk_fold = make_chunk_ring_decode(mesh, jit=False)

    def chunk_body(params, caches, tokens, start, p_end):
        # one prompt CHUNK through every block, consuming and extending
        # an existing ring cache: the admission-path complement of the
        # monolithic `prefill_body`. `tokens` is [B, C] at fixed C (the
        # chunk length is a shape key; ONE length -> one executable);
        # `start` is the chunk's first global position and `p_end` the
        # prompt's true end within this chunk (both traced), so the
        # ragged final chunk runs the same program. Structure per block
        # mirrors `_token_forward` widened to C positions, with the
        # chunk fold (append + per-query causal attend over the whole
        # cache + ring merge) in place of the one-token fold.
        b, c = tokens.shape
        pos_tab = lax.dynamic_slice_in_dim(params["pos"], start, c,
                                           axis=0)
        h = jnp.take(params["embed"], tokens, axis=0) + pos_tab
        new_caches = []
        for i in range(cfg.num_blocks):
            p = params[f"block{i}"]
            kc, vc = caches[i]
            q, k, v = _project_qkv(cfg, ln, p, h, (c,))
            o, kc, vc = chunk_fold(kc, vc, q, k, v, start, p_end)
            h = _attn_residual(p, h, o.reshape(b, c, cfg.embed_dim))
            h = _mlp_residual(ln, p, h)
            new_caches.append((kc, vc))
        # logits of the LAST REAL position in this chunk (p_end is
        # traced -> dynamic gather); intermediate chunks' logits are
        # discarded by the caller, the final chunk's seed decode
        h_last = lax.dynamic_slice_in_dim(h, p_end - start - 1, 1,
                                          axis=1)[:, 0]
        logits = _final_logits(ln, params, h_last)
        sh = cache_sharding(mesh)
        # pin the outgoing caches to the canonical sharding spelling so
        # chunk -> chunk -> insert chains reuse one jit cache entry per
        # program (same discipline as the engine's pin_state)
        new_caches = tuple(
            (lax.with_sharding_constraint(kc, sh),
             lax.with_sharding_constraint(vc, sh))
            for kc, vc in new_caches)
        return logits, new_caches

    prefill_chunk = jax.jit(chunk_body, donate_argnums=(1,))

    pick = _make_pick(cfg)

    def decode_body(params, caches, logits, rng, offsets):
        # the WHOLE decode of len(offsets) tokens is one device
        # program: sample -> embed -> blocks -> ring cache append ->
        # logits, rolled by lax.scan. One host dispatch total, vs one
        # (or more) per token in a host loop — the ~4 ms/token
        # tunneled-dispatch overhead is amortized over the run. The
        # final carry logits correspond to the last sampled token, so
        # chained windows continue exactly where this one stopped.
        def body(carry, off):
            caches, logits, rng = carry
            rng, sub = jax.random.split(rng)
            tok = pick(logits, sub)
            logits, caches = step_body(params, caches, tok, off)
            return (caches, logits, rng), tok

        (caches, logits, _), toks = lax.scan(
            body, (caches, logits, rng), offsets)
        return jnp.moveaxis(toks, 0, 1), logits, caches

    decode_loop = jax.jit(decode_body, donate_argnums=(1,))

    return _ServeFns(init_caches, step, prefill, decode_loop,
                     prefill_chunk)


def make_lm_decoder(params, *, embed_dim: int, num_heads: int,
                    num_blocks: int, t_max: int,
                    mesh: Mesh | None = None,
                    cache_dtype=jnp.bfloat16, block_impl: str = "jnp"):
    """Serving loop for an `attention_lm` parameter tree.

    Returns ``(init_caches, step, prefill_tokens)``:

    - ``init_caches(batch) -> caches`` — one ring-sharded (k, v) cache
      per block (`ring_decode.init_cache`; t_max bounds the context).
    - ``step(caches, tok, pos) -> (logits, caches)`` — tok int32 [B],
      pos the global position: embeds the token, runs every block's
      single-position forward (q/k/v projections of THIS token, the
      block's cache fold, out-projection, residual, MLP), and returns
      the next-token logits [B, vocab].
    - ``prefill_tokens(tokens) -> (logits, caches)`` — the whole prompt
      [B, P] in ONE jitted pass THROUGH THE RING
      (`make_ring_attention` on this mesh, `block_impl` selectable):
      per block a causal ring fold over the seq-sharded prompt — O(P/n)
      score memory per device, never a replicated [B, H, P, P] tensor —
      with the block's K/V placed straight into a fresh ring cache
      (`ring_decode` layout, built in-jit under `cache_sharding`),
      returning the LAST position's logits. Equal to feeding the prompt
      through `step` token by token to fp tolerance, at batch speed
      instead of P dispatches; prompts not divisible by the ring are
      end-padded internally (causal ⇒ exact).

    The compiled programs come from a process-wide cache keyed on the
    decode configuration (`_serving_fns`), with the parameter tree an
    explicit argument — building a second decoder for the same config
    recompiles NOTHING. The per-position math reuses the very parameter
    tree training produced — no export step, no weight transform.
    Dropout is inference-off by construction (decode is eval)."""
    cfg = _serve_config(params, embed_dim=embed_dim,
                        num_heads=num_heads, num_blocks=num_blocks,
                        t_max=t_max, mesh=mesh, cache_dtype=cache_dtype,
                        block_impl=block_impl)
    fns = _serving_fns(cfg)
    params = _place_params(params, cfg.mesh)

    n_ring = cfg.mesh.shape[meshlib.SEQ_AXIS]

    def step(caches, tok, pos):
        return fns.step(params, caches, tok, pos)

    def prefill_tokens(tokens):
        padded, p_len = _pad_prompt(_check_prompt(tokens, t_max),
                                    t_max, n_ring)
        return fns.prefill(params, padded, np.int32(p_len))

    return fns.init_caches, step, prefill_tokens


def chunked_prefill(fns: _ServeFns, params, tokens: np.ndarray,
                    chunk: int, caches=None, start: int = 0):
    """Drive the chunk program over `tokens[:, start:]`: ceil((P-start)/
    chunk) dispatches at ONE compiled shape, each consuming the previous
    chunk's caches (donated) and extending them in place. `caches=None`
    starts from fresh zeroed ring caches; passing caches + a chunk-
    aligned `start` resumes from a prefix snapshot (the prefix-cache hit
    path). Returns (last-real-position logits, caches) — bit-identical
    whether the prefix came from a snapshot or was recomputed, because
    both run the same executables over the same values."""
    b, p_len = tokens.shape
    if start % chunk or not 0 <= start < p_len:
        raise ValueError(f"chunk resume start {start} must be a chunk "
                         f"multiple inside the prompt (P={p_len})")
    if caches is None:
        caches = fns.init_caches(b)
    logits = None
    c0 = start
    while c0 < p_len:
        end = min(c0 + chunk, p_len)
        padded = np.zeros((b, chunk), np.int32)
        padded[:, :end - c0] = tokens[:, c0:end]
        logits, caches = fns.prefill_chunk(params, caches, padded,
                                           np.int32(c0), np.int32(end))
        c0 += chunk
    return logits, caches


class Generator:
    """Reusable compiled serving path: ring prefill + fused scan decode.

    Build ONCE per parameter tree and decode configuration, then serve
    repeated requests: ``gen(prompt, steps, rng=...) -> [B, P + steps]``
    runs the whole generation in two device dispatches — one ring
    prefill over the prompt, one `lax.scan` emitting all `steps` tokens
    (embed → blocks → ring cache append → logits → temperature/top_k
    sample entirely on device, caches donated through the scan).

    The underlying XLA programs live in a process-wide cache keyed on
    the decode configuration with parameters passed explicitly, so a
    second `Generator` (fresh checkpoint, same shapes) or a repeated
    call reuses the compiled executables outright — zero recompilation
    (gated by test). `temperature=0` (default) is greedy argmax;
    `temperature > 0` samples from softmax(logits / temperature)
    (requires `rng` per call), optionally restricted to the `top_k`
    most likely tokens.

    Bounds contract: the Generator owns `pos` — `__call__`/`decode`
    reject any request past `t_max` BEFORE dispatch, because inside the
    fused scan positions are traced and an out-of-range append would
    otherwise be silently dropped (`ring_decode` can only guard
    concrete positions)."""

    def __init__(self, params, *, embed_dim: int, num_heads: int,
                 num_blocks: int, t_max: int, mesh: Mesh | None = None,
                 cache_dtype=jnp.bfloat16, block_impl: str = "jnp",
                 temperature: float = 0.0, top_k: int | None = None,
                 prefill_chunk: int | None = None,
                 partition_rules=None):
        self._cfg = _serve_config(
            params, embed_dim=embed_dim, num_heads=num_heads,
            num_blocks=num_blocks, t_max=t_max, mesh=mesh,
            cache_dtype=cache_dtype, block_impl=block_impl,
            temperature=temperature, top_k=top_k)
        self._fns = _serving_fns(self._cfg)
        # partition_rules shard the params over the mesh's weight axes
        # ("model"/"data" — registry.LM_RULES) while the KV caches keep
        # their seq-ring layout: params and KV shard INDEPENDENTLY
        self._params = _place_params(params, self._cfg.mesh,
                                     rules=partition_rules)
        self.t_max = t_max
        self.temperature = float(temperature)
        # chunked prefill: the prompt runs through the chunk program C
        # tokens at a time instead of one monolithic bucketed dispatch.
        # None (default) keeps the historical single-dispatch path
        # bit-for-bit; an int selects the Sarathi-style path the serving
        # ENGINE uses, so engine-vs-serial parity can be asserted with
        # both sides prefilling identically.
        self.prefill_chunk = (None if prefill_chunk is None
                              else check_prefill_chunk(prefill_chunk,
                                                       t_max))

    def init_caches(self, batch: int):
        """Fresh zeroed ring caches (one (k, v) pair per block)."""
        return self._fns.init_caches(batch)

    def prefill(self, prompt):
        """Prompt [B, P] -> (last-position logits [B, vocab], caches).

        Default (`prefill_chunk=None`): one ring-sharded pass (O(P/n)
        per device), prompts padded to a prefill bucket
        (`prefill_bucket`) with the true length traced, so distinct
        prompt lengths share compiled programs.

        With `prefill_chunk=C`: ceil(P/C) chunk-program dispatches, each
        extending the same ring caches — the path a chunked-admission
        serving engine runs, exposed here so serial reference outputs
        can be produced through the IDENTICAL programs."""
        if self.prefill_chunk is None:
            n_ring = self._cfg.mesh.shape[meshlib.SEQ_AXIS]
            padded, p_len = _pad_prompt(_check_prompt(prompt, self.t_max),
                                        self.t_max, n_ring)
            with trace.span("lm.prefill", p_len=p_len,
                            bucket=padded.shape[1]):
                return self._fns.prefill(self._params, padded,
                                         np.int32(p_len))
        tokens = np.asarray(_check_prompt(prompt, self.t_max))
        with trace.span("lm.prefill", p_len=tokens.shape[1],
                        chunk=self.prefill_chunk):
            return chunked_prefill(self._fns, self._params,
                                   tokens, self.prefill_chunk)

    def decode(self, caches, logits, pos0: int, steps: int, *, rng=None):
        """Emit `steps` tokens in ONE dispatch from (caches, logits) at
        global position `pos0` (the position the next sampled token
        occupies). Returns ``(tokens [B, steps], logits, caches)`` —
        the logits/caches continue a chained window exactly. Donates
        `caches`."""
        if steps < 1:
            raise ValueError(f"decode needs steps >= 1, got {steps}")
        if pos0 < 0:
            raise ValueError(f"decode pos {pos0} must be >= 0 — inside "
                             f"the fused scan a negative append matches "
                             f"no owner shard and would be silently "
                             f"dropped")
        if pos0 + steps > self.t_max:
            raise ValueError(f"decode at pos {pos0} + steps {steps} "
                             f"exceeds t_max {self.t_max} — the cache "
                             f"cannot grow at decode time")
        if self.temperature > 0.0 and rng is None:
            raise ValueError("sampling (temperature > 0) needs an rng "
                             "key")
        if rng is None:
            rng = jax.random.key(0)      # greedy never consumes it
        offsets = jnp.arange(pos0, pos0 + steps, dtype=jnp.int32)
        # span covers the fused-scan DISPATCH (decode is async; the
        # caller's token fetch is the execution fence)
        with trace.span("lm.decode", pos0=pos0, steps=steps):
            return self._fns.decode_loop(self._params, caches, logits,
                                         rng, offsets)

    def __call__(self, prompt, steps: int, *, rng=None):
        prompt = jnp.asarray(prompt, jnp.int32)
        p_len = prompt.shape[1] if prompt.ndim == 2 else 0
        if steps < 1 or p_len < 1:
            raise ValueError(f"generate needs a non-empty prompt and "
                             f"steps >= 1, got prompt length {p_len}, "
                             f"steps {steps}")
        if p_len + steps > self.t_max:
            raise ValueError(f"prompt {p_len} + steps {steps} exceeds "
                             f"t_max {self.t_max}")
        if self.temperature > 0.0 and rng is None:
            # before the prefill dispatch: a 16k-token prompt must not
            # compile and run just to throw away the work on this
            raise ValueError("sampling (temperature > 0) needs an rng "
                             "key")
        logits, caches = self.prefill(prompt)
        toks, _, _ = self.decode(caches, logits, p_len, steps, rng=rng)
        return jnp.concatenate([prompt, toks], axis=1)

    def cache_sizes(self) -> dict:
        """Per-program jit-cache entry counts — observability for the
        zero-recompilation contract (a second same-shape call must not
        grow any of these)."""
        return {"step": self._fns.step._cache_size(),
                "prefill": self._fns.prefill._cache_size(),
                "prefill_chunk": self._fns.prefill_chunk._cache_size(),
                "decode_loop": self._fns.decode_loop._cache_size()}

    def program_costs(self, *, batch: int = 1, steps: int = 8) -> dict:
        """Cost/memory accounts of the serial serving programs
        (observe/profile.py ProgramCost): the full-bucket ring prefill
        and the fused `steps`-token decode scan. Lowers ACCOUNTING
        copies (suppressed from the compile watchdog — lowering
        neither executes nor donates) and registers them in the
        process PROGRAMS table under ``lm.prefill`` / ``lm.decode``."""
        from idc_models_tpu.observe import profile as prof

        vocab = self._params["embed"].shape[0]
        with prof.compiling(None):
            toks = np.zeros((batch, self.t_max), np.int32)
            prefill = prof.register_program(
                "lm.prefill",
                self._fns.prefill.lower(self._params, toks,
                                        np.int32(self.t_max)).compile())
            caches = self._fns.init_caches(batch)
            logits = jnp.zeros((batch, vocab), jnp.float32)
            offsets = jnp.arange(0, steps, dtype=jnp.int32)
            decode = prof.register_program(
                "lm.decode",
                self._fns.decode_loop.lower(
                    self._params, caches, logits, jax.random.key(0),
                    offsets).compile())
        return {"lm.prefill": prefill, "lm.decode": decode}


def generate(params, prompt, steps: int, *, embed_dim: int,
             num_heads: int, num_blocks: int, t_max: int,
             mesh: Mesh | None = None, cache_dtype=jnp.bfloat16,
             temperature: float = 0.0, top_k: int | None = None,
             rng=None, block_impl: str = "jnp"):
    """One-shot convenience around `Generator`: one-pass ring prefill,
    then `steps` tokens in a single fused dispatch. `temperature=0`
    (default) is greedy argmax; `temperature > 0` samples from
    softmax(logits / temperature) (requires `rng`), optionally
    restricted to the `top_k` most likely tokens. Returns int32
    [B, P + steps] (prompt included).

    Repeated calls are cheap: the compiled programs are cached
    process-wide per decode config (see `_serving_fns`), so only the
    first call with a given config + shape pays XLA compilation. Hot
    serving loops should still hold a `Generator` to skip the per-call
    validation and tree re-asserting."""
    gen = Generator(params, embed_dim=embed_dim, num_heads=num_heads,
                    num_blocks=num_blocks, t_max=t_max, mesh=mesh,
                    cache_dtype=cache_dtype, block_impl=block_impl,
                    temperature=temperature, top_k=top_k)
    return gen(prompt, steps, rng=rng)
