"""Causal language model over the ring: train long contexts, then SERVE
them — the model-level composition of `ring_attention` (training) and
`ring_decode` (KV-cache inference) sharing one parameter tree.

The reference has no sequence models at all (its models are the CNN
backbones, SURVEY.md §3.5), so this is beyond-parity: it exists to
close the loop the round-5 pieces opened. `attention_lm` is the
smallest honest decoder-only LM — token embedding + learned positions,
the SAME pre-LN ring-attention blocks as the classifier
(`models/attention.py::transformer_block`), final LN, per-position
vocab head — and `make_lm_decoder` drives the SAME parameters through
single-token KV-cache steps: per block, project this token's q/k/v,
fold against the block's ring-sharded cache (`ring_decode`), residual +
MLP, exactly the block forward restricted to one position.

Incremental == full: teacher-forcing the decoder over a sequence
reproduces the training-path logits at every position to fp tolerance
(tests/test_lm.py gates it on the 2-D mesh, non-power-of-2 rings, and
both block engines' training weights). Because the zigzag layout is an
internal training-schedule permutation that does not change the
function (gated in test_zigzag.py), weights trained under
``layout="zigzag"`` decode identically through this (natural-order)
path — layout is a training knob, not a serving constraint.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models import core
from idc_models_tpu.models.attention import _seq_pin, transformer_block
from idc_models_tpu.ring_decode import init_cache, make_ring_decode


def attention_lm(vocab_size: int, seq_len: int, *,
                 embed_dim: int = 64, num_heads: int = 4,
                 mlp_dim: int = 128, num_blocks: int = 2,
                 mesh: Mesh | None = None,
                 block_impl: str = "jnp",
                 layout: str = "contiguous",
                 dropout_rate: float = 0.0,
                 remat: bool = False) -> core.Module:
    """Decoder-only LM: int32 tokens [B, T] -> logits [B, T, vocab].

    Causal by construction; `layout`/`block_impl`/`remat`/`mesh` behave
    exactly as on `attention_classifier` (the blocks are shared). The
    zigzag permutation, when used, moves the TOKEN ids and positions
    before embedding (per-position embed commutes with it) and the
    output logits are permuted back — training-path logits are always
    in natural order, so the loss/labels need no layout awareness."""
    from idc_models_tpu.ring_attention import from_zigzag, to_zigzag

    blocks = [transformer_block(embed_dim, num_heads, mlp_dim, mesh=mesh,
                                causal=True, block_impl=block_impl,
                                layout=layout,
                                dropout_rate=dropout_rate,
                                name=f"block{i}")
              for i in range(num_blocks)]
    ln_f = core.layer_norm(embed_dim, name="ln_f")
    head = core.dense(embed_dim, vocab_size, name="head")
    n_ring = mesh.shape[meshlib.SEQ_AXIS] if mesh is not None else 1
    zig = layout == "zigzag"
    pin = _seq_pin(mesh)

    def init(rng):
        rngs = jax.random.split(rng, num_blocks + 4)
        params = {
            "embed": 0.02 * jax.random.normal(
                rngs[0], (vocab_size, embed_dim)),
            "pos": 0.02 * jax.random.normal(rngs[1],
                                            (seq_len, embed_dim)),
        }
        for i, (blk, r) in enumerate(zip(blocks, rngs[2:2 + num_blocks])):
            params[f"block{i}"] = blk.init(r).params
        params["ln_f"] = ln_f.init(rngs[-2]).params
        params["head"] = head.init(rngs[-1]).params
        return core.Variables(params, {})

    def apply(params, state, tokens, *, train=False, rng=None):
        # the shared train step casts inputs to its compute dtype;
        # token ids must come back to int before the table gather
        tokens = tokens.astype(jnp.int32)
        pos = params["pos"]
        if zig:
            tokens = to_zigzag(tokens, n_ring)
            pos = to_zigzag(pos[None], n_ring)[0]
        h = jnp.take(params["embed"], tokens, axis=0) + pos
        h = pin(h)
        rngs = (jax.random.split(rng, num_blocks) if rng is not None
                else [None] * num_blocks)
        for i, blk in enumerate(blocks):
            def run_block(p, h, _blk=blk, _r=rngs[i]):
                return _blk.apply(p, {}, h, train=train, rng=_r)[0]

            if remat:
                run_block = jax.checkpoint(run_block)
            h = pin(run_block(params[f"block{i}"], h))
        h, _ = ln_f.apply(params["ln_f"], {}, h, train=train)
        logits, _ = head.apply(params["head"], {}, h, train=train)
        if zig:
            logits = from_zigzag(logits, n_ring)
        return logits, state

    names = (("embed", "pos")
             + tuple(f"block{i}" for i in range(num_blocks))
             + ("ln_f", "head"))
    return core.Module(init, apply, "attention_lm", layer_names=names,
                       children=tuple((f"block{i}", b)
                                      for i, b in enumerate(blocks)))


def next_token_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean cross-entropy of logits[:, :-1] predicting tokens[:, 1:] —
    the standard shifted LM objective, usable as the train step's
    loss_fn with the raw token batch as labels."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
    tgt = tokens[:, 1:]
    ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_lm_decoder(params, *, embed_dim: int, num_heads: int,
                    num_blocks: int, t_max: int,
                    mesh: Mesh | None = None,
                    cache_dtype=jnp.bfloat16):
    """Serving loop for an `attention_lm` parameter tree.

    Returns ``(init_caches, step, prefill_tokens)``:

    - ``init_caches(batch) -> caches`` — one ring-sharded (k, v) cache
      per block (`ring_decode.init_cache`; t_max bounds the context).
    - ``step(caches, tok, pos) -> (logits, caches)`` — tok int32 [B],
      pos the global position: embeds the token, runs every block's
      single-position forward (q/k/v projections of THIS token, the
      block's cache fold, out-projection, residual, MLP), and returns
      the next-token logits [B, vocab].
    - ``prefill_tokens(tokens) -> (logits, caches)`` — the whole prompt
      [B, P] in ONE jitted pass: per block, full causal attention over
      the prompt and the block's K/V placed straight into a fresh ring
      cache (`ring_decode.prefill` layout), returning the LAST
      position's logits. Equal to feeding the prompt through `step`
      token by token to fp tolerance (the batched projections
      reassociate the same matmuls; pinned), at batch speed instead of
      P dispatches.

    The per-position math reuses the very parameter tree training
    produced — no export step, no weight transform. Dropout is inference
    -off by construction (decode is eval)."""
    if embed_dim % num_heads:
        raise ValueError(f"embed_dim {embed_dim} not divisible by "
                         f"num_heads {num_heads}")
    if params["pos"].shape[0] < t_max:
        raise ValueError(
            f"cache t_max {t_max} exceeds the trained position table "
            f"({params['pos'].shape[0]}) — positions past it have no "
            f"embedding")
    head_dim = embed_dim // num_heads
    mesh = mesh if mesh is not None else meshlib.seq_mesh(1)
    decode = make_ring_decode(mesh)
    ln = core.layer_norm(embed_dim)
    # host (numpy) trees are fine to pass in — e.g. a checkpoint straight
    # from device_get/restore; the jitted step needs jax arrays to index
    # with a traced position
    params = jax.tree.map(jnp.asarray, params)

    def init_caches(batch: int):
        return tuple(init_cache(mesh, batch, t_max, num_heads, head_dim,
                                dtype=cache_dtype)
                     for _ in range(num_blocks))

    def step(caches, tok, pos):
        b = tok.shape[0]
        h = (jnp.take(params["embed"], tok, axis=0)
             + params["pos"][pos])                      # [B, E]
        new_caches = []
        for i in range(num_blocks):
            p = params[f"block{i}"]
            kc, vc = caches[i]
            a, _ = ln.apply(p["ln1"], {}, h)
            split = lambda y: y.reshape(b, 1, num_heads, head_dim)
            q = split(a @ p["mha"]["wq"].astype(a.dtype))
            k = split(a @ p["mha"]["wk"].astype(a.dtype))
            v = split(a @ p["mha"]["wv"].astype(a.dtype))
            o, kc, vc = decode(kc, vc, q, k, v, pos)
            o = o.reshape(b, embed_dim)
            h = h + (o @ p["mha"]["wo"].astype(o.dtype)
                     + p["mha"]["bo"].astype(o.dtype))
            a, _ = ln.apply(p["ln2"], {}, h)
            m = jax.nn.gelu(a @ p["fc1"]["kernel"] + p["fc1"]["bias"])
            h = h + (m @ p["fc2"]["kernel"] + p["fc2"]["bias"])
            new_caches.append((kc, vc))
        h, _ = ln.apply(params["ln_f"], {}, h)
        logits = h @ params["head"]["kernel"] + params["head"]["bias"]
        return logits, tuple(new_caches)

    # one dispatch per token: without this, every token pays ~15 eager
    # host-side op dispatches per block around the jitted cache fold —
    # on the tunneled runtime that is ~ms each, swamping the 0.15-0.35
    # ms device floor the decode bench measures. Caches are donated (the
    # serving loop only ever holds the returned ones).
    step = jax.jit(step, donate_argnums=(0,))

    from idc_models_tpu.ring_attention import full_attention
    from idc_models_tpu.ring_decode import prefill as cache_prefill

    @jax.jit
    def _prefill_fwd(tokens):
        b, p_len = tokens.shape
        h = (jnp.take(params["embed"], tokens, axis=0)
             + params["pos"][:p_len])                    # [B, P, E]
        kvs = []
        for i in range(num_blocks):
            p = params[f"block{i}"]
            a, _ = ln.apply(p["ln1"], {}, h)
            split = lambda y: y.reshape(b, p_len, num_heads, head_dim)
            q = split(a @ p["mha"]["wq"].astype(a.dtype))
            k = split(a @ p["mha"]["wk"].astype(a.dtype))
            v = split(a @ p["mha"]["wv"].astype(a.dtype))
            o = full_attention(q, k, v, causal=True)
            o = o.reshape(b, p_len, embed_dim)
            h = h + (o @ p["mha"]["wo"].astype(o.dtype)
                     + p["mha"]["bo"].astype(o.dtype))
            a, _ = ln.apply(p["ln2"], {}, h)
            m = jax.nn.gelu(a @ p["fc1"]["kernel"] + p["fc1"]["bias"])
            h = h + (m @ p["fc2"]["kernel"] + p["fc2"]["bias"])
            kvs.append((k, v))
        h, _ = ln.apply(params["ln_f"], {}, h[:, -1])
        logits = h @ params["head"]["kernel"] + params["head"]["bias"]
        return logits, kvs

    def prefill_tokens(tokens):
        tokens = jnp.asarray(tokens, jnp.int32)
        if tokens.ndim != 2 or tokens.shape[1] < 1:
            raise ValueError(f"prefill_tokens expects non-empty [B, P] "
                             f"tokens, got shape {tokens.shape}")
        if tokens.shape[1] > t_max:
            raise ValueError(f"prompt length {tokens.shape[1]} exceeds "
                             f"t_max {t_max}")
        logits, kvs = _prefill_fwd(tokens)
        caches = tuple(
            cache_prefill(mesh, k.astype(cache_dtype),
                          v.astype(cache_dtype), t_max,
                          dtype=cache_dtype)
            for k, v in kvs)
        return logits, caches

    return init_caches, step, prefill_tokens


def generate(params, prompt, steps: int, *, embed_dim: int,
             num_heads: int, num_blocks: int, t_max: int,
             mesh: Mesh | None = None, cache_dtype=jnp.bfloat16,
             temperature: float = 0.0, top_k: int | None = None,
             rng=None):
    """Generation through the cached decoder: one-pass prompt prefill,
    then `steps` tokens. `temperature=0` (default) is greedy argmax;
    `temperature > 0` samples from softmax(logits / temperature)
    (requires `rng`), optionally restricted to the `top_k` most likely
    tokens. Returns int32 [B, P + steps] (prompt included)."""
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p_len = prompt.shape
    if steps < 1 or p_len < 1:
        raise ValueError(f"generate needs a non-empty prompt and "
                         f"steps >= 1, got prompt length {p_len}, "
                         f"steps {steps}")
    if p_len + steps > t_max:
        raise ValueError(f"prompt {p_len} + steps {steps} exceeds "
                         f"t_max {t_max}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature > 0.0 and rng is None:
        raise ValueError("sampling (temperature > 0) needs an rng key")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    _, step, prefill_tokens = make_lm_decoder(
        params, embed_dim=embed_dim, num_heads=num_heads,
        num_blocks=num_blocks, t_max=t_max, mesh=mesh,
        cache_dtype=cache_dtype)

    @jax.jit  # one dispatch, like the decode step it follows
    def pick(logits, key):
        lg = logits.astype(jnp.float32)
        if top_k is not None and top_k < lg.shape[-1]:
            kth = jax.lax.top_k(lg, top_k)[0][:, -1]
            lg = jnp.where(lg >= kth[:, None], lg, -jnp.inf)
        if temperature == 0.0:
            return jnp.argmax(lg, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, lg / temperature,
                                      axis=-1).astype(jnp.int32)

    # whole prompt in one pass (pinned equal to token-by-token feeding)
    logits, caches = prefill_tokens(prompt)
    out = [prompt]
    for s in range(steps):
        sub = None
        if temperature > 0.0:
            rng, sub = jax.random.split(rng)
        tok = pick(logits, sub)
        out.append(tok[:, None])
        if s + 1 < steps:   # the last token's logits are never needed
            logits, caches = step(caches, tok, p_len + s)
    return jnp.concatenate(out, axis=1)
