"""Deterministic, seeded fault injection for federated training.

The reference's federated path has NO failure handling (SURVEY.md §5): a
crashed, straggling, or poisoned client corrupts the FedAvg round
silently. To build — and regression-test — the resilience layer
(`federated/robust.py` aggregators, `federated/driver.py` self-healing
driver), failures must be reproducible: this module provides declarative
per-client fault plans that are pure functions of (plan, round), so the
same plan replays bit-identically across runs.

Faults are applied to the client UPDATE tensors after local training and
before aggregation (threaded through `make_fedavg_round(faults=plan)`),
which is where every real failure mode lands from the server's point of
view:

- ``crash``      the client never reports: its aggregation weight is
                 forced to 0 (indistinguishable from a dropped
                 connection);
- ``straggler``  the client reports params from round r−k (its local
                 training raced a stale broadcast);
- ``nan`` / ``inf``  a poisoner (or a genuinely diverged client) reports
                 non-finite tensors — caught by ``drop_nonfinite``;
- ``scale``      a gradient-scaling attacker reports
                 server + scale·(update − server): finite but huge, so
                 finite-ness checks can NOT catch it (the gap robust
                 aggregators close);
- ``sign_flip``  the canonical Byzantine attacker reports
                 server − scale·(update − server), pushing the mean
                 AWAY from descent while staying finite.

Plus generic hooks for transient data-pipeline read failures
(`flaky` / `with_retries`), seeded the same way.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from collections.abc import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# fault codes — the integers the jitted round program branches on
OK = 0
CRASH = 1
STRAGGLER = 2
NAN = 3
INF = 4
SCALE = 5
SIGN_FLIP = 6

KINDS = ("crash", "straggler", "nan", "inf", "scale", "sign_flip")
_CODE = {"crash": CRASH, "straggler": STRAGGLER, "nan": NAN, "inf": INF,
         "scale": SCALE, "sign_flip": SIGN_FLIP}
_KIND_OF = {v: k for k, v in _CODE.items()}


def kind_of(code: int) -> str:
    """The human name of a fault code ("ok" for OK) — observability
    surfaces (the driver's per-client fed.client spans) stamp this
    instead of the raw integer the jitted program branches on."""
    return _KIND_OF.get(int(code), "ok")


@dataclasses.dataclass(frozen=True)
class Fault:
    """One declarative fault: `kind` applied to `client` on `rounds`
    (None = every round). `scale` parameterizes the scale/sign_flip
    attackers; `staleness` is the straggler's lag k (params from round
    r−k)."""

    kind: str
    client: int
    rounds: tuple[int, ...] | None = None
    scale: float = 1.0
    staleness: int = 1

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")
        if self.client < 0:
            raise ValueError(f"client must be >= 0, got {self.client}")
        if not np.isfinite(self.scale):
            raise ValueError(f"scale must be finite, got {self.scale} "
                             f"(use kind='nan'/'inf' for non-finite "
                             f"poisoning)")
        if self.staleness < 1:
            raise ValueError(f"staleness must be >= 1, got "
                             f"{self.staleness}")
        if self.rounds is not None:
            object.__setattr__(self, "rounds",
                               tuple(int(r) for r in self.rounds))


class FaultPlan:
    """A deterministic per-client fault schedule for a federated run.

    `codes(r)` is a pure function of the plan and the round index, so a
    run under the plan replays bit-identically: same plan + same rng
    seed -> same round trajectory, down to the last bit (gated by
    test_faults.py). When several faults name the same client for the
    same round, the LAST one listed wins.
    """

    def __init__(self, n_clients: int, faults: Sequence[Fault] = ()):
        if n_clients < 1:
            raise ValueError(f"need n_clients >= 1, got {n_clients}")
        self.n_clients = int(n_clients)
        self.faults = tuple(faults)
        for f in self.faults:
            if f.client >= self.n_clients:
                raise ValueError(
                    f"fault {f.kind!r} names client {f.client} but the "
                    f"plan covers {self.n_clients} clients")
        lags = {f.staleness for f in self.faults
                if f.kind == "straggler"}
        if len(lags) > 1:
            # ONE stale server tree is threaded through the jitted
            # round per call, so mixed lags would silently collapse to
            # the max — refuse rather than run a different fault model
            # than the plan declares
            raise ValueError(
                f"straggler faults in one plan must share a single "
                f"staleness, got {sorted(lags)}; use separate plans "
                f"(or rounds=) for mixed lags")

    @classmethod
    def byzantine(cls, n_clients: int, n_byzantine: int, *,
                  kind: str = "sign_flip", scale: float = 1.0,
                  seed: int = 0,
                  rounds: Sequence[int] | None = None) -> "FaultPlan":
        """Seeded attacker sampling: `n_byzantine` distinct clients are
        drawn with `seed` and given the same attack. The draw is
        deterministic — the canonical way to build the "k of n clients
        are Byzantine" experiment reproducibly."""
        if not 0 <= n_byzantine <= n_clients:
            raise ValueError(f"need 0 <= n_byzantine <= {n_clients}, "
                             f"got {n_byzantine}")
        ids = np.random.default_rng(seed).choice(
            n_clients, size=n_byzantine, replace=False)
        return cls(n_clients, [
            Fault(kind, int(c), rounds=tuple(rounds) if rounds else None,
                  scale=scale) for c in sorted(ids)])

    def active(self, round_idx: int) -> list[Fault]:
        return [f for f in self.faults
                if f.rounds is None or round_idx in f.rounds]

    def codes(self, round_idx: int) -> tuple[np.ndarray, np.ndarray]:
        """(codes [n_clients] int32, scales [n_clients] float32) for one
        round — the arrays the jitted round program branches on."""
        codes = np.zeros((self.n_clients,), np.int32)
        scales = np.ones((self.n_clients,), np.float32)
        for f in self.active(round_idx):
            codes[f.client] = _CODE[f.kind]
            scales[f.client] = f.scale
        return codes, scales

    def staleness(self, round_idx: int) -> int:
        """The stale-params lag k for this round's stragglers (max over
        the round's active straggler faults; 1 when none)."""
        ks = [f.staleness for f in self.active(round_idx)
              if f.kind == "straggler"]
        return max(ks) if ks else 1

    @property
    def max_staleness(self) -> int:
        ks = [f.staleness for f in self.faults if f.kind == "straggler"]
        return max(ks) if ks else 0

    def __repr__(self) -> str:
        return (f"FaultPlan(n_clients={self.n_clients}, "
                f"faults={list(self.faults)!r})")


GRAMMAR = ("comma-separated kind:clients[:param] groups; clients = a "
           "single id, an inclusive a-b range, or a +-joined list; "
           "param = scale (optionally x-prefixed) for scale/sign_flip, "
           "staleness lag for straggler (crash/nan/inf take none)")


def format_spec_error(group: str, detail: str, *, kinds=KINDS,
                      grammar=GRAMMAR) -> str:
    """One message shape for every fault-spec parse failure, federated
    AND serving (serve/faults.py): the offending group, what was wrong
    with it, the full grammar, and the valid kinds — so a mistyped
    drill flag teaches its own syntax instead of bare-rejecting."""
    return (f"bad fault group {group!r}: {detail} (grammar: {grammar}; "
            f"valid kinds: {', '.join(kinds)})")


def parse_id_field(field: str, *, what: str, group: str, kinds=KINDS,
                   grammar=GRAMMAR) -> list[int]:
    """The shared id-list grammar both spec parsers target with
    `field`: a single integer, an inclusive ``a-b`` range, or a
    ``+``-joined list — client ids for the federated plan, tick
    indices for the serving one (serve/faults.py). One implementation
    so a parsing fix cannot land in one grammar and miss the other."""
    try:
        if "-" in field:
            a, b = field.split("-", 1)
            return list(range(int(a), int(b) + 1))
        return [int(c) for c in field.split("+")]
    except ValueError:
        raise ValueError(format_spec_error(
            group, f"bad {what} field {field!r}", kinds=kinds,
            grammar=grammar)) from None


def parse_fault_spec(spec: str, n_clients: int) -> FaultPlan:
    """CLI fault grammar: comma-separated ``kind:clients[:param]``
    groups, clients as a single id, an inclusive ``a-b`` range, or a
    ``+``-joined list. The third field is the kind's OWN parameter —
    scale (optionally ``x``-prefixed) for scale/sign_flip, staleness
    lag for straggler — and is rejected for kinds that take none
    (crash/nan/inf), so a mistyped drill fails loudly instead of
    silently running a different fault model. Every parse error
    enumerates the valid kinds and shows the grammar
    (`format_spec_error`).

        "sign_flip:0-2:x1000,crash:5"     3 sign-flip attackers + crash
        "scale:1+4:100"                   2 scaling attackers
        "straggler:3:2"                   one straggler at lag 2
    """
    faults: list[Fault] = []
    for group in spec.split(","):
        group = group.strip()
        if not group:
            continue
        parts = group.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(format_spec_error(
                group, "want kind:clients[:param]"))
        kind, clients = parts[0].strip(), parts[1].strip()
        if kind not in KINDS:
            raise ValueError(format_spec_error(
                group, f"unknown fault kind {kind!r}"))
        kw = {}
        if len(parts) == 3:
            param = parts[2].strip()
            try:
                if kind in ("scale", "sign_flip"):
                    kw["scale"] = float(param.lstrip("x"))
                elif kind == "straggler":
                    kw["staleness"] = int(param)
                else:
                    raise ValueError(format_spec_error(
                        group, f"fault kind {kind!r} takes no "
                               f"parameter, got {param!r}"))
            except ValueError as e:
                if "bad fault group" in str(e):
                    raise
                raise ValueError(format_spec_error(
                    group, f"bad parameter {param!r} for kind "
                           f"{kind!r}")) from None
        ids = parse_id_field(clients, what="clients", group=group)
        faults.extend(Fault(kind, int(c), **kw) for c in ids)
    return FaultPlan(n_clients, faults)


def apply_faults(codes, scales, new_params, new_model_state, weight,
                 params, model_state, stale_params, stale_state):
    """Apply one round's fault codes to a device's k client updates —
    jit-traceable, called inside the round's shard_map body.

    `codes`/`scales`/`weight` are [k]; `new_*` leaves carry the leading
    [k] client axis; `params`/`model_state` are the incoming (broadcast)
    server trees and `stale_*` the round-(r−k) server trees. Non-float
    leaves pass through untouched (integer state cannot carry NaN and is
    not a gradient target). Returns the faulted (new_params,
    new_model_state, weight).
    """
    k = codes.shape[0]
    weight = jnp.where(codes == CRASH, 0.0, weight)

    def leafwise(new, server, stale):
        if not jnp.issubdtype(new.dtype, jnp.inexact):
            return new
        shape = (k,) + (1,) * (new.ndim - 1)
        c = codes.reshape(shape)
        s = scales.reshape(shape).astype(new.dtype)
        delta = new - server[None]
        out = jnp.where(c == STRAGGLER, stale[None], new)
        out = jnp.where(c == NAN, jnp.asarray(jnp.nan, new.dtype), out)
        out = jnp.where(c == INF, jnp.asarray(jnp.inf, new.dtype), out)
        out = jnp.where(c == SCALE, server[None] + s * delta, out)
        out = jnp.where(c == SIGN_FLIP, server[None] - s * delta, out)
        return out

    new_params = jax.tree.map(leafwise, new_params, params, stale_params)
    new_model_state = jax.tree.map(leafwise, new_model_state, model_state,
                                   stale_state)
    return new_params, new_model_state, weight


# ---------------------------------------------------------------------------
# Population-addressable fault plans (federated/population.py scale)
# ---------------------------------------------------------------------------
#
# `FaultPlan` addresses clients by POSITION in a fully-materialized
# stacked client array — the right shape for the 10–32-client rounds the
# reference simulates. At population scale (federated/population.py:
# 10k+ virtual clients, a sampled cohort per round) a plan must address
# clients by their VIRTUAL id and stay O(cohort) to evaluate: the plan
# below is a pure function of (plan, round, cohort ids), never
# materializing a population-sized array.


@dataclasses.dataclass(frozen=True)
class PopulationFault:
    """One declarative population-scale fault: `kind` applied on
    `rounds` (None = every round) to either an explicit tuple of
    virtual-client ids (`clients`) or a seeded `fraction` of the whole
    population (0 < fraction <= 1; which clients fall in the fraction
    is a stable pure function of (plan seed, client id), so a
    fraction-crashed client is crashed on every listed round)."""

    kind: str
    rounds: tuple[int, ...] | None = None
    clients: tuple[int, ...] | None = None
    fraction: float | None = None
    scale: float = 1.0
    staleness: int = 1

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")
        if (self.clients is None) == (self.fraction is None):
            raise ValueError("exactly one of clients= / fraction= must "
                             "be given (explicit virtual ids, or a "
                             "seeded population fraction)")
        if self.clients is not None:
            if not self.clients:
                raise ValueError("clients= must name at least one id")
            if any(c < 0 for c in self.clients):
                raise ValueError(f"client ids must be >= 0, got "
                                 f"{sorted(self.clients)[0]}")
            object.__setattr__(self, "clients",
                               tuple(int(c) for c in self.clients))
        if self.fraction is not None and not 0.0 < self.fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got "
                             f"{self.fraction}")
        if not np.isfinite(self.scale):
            raise ValueError(f"scale must be finite, got {self.scale}")
        if self.staleness < 1:
            raise ValueError(f"staleness must be >= 1, got "
                             f"{self.staleness}")
        if self.rounds is not None:
            object.__setattr__(self, "rounds",
                               tuple(int(r) for r in self.rounds))


class PopulationFaultPlan:
    """A deterministic fault schedule addressing the VIRTUAL population.

    `codes_for(r, ids)` is a pure function of (plan, round, cohort ids)
    returning arrays aligned to the cohort — O(cohort) work and memory,
    independent of the population size. `delay_unit_s` converts a
    straggler's staleness lag into a wall-clock completion delay
    (lag k ⇒ k * delay_unit_s) for the async/buffered path and the sync
    round barrier, so one plan drives both the stale-params fault model
    and the injected-sleep wall-clock drills."""

    def __init__(self, population: int,
                 faults: Sequence[PopulationFault] = (), *,
                 seed: int = 0, delay_unit_s: float = 0.0):
        if population < 1:
            raise ValueError(f"need population >= 1, got {population}")
        if delay_unit_s < 0:
            raise ValueError(f"delay_unit_s must be >= 0, got "
                             f"{delay_unit_s}")
        self.population = int(population)
        self.faults = tuple(faults)
        self.seed = int(seed)
        self.delay_unit_s = float(delay_unit_s)
        for f in self.faults:
            if f.clients is not None:
                bad = [c for c in f.clients if c >= self.population]
                if bad:
                    raise ValueError(
                        f"fault {f.kind!r} names client c{bad[0]} but "
                        f"the population has {self.population} virtual "
                        f"clients (ids 0..{self.population - 1})")
        lags = {f.staleness for f in self.faults
                if f.kind == "straggler"}
        if len(lags) > 1:
            # same constraint as FaultPlan: ONE stale server tree is
            # threaded through the round per call
            raise ValueError(
                f"straggler faults in one plan must share a single "
                f"staleness, got {sorted(lags)}; use separate plans "
                f"(or rounds=) for mixed lags")

    def active(self, round_idx: int) -> list[PopulationFault]:
        return [f for f in self.faults
                if f.rounds is None or round_idx in f.rounds]

    def _in_fraction(self, f: PopulationFault,
                     ids: np.ndarray) -> np.ndarray:
        """[len(ids)] bool: which of `ids` fall inside the fault's
        seeded population fraction — stable per client id across
        rounds, so a fraction-crash names the same virtual clients on
        every round it is active. The FAULT's index is folded into the
        draw: two fraction faults in one plan select independently
        (sharing one uniform would make the smaller fraction a strict
        subset of the larger, and last-listed-wins in codes_for would
        then erase the earlier fault entirely)."""
        fidx = self.faults.index(f)
        hit = np.zeros(len(ids), bool)
        for i, cid in enumerate(np.asarray(ids, np.int64)):
            u = np.random.default_rng(
                (self.seed, 0xFA, fidx, int(cid))).random()
            hit[i] = u < f.fraction
        return hit

    def codes_for(self, round_idx: int,
                  ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(codes, scales) aligned to the cohort `ids` for one round —
        the arrays the jitted wave program branches on. When several
        faults cover the same client for the same round, the LAST one
        listed wins (FaultPlan semantics)."""
        ids = np.asarray(ids, np.int64)
        codes = np.zeros((len(ids),), np.int32)
        scales = np.ones((len(ids),), np.float32)
        for f in self.active(round_idx):
            if f.clients is not None:
                hit = np.isin(ids, np.asarray(f.clients, np.int64))
            else:
                hit = self._in_fraction(f, ids)
            codes[hit] = _CODE[f.kind]
            scales[hit] = f.scale
        return codes, scales

    def staleness(self, round_idx: int) -> int:
        ks = [f.staleness for f in self.active(round_idx)
              if f.kind == "straggler"]
        return max(ks) if ks else 1

    @property
    def max_staleness(self) -> int:
        ks = [f.staleness for f in self.faults if f.kind == "straggler"]
        return max(ks) if ks else 0

    def delay_s(self, round_idx: int, ids: np.ndarray) -> np.ndarray:
        """[len(ids)] float64 completion delays for the cohort: a
        straggler at lag k completes k * delay_unit_s late; everyone
        else at 0. The sync streamed round sleeps max(delay) (the
        round barrier a synchronous protocol cannot avoid); the async
        buffered server instead sees the completion arrive late."""
        ids = np.asarray(ids, np.int64)
        delay = np.zeros((len(ids),), np.float64)
        if self.delay_unit_s == 0.0:
            return delay
        for f in self.active(round_idx):
            if f.kind != "straggler":
                continue
            if f.clients is not None:
                hit = np.isin(ids, np.asarray(f.clients, np.int64))
            else:
                hit = self._in_fraction(f, ids)
            delay[hit] = f.staleness * self.delay_unit_s
        return delay

    def __repr__(self) -> str:
        return (f"PopulationFaultPlan(population={self.population}, "
                f"faults={list(self.faults)!r}, seed={self.seed}, "
                f"delay_unit_s={self.delay_unit_s})")


POP_GRAMMAR = (
    "comma-separated kind:rounds[:param][@clients] groups; rounds = a "
    "single round, an inclusive a-b range, or a +-joined list; param = "
    "scale (optionally x-prefixed) for scale/sign_flip, staleness lag "
    "for straggler, or a population fraction like 0.1% for any kind; "
    "clients = @-attached comma-separated c-prefixed virtual ids "
    "(e.g. @c97,c4012)")


def parse_population_fault_spec(spec: str, population: int, *,
                                seed: int = 0,
                                delay_unit_s: float = 0.0
                                ) -> PopulationFaultPlan:
    """CLI grammar for population-addressable fault plans:

        "straggler:3-6:2@c97,c4012"   lag-2 stragglers on rounds 3-6,
                                      virtual clients 97 and 4012
        "crash:2:0.1%"                a seeded 0.1% of the population
                                      crashes on round 2
        "sign_flip:0-9:x1000@c5"      one x1000 sign-flip attacker

    Clients address the VIRTUAL population by c-prefixed id (the cohort
    sampler decides whether they participate in a given round); a
    trailing `%` param selects a seeded population fraction instead.
    Every parse failure teaches the grammar (`format_spec_error`)."""
    # client lists are comma-separated INSIDE a group ("@c97,c4012"), so
    # re-attach bare c<id> tokens to the group they continue before
    # parsing group-by-group
    groups: list[str] = []
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        if groups and _CLIENT_TOKEN.fullmatch(token):
            groups[-1] += "," + token
        else:
            groups.append(token)
    faults: list[PopulationFault] = []
    for group in groups:
        faults.append(_parse_population_group(group))
    return PopulationFaultPlan(population, faults, seed=seed,
                               delay_unit_s=delay_unit_s)


_CLIENT_TOKEN = re.compile(r"c\d+")


def _parse_population_group(group: str) -> PopulationFault:
    err = functools.partial(format_spec_error, group,
                            grammar=POP_GRAMMAR)
    clients: tuple[int, ...] | None = None
    body = group
    if "@" in group:
        body, client_field = group.split("@", 1)
        ids = []
        for tok in client_field.split(","):
            tok = tok.strip()
            if not _CLIENT_TOKEN.fullmatch(tok):
                raise ValueError(err(
                    f"bad client token {tok!r} (want c-prefixed "
                    f"virtual ids like c97)"))
            ids.append(int(tok[1:]))
        clients = tuple(ids)
    parts = [p.strip() for p in body.split(":")]
    if len(parts) not in (2, 3):
        raise ValueError(err("want kind:rounds[:param][@clients]"))
    kind = parts[0]
    if kind not in KINDS:
        raise ValueError(err(f"unknown fault kind {kind!r}"))
    rounds = (None if parts[1] == "*" else tuple(
        parse_id_field(parts[1], what="rounds", group=group,
                       grammar=POP_GRAMMAR)))
    kw: dict = {}
    fraction = None
    if len(parts) == 3:
        param = parts[2]
        if param.endswith("%"):
            try:
                fraction = float(param[:-1]) / 100.0
            except ValueError:
                raise ValueError(err(
                    f"bad fraction {param!r}")) from None
            if not 0.0 < fraction <= 1.0:
                raise ValueError(err(
                    f"fraction {param!r} must be in (0%, 100%]"))
        elif kind in ("scale", "sign_flip"):
            try:
                kw["scale"] = float(param.lstrip("x"))
            except ValueError:
                raise ValueError(err(
                    f"bad parameter {param!r} for kind "
                    f"{kind!r}")) from None
        elif kind == "straggler":
            try:
                kw["staleness"] = int(param)
            except ValueError:
                raise ValueError(err(
                    f"bad parameter {param!r} for kind "
                    f"{kind!r}")) from None
        else:
            raise ValueError(err(
                f"fault kind {kind!r} takes no parameter, got "
                f"{param!r} (a population fraction needs the % "
                f"suffix)"))
    if fraction is not None and clients is not None:
        raise ValueError(err(
            "give EITHER a fraction param OR an @clients list, "
            "not both"))
    if fraction is None and clients is None:
        raise ValueError(err(
            "population faults must name their targets: an @clients "
            "list (e.g. @c97,c4012) or a fraction param (e.g. 0.1%)"))
    try:
        return PopulationFault(kind, rounds=rounds, clients=clients,
                               fraction=fraction, **kw)
    except ValueError as e:
        raise ValueError(err(str(e))) from None


# ---------------------------------------------------------------------------
# Transient data-pipeline read failures
# ---------------------------------------------------------------------------


class TransientReadError(IOError):
    """An injected transient read failure (the retryable kind: NFS blip,
    object-store 5xx, preempted decode worker)."""


def flaky(fn: Callable, *, failure_rate: float, seed: int = 0,
          exception=TransientReadError) -> Callable:
    """Wrap a read callable so a seeded `failure_rate` fraction of calls
    raises `exception` BEFORE invoking `fn`. Which call indices fail is
    a pure function of (seed, index): two wrappers built with the same
    seed fail on exactly the same calls — deterministic chaos, so a
    pipeline-hardening test can replay its failure schedule."""
    if not 0.0 <= failure_rate <= 1.0:
        raise ValueError(f"failure_rate must be in [0, 1], got "
                         f"{failure_rate}")
    counter = {"i": 0}

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        i = counter["i"]
        counter["i"] += 1
        if np.random.default_rng((seed, i)).random() < failure_rate:
            raise exception(f"injected transient read failure "
                            f"(call {i}, seed {seed})")
        return fn(*args, **kwargs)

    return wrapped


def with_retries(fn: Callable, *, attempts: int = 3,
                 exceptions=(TransientReadError,)) -> Callable:
    """Retry `fn` up to `attempts` times on the given transient
    exceptions, re-raising the last failure — the consumer-side hook
    that turns an injected (or real) transient read failure into a
    bounded retry instead of a dead pipeline."""
    if attempts < 1:
        raise ValueError(f"need attempts >= 1, got {attempts}")

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        for attempt in range(attempts):
            try:
                return fn(*args, **kwargs)
            except exceptions:
                if attempt == attempts - 1:
                    raise
        raise AssertionError("unreachable")

    return wrapped
