"""Training-curve plot artifact.

Parity with the reference's `log()` (SURVEY.md C18,
dist_model_tf_vgg.py:67-101): concatenate phase-1 + phase-2 accuracy/loss
histories, draw a 2-panel figure with a "Start Fine Tuning" marker at the
phase boundary, and save it to `<path>/logs/plot_dev<N>.png`. The raw
history dicts are printed by the caller (the reference prints them at
dist_model_tf_vgg.py:100-101); the jsonl log carries the same numbers in
structured form.
"""

from __future__ import annotations

import os
from pathlib import Path


def plot_history(path: str | os.PathLike, history: dict,
                 history_fine: dict | None, num_devices: int,
                 *, initial_epochs: int | None = None) -> str:
    """Save the 2-panel acc/loss figure; returns the written file path."""
    # Force the headless backend BEFORE this function's pyplot import:
    # on a display-less CI container an interactive default backend
    # raises at pyplot import time. The env var (honored at matplotlib
    # import) + use(force=True) (re-selects even if someone imported
    # pyplot first) together make plotting display-independent —
    # scoped HERE, not at module import, so merely importing the
    # library never mutates the process environment for an embedding
    # application's own matplotlib use. setdefault keeps an explicit
    # user choice.
    os.environ.setdefault("MPLBACKEND", "Agg")
    import matplotlib

    matplotlib.use("Agg", force=True)
    import matplotlib.pyplot as plt

    acc = list(history.get("accuracy", []))
    val_acc = list(history.get("val_accuracy", []))
    loss = list(history.get("loss", []))
    val_loss = list(history.get("val_loss", []))
    boundary = initial_epochs if initial_epochs is not None else len(acc)
    if history_fine:
        acc += list(history_fine.get("accuracy", []))
        val_acc += list(history_fine.get("val_accuracy", []))
        loss += list(history_fine.get("loss", []))
        val_loss += list(history_fine.get("val_loss", []))

    out_dir = Path(path) / "logs"
    out_dir.mkdir(parents=True, exist_ok=True)
    out = out_dir / f"plot_dev{num_devices}.png"

    fig, (ax1, ax2) = plt.subplots(2, 1, figsize=(8, 8))
    ax1.plot(acc, label="Training Accuracy")
    ax1.plot(val_acc, label="Validation Accuracy")
    if history_fine:
        ax1.axvline(boundary - 0.5, color="k", linestyle="--",
                    label="Start Fine Tuning")
    ax1.legend(loc="lower right")
    ax1.set_title("Training and Validation Accuracy")

    ax2.plot(loss, label="Training Loss")
    ax2.plot(val_loss, label="Validation Loss")
    if history_fine:
        ax2.axvline(boundary - 0.5, color="k", linestyle="--",
                    label="Start Fine Tuning")
    ax2.legend(loc="upper right")
    ax2.set_title("Training and Validation Loss")
    ax2.set_xlabel("epoch")

    fig.savefig(out, dpi=100, bbox_inches="tight")
    plt.close(fig)
    return str(out)
