"""Process-wide metrics registry: labeled counters, gauges, histograms.

The serving, federated, and training loops each grew their own counter
piles (`serve/metrics.py` lists, `federated/driver.py` health events,
`train/loop.py` history dicts). Those stay — their jsonl schemas are a
compatibility contract — but operational state ("how many rounds
failed", "how many XLA compiles did admission trigger", "what is the
slot occupancy RIGHT NOW") belongs in one process-wide registry with
two standard export surfaces:

- `snapshot()` / `log_snapshot(logger)` — plain-JSON records, appended
  to the same jsonl stream every loop already writes.
- `prometheus_text()` — the Prometheus text exposition format, so a
  scrape endpoint (or a file-based textfile collector) needs zero
  translation.

Instruments are created idempotently: `registry.counter("x", ...)`
returns the SAME instrument every call (and raises if the name was
registered as a different type), so call sites never coordinate
construction. Everything is lock-guarded and cheap enough for per-tick
use; per-TOKEN paths should aggregate first.

`REGISTRY` is the process default — module-level, like the compiled
program caches in `models/lm.py` — and `MetricsRegistry()` instances
can be built standalone for tests.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from pathlib import Path

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

# generic latency-seconds buckets (sub-ms dispatch through multi-second
# rounds); override per-histogram when the domain is known
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r} (want "
                         f"[a-zA-Z_:][a-zA-Z0-9_:]*)")
    return name


def _label_key(label_names: tuple, labels: dict) -> tuple:
    if set(labels) != set(label_names):
        raise ValueError(f"labels {sorted(labels)} != declared "
                         f"{sorted(label_names)}")
    return tuple(str(labels[k]) for k in label_names)


def _escape(v: str) -> str:
    return (v.replace("\\", "\\\\").replace("\"", "\\\"")
            .replace("\n", "\\n"))


class _Instrument:
    """Shared base: name, help text, declared label names, and the
    per-label-set value table (lock-guarded)."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: tuple):
        self.name = _check_name(name)
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._values: dict[tuple, object] = {}

    def _series(self) -> list[tuple[dict, object]]:
        # histogram values are MUTABLE dicts observe() updates in place
        # — copy them (buckets list included) while still holding the
        # lock, or an export racing an observe() could emit a _count
        # that disagrees with its own _sum/_bucket increments
        with self._lock:
            items = [(key, {**val, "buckets": list(val["buckets"])}
                      if isinstance(val, dict) else val)
                     for key, val in self._values.items()]
        return [(dict(zip(self.label_names, key)), val)
                for key, val in items]


class Counter(_Instrument):
    """Monotonically increasing count. `inc(amount, **labels)`."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up, got inc({amount})")
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return float(self._values.get(key, 0.0))


class Gauge(_Instrument):
    """Point-in-time value. `set(v, **labels)` / `inc` / `dec`."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, default: float | None = 0.0, **labels):
        """Current value for the label set; `default` when the gauge
        was never set — pass default=None to distinguish unset from 0
        (e.g. a health surface reporting null before the first tick)."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            if key not in self._values:
                return default
            return float(self._values[key])


class Histogram(_Instrument):
    """Bucketed distribution: per-label-set bucket counts + sum + count
    (+ min/max, carried into snapshots — Prometheus text omits them by
    format design)."""

    kind = "histogram"

    def __init__(self, name: str, help: str, label_names: tuple,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, label_names)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs or any(not math.isfinite(b) for b in bs):
            raise ValueError(f"need finite, non-empty buckets, got "
                             f"{buckets}")
        self.buckets = bs

    def observe(self, value: float, **labels) -> None:
        key = _label_key(self.label_names, labels)
        v = float(value)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = self._values[key] = {
                    "buckets": [0] * len(self.buckets),
                    "count": 0, "sum": 0.0, "min": v, "max": v}
            st["count"] += 1
            st["sum"] += v
            st["min"] = min(st["min"], v)
            st["max"] = max(st["max"], v)
            for i, b in enumerate(self.buckets):
                if v <= b:
                    st["buckets"][i] += 1
                    break
            # values above the top bucket land only in +Inf (= count)

    def merge_state(self, state: dict, **labels) -> None:
        """Fold one exported series state (`_series()`'s value shape:
        raw per-bucket counts plus count/sum/min/max) into this
        histogram's series for `labels` — the fleet-merge path
        (serve/cluster/telemetry.py) relabels a whole per-replica
        histogram in one call instead of replaying every observation.
        Bucket layouts must match: a merged distribution across two
        grids has no honest bucket counts."""
        if len(state["buckets"]) != len(self.buckets):
            raise ValueError(
                f"histogram {self.name!r}: cannot merge a series with "
                f"{len(state['buckets'])} buckets into {len(self.buckets)}")
        key = _label_key(self.label_names, labels)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                self._values[key] = {
                    "buckets": list(state["buckets"]),
                    "count": state["count"], "sum": state["sum"],
                    "min": state["min"], "max": state["max"]}
                return
            st["buckets"] = [a + b for a, b in
                             zip(st["buckets"], state["buckets"])]
            st["count"] += state["count"]
            st["sum"] += state["sum"]
            st["min"] = min(st["min"], state["min"])
            st["max"] = max(st["max"], state["max"])


class MetricsRegistry:
    """Name -> instrument table with idempotent registration and the
    two export surfaces (json snapshot, Prometheus text)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[str, _Instrument] = {}

    def _get_or_make(self, cls, name, help, labels, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if type(inst) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{inst.kind}, not {cls.kind}")
                if tuple(labels) != inst.label_names:
                    raise ValueError(
                        f"metric {name!r} already registered with "
                        f"labels {inst.label_names}, not {tuple(labels)}")
                # every registration knob conflicts loudly, buckets
                # included — a second caller silently getting different
                # buckets would file all its observations into +Inf
                want = kw.get("buckets")
                if (want is not None and tuple(sorted(
                        float(b) for b in want)) != inst.buckets):
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {inst.buckets}, not {tuple(want)}")
                return inst
            inst = cls(name, help, tuple(labels), **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "",
                labels: tuple = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: tuple = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels: tuple = (),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(Histogram, name, help, labels,
                                 buckets=buckets)

    def get(self, name: str) -> _Instrument | None:
        """The registered instrument, or None — the read-only lookup
        surfaces like the /healthz endpoint use (they must not CREATE
        a metric whose owner simply has not registered yet)."""
        with self._lock:
            return self._instruments.get(name)

    def instruments(self) -> list[_Instrument]:
        with self._lock:
            return [self._instruments[k]
                    for k in sorted(self._instruments)]

    # -- export ----------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """Every series as one plain-JSON record: counters/gauges carry
        `value`; histograms carry count/sum/min/max plus cumulative
        bucket counts keyed by upper bound."""
        out = []
        for inst in self.instruments():
            for labels, val in inst._series():
                rec = {"name": inst.name, "type": inst.kind,
                       "labels": labels}
                if inst.kind == "histogram":
                    cum, acc = {}, 0
                    for b, n in zip(inst.buckets, val["buckets"]):
                        acc += n
                        cum[str(b)] = acc
                    cum["+Inf"] = val["count"]
                    rec.update(count=val["count"],
                               sum=round(val["sum"], 6),
                               min=val["min"], max=val["max"],
                               buckets=cum)
                else:
                    rec["value"] = val
                out.append(rec)
        return out

    def log_snapshot(self, logger, **extra) -> None:
        """Append the snapshot to a `JsonlLogger` as ONE
        `metrics_snapshot` record — a new event type; no existing
        record schema changes."""
        logger.log(event="metrics_snapshot", metrics=self.snapshot(),
                   **extra)

    def write_snapshot(self, path) -> str:
        """Standalone jsonl snapshot file (one series per line, plus a
        timestamp header) for runs without a logger."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            f.write(json.dumps({"event": "metrics_header",
                                "ts": time.time()}) + "\n")
            for rec in self.snapshot():
                f.write(json.dumps(rec) + "\n")
        return str(path)

    def prometheus_text(self) -> str:
        """The Prometheus text exposition format (one HELP/TYPE pair
        per metric, histogram `_bucket{le=...}`/`_sum`/`_count`
        series with cumulative counts)."""
        lines: list[str] = []
        for inst in self.instruments():
            if inst.help:
                lines.append(f"# HELP {inst.name} {inst.help}")
            lines.append(f"# TYPE {inst.name} {inst.kind}")
            for labels, val in inst._series():
                base = ",".join(f'{k}="{_escape(v)}"'
                                for k, v in labels.items())
                if inst.kind != "histogram":
                    lbl = f"{{{base}}}" if base else ""
                    lines.append(f"{inst.name}{lbl} {_fmt(val)}")
                    continue
                acc = 0
                for b, n in zip(inst.buckets, val["buckets"]):
                    acc += n
                    le = ",".join(x for x in (base, f'le="{_fmt(b)}"')
                                  if x)
                    lines.append(f"{inst.name}_bucket{{{le}}} {acc}")
                le = ",".join(x for x in (base, 'le="+Inf"') if x)
                lines.append(f"{inst.name}_bucket{{{le}}} "
                             f"{val['count']}")
                lbl = f"{{{base}}}" if base else ""
                lines.append(f"{inst.name}_sum{lbl} {_fmt(val['sum'])}")
                lines.append(f"{inst.name}_count{lbl} {val['count']}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: float) -> str:
    f = float(v)
    if not math.isfinite(f):
        # Prometheus's legal sample spellings — one bad value must not
        # take the whole exposition down with an int() OverflowError
        return "NaN" if math.isnan(f) else ("+Inf" if f > 0 else "-Inf")
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


# the process-wide default registry every instrumented loop shares
REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return REGISTRY
