"""Runtime span tracing: nested, thread-safe, exportable to Perfetto.

The reference's entire timing story is a copy-pasted `Timer` print
(SURVEY.md §5/C17); the framework's hot paths — the serve scheduler's
admit/window/collect cycle, chunked prefills, federated round attempts,
training epochs — need to answer "where did this token/round actually
spend its time" without each loop growing its own ad-hoc stopwatch.

One `Tracer` records SPANS: named intervals with a process-unique id, a
parent id (the innermost open span on the same thread), per-span
attributes, and both clocks — a monotonic offset for durations and a
wall-clock anchor so traces line up with jsonl logs. Two export
formats:

- `export_jsonl(path)` — one record per span, the same append-only
  shape every other run log in the framework uses.
- `export_chrome(path)` — Chrome trace-event JSON (`ph:"X"` complete
  events, microsecond `ts`/`dur`), loadable directly in Perfetto /
  `chrome://tracing`.

The DISABLED mode is the production default and must cost ~nothing:
`span()` with no active tracer returns a shared no-op handle — one
global read, no allocation beyond the caller's kwargs. `bench.py`
(`bench_tracer_overhead`) gates this on the serve decode hot loop.

Instrumented call sites use the module-level helper:

    from idc_models_tpu.observe import trace
    with trace.span("serve.collect", tokens=n):
        ...

and a run opts in by installing a tracer (`tracing(...)` context or
`set_tracer`), e.g. the CLI's `--trace-out trace.json`.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from pathlib import Path


class _NullSpan:
    """The disabled-mode handle: every operation is a no-op. A single
    shared instance serves every call site, so tracing-off costs one
    module-global read per span."""

    __slots__ = ()

    # detached-span callers hand `handle.span_id` straight back as a
    # `parent=`; None is the "no parent" value on both sides, so the
    # disabled path needs no branches at the call sites
    span_id = None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def close(self, **attrs) -> None:
        return None

    def set(self, **attrs) -> "_NullSpan":
        return self


_NULL_SPAN = _NullSpan()


class Span:
    """One open interval. Use as a context manager (via `Tracer.span` or
    the module-level `span()`); `set(**attrs)` attaches attributes any
    time before exit."""

    __slots__ = ("name", "span_id", "parent_id", "tid", "attrs",
                 "_tracer", "_t0", "_stack", "_detached", "dur_s")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self._tracer = tracer
        self.span_id = next(tracer._ids)
        self.parent_id = None
        self.tid = 0
        self._t0 = 0.0
        self._stack = None
        self._detached = False
        self.dur_s = 0.0

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        tr = self._tracer
        # the OPENING thread's stack is captured on the span so an
        # exotic exit (closed on a different thread) still removes the
        # span from the stack it actually sits on — popping the closing
        # thread's stack instead would leave it dangling and corrupt
        # the parenting of every later span on the opening thread
        stack = self._stack = tr._stack()
        self.parent_id = stack[-1].span_id if stack else None
        self.tid = threading.get_ident()
        stack.append(self)
        # the clock read is LAST on entry (and first on exit) so nested
        # spans exclude as much of the tracer's own bookkeeping as
        # possible from their measured interval
        self._t0 = tr._clock()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        tr = self._tracer
        t1 = tr._clock()
        self.dur_s = t1 - self._t0
        stack = self._stack
        if stack and stack[-1] is self:
            stack.pop()
        elif stack and self in stack:
            stack.remove(self)
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        with tr._lock:
            tr._spans.append(self)

    def close(self, **attrs) -> None:
        """Finalize a DETACHED span (see `Tracer.start_span`). Safe to
        call more than once — only the first close records — and a
        no-op on any non-detached span: one a with-block manages (it
        already records) or one created but never entered (closing it
        would record a garbage interval timed from t0=0)."""
        if not self._detached or self._stack is not None:
            return
        if attrs:
            self.attrs.update(attrs)
        self.__exit__(None, None, None)
        self._stack = ()


class Tracer:
    """Collects finished spans; thread-safe (each thread keeps its own
    open-span stack, the finished list is lock-guarded). `clock` is the
    monotonic duration clock; wall time is anchored once at
    construction so exported timestamps can be mapped to epoch time."""

    def __init__(self, *, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._local = threading.local()
        self._ids = itertools.count(1)
        self._spans: list[Span] = []
        self.wall_t0 = time.time()
        self.mono_t0 = clock()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def start_span(self, name: str, parent=None, **attrs) -> Span:
        """A DETACHED span: opened now, finalized by `close()`, never on
        any thread's open-span stack. Parenting is explicit (`parent` is
        another span's id, or None for top-level) — the handle for
        logical intervals that outlive any one call frame, e.g. a serve
        request's whole submit→finish lifetime spanning many scheduler
        ticks (a stack-entered span held open that long would corrupt
        the parenting of every tick span under it)."""
        s = Span(self, name, attrs)
        s.parent_id = parent
        s.tid = threading.get_ident()
        s._detached = True
        s._t0 = self._clock()
        return s

    def point(self, name: str, parent=None, **attrs) -> Span:
        """A zero-duration marker span recorded immediately — lifecycle
        events (first token, a finish) inside a detached span chain."""
        s = self.start_span(name, parent, **attrs)
        s.close()
        return s

    def finished(self) -> list[Span]:
        """Snapshot of the finished spans (open spans are excluded —
        they have no duration yet)."""
        with self._lock:
            return list(self._spans)

    # -- export ----------------------------------------------------------

    def records(self) -> list[dict]:
        """Finished spans as plain dicts: `t_ms` is the start offset
        from the tracer's epoch (monotonic), `wall` the corresponding
        wall-clock epoch seconds."""
        out = []
        for s in self.finished():
            start = s._t0 - self.mono_t0
            out.append({
                "event": "span", "name": s.name, "id": s.span_id,
                "parent": s.parent_id, "tid": s.tid,
                "t_ms": round(start * 1e3, 4),
                "dur_ms": round(s.dur_s * 1e3, 4),
                "wall": round(self.wall_t0 + start, 6),
                "attrs": dict(s.attrs),
            })
        out.sort(key=lambda r: r["t_ms"])
        return out

    def export_jsonl(self, path) -> str:
        """One span record per line — the framework's run-log shape, so
        `stats` summarizes traces with the same code as any run jsonl."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            for rec in self.records():
                f.write(json.dumps(rec) + "\n")
        return str(path)

    def export_chrome(self, path) -> str:
        """Chrome trace-event JSON: `ph:"X"` complete events with
        microsecond `ts`/`dur` (Perfetto's expectations), one event per
        finished span, plus a process-name metadata record."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        pid = os.getpid()
        events: list[dict] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": "idc_models_tpu"},
        }]
        for rec in self.records():
            events.append({
                "name": rec["name"], "ph": "X", "pid": pid,
                "tid": rec["tid"],
                "ts": round(rec["t_ms"] * 1e3, 3),
                "dur": round(rec["dur_ms"] * 1e3, 3),
                "args": {**rec["attrs"], "span_id": rec["id"],
                         "parent_id": rec["parent"]},
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"},
                      f)
        return str(path)


# -- the process-wide active tracer ----------------------------------------

_ACTIVE: Tracer | None = None


def set_tracer(tracer: Tracer | None) -> Tracer | None:
    """Install `tracer` as the process-wide active tracer; returns the
    previous one (restore it when your scope ends)."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, tracer
    return prev


def get_tracer() -> Tracer | None:
    return _ACTIVE


def span(name: str, **attrs):
    """A span on the active tracer — or the shared no-op handle when
    tracing is disabled. THE instrumentation entry point for every hot
    path; its disabled cost is gated by `bench_tracer_overhead`."""
    tr = _ACTIVE
    if tr is None:
        return _NULL_SPAN
    return Span(tr, name, attrs)


def start_span(name: str, parent=None, **attrs):
    """A DETACHED span on the active tracer (see `Tracer.start_span`) —
    or the shared no-op handle when tracing is disabled. The entry
    point for request-lifecycle spans that outlive any call frame; the
    no-op handle's `span_id` is None, which is also the "no parent"
    value, so chained call sites need no enabled/disabled branches."""
    tr = _ACTIVE
    if tr is None:
        return _NULL_SPAN
    return tr.start_span(name, parent, **attrs)


def point(name: str, parent=None, **attrs):
    """A zero-duration marker on the active tracer — or the shared
    no-op handle when tracing is disabled."""
    tr = _ACTIVE
    if tr is None:
        return _NULL_SPAN
    return tr.point(name, parent, **attrs)


@contextlib.contextmanager
def tracing(chrome_path=None, jsonl_path=None, tracer: Tracer | None = None):
    """Install a tracer for the enclosed block and export on exit.
    With neither export path nor an explicit tracer this is a true
    no-op (call sites can be unconditional, like `profile_trace`).
    Yields the active tracer (or None when disabled)."""
    if chrome_path is None and jsonl_path is None and tracer is None:
        yield None
        return
    tr = tracer if tracer is not None else Tracer()
    prev = set_tracer(tr)
    try:
        yield tr
    finally:
        set_tracer(prev)
        if chrome_path is not None:
            tr.export_chrome(chrome_path)
        if jsonl_path is not None:
            tr.export_jsonl(jsonl_path)
