"""Offline run-log summarizer — the `stats` CLI subcommand's engine.

Every loop in the framework writes the same append-only jsonl record
shape (`observe.JsonlLogger`): train epochs, federated rounds and
round_health attempts, serve_* request events, timer records, span
exports, metrics snapshots. This module reads ANY of those files and
rolls it up offline: per-event counts, percentiles over every numeric
field, named timer/span timing tables, and the last metrics snapshot —
so "what did this run spend its time on" is one command against the
artifact, no re-run needed.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

# fields that are identifiers/timestamps, not measurements
_SKIP_FIELDS = {"ts", "id", "round", "attempt", "epoch", "step", "seed",
                "parent", "tid", "wall", "t_ms"}


def _num_stats(values: list[float]) -> dict:
    a = np.asarray(values, np.float64)
    return {
        "count": int(a.size),
        "mean": round(float(a.mean()), 4),
        "p50": round(float(np.percentile(a, 50)), 4),
        "p95": round(float(np.percentile(a, 95)), 4),
        "min": round(float(a.min()), 4),
        "max": round(float(a.max()), 4),
    }


def summarize_jsonl(path) -> dict:
    """Parse a run jsonl into the summary dict `format_summary` prints.
    Unparseable lines are counted, never fatal (a crash mid-write can
    truncate the final line of an append-only log)."""
    path = Path(path)
    records, bad = [], 0
    for line in path.read_text().splitlines():
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            bad += 1
    by_event: dict[str, dict] = {}
    timers: dict[str, list[float]] = {}
    spans: dict[str, list[float]] = {}
    last_snapshot = None
    ts = [r["ts"] for r in records
          if isinstance(r.get("ts"), (int, float))]
    for r in records:
        event = str(r.get("event", r.get("kind", "<none>")))
        slot = by_event.setdefault(event, {"count": 0, "fields": {}})
        slot["count"] += 1
        for k, v in r.items():
            if (k in _SKIP_FIELDS or k == "event"
                    or isinstance(v, bool)
                    or not isinstance(v, (int, float))):
                continue
            slot["fields"].setdefault(k, []).append(float(v))
        if event == "timer" and isinstance(r.get("seconds"),
                                           (int, float)):
            timers.setdefault(str(r.get("name")), []).append(
                float(r["seconds"]))
        if event == "span" and isinstance(r.get("dur_ms"),
                                          (int, float)):
            spans.setdefault(str(r.get("name")), []).append(
                float(r["dur_ms"]))
        if event == "metrics_snapshot":
            last_snapshot = r.get("metrics")
    events = {
        ev: {"count": slot["count"],
             "fields": {k: _num_stats(vs)
                        for k, vs in sorted(slot["fields"].items())}}
        for ev, slot in sorted(by_event.items())}
    return {
        "path": str(path),
        "records": len(records),
        "unparseable_lines": bad,
        "wall_span_s": (round(max(ts) - min(ts), 3) if len(ts) >= 2
                        else None),
        "events": events,
        "timers": {n: _num_stats(vs) for n, vs in sorted(timers.items())},
        "spans": {n: {**_num_stats(vs),
                      "total_ms": round(float(np.sum(vs)), 3)}
                  for n, vs in sorted(spans.items())},
        "metrics": last_snapshot,
    }


def format_summary(s: dict) -> str:
    """Human terminal rendering of `summarize_jsonl`'s dict."""
    out = [f"{s['path']}: {s['records']} records"
           + (f" ({s['unparseable_lines']} unparseable)"
              if s["unparseable_lines"] else "")
           + (f", {s['wall_span_s']}s wall span"
              if s["wall_span_s"] is not None else "")]
    out.append("")
    out.append("events:")
    for ev, slot in s["events"].items():
        out.append(f"  {ev:24s} x{slot['count']}")
        for k, st in slot["fields"].items():
            out.append(
                f"    {k:24s} mean={st['mean']} p50={st['p50']} "
                f"p95={st['p95']} min={st['min']} max={st['max']}")
    if s["timers"]:
        out.append("")
        out.append("timers (seconds):")
        for name, st in s["timers"].items():
            out.append(f"  {name:40s} x{st['count']} mean={st['mean']} "
                       f"p95={st['p95']}")
    if s["spans"]:
        out.append("")
        out.append("spans (ms):")
        for name, st in s["spans"].items():
            out.append(f"  {name:28s} x{st['count']} "
                       f"total={st['total_ms']} mean={st['mean']} "
                       f"p50={st['p50']} p95={st['p95']}")
    if s["metrics"]:
        out.append("")
        out.append("last metrics snapshot:")
        for rec in s["metrics"]:
            lbl = ("{" + ",".join(f"{k}={v}" for k, v in
                                  sorted(rec["labels"].items())) + "}"
                   if rec.get("labels") else "")
            if rec["type"] == "histogram":
                out.append(f"  {rec['name']}{lbl} count={rec['count']} "
                           f"sum={rec['sum']} min={rec['min']} "
                           f"max={rec['max']}")
            else:
                out.append(f"  {rec['name']}{lbl} = {rec['value']}")
    return "\n".join(out)
