"""Offline run-log summarizer — the `stats` CLI subcommand's engine.

Every loop in the framework writes the same append-only jsonl record
shape (`observe.JsonlLogger`): train epochs, federated rounds and
round_health attempts, serve_* request events, timer records, span
exports, metrics snapshots. This module reads ANY of those files and
rolls it up offline: per-event counts, percentiles over every numeric
field, named timer/span timing tables, the last metrics snapshot, and
PER-REQUEST timelines (every serve_* event and every rid-stamped span
grouped by request id, time-ordered — the `stats --request RID` view)
— so "what did this run spend its time on" and "what happened to
request X" are one command against the artifact, no re-run needed.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

# fields that are identifiers/timestamps, not measurements
_SKIP_FIELDS = {"ts", "id", "round", "attempt", "epoch", "step", "seed",
                "parent", "tid", "wall", "t_ms"}


def _num_stats(values: list[float]) -> dict:
    a = np.asarray(values, np.float64)
    return {
        "count": int(a.size),
        "mean": round(float(a.mean()), 4),
        "p50": round(float(np.percentile(a, 50)), 4),
        "p95": round(float(np.percentile(a, 95)), 4),
        "min": round(float(a.min()), 4),
        "max": round(float(a.max()), 4),
    }


def summarize_jsonl(path) -> dict:
    """Parse a run jsonl into the summary dict `format_summary` prints.
    Accepts one path or a list of paths — the CLUSTER case: the router
    and each replica write their own files, and merging them here is
    what turns N per-process logs into one fleet view (`JsonlLogger`
    stamps epoch-seconds ``ts`` and span exports epoch ``wall``, so
    records from different processes share one time axis and the
    per-request timelines sort correctly across files). Unparseable
    lines are counted, never fatal (a crash mid-write can truncate the
    final line of an append-only log)."""
    paths = ([Path(p) for p in path]
             if isinstance(path, (list, tuple)) else [Path(path)])
    records, bad = [], 0
    # files concatenate in argument order (NOT globally re-sorted):
    # span self-time segmentation depends on each tracer's records
    # staying contiguous; the timelines sort by wall time themselves
    for p in paths:
        for line in p.read_text().splitlines():
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                bad += 1
    path = paths[0] if len(paths) == 1 else "+".join(map(str, paths))
    by_event: dict[str, dict] = {}
    timers: dict[str, list[float]] = {}
    spans: dict[str, list[float]] = {}
    programs: list[dict] = []
    profile_steps: list[dict] = []
    fed_cohorts: list[dict] = []
    tenants: dict[str, dict] = {}
    ckpt = {"saves": 0, "save_bytes": 0, "save_seconds": 0.0,
            "restores": 0, "restore_bytes": 0, "restore_seconds": 0.0,
            "restore_peak_host_bytes": 0}
    rollouts: list[dict] = []
    cc = {"hits": 0, "misses": 0, "stores": 0, "evicted_corrupt": 0,
          "deserialize_ms": 0.0, "compile_ms": 0.0}
    last_snapshot = None
    ts = [r["ts"] for r in records
          if isinstance(r.get("ts"), (int, float))]
    for r in records:
        event = str(r.get("event", r.get("kind", "<none>")))
        slot = by_event.setdefault(event, {"count": 0, "fields": {}})
        slot["count"] += 1
        for k, v in r.items():
            if (k in _SKIP_FIELDS or k == "event"
                    or isinstance(v, bool)
                    or not isinstance(v, (int, float))):
                continue
            slot["fields"].setdefault(k, []).append(float(v))
        if event == "timer" and isinstance(r.get("seconds"),
                                           (int, float)):
            timers.setdefault(str(r.get("name")), []).append(
                float(r["seconds"]))
        if event == "span" and isinstance(r.get("dur_ms"),
                                          (int, float)):
            spans.setdefault(str(r.get("name")), []).append(
                float(r["dur_ms"]))
        if event == "metrics_snapshot":
            last_snapshot = r.get("metrics")
        if event == "profile_program":
            programs.append({k: v for k, v in r.items()
                             if k not in ("ts", "event")})
        if event == "profile_step":
            profile_steps.append({k: v for k, v in r.items()
                                  if k not in ("ts", "event")})
        if event == "fed_cohort":
            fed_cohorts.append({k: v for k, v in r.items()
                                if k not in ("ts", "event")})
        if event == "serve_tenant_finish":
            slot_t = _tenant_slot(tenants, r)
            slot_t["requests"] += 1
            slot_t["tokens"] += int(r.get("tokens") or 0)
            reason = str(r.get("reason"))
            slot_t["by_reason"][reason] = (
                slot_t["by_reason"].get(reason, 0) + 1)
            if isinstance(r.get("ttft_ms"), (int, float)):
                slot_t["ttft_ms"].append(float(r["ttft_ms"]))
        if event == "serve_tenant_shed":
            _tenant_slot(tenants, r)["shed"] += 1
        if event == "serve_tenant_quota_reject":
            _tenant_slot(tenants, r)["quota_rejections"] += 1
        # sharded checkpoint + weight rollout (ISSUE 17): byte/second
        # totals for the transfer events, the raw transition list for
        # the rollout state machine (serve-level and cluster-level)
        if event == "ckpt_save":
            ckpt["saves"] += 1
            ckpt["save_bytes"] += int(r.get("bytes") or 0)
            ckpt["save_seconds"] += float(r.get("seconds") or 0.0)
        if event == "ckpt_restore":
            ckpt["restores"] += 1
            ckpt["restore_bytes"] += int(r.get("bytes_read") or 0)
            ckpt["restore_seconds"] += float(r.get("seconds") or 0.0)
            ckpt["restore_peak_host_bytes"] = max(
                ckpt["restore_peak_host_bytes"],
                int(r.get("peak_host_bytes") or 0))
        if event in ("serve_rollout", "cluster_rollout"):
            rollouts.append(
                {k: r.get(k) for k in
                 ("event", "stage", "outcome", "reason",
                  "canary_requests", "replica")
                 if r.get(k) is not None})
        # persistent compile cache (PR 18, serve/compile_cache.py):
        # warm-vs-cold spin-up totals — an evict_corrupt already counts
        # itself as a miss at the source, mirrored here
        if event == "compile_cache":
            o = r.get("outcome")
            if o == "hit":
                cc["hits"] += 1
                cc["deserialize_ms"] += float(
                    r.get("deserialize_ms") or 0.0)
            elif o == "store":
                cc["stores"] += 1
                cc["compile_ms"] += float(r.get("compile_ms") or 0.0)
            elif o == "miss":
                cc["misses"] += 1
            elif o == "evict_corrupt":
                cc["evicted_corrupt"] += 1
                cc["misses"] += 1
    events = {
        ev: {"count": slot["count"],
             "fields": {k: _num_stats(vs)
                        for k, vs in sorted(slot["fields"].items())}}
        for ev, slot in sorted(by_event.items())}
    return {
        "path": str(path),
        "records": len(records),
        "unparseable_lines": bad,
        "wall_span_s": (round(max(ts) - min(ts), 3) if len(ts) >= 2
                        else None),
        "events": events,
        "timers": {n: _num_stats(vs) for n, vs in sorted(timers.items())},
        "spans": {n: {**_num_stats(vs),
                      "total_ms": round(float(np.sum(vs)), 3)}
                  for n, vs in sorted(spans.items())},
        "span_self": _span_self_times(records),
        "programs": programs,
        "profile_steps": profile_steps,
        "fed_cohorts": fed_cohorts,
        # per-tenant rollup from the serve_tenant_* events (ISSUE 14):
        # ttft_ms collapses to percentiles here, shed/quota counts ride
        # along — the offline twin of summary()["serve_tenants"]
        "tenants": {
            t: {"requests": v["requests"], "tokens": v["tokens"],
                "ttft_ms_p50": (round(float(np.percentile(
                    v["ttft_ms"], 50)), 2) if v["ttft_ms"] else None),
                "ttft_ms_p95": (round(float(np.percentile(
                    v["ttft_ms"], 95)), 2) if v["ttft_ms"] else None),
                "by_reason": v["by_reason"], "shed": v["shed"],
                "quota_rejections": v["quota_rejections"]}
            for t, v in sorted(tenants.items())},
        # checkpoint traffic totals (None when the run never saved or
        # restored — the key set stays stable either way) and the
        # rollout transition list, in file order
        "checkpoints": (
            {"saves": ckpt["saves"],
             "save_bytes": ckpt["save_bytes"],
             "save_mb_per_s": (
                 round(ckpt["save_bytes"] / 2**20
                       / ckpt["save_seconds"], 2)
                 if ckpt["save_seconds"] > 0 else None),
             "restores": ckpt["restores"],
             "restore_bytes": ckpt["restore_bytes"],
             "restore_mb_per_s": (
                 round(ckpt["restore_bytes"] / 2**20
                       / ckpt["restore_seconds"], 2)
                 if ckpt["restore_seconds"] > 0 else None),
             "restore_peak_host_bytes":
                 ckpt["restore_peak_host_bytes"]}
            if ckpt["saves"] or ckpt["restores"] else None),
        "rollouts": rollouts,
        # compile-cache totals (None when the run never touched one —
        # the key set stays stable either way)
        "compile_cache": (
            {**cc, "deserialize_ms": round(cc["deserialize_ms"], 3),
             "compile_ms": round(cc["compile_ms"], 3)}
            if cc["hits"] or cc["misses"] or cc["stores"] else None),
        "metrics": last_snapshot,
        "requests": _request_timelines(records),
    }


def _tenant_slot(tenants: dict, record: dict) -> dict:
    """Get-or-create one tenant's accumulator — the ONE definition of
    its field set, so the three serve_tenant_* event handlers cannot
    drift."""
    return tenants.setdefault(
        str(record.get("tenant")),
        {"requests": 0, "tokens": 0, "ttft_ms": [], "by_reason": {},
         "shed": 0, "quota_rejections": 0})


def _span_self_times(records: list[dict]) -> dict:
    """Per-span-name EXCLUSIVE time: each span's duration minus the
    durations of its direct children — the flame-graph "where does the
    time actually go" answer, computable from any span jsonl export
    (the `stats --top N` table). Inclusive totals double-count nested
    work (serve.tick contains admit+collect+window); self time sums to
    the traced wall instead."""
    spans = [r for r in records
             if r.get("event") == "span"
             and isinstance(r.get("dur_ms"), (int, float))
             and r.get("id") is not None]
    # span ids are unique within ONE tracer but restart per process, and
    # append-mode run logs can hold several runs — a repeated id marks a
    # new run SEGMENT, and parent links never cross segments, so child
    # sums are computed per segment (joining by raw id across the whole
    # file would subtract one run's children from another run's parents)
    segments: list[list[dict]] = []
    seen: set = set()
    for r in spans:
        if not segments or r["id"] in seen:
            segments.append([])
            seen = set()
        seen.add(r["id"])
        segments[-1].append(r)
    out: dict[str, dict] = {}
    for seg in segments:
        child_sum: dict[object, float] = {}
        for r in seg:
            p = r.get("parent")
            if p is not None:
                child_sum[p] = child_sum.get(p, 0.0) + r["dur_ms"]
        for r in seg:
            name = str(r.get("name"))
            self_ms = max(r["dur_ms"] - child_sum.get(r["id"], 0.0),
                          0.0)
            slot = out.setdefault(name, {"count": 0, "total_ms": 0.0,
                                         "self_ms": 0.0})
            slot["count"] += 1
            slot["total_ms"] += r["dur_ms"]
            slot["self_ms"] += self_ms
    grand = sum(s["self_ms"] for s in out.values())
    for slot in out.values():
        slot["total_ms"] = round(slot["total_ms"], 3)
        slot["self_ms"] = round(slot["self_ms"], 3)
        slot["self_pct"] = (round(100.0 * slot["self_ms"] / grand, 2)
                            if grand > 0 else 0.0)
    return out


def _request_timelines(records: list[dict]) -> dict:
    """rid -> time-ordered timeline entries, collected from BOTH record
    shapes a run can produce: the serve_* jsonl events (`id` field) and
    rid-stamped span records from a tracer's jsonl export. Each entry:
    {"t_s": seconds since the request's first record, "what": event or
    span name, "dur_ms": span duration (events: None), "detail": the
    record's other fields}. cluster_* hop events (router placement,
    handoff, hedge, migration — ISSUE 20) join the serve_* events, so
    a MERGED cluster log renders one end-to-end cross-replica
    timeline."""
    reqs: dict[str, list] = {}
    for r in records:
        ev = r.get("event")
        if (isinstance(ev, str)
                and (ev.startswith("serve_")
                     or ev.startswith("cluster_"))
                and "id" in r):
            reqs.setdefault(str(r["id"]), []).append({
                "_wall": r.get("ts"), "what": ev, "dur_ms": None,
                "detail": {k: v for k, v in r.items()
                           if k not in ("ts", "event", "id")}})
        elif ev == "span":
            attrs = r.get("attrs") or {}
            rid = attrs.get("rid")
            if rid is None:
                continue
            reqs.setdefault(str(rid), []).append({
                "_wall": r.get("wall"), "what": str(r.get("name")),
                "dur_ms": r.get("dur_ms"),
                "detail": {k: v for k, v in attrs.items()
                           if k != "rid"}})
    for rid, entries in reqs.items():
        entries.sort(key=lambda e: (e["_wall"] is None,
                                    e["_wall"] or 0.0))
        t0 = next((e["_wall"] for e in entries
                   if e["_wall"] is not None), None)
        for e in entries:
            wall = e.pop("_wall")
            e["t_s"] = (round(wall - t0, 6)
                        if wall is not None and t0 is not None else None)
    return reqs


def format_summary(s: dict, *, top: int = 15) -> str:
    """Human terminal rendering of `summarize_jsonl`'s dict. `top`
    bounds the span self-time table (stats --top N)."""
    out = [f"{s['path']}: {s['records']} records"
           + (f" ({s['unparseable_lines']} unparseable)"
              if s["unparseable_lines"] else "")
           + (f", {s['wall_span_s']}s wall span"
              if s["wall_span_s"] is not None else "")]
    out.append("")
    out.append("events:")
    for ev, slot in s["events"].items():
        out.append(f"  {ev:24s} x{slot['count']}")
        for k, st in slot["fields"].items():
            out.append(
                f"    {k:24s} mean={st['mean']} p50={st['p50']} "
                f"p95={st['p95']} min={st['min']} max={st['max']}")
    if s["timers"]:
        out.append("")
        out.append("timers (seconds):")
        for name, st in s["timers"].items():
            out.append(f"  {name:40s} x{st['count']} mean={st['mean']} "
                       f"p95={st['p95']}")
    if s["spans"]:
        out.append("")
        out.append("spans (ms):")
        for name, st in s["spans"].items():
            out.append(f"  {name:28s} x{st['count']} "
                       f"total={st['total_ms']} mean={st['mean']} "
                       f"p50={st['p50']} p95={st['p95']}")
    if s.get("span_self"):
        ranked = sorted(s["span_self"].items(),
                        key=lambda kv: kv[1]["self_ms"], reverse=True)
        shown = ranked[:max(int(top), 1)]
        out.append("")
        out.append(f"span self-time (exclusive, top {len(shown)} of "
                   f"{len(ranked)}):")
        for name, st in shown:
            out.append(f"  {name:28s} x{st['count']} "
                       f"self={st['self_ms']}ms ({st['self_pct']}%) "
                       f"total={st['total_ms']}ms")
    if s.get("programs"):
        from idc_models_tpu.observe.profile import format_program

        out.append("")
        out.append("programs (performance attribution):")
        for rec in s["programs"]:
            out.append(format_program(rec))
    if s.get("profile_steps"):
        out.append("")
        out.append("step-time attribution:")
        for rec in s["profile_steps"]:
            out.append(
                f"  {rec['loop']:14s} {rec['steps']:>5} steps — device "
                f"{rec['device_busy_fraction']:.1%} / host-gap "
                f"{rec['host_gap_fraction']:.1%} "
                f"(mean {rec['step_ms_mean']} ms/step)")
    if s.get("fed_cohorts"):
        out.append("")
        out.append("fed cohorts (per round):")
        for rec in s["fed_cohorts"]:
            mode = rec.get("mode", "sync")
            line = (f"  round {rec.get('round'):>4} [{mode:5s}] "
                    f"cohort={rec.get('cohort')} of "
                    f"{rec.get('population')} "
                    f"participants={rec.get('participants')}")
            if mode == "async":
                hist = rec.get("staleness_hist") or []
                line += (f" buffer={rec.get('buffer')} "
                         f"updates={rec.get('updates')} staleness "
                         f"mean={rec.get('staleness_mean')} "
                         f"max={rec.get('staleness_max')} "
                         f"hist={hist}")
            else:
                line += (f" waves={rec.get('waves')}"
                         f"x{rec.get('wave_size')}")
            out.append(line)
    if s.get("tenants"):
        out.append("")
        out.append("tenants:")
        for name, st in s["tenants"].items():
            reasons = ",".join(f"{k}={v}" for k, v in
                               sorted(st["by_reason"].items()))
            out.append(
                f"  {name:16s} requests={st['requests']} "
                f"tokens={st['tokens']} ttft p50={st['ttft_ms_p50']} "
                f"p95={st['ttft_ms_p95']} shed={st['shed']} "
                f"quota_rej={st['quota_rejections']}"
                + (f" ({reasons})" if reasons else ""))
    if s.get("checkpoints"):
        ck = s["checkpoints"]
        out.append("")
        out.append(
            f"checkpoints: {ck['saves']} save(s) "
            f"({ck['save_bytes']} bytes"
            + (f", {ck['save_mb_per_s']} MB/s"
               if ck["save_mb_per_s"] is not None else "")
            + f"), {ck['restores']} restore(s) "
            f"({ck['restore_bytes']} bytes"
            + (f", {ck['restore_mb_per_s']} MB/s"
               if ck["restore_mb_per_s"] is not None else "")
            + f", peak host {ck['restore_peak_host_bytes']} bytes)")
    if s.get("compile_cache"):
        cc = s["compile_cache"]
        out.append("")
        out.append(
            f"compile cache: {cc['hits']} hit(s) "
            f"({cc['deserialize_ms']} ms deserializing), "
            f"{cc['misses']} miss(es) -> {cc['stores']} store(s) "
            f"({cc['compile_ms']} ms compiling), "
            f"{cc['evicted_corrupt']} corrupt eviction(s)")
    if s.get("rollouts"):
        out.append("")
        out.append("rollouts (state transitions, file order):")
        for rec in s["rollouts"]:
            line = f"  {rec.get('event'):16s} stage={rec.get('stage')}"
            for k in ("outcome", "replica", "canary_requests",
                      "reason"):
                if rec.get(k) is not None:
                    line += f" {k}={rec[k]}"
            out.append(line)
    if s.get("requests"):
        out.append("")
        out.append(f"requests: {len(s['requests'])} with per-request "
                   f"timelines (render one with --request RID)")
    if s["metrics"]:
        out.append("")
        out.append("last metrics snapshot:")
        for rec in s["metrics"]:
            lbl = ("{" + ",".join(f"{k}={v}" for k, v in
                                  sorted(rec["labels"].items())) + "}"
                   if rec.get("labels") else "")
            if rec["type"] == "histogram":
                out.append(f"  {rec['name']}{lbl} count={rec['count']} "
                           f"sum={rec['sum']} min={rec['min']} "
                           f"max={rec['max']}")
            else:
                out.append(f"  {rec['name']}{lbl} = {rec['value']}")
    return "\n".join(out)


def format_request_timeline(summary: dict, rid: str) -> str:
    """Human rendering of ONE request's timeline from a
    `summarize_jsonl` summary — submit through finish, every jsonl
    event and rid-stamped span in time order."""
    entries = summary.get("requests", {}).get(rid)
    if entries is None:
        known = sorted(summary.get("requests", {}))
        preview = ", ".join(known[:8]) + ("..." if len(known) > 8 else "")
        raise KeyError(f"no records for request id {rid!r} "
                       f"({len(known)} request ids in {summary['path']}"
                       f"{': ' + preview if known else ''})")
    out = [f"request {rid} — {len(entries)} records "
           f"({summary['path']}):"]
    prev = None
    for e in entries:
        t = ("t+?     " if e["t_s"] is None
             else f"t+{e['t_s'] * 1e3:9.3f}ms")
        # per-hop latency attribution: wall time since the PREVIOUS
        # timeline record, so "where did the request wait" reads
        # straight off the merged cluster view
        delta = ""
        if e["t_s"] is not None:
            if prev is not None:
                delta = f" (+{(e['t_s'] - prev) * 1e3:.3f}ms)"
            prev = e["t_s"]
        dur = (f" [{e['dur_ms']:.3f} ms]"
               if isinstance(e.get("dur_ms"), (int, float)) else "")
        detail = " ".join(
            f"{k}={v}" for k, v in sorted(e["detail"].items())
            if v is not None)
        out.append(f"  {t}  {e['what']:22s}{dur}"
                   + (f"  {detail}" if detail else "") + delta)
    return "\n".join(out)
