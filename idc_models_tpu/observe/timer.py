"""Named wall-clock spans + TPU profiler hooks.

Parity: the reference's `Timer` context manager is copy-pasted into all
five scripts and prints "{name} took {t} seconds" around every expensive
phase (SURVEY.md C17, e.g. dist_model_tf_dense.py:31-44, usage
dist_model_tf_vgg.py:135,156). Here it is one class, optionally feeding a
structured jsonl log, plus a `jax.profiler` trace context for real TPU
profiling (the reference has no profiler integration — SURVEY.md §5).
"""

from __future__ import annotations

import contextlib
import time

from idc_models_tpu.observe import trace


class Timer:
    """`with Timer("Pre-training for 10 epochs"):` — prints the reference's
    exact line; `.seconds` holds the measurement afterwards.

    When a tracer is active (observe/trace.py) the Timer ALSO records a
    span of the same name, so every legacy Timer call site shows up in
    exported traces for free; with tracing disabled the span handle is
    the shared no-op and the historical behavior (print + optional
    jsonl record) is unchanged."""

    def __init__(self, name: str, *, logger=None, quiet: bool = False):
        self.name = name
        self.logger = logger
        self.quiet = quiet
        self.seconds: float | None = None

    def __enter__(self) -> "Timer":
        self._span = trace.span(self.name, timer=True).__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.seconds = time.perf_counter() - self._t0
        self._span.__exit__(exc_type, exc, tb)
        if not self.quiet:
            print(f"{self.name} took {self.seconds} seconds")
        if self.logger is not None:
            self.logger.log(event="timer", name=self.name,
                            seconds=self.seconds)


@contextlib.contextmanager
def profile_trace(logdir: str | None):
    """jax.profiler trace over the span (TensorBoard-viewable); no-op when
    `logdir` is None so call sites can be unconditional."""
    if logdir is None:
        yield
        return
    import jax

    with jax.profiler.trace(str(logdir)):
        yield
