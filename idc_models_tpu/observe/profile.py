"""Performance attribution: program accounting, step-time attribution,
roofline verdicts, and a compile-churn watchdog (ISSUE 9).

The framework could MEASURE (PR 5 tracer/metrics) but not EXPLAIN: why
is MobileNet at MFU 0.14 while VGG hits 0.62 (BENCH_r05)? Is a step
compute-bound or bandwidth-bound, is the chip idling on host gaps, is
something recompiling every call? This module turns the substrate into
answers, in four pieces:

1. **Program accounting** — `program_report(compiled)` is THE one
   extraction point over XLA's `compiled.cost_analysis()` +
   `memory_analysis()` (a static scan in test_static_robustness.py
   bans calls anywhere else). It normalizes the backend quirks (list-
   vs-dict cost returns, missing analyses) into a stable `ProgramCost`
   record and degrades loudly-but-gracefully: a backend returning
   nothing yields `available=False` + a `warnings.warn`, never a
   crash. `register_program(name, compiled)` files the report in the
   process-wide `PROGRAMS` table and surfaces `program_flops{program}`
   / `program_bytes_accessed{program}` gauges, so train steps,
   `_ServeFns` programs, and federated rounds all report through one
   schema.

2. **Step-time attribution** — the instrumented loops wrap their
   blocking device fetches in a `device.sync` span (the PR 5 tracer's
   stream carries it for free; disabled cost is one global read).
   `DeviceTimeline` consumes a span stream and splits each loop span
   (`profile.step`, `train.step`/`train.epoch`, `serve.tick`,
   `fed.round`) into device-wait vs host-gap time: on a synchronously
   fenced loop the host's blocked-on-device time is the device-busy
   floor and everything else is bubble. Surfaced as the
   `device_busy_fraction{loop}` gauge and a per-loop report whose two
   fractions sum to 1 by construction. (With the serve scheduler's
   two-deep pipelining the device overlaps host bookkeeping, so there
   the device fraction is a lower bound — documented, not hidden.)

3. **Roofline verdicts** — `BACKEND_ROOFS` maps device_kind
   substrings to (peak bf16 TFLOP/s, peak HBM GB/s), seeded from the
   tables bench.py and experiments/backbone_mfu.py measured against
   (both now delegate here). `roofline_verdict(cost, step_seconds)`
   combines (1) + a measured step time into compute-bound vs
   bandwidth-bound with achieved-fraction-of-roof numbers. Unknown
   backends (CPU) verdict "unknown" unless `register_roof` (CLI:
   `profile --peak-tflops/--peak-gbps`) supplies the roof.

4. **Compile-churn watchdog** — `arm_watchdog()` registers ONE
   process-wide `jax.monitoring` duration listener for XLA's
   `backend_compile_duration` event, so every compile in the process
   is recorded: `compiles_total{program}` / `compile_seconds_total`
   metrics plus a `compile` trace marker. Program names come from the
   `compiling(name)` thread-local context at the framework's compile
   choke points, falling back to the innermost open trace span, else
   `"<unnamed>"`; `compiling(None)` suppresses recording (accounting
   copies must not look like churn). A program compiled more than
   `limit` times flags once — the recompile-loop failure mode (a
   shape/dtype varying per call) that the serve jit-cache gates only
   catch for serve.

The `profile` CLI verb (cli.py) drives all four over any subsystem's
hot loop and writes frozen-schema `profile_program`/`profile_step`
jsonl events; `bench_profile_overhead` (bench.py) holds the armed
cost under the house <2%-of-a-decode-window bar.
"""

from __future__ import annotations

import dataclasses
import threading
import warnings

from idc_models_tpu.observe import metrics_registry as mreg
from idc_models_tpu.observe import trace

# ---------------------------------------------------------------------------
# 1. program accounting
# ---------------------------------------------------------------------------

_COST_FIELDS = ("flops", "bytes_accessed")
_MEM_FIELDS = ("argument_bytes", "output_bytes", "temp_bytes",
               "alias_bytes", "generated_code_bytes")


@dataclasses.dataclass(frozen=True)
class ProgramCost:
    """One compiled program's post-DCE cost/memory account. Every
    numeric field is `None` when the backend did not report it —
    consumers branch on `available` / `missing` instead of guessing."""

    program: str
    flops: float | None = None
    bytes_accessed: float | None = None
    arithmetic_intensity: float | None = None   # flops / bytes_accessed
    argument_bytes: float | None = None
    output_bytes: float | None = None
    temp_bytes: float | None = None
    alias_bytes: float | None = None
    generated_code_bytes: float | None = None
    peak_hbm_bytes: float | None = None  # args + outputs + temps − aliased
    available: bool = True
    missing: tuple = ()


_warned_programs: set[str] = set()
_warn_lock = threading.Lock()


def _positive(d, key) -> float | None:
    try:
        v = float(d.get(key, 0.0) or 0.0)
    except (TypeError, ValueError):
        return None
    return v if v > 0 else None


def program_report(compiled, *, name: str = "<program>") -> ProgramCost:
    """THE extraction point over ``compiled.cost_analysis()`` +
    ``compiled.memory_analysis()`` (jax AOT `Compiled` objects; the
    static scan bans direct calls elsewhere).

    Normalizes the version quirks — cost_analysis returning a dict, a
    list of dicts, or None; memory_analysis raising or absent on some
    backends — into one `ProgramCost`. A backend returning nothing is
    a DEGRADED record (`available=False`, fields None), reported once
    per program via `warnings.warn` so the gap is loud without killing
    the run that only wanted wall-clock numbers.
    """
    flops = bytes_accessed = None
    missing = []
    try:
        ca = compiled.cost_analysis()
    except Exception as e:  # noqa: BLE001 — degraded record carries the gap
        ca = None
        warnings.warn(f"cost_analysis() raised for {name!r}: {e}",
                      RuntimeWarning, stacklevel=2)
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        flops = _positive(ca, "flops")
        bytes_accessed = _positive(ca, "bytes accessed")
    if flops is None:
        missing.append("flops")
    if bytes_accessed is None:
        missing.append("bytes_accessed")

    mem = dict.fromkeys(_MEM_FIELDS)
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — not every backend exposes it
        ma = None
    if ma is not None:
        for field, attr in (("argument_bytes", "argument_size_in_bytes"),
                            ("output_bytes", "output_size_in_bytes"),
                            ("temp_bytes", "temp_size_in_bytes"),
                            ("alias_bytes", "alias_size_in_bytes"),
                            ("generated_code_bytes",
                             "generated_code_size_in_bytes")):
            v = getattr(ma, attr, None)
            mem[field] = float(v) if v is not None else None
    else:
        missing.extend(_MEM_FIELDS)

    peak = None
    if mem["argument_bytes"] is not None:
        # resident-footprint estimate: arguments + outputs + XLA temps,
        # minus buffers aliased input->output (donation) which exist
        # once, floored at 0 (alias can exceed outputs on full-donation
        # programs)
        peak = max(0.0, (mem["argument_bytes"]
                         + (mem["output_bytes"] or 0.0)
                         + (mem["temp_bytes"] or 0.0)
                         - (mem["alias_bytes"] or 0.0)))
    intensity = (flops / bytes_accessed
                 if flops and bytes_accessed else None)
    available = (flops is not None or bytes_accessed is not None
                 or mem["argument_bytes"] is not None)
    if not available:
        with _warn_lock:
            fresh = name not in _warned_programs
            _warned_programs.add(name)
        if fresh:
            warnings.warn(
                f"backend returned no cost OR memory analysis for "
                f"program {name!r} — ProgramCost degrades to "
                f"available=False (roofline verdicts for it will read "
                f"'unknown')", RuntimeWarning, stacklevel=2)
    return ProgramCost(
        program=name, flops=flops, bytes_accessed=bytes_accessed,
        arithmetic_intensity=intensity,
        argument_bytes=mem["argument_bytes"],
        output_bytes=mem["output_bytes"], temp_bytes=mem["temp_bytes"],
        alias_bytes=mem["alias_bytes"],
        generated_code_bytes=mem["generated_code_bytes"],
        peak_hbm_bytes=peak, available=available,
        missing=tuple(missing))


# the process-wide named-program table (train.step, serve.window,
# lm.prefill, fed.round, ... — whatever registered this process)
PROGRAMS: dict[str, ProgramCost] = {}
_programs_lock = threading.Lock()


def augment_cost(cost: ProgramCost, *, flops: float = 0.0,
                 bytes_accessed: float = 0.0) -> ProgramCost:
    """Merge hand-computed FLOPs/bytes into a ProgramCost.

    The accounting path for Pallas kernels: XLA's `cost_analysis`
    cannot see inside a custom call, so a program whose hot ops are
    Pallas (e.g. the fused depthwise chains of
    `profile --model mobile --depthwise-impl fused`) under-reports —
    silently poisoning every MFU/roofline figure built on it. Callers
    add the kernels' analytic account (ops/fused_conv.py
    `depthwise_chain_cost`) here, then file the merged record via
    `register_cost`; `arithmetic_intensity`, `available`, and
    `missing` are recomputed so a previously degraded record becomes a
    real one."""
    if not flops and not bytes_accessed:
        return cost
    new_flops = (cost.flops or 0.0) + float(flops)
    new_bytes = (cost.bytes_accessed or 0.0) + float(bytes_accessed)
    missing = tuple(m for m in cost.missing
                    if not (m == "flops" and new_flops)
                    and not (m == "bytes_accessed" and new_bytes))
    return dataclasses.replace(
        cost,
        flops=new_flops if new_flops else None,
        bytes_accessed=new_bytes if new_bytes else None,
        arithmetic_intensity=(new_flops / new_bytes
                              if new_flops and new_bytes else None),
        available=True, missing=missing)


def register_cost(name: str, cost: ProgramCost, *,
                  registry: mreg.MetricsRegistry | None = None
                  ) -> ProgramCost:
    """File an already-built ProgramCost under `name` in `PROGRAMS` and
    the metrics registry — the shared tail of `register_program`, and
    the entry point for costs that are partly hand-computed
    (`augment_cost`) rather than extracted from a compiled executable
    (which keeps `program_report` the single cost_analysis site the
    static scan enforces)."""
    if cost.program != name:
        cost = dataclasses.replace(cost, program=name)
    with _programs_lock:
        PROGRAMS[name] = cost
    reg = registry if registry is not None else mreg.REGISTRY
    for metric, help_txt, value in (
            ("program_flops", "post-DCE FLOPs per execution of a "
             "registered program", cost.flops),
            ("program_bytes_accessed", "XLA bytes-accessed estimate "
             "per execution of a registered program",
             cost.bytes_accessed),
            ("program_peak_hbm_bytes", "resident-footprint estimate "
             "(args + outputs + temps - aliased) of a registered "
             "program", cost.peak_hbm_bytes)):
        if value is not None:
            reg.gauge(metric, help_txt, labels=("program",)).set(
                value, program=name)
    wd = _WATCHDOG
    if wd is not None and cost.flops is not None:
        wd.note_flops(name, cost.flops)
    return cost


def register_program(name: str, compiled, *,
                     registry: mreg.MetricsRegistry | None = None
                     ) -> ProgramCost:
    """`program_report` + file the result under `name` in `PROGRAMS`
    and the metrics registry (`program_flops{program}` etc.), so every
    subsystem's programs report through one table."""
    return register_cost(name, program_report(compiled, name=name),
                         registry=registry)


def register_jit(name: str, fn, *args, **kw) -> ProgramCost | None:
    """Best-effort accounting registration of a (jitted or traceable)
    function at the given example arguments: lowers + compiles an
    ACCOUNTING COPY (suppressed from the compile watchdog — it is not
    churn) and registers its report. Returns None, with a warning,
    when the function cannot be lowered (host-side wrappers); callers
    on hot paths gate this behind `accounting_enabled()`."""
    try:
        target = fn
        if not hasattr(target, "lower"):
            import jax

            target = jax.jit(fn)
        with compiling(None):
            compiled = target.lower(*args, **kw).compile()
    except Exception as e:  # noqa: BLE001 — accounting is best-effort
        warnings.warn(f"program accounting for {name!r} failed "
                      f"({type(e).__name__}: {e}); skipping",
                      RuntimeWarning, stacklevel=2)
        return None
    return register_program(name, compiled)


def registered_programs() -> dict[str, ProgramCost]:
    with _programs_lock:
        return dict(PROGRAMS)


# opt-in switch for the always-on loops (fit, run_rounds): program
# accounting costs one extra compile per loop, so it only runs when a
# profile driver armed it
_ACCOUNTING = False


def enable_accounting(on: bool = True) -> None:
    global _ACCOUNTING
    _ACCOUNTING = bool(on)


def accounting_enabled() -> bool:
    return _ACCOUNTING


# ---------------------------------------------------------------------------
# 2. step-time attribution
# ---------------------------------------------------------------------------

# the loop spans a timeline splits (nearest-ancestor match, so a
# device.sync under serve.collect under serve.tick attributes to the
# tick) and the device-wait span the instrumented fetch sites emit
LOOP_SPANS = ("profile.step", "train.step", "train.epoch", "serve.tick",
              "fed.round")
DEVICE_SPAN = "device.sync"


class DeviceTimeline:
    """Aggregates a span stream into per-loop device-wait vs host-gap
    time. Feed it `Tracer.records()` (or span-jsonl dicts); `report()`
    returns per-loop totals and fractions and stamps the
    `device_busy_fraction{loop}` gauge."""

    def __init__(self, *, loops=LOOP_SPANS, device_span: str = DEVICE_SPAN,
                 registry: mreg.MetricsRegistry | None = None):
        self.loops = tuple(loops)
        self.device_span = device_span
        self._registry = registry
        self._wall: dict[str, float] = {}
        self._count: dict[str, int] = {}
        self._device: dict[str, float] = {}

    def consume(self, records) -> "DeviceTimeline":
        spans = [r for r in records
                 if r.get("event", "span") == "span"
                 and isinstance(r.get("dur_ms"), (int, float))]
        # span ids are unique within ONE tracer but restart per
        # process, and append-mode run logs can hold several runs — a
        # repeated id starts a new SEGMENT, and parent links never
        # cross segments (joining by raw id across the whole input
        # would walk one run's device.sync into another run's spans)
        segments: list[list[dict]] = []
        seen: set = set()
        for r in spans:
            rid = r.get("id")
            if not segments or (rid is not None and rid in seen):
                segments.append([])
                seen = set()
            if rid is not None:
                seen.add(rid)
            segments[-1].append(r)
        for seg in segments:
            self._consume_segment(seg)
        return self

    def _consume_segment(self, spans: list) -> None:
        by_id = {r["id"]: r for r in spans if r.get("id") is not None}
        loop_set = set(self.loops)
        for r in spans:
            if r.get("name") in loop_set:
                name = r["name"]
                self._wall[name] = self._wall.get(name, 0.0) + r["dur_ms"]
                self._count[name] = self._count.get(name, 0) + 1
        for r in spans:
            if r.get("name") != self.device_span:
                continue
            # nearest loop ancestor (bounded walk guards a cyclic file)
            parent, hops = r.get("parent"), 0
            while parent is not None and hops < 64:
                anc = by_id.get(parent)
                if anc is None:
                    break
                if anc.get("name") in loop_set:
                    nm = anc["name"]
                    self._device[nm] = (self._device.get(nm, 0.0)
                                        + r["dur_ms"])
                    break
                parent, hops = anc.get("parent"), hops + 1

    def report(self) -> dict:
        """{loop: {steps, wall_ms, device_ms, host_gap_ms,
        device_busy_fraction, host_gap_fraction, step_ms_mean}} —
        fractions sum to 1 by construction (device clamped to wall)."""
        out = {}
        reg = (self._registry if self._registry is not None
               else mreg.REGISTRY)
        gauge = reg.gauge(
            "device_busy_fraction",
            "fraction of a loop span's wall the host spent blocked on "
            "device results (device-busy floor; the rest is host gap)",
            labels=("loop",))
        for name, wall in sorted(self._wall.items()):
            dev = min(self._device.get(name, 0.0), wall)
            n = self._count[name]
            frac = dev / wall if wall > 0 else 0.0
            out[name] = {
                "steps": n,
                "wall_ms": round(wall, 3),
                "device_ms": round(dev, 3),
                "host_gap_ms": round(wall - dev, 3),
                "device_busy_fraction": round(frac, 4),
                "host_gap_fraction": round(1.0 - frac, 4),
                "step_ms_mean": round(wall / n, 4) if n else None,
            }
            gauge.set(frac, loop=name)
        return out

    def format_report(self, report: dict | None = None) -> str:
        """Human lines for a `report()` dict — pass one in when the
        caller already computed it (report() re-stamps the gauges)."""
        lines = []
        if report is None:
            report = self.report()
        for name, st in report.items():
            lines.append(
                f"  {name:14s} {st['steps']:>5d} steps  mean "
                f"{st['step_ms_mean']:.3f} ms — device "
                f"{st['device_busy_fraction']:.1%} / host-gap "
                f"{st['host_gap_fraction']:.1%} "
                f"({st['host_gap_ms']:.1f} ms bubble)")
        return "\n".join(lines) if lines else "  (no loop spans seen)"


def trace_mark(tracer) -> float:
    """Monotonic offset (ms) into `tracer`'s epoch right now — pair
    with `records_since` so a timeline covers only a measured region
    (build/warmup spans would otherwise read as one huge host gap)."""
    if tracer is None:
        return 0.0
    return (tracer._clock() - tracer.mono_t0) * 1e3


def records_since(tracer, mark_ms: float) -> list[dict]:
    """The tracer's span records that STARTED at or after `mark_ms`."""
    if tracer is None:
        return []
    return [r for r in tracer.records() if r["t_ms"] >= mark_ms]


# ---------------------------------------------------------------------------
# 3. roofline registry + verdicts
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RooflineSpec:
    """One backend's nominal roof: dense bf16 TFLOP/s and HBM GB/s per
    chip (public spec-sheet numbers)."""

    key: str
    peak_tflops: float
    peak_hbm_gbps: float

    @property
    def ridge_intensity(self) -> float:
        """flops/byte where the compute and bandwidth roofs cross —
        programs below it are bandwidth-bound at best."""
        return self.peak_tflops * 1e12 / (self.peak_hbm_gbps * 1e9)


# device_kind substring -> roof; longest matching key wins. Seeded from
# the tables bench.py (_PEAK_BF16_TFLOPS) and
# experiments/backbone_mfu.py (_PEAK_HBM_GBPS) measured against — both
# now read THIS table.
BACKEND_ROOFS: dict[str, RooflineSpec] = {
    k: RooflineSpec(k, tf, bw) for k, tf, bw in (
        ("v2", 46.0, 700.0),
        ("v3", 123.0, 900.0),
        ("v4", 275.0, 1228.0),
        ("v5 lite", 197.0, 819.0),
        ("v5e", 197.0, 819.0),
        ("v5p", 459.0, 2765.0),
        ("v6 lite", 918.0, 1640.0),
        ("v6e", 918.0, 1640.0),
    )
}


def register_roof(key: str, peak_tflops: float,
                  peak_hbm_gbps: float) -> RooflineSpec:
    """Add/override a backend roof (e.g. the CLI's --peak-tflops /
    --peak-gbps escape hatch for kinds the table does not know)."""
    if peak_tflops <= 0 or peak_hbm_gbps <= 0:
        raise ValueError(f"roof peaks must be > 0, got "
                         f"({peak_tflops}, {peak_hbm_gbps})")
    spec = RooflineSpec(key.lower(), float(peak_tflops),
                        float(peak_hbm_gbps))
    BACKEND_ROOFS[spec.key] = spec
    return spec


def roofline_for(device) -> RooflineSpec | None:
    """The roof for a jax device (or device_kind string): longest
    substring match over `BACKEND_ROOFS`, None when unknown."""
    kind = getattr(device, "device_kind", device)
    kind = str(kind).lower()
    best = None
    for key, spec in BACKEND_ROOFS.items():
        if key in kind and (best is None or len(key) > len(best.key)):
            best = spec
    return best


def roofline_verdict(cost: ProgramCost, step_seconds: float | None,
                     device=None, *, spec: RooflineSpec | None = None,
                     n_dev: int = 1) -> dict:
    """Combine a program's cost account with its measured per-step wall
    into a roofline verdict. `cost_analysis` FLOPs/bytes cover the
    whole (multi-device) program, so `n_dev` divides them back to
    per-chip before comparing against the per-chip roofs.

    Returns {verdict, achieved_tflops, achieved_hbm_gbps, mfu,
    hbm_utilization, bound_fraction, ridge_intensity, peak_tflops,
    peak_hbm_gbps} with None where inputs were unavailable; verdict is
    "compute-bound" / "bandwidth-bound" / "unknown"."""
    spec = spec if spec is not None else roofline_for(device)
    achieved_tf = achieved_bw = None
    if step_seconds and step_seconds > 0:
        if cost.flops:
            achieved_tf = cost.flops / n_dev / step_seconds / 1e12
        if cost.bytes_accessed:
            achieved_bw = cost.bytes_accessed / n_dev / step_seconds / 1e9
    out = {
        "verdict": "unknown",
        "achieved_tflops": (round(achieved_tf, 4)
                            if achieved_tf is not None else None),
        "achieved_hbm_gbps": (round(achieved_bw, 3)
                              if achieved_bw is not None else None),
        "mfu": None, "hbm_utilization": None, "bound_fraction": None,
        "ridge_intensity": None, "peak_tflops": None,
        "peak_hbm_gbps": None,
    }
    if spec is None:
        return out
    out["peak_tflops"] = spec.peak_tflops
    out["peak_hbm_gbps"] = spec.peak_hbm_gbps
    out["ridge_intensity"] = round(spec.ridge_intensity, 2)
    if achieved_tf is not None:
        out["mfu"] = round(achieved_tf / spec.peak_tflops, 4)
    if achieved_bw is not None:
        out["hbm_utilization"] = round(achieved_bw / spec.peak_hbm_gbps,
                                       4)
    if cost.arithmetic_intensity is not None:
        compute_bound = (cost.arithmetic_intensity
                         >= spec.ridge_intensity)
        out["verdict"] = ("compute-bound" if compute_bound
                          else "bandwidth-bound")
        out["bound_fraction"] = (out["mfu"] if compute_bound
                                 else out["hbm_utilization"])
    return out


# ---------------------------------------------------------------------------
# 4. compile-churn watchdog
# ---------------------------------------------------------------------------

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_SUPPRESS = object()          # compiling(None): accounting, not churn
UNNAMED = "<unnamed>"
_tls = threading.local()


class _NullCtx:
    """Shared no-op context — `naming_compiles` when no watchdog is
    armed costs one module-global read, same discipline as the
    disabled tracer span."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NULL_CTX = _NullCtx()


class _CompileName:
    """Reentrant thread-local program-name context for compile events
    (the jax.monitoring listener carries no identity of its own)."""

    __slots__ = ("name", "_prev")

    def __init__(self, name):
        self.name = _SUPPRESS if name is None else name

    def __enter__(self):
        self._prev = getattr(_tls, "program", None)
        _tls.program = self.name
        return self

    def __exit__(self, *exc):
        _tls.program = self._prev
        return None


def compiling(name: str | None) -> _CompileName:
    """Name every compile observed inside the block (`None` suppresses
    recording — accounting copies must not read as churn)."""
    return _CompileName(name)


def naming_compiles(name: str):
    """Hot-path form of `compiling`: the shared no-op handle unless a
    watchdog is armed (the serve scheduler wraps its admission section
    with this every tick)."""
    return _CompileName(name) if _WATCHDOG is not None else _NULL_CTX


class CompileWatchdog:
    """Records every observed compile (program name, seconds, flops
    when a registration supplied them) and flags CHURN: any program
    compiled more than `limit` times — the recompile-loop failure mode
    where a shape/dtype varies per call and every "cached" dispatch
    silently recompiles."""

    def __init__(self, *, limit: int = 5,
                 registry: mreg.MetricsRegistry | None = None):
        if limit < 1:
            raise ValueError(f"churn limit must be >= 1, got {limit}")
        self.limit = int(limit)
        self._lock = threading.Lock()
        self.programs: dict[str, dict] = {}
        self.flagged: list[str] = []
        reg = registry if registry is not None else mreg.REGISTRY
        self._m_compiles = reg.counter(
            "compiles_total", "XLA backend compiles observed "
            "process-wide while the watchdog is armed",
            labels=("program",))
        self._m_seconds = reg.counter(
            "compile_seconds_total", "wall seconds spent in observed "
            "XLA backend compiles")
        self._m_churn = reg.counter(
            "compile_churn_flagged_total", "programs flagged for "
            "compile churn (compiled more than the configured limit)",
            labels=("program",))

    def on_compile(self, program: str, seconds: float = 0.0) -> None:
        with self._lock:
            st = self.programs.setdefault(
                program, {"count": 0, "seconds": 0.0, "flops": None})
            st["count"] += 1
            st["seconds"] += seconds
            # churn only fires for NAMED programs: the unnamed bucket
            # aggregates unrelated one-shot compiles (model inits,
            # data placement, digests) whose combined count says
            # nothing about any one program recompiling — flagging it
            # would false-positive on every cold start
            fire = (program != UNNAMED
                    and st["count"] > self.limit
                    and program not in self.flagged)
            if fire:
                self.flagged.append(program)
            count = st["count"]
        self._m_compiles.inc(program=program)
        self._m_seconds.inc(max(seconds, 0.0))
        trace.point("compile", program=program,
                    seconds=round(seconds, 6))
        if fire:
            self._m_churn.inc(program=program)
            warnings.warn(
                f"compile churn: program {program!r} compiled {count} "
                f"times (> limit {self.limit}) — some shape/dtype is "
                f"varying per call, so every dispatch pays a fresh XLA "
                f"compile instead of the cache (bucket the shape, pin "
                f"the dtype, or raise the limit if this growth is "
                f"expected)", RuntimeWarning, stacklevel=3)

    def note_flops(self, program: str, flops: float) -> None:
        with self._lock:
            st = self.programs.setdefault(
                program, {"count": 0, "seconds": 0.0, "flops": None})
            st["flops"] = flops

    def report(self) -> dict:
        with self._lock:
            programs = {k: dict(v) for k, v in self.programs.items()}
            flagged = list(self.flagged)
        return {
            "limit": self.limit,
            "total_compiles": sum(v["count"] for v in programs.values()),
            "compile_seconds_total": round(
                sum(v["seconds"] for v in programs.values()), 4),
            "programs": programs,
            "flagged": flagged,
        }


_WATCHDOG: CompileWatchdog | None = None
_listener_registered = False
_arm_lock = threading.Lock()


def _compile_listener(event, duration, **kw) -> None:
    wd = _WATCHDOG
    if wd is None or event != _COMPILE_EVENT:
        return
    name = getattr(_tls, "program", None)
    if name is _SUPPRESS:
        return
    if name is None:
        tr = trace.get_tracer()
        if tr is not None:
            stack = tr._stack()
            if stack:
                name = stack[-1].name
    wd.on_compile(name or UNNAMED, seconds=float(duration))


def arm_watchdog(*, limit: int = 5,
                 registry: mreg.MetricsRegistry | None = None
                 ) -> CompileWatchdog:
    """Install a process-wide `CompileWatchdog`. The jax.monitoring
    listener is registered exactly once per process (the API has no
    unregister); when no watchdog is armed it is a two-comparison
    no-op. Returns the armed watchdog; `disarm_watchdog()` ends the
    observation window."""
    global _WATCHDOG, _listener_registered
    wd = CompileWatchdog(limit=limit, registry=registry)
    with _arm_lock:
        if not _listener_registered:
            try:
                import jax.monitoring

                jax.monitoring.register_event_duration_secs_listener(
                    _compile_listener)
                _listener_registered = True
            except (ImportError, AttributeError) as e:
                warnings.warn(
                    f"jax.monitoring unavailable ({e}); the compile "
                    f"watchdog will only see compiles reported "
                    f"explicitly via on_compile()", RuntimeWarning,
                    stacklevel=2)
        _WATCHDOG = wd
    return wd


def disarm_watchdog() -> None:
    global _WATCHDOG
    _WATCHDOG = None


def watchdog() -> CompileWatchdog | None:
    return _WATCHDOG


# ---------------------------------------------------------------------------
# frozen jsonl record shapes (profile_program / profile_step)
# ---------------------------------------------------------------------------

def program_record(cost: ProgramCost, roofline: dict | None = None,
                   step_ms: float | None = None,
                   device_kind: str | None = None) -> dict:
    """The `profile_program` jsonl payload (minus ts/event, which the
    JsonlLogger owns) — ONE construction site so the frozen schema in
    tests/test_observability.py is enforced everywhere."""
    rl = roofline or {}
    return {
        "program": cost.program,
        "flops": cost.flops,
        "bytes_accessed": cost.bytes_accessed,
        "arithmetic_intensity": (round(cost.arithmetic_intensity, 4)
                                 if cost.arithmetic_intensity is not None
                                 else None),
        "argument_bytes": cost.argument_bytes,
        "output_bytes": cost.output_bytes,
        "temp_bytes": cost.temp_bytes,
        "peak_hbm_bytes": cost.peak_hbm_bytes,
        "generated_code_bytes": cost.generated_code_bytes,
        "available": cost.available,
        "step_ms": round(step_ms, 4) if step_ms is not None else None,
        "verdict": rl.get("verdict", "unknown"),
        "achieved_tflops": rl.get("achieved_tflops"),
        "achieved_hbm_gbps": rl.get("achieved_hbm_gbps"),
        "mfu": rl.get("mfu"),
        "hbm_utilization": rl.get("hbm_utilization"),
        "bound_fraction": rl.get("bound_fraction"),
        "ridge_intensity": rl.get("ridge_intensity"),
        "peak_tflops": rl.get("peak_tflops"),
        "peak_hbm_gbps": rl.get("peak_hbm_gbps"),
        "device_kind": device_kind,
    }


def step_record(loop: str, stats: dict) -> dict:
    """The `profile_step` jsonl payload from one `DeviceTimeline`
    report row — same one-construction-site discipline."""
    return {
        "loop": loop,
        "steps": stats["steps"],
        "wall_ms": stats["wall_ms"],
        "device_ms": stats["device_ms"],
        "host_gap_ms": stats["host_gap_ms"],
        "device_busy_fraction": stats["device_busy_fraction"],
        "host_gap_fraction": stats["host_gap_fraction"],
        "step_ms_mean": stats["step_ms_mean"],
    }


def format_program(rec: dict) -> str:
    """One human line per profile_program record (CLI + stats share
    it)."""
    bits = [f"  {rec['program']:14s}"]
    if rec.get("flops"):
        bits.append(f"{rec['flops'] / 1e9:8.2f} GFLOP")
    if rec.get("bytes_accessed"):
        bits.append(f"{rec['bytes_accessed'] / 1e9:7.3f} GB moved")
    if rec.get("arithmetic_intensity") is not None:
        bits.append(f"intensity {rec['arithmetic_intensity']:.1f}")
    if rec.get("peak_hbm_bytes"):
        bits.append(f"peak {rec['peak_hbm_bytes'] / 2**30:.2f} GiB")
    if not rec.get("available", True):
        bits.append("(backend reported no analysis)")
    v = rec.get("verdict", "unknown")
    if v != "unknown":
        frac = rec.get("bound_fraction")
        roof = ("peak FLOP/s" if v == "compute-bound"
                else "peak HBM bytes/s")
        at = f" at {frac:.2f} of {roof}" if frac is not None else ""
        extra = ""
        if rec.get("mfu") is not None:
            extra = (f" (mfu {rec['mfu']:.3f}, hbm "
                     f"{rec.get('hbm_utilization')})")
        bits.append(f"-> {v}{at}{extra}")
    elif rec.get("step_ms") is not None:
        bits.append("-> unknown roof (pass --peak-tflops/--peak-gbps "
                    "or register_roof)")
    return " ".join(bits)
