"""Live metrics exposition: a stdlib HTTP endpoint over the registry.

PR 5's `MetricsRegistry` exports in batch — a `metrics_snapshot` jsonl
record at run end, `prometheus_text()` on demand from code. Operating a
serving process needs the LIVE surface Prometheus actually scrapes:

- ``GET /metrics``  — the registry's text exposition, byte-identical to
  `registry.prometheus_text()` at the instant of the scrape (gated by
  test). `Content-Type: text/plain; version=0.0.4`.
- ``GET /healthz``  — a small JSON health document for load-balancer
  probes: seconds since the serve scheduler's last cycle
  (`last_tick_age_s`, from the `serve_last_tick_monotonic_seconds`
  gauge the metrics hooks maintain), current `queue_depth` and
  `slot_occupancy` gauge values, the paged engine's
  `kv_pages_used`/`kv_pages_total` pool occupancy, the brownout
  controller's `brownout_stage` (0 = normal .. 3 = shedding), and
  `"status": "ok"`. The page and brownout fields are what a cluster
  router routes on: a replica with no page headroom should not take
  a long prompt, and a replica deep in its brownout stages (draining,
  or organically overloaded) is unplaceable. Fields whose gauge was
  never set are null — a trainer process exposing /metrics has no
  queue, no pool, no brownout.

The server is a daemon `ThreadingHTTPServer` on its own thread: scrapes
never block the scheduler (instruments are individually lock-guarded,
and `prometheus_text()` takes each lock only long enough to copy), and
a wedged scrape client cannot wedge shutdown. `close()` (or the context
manager exit) tears the thread down with the owning loop — the CLI's
`serve --metrics-port` arms one around the serve run and closes it with
the scheduler.

Port 0 binds an OS-assigned ephemeral port (read it back from `.port`)
— the form tests use so parallel runs never collide.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from idc_models_tpu.observe import metrics_registry as mreg

# the /healthz freshness anchor: the serve metrics hooks stamp this
# gauge with time.monotonic() once per scheduler cycle
LAST_TICK_GAUGE = "serve_last_tick_monotonic_seconds"


class MetricsExporter:
    """Serve `registry` over HTTP from a daemon thread.

    >>> with MetricsExporter(port=0) as exp:
    ...     print(exp.url)          # http://127.0.0.1:<os-assigned>
    """

    def __init__(self, registry: mreg.MetricsRegistry | None = None, *,
                 port: int = 0, host: str = "127.0.0.1", cluster=None):
        self.registry = registry if registry is not None else mreg.REGISTRY
        # a serve.cluster.ClusterTelemetry arms the FLEET surfaces:
        # /metrics serves the merged replica-labeled registry (with
        # rollups) and /healthz the fleet document. None keeps the
        # single-process surfaces byte-identical to their historical
        # shape.
        self.cluster = cluster
        self._host = host
        self._requested_port = int(port)
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            raise RuntimeError("exporter already started")
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            # scrape logging would interleave with the run's own output
            def log_message(self, fmt, *args):  # noqa: ARG002
                return

            def do_GET(self):
                try:
                    if self.path in ("/metrics", "/metrics/"):
                        text = (exporter.cluster.prometheus_text()
                                if exporter.cluster is not None
                                else exporter.registry.prometheus_text())
                        body = text.encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    elif self.path in ("/healthz", "/healthz/"):
                        body = (json.dumps(exporter.health())
                                + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404, "unknown path (serving "
                                             "/metrics and /healthz)")
                        return
                except Exception as e:  # noqa: BLE001 — a scrape must
                    # never kill the handler thread; surface the error
                    # to the scraper instead
                    self.send_error(500, f"{type(e).__name__}: {e}")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="idc-metrics-exporter", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        """Shut the endpoint down with its owning loop. Idempotent."""
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()           # stops serve_forever
        server.server_close()       # releases the socket
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- introspection ----------------------------------------------------

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("exporter not started")
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def health(self) -> dict:
        """The /healthz document, from the registry's gauges alone (no
        reference into the scheduler: any process that maintains the
        gauges gets an honest health surface). Cluster-armed exporters
        serve the fleet document instead — every replica's health doc
        embedded, plus autoscaler and compile-cache state."""
        if self.cluster is not None:
            return self.cluster.health()

        def gauge_value(name):
            # the health gauges are unlabeled by contract — a labeled
            # gauge under one of these names has no single honest value
            inst = self.registry.get(name)
            if inst is None or inst.kind != "gauge" or inst.label_names:
                return None
            return inst.value(default=None)

        def tenant_series(name):
            # the tenant gauges are labeled by contract: collect every
            # tenant's point into {tenant: value}
            inst = self.registry.get(name)
            if (inst is None or inst.kind != "gauge"
                    or inst.label_names != ("tenant",)):
                return {}
            return {labels["tenant"]: val
                    for labels, val in inst._series()}

        last_tick = gauge_value(LAST_TICK_GAUGE)
        stage = gauge_value("serve_brownout_stage")
        doc = {
            "status": "ok",
            "last_tick_age_s": (
                None if last_tick is None
                else round(time.monotonic() - last_tick, 4)),
            "queue_depth": gauge_value("serve_queue_depth"),
            "slot_occupancy": gauge_value("serve_slot_occupancy"),
            # the cluster-router placement signals (ISSUE 12): page
            # headroom for paged engines, and the brownout stage so a
            # draining/shedding replica reads as unplaceable
            "kv_pages_used": gauge_value("serve_kv_pages_used"),
            "kv_pages_total": gauge_value("serve_kv_pages_total"),
            "brownout_stage": None if stage is None else int(stage),
        }
        # multi-tenant servers (serve/tenancy.py, ISSUE 14) grow a
        # per-tenant block — queue depth, slots, page reservations,
        # and each tenant's OWN brownout stage — so a load balancer
        # (or operator curl) can see WHICH tenant is degraded while
        # the server-wide document stays healthy. Absent (no key) on
        # tenant-less servers: the historical document shape is
        # byte-identical.
        depths = tenant_series("serve_tenant_queue_depth")
        slots = tenant_series("serve_tenant_slots_used")
        pages = tenant_series("serve_tenant_kv_pages_used")
        stages = tenant_series("serve_tenant_brownout_stage")
        names = (set(depths) | set(slots) | set(pages) | set(stages))
        if names:
            doc["tenants"] = {
                t: {
                    "queue_depth": depths.get(t),
                    "slots_used": slots.get(t),
                    "kv_pages_used": pages.get(t),
                    "brownout_stage": (None if t not in stages
                                       else int(stages[t])),
                } for t in sorted(names)}
        return doc
