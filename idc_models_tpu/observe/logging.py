"""Structured jsonl run logs.

The reference's observability is raw history-dict prints and a per-round
CSV-ish line (SURVEY.md §5, fed_model.py:229, dist_model_tf_vgg.py:100-101).
The framework keeps those human-readable prints at the call sites and adds
an append-only jsonl stream — one timestamped record per step/epoch/round —
so runs are machine-comparable.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path


class JsonlLogger:
    """Append-only jsonl writer; every record gets a wall-clock timestamp.

    Records with numpy/jax scalar values are coerced to Python floats so
    the file is plain JSON.
    """

    def __init__(self, path: str | os.PathLike):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a", buffering=1)

    def log(self, **record) -> None:
        rec = {"ts": time.time()}
        for k, v in record.items():
            rec[k] = _jsonable(v)
        self._f.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        """Flush + fsync before closing: a run log that dies with the
        process (OOM, preemption) must still hold every record already
        logged — line buffering alone leaves the last page in the OS
        cache."""
        if self._f.closed:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()

    def __enter__(self) -> "JsonlLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# arrays above this many elements are summarized, not inlined: a logger
# fed a whole activation/batch by accident must not write megabyte lines
# (or hang serializing them) into an append-only run log
_MAX_INLINE_ELEMENTS = 1024


def _jsonable(v):
    if hasattr(v, "item") and getattr(v, "ndim", None) in (0, None):
        try:
            return v.item()
        except Exception:
            pass
    if hasattr(v, "ndim") and getattr(v, "ndim", 0) > 0:
        # numpy/jax arrays: json.dumps would otherwise raise mid-run
        # (losing the record AND crashing the caller's loop). Size-check
        # from the SHAPE before any materialization — summarizing an
        # oversized device array must not fetch it to host first.
        try:
            import math

            import numpy as _np

            if math.prod(v.shape) > _MAX_INLINE_ELEMENTS:
                return {"__array__": True, "shape": list(v.shape),
                        "dtype": str(v.dtype)}
            return _np.asarray(v).tolist()
        except Exception:
            return repr(v)
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v
