"""Structured jsonl run logs.

The reference's observability is raw history-dict prints and a per-round
CSV-ish line (SURVEY.md §5, fed_model.py:229, dist_model_tf_vgg.py:100-101).
The framework keeps those human-readable prints at the call sites and adds
an append-only jsonl stream — one timestamped record per step/epoch/round —
so runs are machine-comparable.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path


class JsonlLogger:
    """Append-only jsonl writer; every record gets a wall-clock timestamp.

    Records with numpy/jax scalar values are coerced to Python floats so
    the file is plain JSON.
    """

    def __init__(self, path: str | os.PathLike):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a", buffering=1)

    def log(self, **record) -> None:
        rec = {"ts": time.time()}
        for k, v in record.items():
            rec[k] = _jsonable(v)
        self._f.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "JsonlLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _jsonable(v):
    if hasattr(v, "item") and getattr(v, "ndim", None) in (0, None):
        try:
            return v.item()
        except Exception:
            pass
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v
