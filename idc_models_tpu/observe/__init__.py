from idc_models_tpu.observe import trace  # noqa: F401
from idc_models_tpu.observe import profile  # noqa: F401
from idc_models_tpu.observe.exporter import MetricsExporter  # noqa: F401
from idc_models_tpu.observe.profile import (  # noqa: F401
    CompileWatchdog, DeviceTimeline, ProgramCost, RooflineSpec,
    arm_watchdog, disarm_watchdog, program_report, register_program,
    register_roof, roofline_for, roofline_verdict,
)
from idc_models_tpu.observe.logging import JsonlLogger  # noqa: F401
from idc_models_tpu.observe.metrics_registry import (  # noqa: F401
    REGISTRY, Counter, Gauge, Histogram, MetricsRegistry,
    default_registry,
)
from idc_models_tpu.observe.plots import plot_history  # noqa: F401
from idc_models_tpu.observe.slo import SLO, SLOEngine  # noqa: F401
from idc_models_tpu.observe.stats import (  # noqa: F401
    format_request_timeline, format_summary, summarize_jsonl,
)
from idc_models_tpu.observe.timer import Timer, profile_trace  # noqa: F401
from idc_models_tpu.observe.trace import (  # noqa: F401
    Tracer, get_tracer, set_tracer, tracing,
)
