from idc_models_tpu.observe.timer import Timer, profile_trace  # noqa: F401
from idc_models_tpu.observe.logging import JsonlLogger  # noqa: F401
from idc_models_tpu.observe.plots import plot_history  # noqa: F401
