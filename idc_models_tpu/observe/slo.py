"""Declarative SLOs with multi-window burn-rate alerting.

PR 5 made the metrics OBSERVABLE (registry + Prometheus text); this
module makes them ACTIONABLE: an `SLOEngine` holds a set of declared
objectives — "TTFT p95 <= 200 ms", "error rate <= 1%" — ingests the
same per-request/per-round samples the metrics hooks already see, and
evaluates them over two sliding windows with the standard burn-rate
alerting rule (Google SRE workbook): alert only when BOTH the short
window (fast detection, noisy alone) and the long window (sustained
evidence, slow alone) are burning error budget faster than
`burn_threshold`x. A breach surfaces three ways:

- a ``slo_alert`` jsonl record through the run's `JsonlLogger` (and a
  ``slo_resolved`` record when both windows recover);
- registry gauges ``slo_burn_rate{slo,window}`` / ``slo_breached{slo}``
  and counter ``slo_alerts_total{slo}`` — live on ``/metrics`` via
  `observe.exporter.MetricsExporter`;
- `breached(name)` — the boolean admission signal the multi-tenant
  scheduler (ROADMAP item 5) consumes to shed/deprioritize a tenant.

Every objective reduces to an ERROR BUDGET — the allowed fraction of
bad samples. A latency SLO "p95 <= T" is exactly "at most 5% of samples
exceed T", so a sample is *bad* when value > threshold and the budget
is 1 - 0.95; a rate SLO's budget is declared directly. Burn rate =
(observed bad fraction) / budget: 1.0 means "spending budget exactly as
fast as allowed", 2.0 means the budget will be gone in half the SLO
period.

Wired-in sample sources (each guarded by `has(name)` so an engine only
declares what it cares about):

- `serve/metrics.py`: ``ttft`` (seconds, per first token),
  ``queue_wait`` (seconds, per admission), ``error_rate`` (bad =
  finish reason error/timeout/deadline or a rejected submit);
  `evaluate()` runs once per scheduler cycle.
- `federated/driver.py`: ``round_seconds`` (wall seconds per attempt),
  ``round_failure_rate`` (bad = attempt status != ok); `evaluate()`
  runs once per attempt.

Clocks are injectable (`clock=`, monotonic by default) so tests drive
window arithmetic deterministically.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

from idc_models_tpu.observe import metrics_registry as mreg


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declared objective. Build via `SLO.latency(...)` or
    `SLO.rate(...)` — the constructors keep kind/threshold/budget
    consistent. `budget` is the allowed bad-sample fraction; for a
    latency objective it is implied by the percentile (p95 -> 0.05)."""

    name: str
    kind: str                    # "latency" | "rate"
    budget: float                # allowed bad fraction, in (0, 1)
    threshold_s: float | None = None   # latency kind: the bad cutoff
    percentile: float | None = None    # latency kind: documentation only

    def __post_init__(self):
        if self.kind not in ("latency", "rate"):
            raise ValueError(f"SLO kind must be 'latency' or 'rate', "
                             f"got {self.kind!r}")
        if not 0.0 < self.budget < 1.0:
            raise ValueError(f"SLO {self.name!r}: budget must be in "
                             f"(0, 1), got {self.budget}")
        if self.kind == "latency" and (self.threshold_s is None
                                       or self.threshold_s <= 0):
            raise ValueError(f"SLO {self.name!r}: latency objectives "
                             f"need threshold_s > 0, got "
                             f"{self.threshold_s}")

    @classmethod
    def latency(cls, name: str, *, threshold_s: float,
                percentile: float = 95.0) -> "SLO":
        """"p{percentile} of samples <= threshold_s": a sample is bad
        when it exceeds the threshold; the budget is the tail the
        percentile leaves (p95 -> 5% of samples may exceed it)."""
        if not 0.0 < percentile < 100.0:
            raise ValueError(f"percentile must be in (0, 100), got "
                             f"{percentile}")
        return cls(name=name, kind="latency",
                   budget=1.0 - percentile / 100.0,
                   threshold_s=float(threshold_s),
                   percentile=float(percentile))

    @classmethod
    def rate(cls, name: str, *, budget: float) -> "SLO":
        """"at most `budget` fraction of events are bad" — e.g.
        budget=0.01 for a 99% success objective."""
        return cls(name=name, kind="rate", budget=float(budget))


class _Window:
    """One sliding window's samples with running totals. Append and
    expiry are O(1) amortized, so a burn-rate evaluation costs
    O(expired samples) — it runs once per scheduler cycle on the serve
    hot path, where rescanning every sample retained over a 300 s long
    window would grow the tick cost with sustained load."""

    __slots__ = ("window_s", "q", "n", "bad")

    def __init__(self, window_s: float):
        self.window_s = window_s
        self.q: deque = deque()
        self.n = 0
        self.bad = 0

    def append(self, sample) -> None:
        self.q.append(sample)
        self.n += 1
        self.bad += sample[1]

    def prune(self, now: float) -> None:
        cutoff = now - self.window_s
        q = self.q
        while q and q[0][0] < cutoff:
            self.bad -= q.popleft()[1]
            self.n -= 1


class SLOEngine:
    """Sliding-window burn-rate evaluator over a set of `SLO`s.

    Feed latency objectives with `observe(name, seconds)` and rate
    objectives with `record(name, ok=...)`; call `evaluate()`
    periodically (per scheduler cycle / per round attempt — it is
    O(pruned samples) cheap). `alerts` accumulates every fired alert
    record; `breached(name)` is the live admission signal.

    An alert FIRES on the transition into "both windows burning >=
    burn_threshold with at least min_samples in the short window" and
    stays active (hysteresis) until both windows drop back below the
    threshold, at which point a ``slo_resolved`` record is emitted —
    so a flapping metric cannot spam one alert per evaluate().
    """

    def __init__(self, slos, *, short_window_s: float = 60.0,
                 long_window_s: float = 300.0,
                 burn_threshold: float = 2.0, min_samples: int = 10,
                 logger=None, registry=None, clock=time.monotonic):
        slos = list(slos)
        if not slos:
            raise ValueError("need at least one SLO")
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {sorted(names)}")
        if not 0 < short_window_s < long_window_s:
            raise ValueError(
                f"need 0 < short_window_s < long_window_s, got "
                f"{short_window_s} / {long_window_s}")
        if burn_threshold <= 0:
            raise ValueError(f"need burn_threshold > 0, got "
                             f"{burn_threshold}")
        self.slos = {s.name: s for s in slos}
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.burn_threshold = float(burn_threshold)
        self.min_samples = int(min_samples)
        self.logger = logger
        self.clock = clock
        reg = registry if registry is not None else mreg.REGISTRY
        self._g_burn = reg.gauge(
            "slo_burn_rate", "error-budget burn rate per SLO and "
            "evaluation window (1.0 = spending budget exactly as fast "
            "as the objective allows)", labels=("slo", "window"))
        self._g_breached = reg.gauge(
            "slo_breached", "1 while the SLO's multi-window burn-rate "
            "alert is active, else 0 — the admission/shedding signal",
            labels=("slo",))
        self._c_alerts = reg.counter(
            "slo_alerts_total", "burn-rate alerts fired per SLO",
            labels=("slo",))
        # per-SLO (t, bad) samples held once per window with running
        # counters (the tuple object is shared between the two deques)
        self._windows: dict[str, tuple[_Window, _Window]] = {
            n: (_Window(self.short_window_s), _Window(self.long_window_s))
            for n in self.slos}
        self._alerting: dict[str, bool] = {n: False for n in self.slos}
        self.alerts: list[dict] = []
        for n in self.slos:
            self._g_breached.set(0, slo=n)

    # -- ingestion -------------------------------------------------------

    def has(self, name: str) -> bool:
        """Whether `name` is a declared objective — instrumentation
        call sites guard on this so one engine wiring serves any SLO
        subset."""
        return name in self.slos

    def observe(self, name: str, value_s: float) -> None:
        """One latency sample (seconds) for a latency-kind SLO."""
        slo = self._get(name, "latency")
        self._append(name, float(value_s) > slo.threshold_s)

    def record(self, name: str, *, ok: bool) -> None:
        """One event outcome for a rate-kind SLO."""
        self._get(name, "rate")
        self._append(name, not ok)

    def _append(self, name: str, is_bad: bool) -> None:
        sample = (self.clock(), is_bad)
        for win in self._windows[name]:
            win.append(sample)

    def _get(self, name: str, kind: str) -> SLO:
        slo = self.slos.get(name)
        if slo is None:
            raise ValueError(f"unknown SLO {name!r} (declared: "
                             f"{sorted(self.slos)})")
        if slo.kind != kind:
            raise ValueError(
                f"SLO {name!r} is {slo.kind}-kind; use "
                f"{'observe()' if slo.kind == 'latency' else 'record()'}")
        return slo

    # -- evaluation ------------------------------------------------------

    def _window_burn(self, name: str, now: float,
                     win: _Window) -> tuple[float, int]:
        """(burn rate, sample count) over the trailing window."""
        win.prune(now)
        if win.n == 0:
            return 0.0, 0
        return (win.bad / win.n) / self.slos[name].budget, win.n

    def evaluate(self, now: float | None = None) -> list[dict]:
        """Evaluate every SLO at `now` (default: the engine clock).
        Updates the gauges, fires/resolves alerts on state transitions,
        and returns the alert records fired by THIS call."""
        now = self.clock() if now is None else now
        fired: list[dict] = []
        for name in self.slos:
            short_win, long_win = self._windows[name]
            burn_s, n_s = self._window_burn(name, now, short_win)
            burn_l, n_l = self._window_burn(name, now, long_win)
            self._g_burn.set(round(burn_s, 4), slo=name, window="short")
            self._g_burn.set(round(burn_l, 4), slo=name, window="long")
            breaching = (n_s >= self.min_samples
                         and burn_s >= self.burn_threshold
                         and burn_l >= self.burn_threshold)
            was = self._alerting[name]
            if breaching and not was:
                self._alerting[name] = True
                self._g_breached.set(1, slo=name)
                self._c_alerts.inc(slo=name)
                slo = self.slos[name]
                alert = {
                    "slo": name, "kind": slo.kind,
                    "burn_short": round(burn_s, 4),
                    "burn_long": round(burn_l, 4),
                    "samples_short": n_s, "samples_long": n_l,
                    "budget": slo.budget,
                    "burn_threshold": self.burn_threshold,
                    "short_window_s": self.short_window_s,
                    "long_window_s": self.long_window_s,
                }
                if slo.threshold_s is not None:
                    alert["threshold_s"] = slo.threshold_s
                self.alerts.append(alert)
                fired.append(alert)
                if self.logger is not None:
                    self.logger.log(event="slo_alert", **alert)
            elif was and not breaching:
                self._alerting[name] = False
                self._g_breached.set(0, slo=name)
                if self.logger is not None:
                    self.logger.log(event="slo_resolved", slo=name,
                                    burn_short=round(burn_s, 4),
                                    burn_long=round(burn_l, 4))
        return fired

    def state_doc(self) -> dict:
        """Per-objective live state for an embedding health document
        (the fleet /healthz, ISSUE 20): breached flag, current burn
        rates, and alerts fired so far — read off the gauges this
        engine already maintains, so the document and /metrics can
        never disagree."""
        return {
            name: {
                "kind": self.slos[name].kind,
                "breached": self._alerting[name],
                "burn_short": self._g_burn.value(
                    default=0.0, slo=name, window="short"),
                "burn_long": self._g_burn.value(
                    default=0.0, slo=name, window="long"),
                "alerts": int(self._c_alerts.value(slo=name)),
            }
            for name in sorted(self.slos)}

    def breached(self, name: str | None = None) -> bool:
        """Live alert state for `name` — the signal an admission policy
        consumes (shed/deprioritize while True). With ``name=None``,
        True while ANY declared objective is breached — the brownout
        controller's default trigger (serve/brownout.py), so one
        controller can guard a server that declares several SLOs."""
        if name is None:
            return any(self._alerting.values())
        if name not in self.slos:
            raise ValueError(f"unknown SLO {name!r} (declared: "
                             f"{sorted(self.slos)})")
        return self._alerting[name]
