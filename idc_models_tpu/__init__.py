"""idc_models_tpu — a TPU-native framework for IDC histopathology classification.

A ground-up JAX/XLA re-design of the capabilities of the reference
``jamesnguyen123/idc_models`` repository (see SURVEY.md): distributed
data-parallel transfer learning (VGG16 / MobileNetV2 / DenseNet201),
federated averaging, and secure (masked / homomorphic) aggregation —
expressed as sharded, jitted programs over a `jax.sharding.Mesh` instead
of tf.distribute strategies and NCCL.

Layering (bottom-up):

- `mesh` / `collectives`    device mesh + XLA collective wrappers (ICI/DCN)
- `partition`               regex->PartitionSpec sharding rules (FSDP/TP)
- `tp`                      channel-wise tensor parallelism ("model" axis)
- `ring_attention`          exact long-context attention, "seq"-sharded ring
- `ring_decode`             ring-sharded KV-cache single-token decoding
- `data`                    host-side loaders + host->HBM prefetch pipeline
- `models`                  explicit-pytree model zoo (pure jnp)
- `train`                   jitted train/eval steps, two-phase loops, metrics
- `federated`               FedAvg with client-per-core sharding
- `secure`                  pairwise-mask secure aggregation + Paillier parity
- `observe`                 timers, structured logs, curve plots, profiler
- `configs` / `cli`         the five reference preset workloads
"""

__version__ = "0.1.0"

from idc_models_tpu import (  # noqa: F401
    collectives, mesh, partition, ring_attention, ring_decode, tp,
)
