"""The five reference workloads as dataclass presets.

Parity target (SURVEY.md C19, §5 config): the reference configures runs
with module-level constants plus positional sys.argv (e.g.
dist_model_tf_vgg.py:8-17, fed_model.py:169-171, secure_fed_model.py:
213-216). Here each workload is a frozen dataclass; the five presets carry
the reference's exact hyperparameters and map 1:1 to `BASELINE.json`
"configs". The CLI exposes every field as a flag.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class DistPreset:
    """Data-parallel two-phase transfer learning (dist_model_tf_*.py)."""

    name: str
    model: str                   # registry key
    dataset: str                 # "idc" | "cifar10"
    num_outputs: int
    image_size: int
    lr: float
    epochs: int                  # phase-1 epochs
    fine_tune_epochs: int
    batch_size: int              # global (vgg/mobile) or per-replica (dense)
    per_replica_batch: bool      # dense scales batch by replica count
    fine_tune_at: int
    dataset_limit: int | None    # balanced-subset size
    repeats: int = 1             # dataset passes per epoch (dense=2,
    #                              dist_model_tf_dense.py:122-123 repeat(2))


@dataclasses.dataclass(frozen=True)
class FedPreset:
    """FedAvg with a pretrained backbone (fed_model.py)."""

    name: str = "fed"
    model: str = "vgg16"
    num_outputs: int = 1
    image_size: int = 50
    lr: float = 1e-3             # pretrain lr; clients use lr/10 (fed_model.py:208)
    pretrain_epochs: int = 10
    fine_tune_at: int = 15       # fed_model.py:63
    num_clients: int = 10        # fed_model.py:47 (scale to 32 on a pod)
    test_client_fraction: float = 0.2   # 8 train / 2 test (fed_model.py:47-49)
    local_epochs: int = 1
    batch_size: int = 32
    rounds: int = 10
    iid: bool = True
    dataset_limit: int | None = 30000


@dataclasses.dataclass(frozen=True)
class SecureFedPreset:
    """Secure-aggregation FedAvg on the small CNN (secure_fed_model.py)."""

    name: str = "secure_fed"
    model: str = "small_cnn"
    num_outputs: int = 1
    image_size: int = 10         # secure_fed_model.py:173-184 decodes 10x10
    lr: float = 1e-3
    num_clients: int = 8         # one per device; reference shards by NUM_CLIENTS
    local_epochs: int = 5        # secure_fed_model.py:131
    batch_size: int = 32
    rounds: int = 10
    percent: float = 0.5         # fraction of tensors encrypted/masked
    client_examples: int = 24000  # secure_fed_model.py:219
    test_examples: int = 6000     # secure_fed_model.py:220
    paillier: bool = False       # host-side parity mode instead of masks


# The reference's constants, file by file:
PRESETS = {
    # dist_model_tf_vgg.py:8-17,130 — VGG16, binary IDC, global B=32, lr 1e-3
    "vgg": DistPreset(
        name="vgg", model="vgg16", dataset="idc", num_outputs=1,
        image_size=50, lr=1e-3, epochs=10, fine_tune_epochs=10,
        batch_size=32, per_replica_batch=False, fine_tune_at=15,
        dataset_limit=30000),
    # dist_model_tf_mobile.py:8-16,130,146 — MobileNetV2, lr 1e-4, ft@100
    "mobile": DistPreset(
        name="mobile", model="mobilenet_v2", dataset="idc", num_outputs=1,
        image_size=50, lr=1e-4, epochs=10, fine_tune_epochs=10,
        batch_size=32, per_replica_batch=False, fine_tune_at=100,
        dataset_limit=24257),
    # dist_model_tf_dense.py:26-28,122-123,131-158 — DenseNet201 on
    # CIFAR-10, B=256/replica, lr 1e-4, ft@150, sparse CE (fixing quirk
    # Q4), train set repeat(2) per epoch
    "dense": DistPreset(
        name="dense", model="densenet201", dataset="cifar10", num_outputs=10,
        image_size=32, lr=1e-4, epochs=10, fine_tune_epochs=10,
        batch_size=256, per_replica_batch=True, fine_tune_at=150,
        dataset_limit=None, repeats=2),
    "fed": FedPreset(),
    "secure_fed": SecureFedPreset(),
}


# Bench/profile train-step configurations: the MEASURED-optimum
# per-chip batches and fine-tune settings the official benchmark
# (bench.py) times each backbone's train step at. The `profile` CLI
# verb reads the SAME table, because its acceptance bar is MFU
# agreement with bench's independently computed figure — re-tune a
# batch here and both surfaces move together. (Batch provenance:
# VGG 2048 measures ~5% above 1024, fits 16 GB HBM with the frozen
# backward DCE'd; mobile 4096 / dense 2048 are the
# experiments/backbone_mfu.jsonl optima. `lr` is the rate handed to
# rmsprop — the phase-2 client rate, preset lr / 10 for the BN
# backbones.)
BENCH_TRAIN_CONFIGS = {
    "vgg16": dict(image_size=50, num_outputs=1, fine_tune_at=15,
                  lr=1e-4, batch_per_chip=2048),
    "mobilenet_v2": dict(image_size=50, num_outputs=1, fine_tune_at=100,
                         lr=1e-5, batch_per_chip=4096),
    "densenet201": dict(image_size=32, num_outputs=10, fine_tune_at=150,
                        lr=1e-5, batch_per_chip=2048),
}


def get_preset(name: str):
    key = name.replace("-", "_")
    if key not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return PRESETS[key]
