"""Metric parity tests (AUROC vs sklearn, accuracies on fixed tensors)."""

import jax.numpy as jnp
import numpy as np
from sklearn.metrics import roc_auc_score

from idc_models_tpu.train import losses, metrics


def test_accuracy():
    logits = jnp.array([[2.0, 1.0, 0.0], [0.0, 3.0, 1.0]])
    labels = jnp.array([0, 2])
    assert float(metrics.accuracy(logits, labels)) == 0.5


def test_binary_accuracy():
    logits = jnp.array([1.5, -0.5, 0.2, -2.0])
    labels = jnp.array([1, 0, 0, 0])
    assert float(metrics.binary_accuracy(logits, labels)) == 0.75


def test_auroc_matches_sklearn():
    rng = np.random.default_rng(0)
    for _ in range(5):
        scores = rng.normal(size=200).astype(np.float32)
        labels = (rng.random(200) < 0.4).astype(np.int32)
        ours = float(metrics.auroc(jnp.asarray(scores), jnp.asarray(labels)))
        ref = roc_auc_score(labels, scores)
        np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_auroc_with_ties():
    scores = np.array([0.1, 0.1, 0.1, 0.9, 0.9, 0.5], np.float32)
    labels = np.array([0, 1, 0, 1, 0, 1], np.int32)
    ours = float(metrics.auroc(jnp.asarray(scores), jnp.asarray(labels)))
    ref = roc_auc_score(labels, scores)
    np.testing.assert_allclose(ours, ref, atol=1e-6)


def test_bce_matches_manual():
    logits = jnp.array([0.0, 2.0])
    labels = jnp.array([0, 1])
    expect = np.mean([np.log(2.0), np.log1p(np.exp(-2.0))])
    np.testing.assert_allclose(
        float(losses.binary_cross_entropy(logits, labels)), expect, rtol=1e-4)


def test_sparse_ce_uniform():
    logits = jnp.zeros((4, 10))
    labels = jnp.array([0, 3, 7, 9])
    np.testing.assert_allclose(
        float(losses.sparse_categorical_cross_entropy(logits, labels)),
        np.log(10.0), rtol=1e-4)
