"""Regression test: frozen parameters must receive exactly zero updates.

(optax.masked alone passes raw gradients through False leaves — caught by
driving the two-phase VGG flow; freeze_where is the fix.)
"""

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.models import small_cnn
from idc_models_tpu.models.core import trainability_mask
from idc_models_tpu.train import create_train_state, make_train_step, rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy


def test_frozen_params_do_not_move():
    model = small_cnn(10, 3, 1)
    variables = model.init(jax.random.key(0))
    mask = trainability_mask(variables.params, lambda p: p[0] == "head")
    opt = rmsprop(1e-2, trainable_mask=mask)
    state = create_train_state(model, opt, jax.random.key(0))
    step = jax.jit(make_train_step(model, opt, binary_cross_entropy))
    x = jnp.asarray(np.random.default_rng(0).random((16, 10, 10, 3)),
                    jnp.float32)
    y = jnp.asarray(np.arange(16) % 2)
    before = jax.device_get(state.params)
    for i in range(3):
        state, _ = step(state, x, y, jax.random.key(i))
    after = jax.device_get(state.params)
    for name in ("conv1", "fc1"):
        for k in before[name]:
            np.testing.assert_array_equal(before[name][k], after[name][k])
    assert not np.array_equal(before["head"]["kernel"],
                              after["head"]["kernel"])
