"""Fused masked-quantize Pallas kernel: bit-parity with the jnp reference
implementation and exact mask cancellation (interpret mode on CPU; the
same kernel compiles natively on TPU — verified on-chip)."""

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.ops import (
    fused_masked_quantize, masked_quantize_reference, pair_seeds_and_signs,
)

N = 8


def test_kernel_matches_reference_bitexact():
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(37, 13)).astype(np.float32))
    seeds, signs = pair_seeds_and_signs(123, 3, N, round_index=5)
    mk = fused_masked_quantize(x, seeds, signs, scale_bits=20, clip_abs=64.0,
                               interpret=True)
    mr = masked_quantize_reference(x, seeds, signs, scale_bits=20,
                                   clip_abs=64.0)
    np.testing.assert_array_equal(np.asarray(mk), np.asarray(mr))


def test_multiblock_grid_matches_reference():
    """> _BLOCK_ROWS rows: exercises the grid index math."""
    x = jnp.asarray(np.random.default_rng(1).normal(
        size=(600 * 128 + 7,)).astype(np.float32))
    seeds, signs = pair_seeds_and_signs(9, 1, 4)
    mk = fused_masked_quantize(x, seeds, signs, scale_bits=18, clip_abs=64.0,
                               interpret=True)
    mr = masked_quantize_reference(x, seeds, signs, scale_bits=18,
                                   clip_abs=64.0)
    np.testing.assert_array_equal(np.asarray(mk), np.asarray(mr))


def test_masks_cancel_and_hide():
    xs = {i: jnp.asarray(np.random.default_rng(i).normal(
        size=(11, 5)).astype(np.float32)) for i in range(N)}
    total_masked = jnp.zeros((11, 5), jnp.int32)
    total_plain = jnp.zeros((11, 5), jnp.int32)
    for i in range(N):
        seeds, signs = pair_seeds_and_signs(42, i, N, round_index=2)
        m = fused_masked_quantize(xs[i], seeds, signs, scale_bits=20,
                                  clip_abs=64.0, interpret=True)
        q = jnp.round(jnp.clip(xs[i], -64, 64) * 2**20).astype(jnp.int32)
        assert not np.array_equal(np.asarray(m), np.asarray(q)), \
            "masked contribution leaked plaintext"
        total_masked = total_masked + m
        total_plain = total_plain + q
    np.testing.assert_array_equal(np.asarray(total_masked),
                                  np.asarray(total_plain))


def test_pair_seeds_symmetric_antisymmetric():
    for i in range(N):
        si, gi = pair_seeds_and_signs(7, i, N)
        for j in range(N):
            sj, gj = pair_seeds_and_signs(7, j, N)
            assert int(si[j]) == int(sj[i])          # shared pair seed
            assert int(gi[j]) == -int(gj[i])         # antisymmetric signs
    # distinct rounds get distinct streams
    a, _ = pair_seeds_and_signs(7, 0, N, round_index=0)
    b, _ = pair_seeds_and_signs(7, 0, N, round_index=1)
    assert not np.array_equal(np.asarray(a), np.asarray(b))
