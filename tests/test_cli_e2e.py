"""End-to-end CLI smoke tests: every subcommand runs in-process on the
virtual 8-device mesh with tiny sizes.

The reference's product surface is its five entry points
(dist_model_tf_vgg.py:103, dist_model_tf_mobile.py:103,
dist_model_tf_dense.py:118, fed_model.py:168, secure_fed_model.py:212);
these tests drive the equivalent presets through `cli.main` exactly as a
user would, including the fed checkpoint gate + round resume and the
Paillier parity mode.
"""

import jax
import numpy as np
import pytest

from idc_models_tpu import cli

pytestmark = pytest.mark.filterwarnings(
    "ignore:.*synthetic.*:UserWarning")


def _run(args, capsys):
    assert cli.main(args) == 0
    return capsys.readouterr().out


def test_cli_vgg_two_phase(tmp_path, capsys):
    out = _run(["vgg", "--path", str(tmp_path), "--host-devices", "8",
                "--synthetic-examples", "64", "--batch-size", "8",
                "--epochs", "1", "--fine-tune-epochs", "1"], capsys)
    assert "Number of devices: 8" in out
    assert "initial loss" in out            # the evaluate floor (quirk Q3)
    assert "epoch 1/1" in out               # phase 1
    assert "epoch 2/2" in out               # phase 2 continues the counter
    assert "test:" in out
    assert (tmp_path / "logs" / "plot_dev8.png").exists()   # C18 artifact
    assert (tmp_path / "logs" / "run.jsonl").exists()


def test_cli_vgg_model_parallel(capsys):
    """--model-parallel 2 trains on a 4x2 ("data", "model") mesh through
    the product surface; the batch scales with the DATA axis only."""
    out = _run(["vgg", "--host-devices", "8", "--synthetic-examples", "64",
                "--batch-size", "8", "--epochs", "1",
                "--fine-tune-epochs", "1", "--model-parallel", "2"], capsys)
    assert "Number of devices: 8" in out
    assert "epoch 2/2" in out
    assert "test:" in out


def test_cli_vgg_pretrained_weights(tmp_path, capsys):
    """The --pretrained-weights flag demonstrably reaches the init: the
    run reports the load and starts from a different baseline."""
    from idc_models_tpu.models import pretrained
    from idc_models_tpu.models.vgg import vgg16

    variables = vgg16(1).init(jax.random.key(0))
    rng = np.random.default_rng(0)
    noisy = jax.tree.map(
        lambda x: np.asarray(x) + rng.normal(0, 0.1, np.shape(x))
        .astype(np.float32), variables.params["backbone"])
    npz = tmp_path / "bb.npz"
    pretrained.save_npz(npz, noisy)

    args = ["vgg", "--host-devices", "8", "--synthetic-examples", "64",
            "--batch-size", "8", "--epochs", "1", "--fine-tune-epochs", "0"]
    base = _run(args, capsys)
    warm = _run(args + ["--pretrained-weights", str(npz)], capsys)
    assert "loaded pretrained weights" in warm
    assert "loaded pretrained weights" not in base

    def floor(out):
        line = [ln for ln in out.splitlines() if "initial loss" in ln][0]
        return float(line.split(":")[1])

    assert floor(base) != floor(warm)


def test_cli_vgg_streamed(tmp_path, capsys):
    """--stream decodes train batches from disk on the fly; val/test are
    materialized from the same file-level split."""
    from PIL import Image

    data = tmp_path / "idc"
    rng = np.random.default_rng(0)
    for label in ("0", "1"):
        d = data / label
        d.mkdir(parents=True)
        for i in range(40):
            arr = (rng.random((50, 50, 3)) * 200).astype(np.uint8)
            Image.fromarray(arr).save(d / f"p{i}.png")
    out = _run(["vgg", "--path", str(tmp_path), "--data-dir", str(data),
                "--host-devices", "8", "--batch-size", "8", "--stream",
                "--epochs", "1", "--fine-tune-epochs", "1"], capsys)
    assert "epoch 2/2" in out and "test:" in out


def test_cli_vgg_streamed_decode_workers(tmp_path, capsys):
    """--decode-workers 2 fans decoding over worker processes and the
    run still trains (the stream itself is pinned bit-identical in
    test_data.py; this drives the CLI wiring)."""
    from PIL import Image

    data = tmp_path / "idc"
    rng = np.random.default_rng(1)
    for label in ("0", "1"):
        d = data / label
        d.mkdir(parents=True)
        for i in range(40):
            arr = (rng.random((50, 50, 3)) * 200).astype(np.uint8)
            Image.fromarray(arr).save(d / f"p{i}.png")
    out = _run(["vgg", "--path", str(tmp_path), "--data-dir", str(data),
                "--host-devices", "8", "--batch-size", "8", "--stream",
                "--decode-workers", "2", "--epochs", "1",
                "--fine-tune-epochs", "0"], capsys)
    assert "epoch 1/1" in out and "test:" in out


def test_cli_attention(tmp_path, capsys):
    """The sequence-parallel transformer workload from the product
    surface: trains on a ("data", "seq") mesh and reports val metrics
    incl. AUROC; the zigzag layout works through the same flags."""
    out = _run(["attention", "--host-devices", "8", "--steps", "40",
                "--seq-len", "32", "--embed-dim", "16", "--num-heads",
                "2", "--mlp-dim", "32", "--num-blocks", "1",
                "--batch-size", "32", "--path", str(tmp_path)], capsys)
    assert "(data=2, seq=4)" in out
    assert "val:" in out and "auroc=" in out
    assert (tmp_path / "logs" / "run.jsonl").exists()
    out = _run(["attention", "--host-devices", "8", "--steps", "10",
                "--seq-len", "64", "--embed-dim", "16", "--num-heads",
                "2", "--mlp-dim", "32", "--num-blocks", "1",
                "--layout", "zigzag", "--batch-size", "32"], capsys)
    assert "val:" in out


def test_cli_attention_rejects_bad_ring(capsys):
    with pytest.raises(SystemExit):
        cli.main(["attention", "--host-devices", "8",
                  "--seq-parallel", "3"])
    with pytest.raises(SystemExit):
        cli.main(["attention", "--host-devices", "8", "--seq-len", "30",
                  "--layout", "zigzag"])


def test_cli_attention_idc_tree(tmp_path, capsys):
    """--data-dir routes the SP workload onto the reference's own data
    domain (VERDICT r4 #5): the labeled IDC tree decodes through C1,
    splits 80/10/10, and each patch trains as a raster token sequence —
    seq-len/features derived from --image-size/--patch-size, ring
    divisibility still enforced."""
    from PIL import Image

    data = tmp_path / "idc"
    rng = np.random.default_rng(2)
    for label in ("0", "1"):
        d = data / label
        d.mkdir(parents=True)
        for i in range(30):
            arr = (rng.random((20, 20, 3)) * 200).astype(np.uint8)
            Image.fromarray(arr).save(d / f"p{i}.png")
    out = _run(["attention", "--host-devices", "8", "--data-dir",
                str(data), "--image-size", "20", "--patch-size", "5",
                "--steps", "12", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1", "--batch-size",
                "16", "--path", str(tmp_path)], capsys)
    # 20x20 at patch 5 -> 16 tokens x 75 features
    assert "16 tokens x 75 features" in out
    assert "val:" in out and "auroc=" in out
    # indivisible token count fails with the derived-shape message
    with pytest.raises(SystemExit):
        cli.main(["attention", "--host-devices", "8", "--data-dir",
                  str(data), "--image-size", "20", "--patch-size", "4",
                  "--layout", "zigzag"])   # 25 tokens, 8 stripes
    # patch size not dividing the image fails at flag validation
    with pytest.raises(SystemExit):
        cli.main(["attention", "--host-devices", "8", "--data-dir",
                  str(data), "--image-size", "20", "--patch-size", "3"])


def test_cli_mobile(capsys):
    out = _run(["mobile", "--host-devices", "8", "--synthetic-examples",
                "64", "--batch-size", "8", "--epochs", "1",
                "--fine-tune-epochs", "0"], capsys)
    assert "epoch 1/1" in out and "test:" in out


def test_cli_dense_cifar(capsys):
    out = _run(["dense", "--host-devices", "8", "--synthetic-examples",
                "64", "--batch-size", "4", "--epochs", "1",
                "--fine-tune-epochs", "0"], capsys)
    assert "epoch 1/1" in out and "test:" in out


def test_cli_fed_checkpoint_gate_and_resume(tmp_path, capsys):
    args = ["fed", "--path", str(tmp_path), "--host-devices", "8",
            "--synthetic-examples", "64", "--batch-size", "8",
            "--rounds", "2", "--num-clients", "8", "--local-epochs", "1",
            "--pretrain-epochs", "1", "--iid"]
    first = _run(args, capsys)
    assert "round, train_loss, train_acc, test_loss, test_acc" in first
    assert first.count("\n0, ") + first.count("\n1, ") == 2
    assert (tmp_path / "pretrained" / "cp.ckpt").exists()

    # Second run: pretrain gate skips training (fed_model.py:175, fixed
    # quirk Q5) and the round loop resumes past the completed rounds.
    second = _run(args + ["--rounds", "3"], capsys)
    assert "restored pretrained weights" in second
    assert "resuming federated training from round 2" in second
    assert "\n2, " in second and "\n1, " not in second

    # the append-only run.jsonl must hold exactly ONE record per round
    # across both runs (replayed rounds after an every-N checkpoint
    # resume print but do not re-log)
    import json

    recs = [json.loads(line) for line in
            (tmp_path / "logs" / "run.jsonl").read_text().splitlines()]
    rounds = [r["round"] for r in recs if r.get("event") == "round"]
    assert sorted(rounds) == [0, 1, 2]


def test_cli_secure_fed_masked(capsys):
    out = _run(["secure-fed", "--host-devices", "8",
                "--synthetic-examples", "256", "--batch-size", "8",
                "--rounds", "2", "--num-clients", "8",
                "--local-epochs", "1", "--percent", "0.5"], capsys)
    assert "round 0:" in out and "round 1:" in out
    assert "auroc=" in out                   # C16 metric on the eval path


def test_cli_secure_fed_paillier(capsys):
    out = _run(["secure-fed", "--host-devices", "8",
                "--synthetic-examples", "128", "--batch-size", "8",
                "--rounds", "1", "--num-clients", "2",
                "--local-epochs", "1", "--percent", "0.25", "--paillier"],
               capsys)
    assert "round 0:" in out
    assert "Client 0 training took" in out   # C17 per-client Timers


def test_cli_serve_synthetic_trace(tmp_path, capsys):
    """The continuous-batching engine from the product surface: a
    synthetic Poisson trace through `serve` on the virtual pod — the
    summary line, the request accounting, and the jsonl artifact. Engine
    semantics (parity, recycling, backpressure) are owned by
    tests/test_serve.py; this drives the CLI wiring end to end."""
    import json

    out = _run(["serve", "--host-devices", "8", "--requests", "6",
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1",
                "--path", str(tmp_path)], capsys)
    assert "serving 6 requests on 2 slots" in out
    assert "served: ok=6 timeout=0 rejected=0" in out
    line = [ln for ln in out.splitlines()
            if ln.startswith("serve summary:")][0]
    summary = json.loads(line.split("serve summary:", 1)[1])
    assert summary["serve_requests"] == 6
    assert summary["serve_tokens_per_sec"] > 0
    log = tmp_path / "logs" / "serve.jsonl"
    assert log.exists()
    events = {json.loads(l)["event"] for l in
              log.read_text().splitlines()}
    assert {"serve_submit", "serve_finish", "serve_summary"} <= events
    # a replayed JSONL trace drives the same path (load_trace format)
    from idc_models_tpu.serve import Request, save_trace

    trace = [(0.0, Request(id="t0", prompt=(1, 2, 3), max_new_tokens=4)),
             (0.01, Request(id="t1", prompt=(4, 5), max_new_tokens=6))]
    tr = save_trace(tmp_path / "trace.jsonl", trace)
    out = _run(["serve", "--host-devices", "8", "--trace", tr,
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1"], capsys)
    assert "serving 2 requests" in out and "served: ok=2" in out


def test_cli_serve_chunked_prefix_int8(tmp_path, capsys):
    """The PR-4 admission knobs from the product surface: chunked
    prefill + prefix cache + int8 KV together, the TTFT decomposition
    epilogue, and the serve_prefix_* summary fields. Correctness of the
    underlying machinery is owned by tests/test_serve.py and
    tests/test_prefix_cache.py."""
    import json

    out = _run(["serve", "--host-devices", "8", "--requests", "6",
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1",
                "--prefill-chunk", "8", "--prefix-cache-mb", "16",
                "--kv-dtype", "int8", "--path", str(tmp_path)], capsys)
    assert "served: ok=6" in out
    assert "ttft p95" in out and "queue-wait" in out
    assert "prefix cache: hit rate" in out
    line = [ln for ln in out.splitlines()
            if ln.startswith("serve summary:")][0]
    summary = json.loads(line.split("serve summary:", 1)[1])
    assert "serve_prefix_hit_rate" in summary
    assert summary["serve_queue_wait_ms_p95"] is not None
    assert summary["serve_prefill_ms_p95"] is not None
    # invalid knob combinations die with a usage error, not a traceback
    with pytest.raises(SystemExit):
        cli.main(["serve", "--host-devices", "8", "--t-max", "32",
                  "--prefill-chunk", "5"])
    with pytest.raises(SystemExit):
        cli.main(["serve", "--host-devices", "8", "--t-max", "32",
                  "--prefix-cache-mb", "4"])


def test_cli_serve_trace_out_and_stats(tmp_path, capsys):
    """ISSUE-5 observability from the product surface: a tiny chunked
    serve run with --trace-out produces a Perfetto-loadable Chrome
    trace-event JSON whose admission -> prefill-chunk and tick ->
    decode-window spans nest correctly, and the offline `stats`
    subcommand rolls the run's jsonl up into the percentile/counter
    summary — no re-run needed."""
    import json

    trace_path = tmp_path / "trace.json"
    out = _run(["serve", "--host-devices", "8", "--requests", "5",
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1",
                "--prefill-chunk", "8", "--path", str(tmp_path),
                "--trace-out", str(trace_path)], capsys)
    assert "served: ok=5" in out
    doc = json.loads(trace_path.read_text())
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    assert {"serve.tick", "serve.admit", "serve.collect",
            "serve.window", "serve.prefill_chunk",
            "Serving trace"} <= names
    by_id = {e["args"]["span_id"]: e for e in spans}
    # Perfetto's expectations: numeric microsecond ts/dur, and children
    # contained in their parent's interval
    for e in spans:
        assert e["ts"] >= 0 and e["dur"] >= 0
        parent = e["args"]["parent_id"]
        if parent is not None:
            p = by_id[parent]
            assert p["ts"] <= e["ts"] + 1e-3
            assert e["ts"] + e["dur"] <= p["ts"] + p["dur"] + 1e-3
    chunk_parents = {by_id[e["args"]["parent_id"]]["name"]
                     for e in spans
                     if e["name"] == "serve.prefill_chunk"}
    assert chunk_parents == {"serve.admit"}
    window_parents = {by_id[e["args"]["parent_id"]]["name"]
                      for e in spans if e["name"] == "serve.window"}
    assert window_parents == {"serve.tick"}

    # offline stats over the run's serve.jsonl
    out = _run(["stats", str(tmp_path / "logs" / "serve.jsonl")], capsys)
    assert "serve_submit" in out and "serve_finish" in out
    assert "p95=" in out and "mean=" in out
    assert "last metrics snapshot:" in out
    assert "serve_requests_total" in out
    out = _run(["stats", str(tmp_path / "logs" / "serve.jsonl"),
                "--json"], capsys)
    summary = json.loads(out)
    assert summary["events"]["serve_finish"]["count"] == 5
    # usage error, not a traceback, for a missing file
    with pytest.raises(SystemExit):
        cli.main(["stats", str(tmp_path / "nope.jsonl")])


def test_cli_lm(tmp_path, capsys):
    """The causal-LM workload from the product surface: the CLI wiring
    only (mesh line, metric line, generate line, jsonl artifact, ring
    rejection) — convergence + pattern-match is owned by
    tests/test_lm.py::test_lm_learns_and_generates, not re-proven
    here."""
    out = _run(["lm", "--host-devices", "8", "--steps", "20",
                "--vocab", "11", "--seq-len", "32", "--embed-dim", "16",
                "--num-heads", "2", "--mlp-dim", "32", "--num-blocks",
                "1", "--batch-size", "16", "--generate", "6",
                "--path", str(tmp_path)], capsys)
    assert "(data=2, seq=4)" in out
    assert "next-token accuracy" in out
    assert "generate:" in out
    assert (tmp_path / "logs" / "run.jsonl").exists()
    with pytest.raises(SystemExit):
        cli.main(["lm", "--host-devices", "8", "--seq-len", "30",
                  "--layout", "zigzag"])
