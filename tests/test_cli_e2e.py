"""End-to-end CLI smoke tests: every subcommand runs in-process on the
virtual 8-device mesh with tiny sizes.

The reference's product surface is its five entry points
(dist_model_tf_vgg.py:103, dist_model_tf_mobile.py:103,
dist_model_tf_dense.py:118, fed_model.py:168, secure_fed_model.py:212);
these tests drive the equivalent presets through `cli.main` exactly as a
user would, including the fed checkpoint gate + round resume and the
Paillier parity mode.
"""

import jax
import numpy as np
import pytest

from idc_models_tpu import cli

pytestmark = pytest.mark.filterwarnings(
    "ignore:.*synthetic.*:UserWarning")


@pytest.fixture(autouse=True)
def _restore_backend_roofs():
    """`profile --peak-tflops/--peak-gbps` registers the declared roof
    under the live device kind ("cpu" here) in the process-global
    BACKEND_ROOFS — restore it so tests/test_profile.py's
    unknown-backend assertions see the pristine table."""
    from idc_models_tpu.observe import profile as prof

    saved = dict(prof.BACKEND_ROOFS)
    yield
    prof.BACKEND_ROOFS.clear()
    prof.BACKEND_ROOFS.update(saved)


def _run(args, capsys):
    assert cli.main(args) == 0
    return capsys.readouterr().out


def test_cli_vgg_two_phase(tmp_path, capsys):
    out = _run(["vgg", "--path", str(tmp_path), "--host-devices", "8",
                "--synthetic-examples", "64", "--batch-size", "8",
                "--epochs", "1", "--fine-tune-epochs", "1"], capsys)
    assert "Number of devices: 8" in out
    assert "initial loss" in out            # the evaluate floor (quirk Q3)
    assert "epoch 1/1" in out               # phase 1
    assert "epoch 2/2" in out               # phase 2 continues the counter
    assert "test:" in out
    assert (tmp_path / "logs" / "plot_dev8.png").exists()   # C18 artifact
    assert (tmp_path / "logs" / "run.jsonl").exists()


def test_cli_vgg_model_parallel(capsys):
    """--model-parallel 2 trains on a 4x2 ("data", "model") mesh through
    the product surface; the batch scales with the DATA axis only."""
    out = _run(["vgg", "--host-devices", "8", "--synthetic-examples", "64",
                "--batch-size", "8", "--epochs", "1",
                "--fine-tune-epochs", "1", "--model-parallel", "2"], capsys)
    assert "Number of devices: 8" in out
    assert "epoch 2/2" in out
    assert "test:" in out


def test_cli_vgg_pretrained_weights(tmp_path, capsys):
    """The --pretrained-weights flag demonstrably reaches the init: the
    run reports the load and starts from a different baseline."""
    from idc_models_tpu.models import pretrained
    from idc_models_tpu.models.vgg import vgg16

    variables = vgg16(1).init(jax.random.key(0))
    rng = np.random.default_rng(0)
    noisy = jax.tree.map(
        lambda x: np.asarray(x) + rng.normal(0, 0.1, np.shape(x))
        .astype(np.float32), variables.params["backbone"])
    npz = tmp_path / "bb.npz"
    pretrained.save_npz(npz, noisy)

    args = ["vgg", "--host-devices", "8", "--synthetic-examples", "64",
            "--batch-size", "8", "--epochs", "1", "--fine-tune-epochs", "0"]
    base = _run(args, capsys)
    warm = _run(args + ["--pretrained-weights", str(npz)], capsys)
    assert "loaded pretrained weights" in warm
    assert "loaded pretrained weights" not in base

    def floor(out):
        line = [ln for ln in out.splitlines() if "initial loss" in ln][0]
        return float(line.split(":")[1])

    assert floor(base) != floor(warm)


def test_cli_vgg_streamed(tmp_path, capsys):
    """--stream decodes train batches from disk on the fly; val/test are
    materialized from the same file-level split."""
    from PIL import Image

    data = tmp_path / "idc"
    rng = np.random.default_rng(0)
    for label in ("0", "1"):
        d = data / label
        d.mkdir(parents=True)
        for i in range(40):
            arr = (rng.random((50, 50, 3)) * 200).astype(np.uint8)
            Image.fromarray(arr).save(d / f"p{i}.png")
    out = _run(["vgg", "--path", str(tmp_path), "--data-dir", str(data),
                "--host-devices", "8", "--batch-size", "8", "--stream",
                "--epochs", "1", "--fine-tune-epochs", "1"], capsys)
    assert "epoch 2/2" in out and "test:" in out


def test_cli_vgg_streamed_decode_workers(tmp_path, capsys):
    """--decode-workers 2 fans decoding over worker processes and the
    run still trains (the stream itself is pinned bit-identical in
    test_data.py; this drives the CLI wiring)."""
    from PIL import Image

    data = tmp_path / "idc"
    rng = np.random.default_rng(1)
    for label in ("0", "1"):
        d = data / label
        d.mkdir(parents=True)
        for i in range(40):
            arr = (rng.random((50, 50, 3)) * 200).astype(np.uint8)
            Image.fromarray(arr).save(d / f"p{i}.png")
    out = _run(["vgg", "--path", str(tmp_path), "--data-dir", str(data),
                "--host-devices", "8", "--batch-size", "8", "--stream",
                "--decode-workers", "2", "--epochs", "1",
                "--fine-tune-epochs", "0"], capsys)
    assert "epoch 1/1" in out and "test:" in out


def test_cli_attention(tmp_path, capsys):
    """The sequence-parallel transformer workload from the product
    surface: trains on a ("data", "seq") mesh and reports val metrics
    incl. AUROC; the zigzag layout works through the same flags."""
    out = _run(["attention", "--host-devices", "8", "--steps", "40",
                "--seq-len", "32", "--embed-dim", "16", "--num-heads",
                "2", "--mlp-dim", "32", "--num-blocks", "1",
                "--batch-size", "32", "--path", str(tmp_path)], capsys)
    assert "(data=2, seq=4)" in out
    assert "val:" in out and "auroc=" in out
    assert (tmp_path / "logs" / "run.jsonl").exists()
    out = _run(["attention", "--host-devices", "8", "--steps", "10",
                "--seq-len", "64", "--embed-dim", "16", "--num-heads",
                "2", "--mlp-dim", "32", "--num-blocks", "1",
                "--layout", "zigzag", "--batch-size", "32"], capsys)
    assert "val:" in out


def test_cli_attention_rejects_bad_ring(capsys):
    with pytest.raises(SystemExit):
        cli.main(["attention", "--host-devices", "8",
                  "--seq-parallel", "3"])
    with pytest.raises(SystemExit):
        cli.main(["attention", "--host-devices", "8", "--seq-len", "30",
                  "--layout", "zigzag"])


def test_cli_attention_idc_tree(tmp_path, capsys):
    """--data-dir routes the SP workload onto the reference's own data
    domain (VERDICT r4 #5): the labeled IDC tree decodes through C1,
    splits 80/10/10, and each patch trains as a raster token sequence —
    seq-len/features derived from --image-size/--patch-size, ring
    divisibility still enforced."""
    from PIL import Image

    data = tmp_path / "idc"
    rng = np.random.default_rng(2)
    for label in ("0", "1"):
        d = data / label
        d.mkdir(parents=True)
        for i in range(30):
            arr = (rng.random((20, 20, 3)) * 200).astype(np.uint8)
            Image.fromarray(arr).save(d / f"p{i}.png")
    out = _run(["attention", "--host-devices", "8", "--data-dir",
                str(data), "--image-size", "20", "--patch-size", "5",
                "--steps", "12", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1", "--batch-size",
                "16", "--path", str(tmp_path)], capsys)
    # 20x20 at patch 5 -> 16 tokens x 75 features
    assert "16 tokens x 75 features" in out
    assert "val:" in out and "auroc=" in out
    # indivisible token count fails with the derived-shape message
    with pytest.raises(SystemExit):
        cli.main(["attention", "--host-devices", "8", "--data-dir",
                  str(data), "--image-size", "20", "--patch-size", "4",
                  "--layout", "zigzag"])   # 25 tokens, 8 stripes
    # patch size not dividing the image fails at flag validation
    with pytest.raises(SystemExit):
        cli.main(["attention", "--host-devices", "8", "--data-dir",
                  str(data), "--image-size", "20", "--patch-size", "3"])


def test_cli_mobile(capsys):
    out = _run(["mobile", "--host-devices", "8", "--synthetic-examples",
                "64", "--batch-size", "8", "--epochs", "1",
                "--fine-tune-epochs", "0"], capsys)
    assert "epoch 1/1" in out and "test:" in out


def test_cli_dense_cifar(capsys):
    out = _run(["dense", "--host-devices", "8", "--synthetic-examples",
                "64", "--batch-size", "4", "--epochs", "1",
                "--fine-tune-epochs", "0"], capsys)
    assert "epoch 1/1" in out and "test:" in out


def test_cli_fed_checkpoint_gate_and_resume(tmp_path, capsys):
    args = ["fed", "--path", str(tmp_path), "--host-devices", "8",
            "--synthetic-examples", "64", "--batch-size", "8",
            "--rounds", "2", "--num-clients", "8", "--local-epochs", "1",
            "--pretrain-epochs", "1", "--iid"]
    first = _run(args, capsys)
    assert "round, train_loss, train_acc, test_loss, test_acc" in first
    assert first.count("\n0, ") + first.count("\n1, ") == 2
    assert (tmp_path / "pretrained" / "cp.ckpt").exists()

    # Second run: pretrain gate skips training (fed_model.py:175, fixed
    # quirk Q5) and the round loop resumes past the completed rounds.
    second = _run(args + ["--rounds", "3"], capsys)
    assert "restored pretrained weights" in second
    assert "resuming federated training from round 2" in second
    assert "\n2, " in second and "\n1, " not in second

    # the append-only run.jsonl must hold exactly ONE record per round
    # across both runs (replayed rounds after an every-N checkpoint
    # resume print but do not re-log)
    import json

    recs = [json.loads(line) for line in
            (tmp_path / "logs" / "run.jsonl").read_text().splitlines()]
    rounds = [r["round"] for r in recs if r.get("event") == "round"]
    assert sorted(rounds) == [0, 1, 2]


def test_cli_fed_population_sync_and_resume(tmp_path, capsys):
    """Population mode through the product surface: virtual clients,
    cohort sampling, streamed waves, the population epilogue line, the
    fed_cohort jsonl events, and checkpoint/resume regenerating later
    cohorts in a REAL second run (the cross-process half of the
    sampler-determinism satellite)."""
    import json

    args = ["fed", "--population", "64", "--cohort", "8",
            "--cohort-wave", "4", "--rounds", "2", "--batch-size", "8",
            "--client-examples", "8", "--local-epochs", "1",
            "--model", "small_cnn", "--path", str(tmp_path)]
    first = _run(args, capsys)
    assert "round, train_loss, train_acc, test_loss, test_acc" in first
    assert ("population: 64 virtual clients, cohort 8 (uniform) in "
            "2 wave(s) of 4") in first
    second = _run(args + ["--rounds", "3"], capsys)  # last flag wins
    assert "resuming federated training from round 2" in second
    assert "\n2, " in second and "\n1, " not in second
    recs = [json.loads(line) for line in
            (tmp_path / "logs" / "run.jsonl").read_text().splitlines()]
    cohorts = [r for r in recs if r.get("event") == "fed_cohort"]
    assert [r["round"] for r in cohorts] == [0, 1, 2]
    assert all(r["mode"] == "sync" and r["population"] == 64
               and r["waves"] == 2 for r in cohorts)
    rounds = [r["round"] for r in recs if r.get("event") == "round"]
    assert sorted(rounds) == [0, 1, 2]       # resume never double-logs


def test_cli_fed_population_async(tmp_path, capsys):
    out = _run(["fed", "--population", "64", "--cohort", "8",
                "--rounds", "2", "--batch-size", "8",
                "--client-examples", "8", "--local-epochs", "1",
                "--model", "small_cnn", "--async-buffer", "4",
                "--staleness-decay", "0.8",
                "--faults", "crash:*:10%",
                "--path", str(tmp_path)], capsys)
    assert "async buffer: K=4, staleness decay 0.8" in out
    assert "buffered update(s)" in out
    import json

    recs = [json.loads(line) for line in
            (tmp_path / "logs" / "run.jsonl").read_text().splitlines()]
    cohorts = [r for r in recs if r.get("event") == "fed_cohort"]
    assert cohorts and all(r["mode"] == "async" and r["buffer"] == 4
                           for r in cohorts)
    assert all(len(r["staleness_hist"]) == 6 for r in cohorts)


def test_cli_fed_population_usage_errors(capsys):
    """ISSUE-13 satellite: every bad population knob dies as a TEACHING
    usage error, never a traceback — cohort > population, non-positive
    async buffer, staleness decay out of range, non-dividing wave, a
    bad population fault spec, and secure x async rejected at build."""
    base = ["fed", "--host-devices", "2", "--model", "small_cnn"]
    with pytest.raises(SystemExit, match="exceeds --population"):
        cli.main(base + ["--population", "10", "--cohort", "20"])
    with pytest.raises(SystemExit, match="--async-buffer must be"):
        cli.main(base + ["--population", "10", "--cohort", "5",
                         "--async-buffer", "-2"])
    with pytest.raises(SystemExit, match="--staleness-decay must be"):
        cli.main(base + ["--population", "10", "--cohort", "5",
                         "--staleness-decay", "1.5"])
    with pytest.raises(SystemExit, match="--client-examples must be"):
        cli.main(base + ["--population", "10", "--cohort", "5",
                         "--client-examples", "0"])
    with pytest.raises(SystemExit, match="--cohort-wave only applies"):
        cli.main(base + ["--population", "10", "--cohort", "4",
                         "--cohort-wave", "2", "--async-buffer", "2"])
    with pytest.raises(SystemExit, match="--fault-delay-ms must be"):
        cli.main(base + ["--population", "10", "--cohort", "4",
                         "--fault-delay-ms", "-5"])
    with pytest.raises(SystemExit, match="must divide the cohort"):
        cli.main(base + ["--population", "10", "--cohort", "6",
                         "--cohort-wave", "4"])
    with pytest.raises(SystemExit) as ei:
        cli.main(base + ["--population", "10", "--cohort", "4",
                         "--faults", "meteor:1:5%"])
    assert "grammar" in str(ei.value)        # the teaching message
    with pytest.raises(SystemExit, match="secure aggregation"):
        cli.main(["secure-fed", "--host-devices", "2",
                  "--async-buffer", "4"])


def test_cli_secure_fed_masked(capsys):
    out = _run(["secure-fed", "--host-devices", "8",
                "--synthetic-examples", "256", "--batch-size", "8",
                "--rounds", "2", "--num-clients", "8",
                "--local-epochs", "1", "--percent", "0.5"], capsys)
    assert "round 0:" in out and "round 1:" in out
    assert "auroc=" in out                   # C16 metric on the eval path


def test_cli_secure_fed_paillier(capsys):
    out = _run(["secure-fed", "--host-devices", "8",
                "--synthetic-examples", "128", "--batch-size", "8",
                "--rounds", "1", "--num-clients", "2",
                "--local-epochs", "1", "--percent", "0.25", "--paillier"],
               capsys)
    assert "round 0:" in out
    assert "Client 0 training took" in out   # C17 per-client Timers


def test_cli_serve_synthetic_trace(tmp_path, capsys):
    """The continuous-batching engine from the product surface: a
    synthetic Poisson trace through `serve` on the virtual pod — the
    summary line, the request accounting, and the jsonl artifact. Engine
    semantics (parity, recycling, backpressure) are owned by
    tests/test_serve.py; this drives the CLI wiring end to end."""
    import json

    out = _run(["serve", "--host-devices", "8", "--requests", "6",
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1",
                "--path", str(tmp_path)], capsys)
    assert "serving 6 requests on 2 slots" in out
    assert "served: ok=6 timeout=0 rejected=0" in out
    line = [ln for ln in out.splitlines()
            if ln.startswith("serve summary:")][0]
    summary = json.loads(line.split("serve summary:", 1)[1])
    assert summary["serve_requests"] == 6
    assert summary["serve_tokens_per_sec"] > 0
    log = tmp_path / "logs" / "serve.jsonl"
    assert log.exists()
    events = {json.loads(l)["event"] for l in
              log.read_text().splitlines()}
    assert {"serve_submit", "serve_finish", "serve_summary"} <= events
    # a replayed JSONL trace drives the same path (load_trace format)
    from idc_models_tpu.serve import Request, save_trace

    trace = [(0.0, Request(id="t0", prompt=(1, 2, 3), max_new_tokens=4)),
             (0.01, Request(id="t1", prompt=(4, 5), max_new_tokens=6))]
    tr = save_trace(tmp_path / "trace.jsonl", trace)
    out = _run(["serve", "--host-devices", "8", "--trace", tr,
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1"], capsys)
    assert "serving 2 requests" in out and "served: ok=2" in out


def test_cli_serve_drafter_learned_and_usage_gates(tmp_path, capsys):
    """`serve --drafter chained --draft-ckpt DIR` from the product
    surface: an (untrained) distilled checkpoint loads, the chained
    drafter serves the trace, and the speculative epilogue names the
    drafter and its propose accounting. Usage misfits — a learned
    drafter without its checkpoint, a drafter outside the speculative
    loop, an orphaned checkpoint, a vocab mismatch — die as teaching
    errors before any device work."""
    import jax

    from idc_models_tpu.models import draft_lm as dlm

    cfg = dlm.draft_config(11, 32)
    dparams = dlm.draft_lm(cfg).init(jax.random.key(5)).params
    ckpt = str(tmp_path / "draft_ckpt")
    dlm.save_draft_lm(ckpt, jax.device_get(dparams),
                      config=cfg).wait()
    dims = ["--host-devices", "8", "--requests", "4", "--slots", "2",
            "--window", "4", "--t-max", "32", "--vocab", "11",
            "--embed-dim", "16", "--num-heads", "2", "--mlp-dim",
            "32", "--num-blocks", "1"]
    out = _run(["serve", *dims, "--spec-decode", "--draft-k", "3",
                "--drafter", "chained", "--draft-ckpt", ckpt], capsys)
    assert "served: ok=4" in out
    assert "speculative (chained):" in out
    assert "propose_s=" in out
    # usage gates: each one a SystemExit that says what to change
    with pytest.raises(SystemExit):
        cli.main(["serve", *dims, "--spec-decode", "--draft-k", "3",
                  "--drafter", "learned"])        # no --draft-ckpt
    with pytest.raises(SystemExit):
        cli.main(["serve", *dims, "--drafter", "learned",
                  "--draft-ckpt", ckpt])          # no --spec-decode
    with pytest.raises(SystemExit):
        cli.main(["serve", *dims, "--spec-decode", "--draft-k", "3",
                  "--draft-ckpt", ckpt])          # ckpt with ngram
    # tokenizer mismatch: vocab-11 checkpoint against a --vocab 13
    # target dies naming both vocabs
    dims13 = [a if a != "11" else "13" for a in dims]
    with pytest.raises(SystemExit) as e:
        cli.main(["serve", *dims13, "--spec-decode", "--draft-k", "3",
                  "--drafter", "learned", "--draft-ckpt", ckpt])
    assert "vocab" in str(e.value)


def test_cli_serve_chunked_prefix_int8(tmp_path, capsys):
    """The PR-4 admission knobs from the product surface: chunked
    prefill + prefix cache + int8 KV together, the TTFT decomposition
    epilogue, and the serve_prefix_* summary fields. Correctness of the
    underlying machinery is owned by tests/test_serve.py and
    tests/test_prefix_cache.py."""
    import json

    out = _run(["serve", "--host-devices", "8", "--requests", "6",
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1",
                "--prefill-chunk", "8", "--prefix-cache-mb", "16",
                "--kv-dtype", "int8", "--path", str(tmp_path)], capsys)
    assert "served: ok=6" in out
    assert "ttft p95" in out and "queue-wait" in out
    assert "prefix cache: hit rate" in out
    line = [ln for ln in out.splitlines()
            if ln.startswith("serve summary:")][0]
    summary = json.loads(line.split("serve summary:", 1)[1])
    assert "serve_prefix_hit_rate" in summary
    assert summary["serve_queue_wait_ms_p95"] is not None
    assert summary["serve_prefill_ms_p95"] is not None
    # invalid knob combinations die with a usage error, not a traceback
    with pytest.raises(SystemExit):
        cli.main(["serve", "--host-devices", "8", "--t-max", "32",
                  "--prefill-chunk", "5"])
    with pytest.raises(SystemExit):
        cli.main(["serve", "--host-devices", "8", "--t-max", "32",
                  "--prefix-cache-mb", "4"])


def test_cli_serve_paged_kv(tmp_path, capsys):
    """ISSUE-11 paged KV from the product surface: the --kv-page-size/
    --kv-pages knobs, the page-occupancy epilogue, the serve_kv_*
    summary fields, and the usage-error gates. Engine semantics are
    owned by tests/test_paged_kv.py."""
    import json

    out = _run(["serve", "--host-devices", "8", "--requests", "6",
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1",
                "--prefill-chunk", "8", "--kv-page-size", "4",
                "--kv-pages", "16", "--prefix-cache-mb", "4",
                "--path", str(tmp_path)], capsys)
    assert "served: ok=6" in out
    assert "paged kv:" in out and "pages peak" in out
    assert "tokens/HBM-byte" in out
    line = [ln for ln in out.splitlines()
            if ln.startswith("serve summary:")][0]
    summary = json.loads(line.split("serve summary:", 1)[1])
    assert summary["serve_kv_pages_total"] == 16
    assert 0 < summary["serve_kv_pages_used_peak"] <= 16
    assert summary["serve_kv_tokens_per_hbm_byte"] > 0
    # usage-error gates: each bad combination dies cleanly
    for args in (["--kv-page-size", "4"],                  # no pages
                 ["--kv-pages", "16"],                     # no size
                 ["--kv-page-size", "4", "--kv-pages", "16"],  # no chunk
                 ["--prefill-chunk", "8", "--kv-page-size", "5",
                  "--kv-pages", "16"],                     # 5 !| 32
                 ["--prefill-chunk", "8", "--kv-page-size", "16",
                  "--kv-pages", "16"],                     # 16 !| 8
                 ["--prefill-chunk", "8", "--kv-page-size", "4",
                  "--kv-pages", "4"],                      # < t_max
                 ["--kv-decode-reserve", "4"]):            # not paged
        with pytest.raises(SystemExit):
            cli.main(["serve", "--host-devices", "8", "--t-max", "32"]
                     + args)


def test_cli_serve_trace_out_and_stats(tmp_path, capsys):
    """ISSUE-5/7 observability from the product surface, one chunked
    serve run covering the whole stack: --trace-out produces a
    Perfetto-loadable Chrome trace whose admission -> prefill-chunk
    and tick -> decode-window spans nest correctly AND whose
    request-lifecycle chain (serve.request > serve.queued /
    serve.first_token, rid-stamped prefill chunks and windows)
    reconstructs every finished rid's timeline; --metrics-port serves
    a live /metrics + /healthz a scraper hits DURING the run; the SLO
    flags stay silent on this clean run; and the offline `stats`
    subcommand rolls the run's jsonl up, including the per-request
    timeline (--request RID)."""
    import json
    import socket
    import threading
    import urllib.request

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    scraped = {}

    def scrape():
        # poll until the exporter binds (it arms before the engine's
        # warmup compiles, so the window is wide), then scrape both
        # endpoints while the run is LIVE
        import time as _time

        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=2) as r:
                    scraped["metrics"] = r.read().decode()
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz",
                        timeout=2) as r:
                    scraped["healthz"] = r.read().decode()
                return
            except OSError:
                _time.sleep(0.02)

    scraper = threading.Thread(target=scrape, daemon=True)
    scraper.start()
    trace_path = tmp_path / "trace.json"
    # --realtime at ~2 req/s stretches the run over a couple of wall
    # seconds even with every program warm in the jit cache, so the
    # scraper thread deterministically lands inside the live window
    out = _run(["serve", "--host-devices", "8", "--requests", "5",
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
                "--mlp-dim", "32", "--num-blocks", "1",
                "--prefill-chunk", "8", "--path", str(tmp_path),
                "--trace-out", str(trace_path),
                "--rate", "2.0", "--realtime",
                "--metrics-port", str(port),
                "--slo-ttft-p95-ms", "60000",
                "--slo-error-rate", "0.5"], capsys)
    scraper.join(timeout=10)
    assert "served: ok=5" in out
    # the live exposition was really scraped mid-run, in the exact
    # Prometheus text shape, and /healthz parsed
    assert f"metrics: http://127.0.0.1:{port}/metrics" in out
    assert "metrics" in scraped, "scraper never reached /metrics"
    assert "# TYPE serve_requests_submitted_total counter" \
        in scraped["metrics"]
    health = json.loads(scraped["healthz"])
    assert health["status"] == "ok"
    # the clean run trips no SLO alert (the faulty side is gated in
    # tests/test_slo.py)
    assert "slo: 0 alert(s)" in out
    doc = json.loads(trace_path.read_text())
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    assert {"serve.tick", "serve.admit", "serve.collect",
            "serve.window", "serve.prefill_chunk",
            "Serving trace"} <= names
    by_id = {e["args"]["span_id"]: e for e in spans}
    # Perfetto's expectations: numeric microsecond ts/dur, and children
    # contained in their parent's interval
    for e in spans:
        assert e["ts"] >= 0 and e["dur"] >= 0
        parent = e["args"]["parent_id"]
        if parent is not None:
            p = by_id[parent]
            assert p["ts"] <= e["ts"] + 1e-3
            assert e["ts"] + e["dur"] <= p["ts"] + p["dur"] + 1e-3
    chunk_parents = {by_id[e["args"]["parent_id"]]["name"]
                     for e in spans
                     if e["name"] == "serve.prefill_chunk"}
    assert chunk_parents == {"serve.admit"}
    window_parents = {by_id[e["args"]["parent_id"]]["name"]
                      for e in spans if e["name"] == "serve.window"}
    assert window_parents == {"serve.tick"}

    # ISSUE-7 acceptance: for EVERY finished rid, the submit->finish
    # chain reconstructs from the exported file with correct nesting
    finished = {json.loads(l)["id"] for l in
                (tmp_path / "logs" / "serve.jsonl").read_text()
                .splitlines()
                if json.loads(l).get("event") == "serve_finish"}
    assert len(finished) == 5
    req_by_rid = {e["args"]["rid"]: e for e in spans
                  if e["name"] == "serve.request"}
    for rid in finished:
        req = req_by_rid[rid]
        assert req["args"]["status"] == "ok"
        assert req["args"]["parent_id"] is None
        mine = [e for e in spans if e["args"].get("rid") == rid]
        names = {e["name"] for e in mine}
        assert {"serve.request", "serve.queued", "serve.first_token",
                "serve.prefill_chunk"} <= names, (rid, names)
        for e in mine:
            # the whole chain shares the request's trace_id (where
            # stamped) and sits inside the request span's interval
            if "trace_id" in e["args"]:
                assert e["args"]["trace_id"] == req["args"]["trace_id"]
            assert req["ts"] <= e["ts"] + 1e-3
            assert (e["ts"] + e["dur"]
                    <= req["ts"] + req["dur"] + 1e-3)
            if e["name"] in ("serve.queued", "serve.first_token"):
                assert (e["args"]["parent_id"]
                        == req["args"]["span_id"])
        # the decode windows that carried this rid name it
        assert any(rid in (e["args"].get("rids") or [])
                   for e in spans if e["name"] == "serve.window")

    # offline stats over the run's serve.jsonl
    out = _run(["stats", str(tmp_path / "logs" / "serve.jsonl")], capsys)
    assert "serve_submit" in out and "serve_finish" in out
    assert "p95=" in out and "mean=" in out
    assert "last metrics snapshot:" in out
    assert "serve_requests_total" in out
    assert "requests: 5 with per-request timelines" in out
    out = _run(["stats", str(tmp_path / "logs" / "serve.jsonl"),
                "--json"], capsys)
    summary = json.loads(out)
    assert summary["events"]["serve_finish"]["count"] == 5
    # the per-request timeline rides the --json output too
    rid = sorted(summary["requests"])[0]
    whats = [e["what"] for e in summary["requests"][rid]]
    assert whats[0] == "serve_submit" and "serve_finish" in whats
    # ...and --request renders ONE request's timeline
    out = _run(["stats", str(tmp_path / "logs" / "serve.jsonl"),
                "--request", rid], capsys)
    assert f"request {rid}" in out
    assert "serve_submit" in out and "serve_finish" in out
    # usage error, not a traceback, for a missing file / unknown rid /
    # bad SLO or port flags
    with pytest.raises(SystemExit):
        cli.main(["stats", str(tmp_path / "nope.jsonl")])
    with pytest.raises(SystemExit):
        cli.main(["stats", str(tmp_path / "logs" / "serve.jsonl"),
                  "--request", "no-such-rid"])
    with pytest.raises(SystemExit):
        cli.main(["serve", "--host-devices", "8",
                  "--slo-error-rate", "2.0"])
    with pytest.raises(SystemExit):
        cli.main(["serve", "--host-devices", "8",
                  "--metrics-port", "-1"])


def test_cli_stats_covers_train_and_fed_jsonl(tmp_path, capsys):
    """ISSUE-7 satellite: the `stats` verb end-to-end over a train/fed-
    SHAPED run.jsonl (epoch records + the driver's real round/
    round_health stream + a metrics snapshot) — the serve path is
    covered by test_cli_serve_trace_out_and_stats; this closes the gap
    for the other two run-log families."""
    import json

    import jax.numpy as jnp
    import numpy as np

    from idc_models_tpu.federated.driver import DriverConfig, run_rounds
    from idc_models_tpu.federated.fedavg import ServerState
    from idc_models_tpu.observe import REGISTRY, JsonlLogger

    def round_fn(server, images, labels, weights, rng):
        new = ServerState(round=server.round + 1, params=server.params,
                          model_state=server.model_state)
        return new, {"loss": jnp.float32(0.4),
                     "accuracy": jnp.float32(0.9),
                     "clients_dropped": jnp.int32(0)}

    server = ServerState(round=jnp.zeros((), jnp.int32),
                         params={"w": jnp.ones((2,))}, model_state={})
    log = tmp_path / "run.jsonl"
    with JsonlLogger(log) as logger:
        for e in range(2):
            logger.log(event="epoch", epoch=e, loss=1.0 - 0.3 * e,
                       accuracy=0.5 + 0.2 * e, val_loss=1.0,
                       val_accuracy=0.5)
        run_rounds(round_fn, server, None, None,
                   np.ones(3, np.float32),
                   config=DriverConfig(rounds=3), logger=logger)
        REGISTRY.log_snapshot(logger)

    out = _run(["stats", str(log)], capsys)
    assert "epoch" in out and "round_health" in out
    assert "fed_round_attempts_total" in out    # the snapshot rendered
    out = _run(["stats", str(log), "--json"], capsys)
    s = json.loads(out)
    assert s["events"]["epoch"]["count"] == 2
    assert s["events"]["round"]["count"] == 3
    assert s["events"]["round_health"]["fields"]["seconds"]["count"] == 3
    assert s["events"]["epoch"]["fields"]["loss"]["min"] == 0.7
    assert s["requests"] == {}      # nothing serve-shaped in this log


def test_cli_profile_train_and_stats(tmp_path, capsys):
    """ISSUE-9 acceptance from the product surface: `profile` over a
    train step emits a program cost account with a roofline verdict
    (declared roof — CPU is not in the backend table), a device-vs-
    host step-time split whose fractions sum to ~1, and frozen-schema
    profile_program/profile_step jsonl the `stats` verb renders; the
    compile-churn watchdog stays SILENT on the clean run and fires on
    the injected shape-varying recompile loop (--churn-drill).
    Attribution/verdict math is owned by tests/test_profile.py; this
    drives the CLI wiring end to end."""
    import json

    out = _run(["profile", "--model", "small", "--host-devices", "8",
                "--steps", "3", "--peak-tflops", "1.0",
                "--peak-gbps", "50.0", "--path", str(tmp_path)], capsys)
    assert "profile: train.step (small_cnn" in out
    assert "programs (performance attribution):" in out
    assert "train.step" in out
    assert "-bound at" in out            # a real verdict, not unknown
    assert "step-time attribution" in out and "profile.step" in out
    assert "churn: none" in out          # clean warm run stays silent
    jsonl = tmp_path / "logs" / "profile.jsonl"
    recs = [json.loads(l) for l in jsonl.read_text().splitlines()]
    progs = [r for r in recs if r["event"] == "profile_program"]
    steps = [r for r in recs if r["event"] == "profile_step"]
    assert progs[0]["program"] == "train.step"
    assert progs[0]["verdict"] in ("compute-bound", "bandwidth-bound")
    assert progs[0]["flops"] > 0 and progs[0]["mfu"] is not None
    fr = [r for r in steps if r["loop"] == "profile.step"][0]
    assert fr["steps"] == 3
    assert (fr["device_busy_fraction"] + fr["host_gap_fraction"]
            == pytest.approx(1.0))
    assert any(r["event"] == "metrics_snapshot" for r in recs)

    # the injected recompile loop trips the watchdog (named program
    # fed a different shape every call past --compile-limit)
    out = _run(["profile", "--model", "small", "--host-devices", "8",
                "--steps", "2", "--compile-limit", "3",
                "--churn-drill"], capsys)
    assert "CHURN flagged: churn.drill" in out


@pytest.mark.slow
def test_cli_profile_mobile_fused(tmp_path, capsys):
    """ISSUE-16 satellite: `profile --model mobile --depthwise-impl
    fused` still prints a REAL roofline verdict — XLA's cost analysis
    is blind inside the Pallas calls, so the CLI merges the analytic
    kernel cost (fused_conv.depthwise_chain_cost over
    mobilenet.fused_call_shapes) into the program account before
    registering it — and the clean fused run stays churn-silent (the
    lru_cached kernel closure must not recompile per call). Marked
    slow: compiling the ~17 distinct interpret-mode Pallas configs
    (fwd + custom_vjp bwd each) costs minutes on CPU regardless of
    batch/step count; the fast fused-parity subset lives in
    test_fused_conv.py."""
    import json

    out = _run(["profile", "--model", "mobile", "--depthwise-impl",
                "fused", "--host-devices", "2", "--steps", "2",
                "--peak-tflops", "1.0", "--peak-gbps", "50.0",
                "--path", str(tmp_path)], capsys)
    assert "profile: train.step (mobilenet_v2" in out
    assert "-bound at" in out            # a real verdict, not unknown
    assert "churn: none" in out          # zero compile-churn warnings
    jsonl = tmp_path / "logs" / "profile.jsonl"
    recs = [json.loads(l) for l in jsonl.read_text().splitlines()]
    progs = [r for r in recs if r["event"] == "profile_program"]
    prog = progs[0]
    assert prog["program"] == "train.step"
    assert prog["verdict"] in ("compute-bound", "bandwidth-bound")
    # the analytic merge actually landed: the fused step must account
    # at least the kernel chain's own bytes (XLA alone reports almost
    # nothing for the custom calls)
    from idc_models_tpu.models import mobilenet
    from idc_models_tpu.ops import fused_conv

    k_flops, k_bytes = fused_conv.depthwise_chain_cost(
        mobilenet.fused_call_shapes(2 * 8, 50))
    assert prog["flops"] >= k_flops
    assert prog["bytes_accessed"] >= k_bytes

    # stats renders the profile events + the self-time table
    out = _run(["stats", str(jsonl)], capsys)
    assert "programs (performance attribution):" in out
    assert "step-time attribution:" in out
    out = _run(["stats", str(jsonl), "--json"], capsys)
    s = json.loads(out)
    assert s["events"]["profile_program"]["count"] == len(progs)
    assert s["programs"][0]["program"] == "train.step"

    # usage errors die cleanly: half a roofline, bad steps/limit/top
    with pytest.raises(SystemExit):
        cli.main(["profile", "--model", "small", "--host-devices", "8",
                  "--peak-tflops", "1.0"])
    with pytest.raises(SystemExit):
        cli.main(["profile", "--model", "small", "--host-devices", "8",
                  "--steps", "0"])
    with pytest.raises(SystemExit):
        cli.main(["profile", "--model", "small", "--host-devices", "8",
                  "--compile-limit", "0"])
    with pytest.raises(SystemExit):
        cli.main(["stats", str(jsonl), "--top", "0"])


def test_cli_profile_serve(tmp_path, capsys):
    """The `profile` verb's serve mode: engine program accounts
    (window + prefill) and the serve.tick device-vs-host split from a
    saturated decode loop, through the CLI."""
    import json

    out = _run(["profile", "--model", "serve", "--host-devices", "8",
                "--steps", "5", "--path", str(tmp_path)], capsys)
    assert "profile: serve decode loop" in out
    assert "serve.window" in out and "serve.prefill" in out
    assert "serve.propose" in out        # drafter roofline rides along
    assert "serve.tick" in out
    recs = [json.loads(l) for l in
            (tmp_path / "logs" / "profile.jsonl").read_text()
            .splitlines()]
    progs = {r["program"] for r in recs
             if r["event"] == "profile_program"}
    assert {"serve.window", "serve.prefill"} <= progs
    steps = [r for r in recs if r["event"] == "profile_step"]
    tick = [r for r in steps if r["loop"] == "serve.tick"][0]
    assert tick["steps"] >= 1
    assert (tick["device_busy_fraction"] + tick["host_gap_fraction"]
            == pytest.approx(1.0))


def test_cli_lm(tmp_path, capsys):
    """The causal-LM workload from the product surface: the CLI wiring
    only (mesh line, metric line, generate line, jsonl artifact, ring
    rejection) — convergence + pattern-match is owned by
    tests/test_lm.py::test_lm_learns_and_generates, not re-proven
    here."""
    out = _run(["lm", "--host-devices", "8", "--steps", "20",
                "--vocab", "11", "--seq-len", "32", "--embed-dim", "16",
                "--num-heads", "2", "--mlp-dim", "32", "--num-blocks",
                "1", "--batch-size", "16", "--generate", "6",
                "--path", str(tmp_path)], capsys)
    assert "(data=2, seq=4)" in out
    assert "next-token accuracy" in out
    assert "generate:" in out
    assert (tmp_path / "logs" / "run.jsonl").exists()
    with pytest.raises(SystemExit):
        cli.main(["lm", "--host-devices", "8", "--seq-len", "30",
                  "--layout", "zigzag"])


def test_cli_serve_faulted_lifecycle_and_journal_recovery(tmp_path,
                                                          capsys):
    """ISSUE-8 acceptance from the product surface, two drills:

    1. LIFECYCLE — a traced serve run with an injected nan_logits
       fault and retries armed: one rid grep of the exported trace
       reconstructs submit -> fault -> quarantine -> retry -> finish
       under the request's shared trace_id, the recovered request
       finishes ok, and the resilience epilogue reports the counts.
    2. CRASH RECOVERY — an injected mid-run engine crash with
       --journal armed kills the run honestly (salvaged results +
       recovery hint); rerunning with the same journal re-admits the
       in-flight requests and serves them.

    Recovery bit-parity is owned by tests/test_serve_resilience.py;
    this drives the CLI wiring end to end."""
    import json

    from idc_models_tpu.serve import Request, save_trace

    model = ["--host-devices", "8", "--slots", "2", "--window", "4",
             "--t-max", "32", "--vocab", "11", "--embed-dim", "16",
             "--num-heads", "2", "--mlp-dim", "32", "--num-blocks", "1"]
    trace = [(0.0, Request(id=f"f{i}", prompt=(1 + i, 2, 3),
                           max_new_tokens=12))
             for i in range(3)]
    tr = save_trace(tmp_path / "trace.jsonl", trace)
    trace_json = tmp_path / "faulted.json"
    out = _run(["serve", *model, "--trace", tr,
                "--serve-faults", "nan_logits:1:0",
                "--max-retries", "2", "--retry-backoff-ms", "0",
                "--trace-out", str(trace_json),
                "--path", str(tmp_path)], capsys)
    assert "served: ok=3" in out
    assert "resilience: injected=1 slot_faults=1 retries=1" in out
    line = [ln for ln in out.splitlines()
            if ln.startswith("serve summary:")][0]
    summary = json.loads(line.split("serve summary:", 1)[1])
    assert summary["serve_slot_faults"] == 1
    assert summary["serve_retries"] == 1

    # ONE rid grep over the exported trace tells the whole story
    doc = json.loads(trace_json.read_text())
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    fault = next(e for e in spans if e["name"] == "serve.slot_fault")
    rid = fault["args"]["rid"]
    assert fault["args"]["kind"] == "nonfinite_logits"
    mine = [e for e in spans if e["args"].get("rid") == rid]
    names = {e["name"] for e in mine}
    assert {"serve.request", "serve.queued", "serve.slot_fault",
            "serve.retry", "serve.first_token"} <= names, names
    req = next(e for e in mine if e["name"] == "serve.request")
    assert req["args"]["status"] == "ok"
    tids = {e["args"]["trace_id"] for e in mine
            if "trace_id" in e["args"]}
    assert tids == {req["args"]["trace_id"]}
    retry = next(e for e in mine if e["name"] == "serve.retry")
    assert retry["args"]["attempt"] == 2
    # the fault/retry markers hang off the request's lifecycle span
    assert fault["args"]["parent_id"] == req["args"]["span_id"]
    # ...and the run's jsonl carries the same chain as events
    events = [json.loads(l) for l in
              (tmp_path / "logs" / "serve.jsonl").read_text()
              .splitlines()]
    chain = [r["event"] for r in events if r.get("id") == rid]
    for ev in ("serve_submit", "serve_slot_fault", "serve_retry",
               "serve_finish"):
        assert ev in chain, (ev, chain)
    assert chain.index("serve_slot_fault") \
        < chain.index("serve_retry") < chain.index("serve_finish")

    # -- drill 2: crash + journal recovery ------------------------------
    wal = tmp_path / "journal.jsonl"
    trace2 = [(0.0, Request(id=f"j{i}", prompt=(2 + i, 4),
                            max_new_tokens=16))
              for i in range(3)]
    tr2 = save_trace(tmp_path / "trace2.jsonl", trace2)
    out = _run(["serve", *model, "--trace", tr2,
                "--serve-faults", "crash:2",
                "--journal", str(wal)], capsys)
    assert "engine crashed mid-run (injected)" in out
    assert f"rerun with --journal {wal}" in out
    out = _run(["serve", *model, "--trace",
                save_trace(tmp_path / "empty.jsonl", []),
                "--journal", str(wal)], capsys)
    assert "journal: re-admitted 3 in-flight request(s)" in out
    assert "served: ok=3" in out
    # a second recovery finds a clean WAL
    from idc_models_tpu.serve import pending_requests

    assert pending_requests(wal) == []
    # usage errors die cleanly: bad fault spec (teaching message), bad
    # retry knobs
    with pytest.raises(SystemExit):
        cli.main(["serve", *model, "--serve-faults", "meteor:1"])
    with pytest.raises(SystemExit):
        cli.main(["serve", *model, "--max-retries", "-1"])


def test_cli_serve_tenants_e2e(tmp_path, capsys):
    """ISSUE-14: the multi-tenant serve verb end to end — round-robin
    tenant tagging, per-tenant quota + TTFT SLO wiring, per-tenant
    epilogue lines, the serve_tenants summary rollup, and the tenant
    events in the run jsonl."""
    import json

    out = _run([
        "serve", "--path", str(tmp_path), "--requests", "10",
        "--t-max", "32", "--vocab", "12", "--embed-dim", "16",
        "--num-heads", "2", "--mlp-dim", "32", "--num-blocks", "1",
        "--slots", "3", "--window", "4",
        "--tenants", "acme,globex",
        "--tenant-quota", "acme=2:6:-",
        "--tenant-slo-ttft-ms", "acme=200"], capsys)
    assert "tenant acme:" in out and "tenant globex:" in out
    assert "brownout_max_stage=" in out and "slo_alerts=" in out
    summary = json.loads(
        [ln for ln in out.splitlines()
         if ln.startswith("serve summary:")][0].split(":", 1)[1])
    tenants = summary["serve_tenants"]
    assert set(tenants) == {"acme", "globex"}
    assert tenants["acme"]["requests"] == 5
    assert tenants["globex"]["requests"] == 5
    recs = [json.loads(ln) for ln in
            open(tmp_path / "logs" / "serve.jsonl")]
    tenant_fin = [r for r in recs
                  if r.get("event") == "serve_tenant_finish"]
    assert len(tenant_fin) == 10
    assert {r["tenant"] for r in tenant_fin} == {"acme", "globex"}


def test_cli_serve_tenant_usage_errors(capsys):
    """ISSUE-14: every bad tenancy knob dies as a TEACHING usage error
    that states the grammar — never a traceback."""
    base = ["serve", "--requests", "1", "--t-max", "32"]
    with pytest.raises(SystemExit, match="--tenant-quota needs "
                                         "--tenants"):
        cli.main(base + ["--tenant-quota", "a=2"])
    with pytest.raises(SystemExit, match="--tenant-slo-ttft-ms needs"):
        cli.main(base + ["--tenant-slo-ttft-ms", "250"])
    with pytest.raises(SystemExit, match="duplicate tenant"):
        cli.main(base + ["--tenants", "a,a"])
    with pytest.raises(SystemExit, match="empty tenant name"):
        cli.main(base + ["--tenants", "a,,b"])
    with pytest.raises(SystemExit, match="unknown tenant 'ghost'"):
        cli.main(base + ["--tenants", "a", "--tenant-quota",
                         "ghost=2"])
    with pytest.raises(SystemExit, match="grammar"):
        cli.main(base + ["--tenants", "a", "--tenant-quota", "a=x"])
    with pytest.raises(SystemExit, match="admit nothing ever"):
        cli.main(base + ["--tenants", "a", "--tenant-quota", "a=0"])
    with pytest.raises(SystemExit, match="already has a quota"):
        cli.main(base + ["--tenants", "a", "--tenant-quota", "a=2",
                         "--tenant-quota", "a=3"])
    with pytest.raises(SystemExit, match="must be > 0"):
        cli.main(base + ["--tenants", "a", "--tenant-slo-ttft-ms",
                         "a=0"])
    with pytest.raises(SystemExit, match="already has a TTFT SLO"):
        cli.main(base + ["--tenants", "a", "--tenant-slo-ttft-ms",
                         "150", "--tenant-slo-ttft-ms", "a=250"])
    with pytest.raises(SystemExit, match="is not a number"):
        cli.main(base + ["--tenants", "a", "--tenant-slo-ttft-ms",
                         "a=fast"])


def test_cli_profile_lm_sharded(tmp_path, capsys):
    """ISSUE-15 acceptance from the product surface: `profile --model
    lm --fsdp 2` accounts the rule-sharded LM train step and prints
    the per-device peak-HBM epilogue line; the replicated run prints
    the same line so the two figures are comparable from the command
    line (the gate itself — sharded < replicated — is asserted in
    tests/test_partition.py)."""
    import json
    import re as _re

    def peak_of(out):
        m = _re.search(r"per-device peak HBM: ([0-9.]+) MiB over "
                       r"(\d+) device", out)
        assert m, out
        return float(m.group(1)), int(m.group(2))

    out = _run(["profile", "--model", "lm", "--host-devices", "8",
                "--steps", "2", "--path", str(tmp_path)], capsys)
    assert "profile: train.step (lm" in out and "replicated" in out
    rep_mib, n = peak_of(out)
    assert n == 1

    out = _run(["profile", "--model", "lm", "--host-devices", "8",
                "--steps", "2", "--fsdp", "2", "--tp", "2"], capsys)
    assert "fsdp=2, tp=2 (rule set 'lm'" in out
    sh_mib, n = peak_of(out)
    assert n == 4
    assert sh_mib < rep_mib          # the CLI surfaces the capacity win
    jsonl = tmp_path / "logs" / "profile.jsonl"
    recs = [json.loads(l) for l in jsonl.read_text().splitlines()]
    prog = [r for r in recs if r["event"] == "profile_program"][0]
    assert prog["program"] == "train.step"
    assert prog["peak_hbm_bytes"] == pytest.approx(rep_mib * 2**20,
                                                   rel=1e-3)

    # usage gates: the flags teach
    with pytest.raises(SystemExit, match="--model lm"):
        cli.main(["profile", "--model", "small", "--host-devices", "8",
                  "--fsdp", "2"])
    with pytest.raises(SystemExit, match="devices"):
        cli.main(["profile", "--model", "lm", "--host-devices", "8",
                  "--fsdp", "16"])
    with pytest.raises(SystemExit, match="must be >= 0"):
        cli.main(["profile", "--model", "lm", "--host-devices", "8",
                  "--fsdp", "-1"])
    with pytest.raises(SystemExit, match="divide by --fsdp"):
        cli.main(["profile", "--model", "lm", "--host-devices", "8",
                  "--fsdp", "2", "--batch-size", "3"])


def test_cli_lm_fsdp_tp(capsys):
    """The lm train verb on a rule-sharded ('data', 'model', 'seq')
    mesh: trains, reports the sharded mesh line, and the compiled
    serving path still generates — plus the usage gates."""
    out = _run(["lm", "--host-devices", "8", "--fsdp", "2", "--tp",
                "2", "--steps", "30", "--seq-len", "32",
                "--generate", "4"], capsys)
    assert "fsdp=2, tp=2" in out
    assert "sharded by rule set 'lm'" in out
    assert "generate:" in out
    with pytest.raises(SystemExit, match="devices"):
        cli.main(["lm", "--host-devices", "8", "--fsdp", "8", "--tp",
                  "2", "--steps", "1"])
    with pytest.raises(SystemExit, match="divide by --fsdp"):
        cli.main(["lm", "--host-devices", "8", "--fsdp", "2",
                  "--batch-size", "5", "--steps", "1"])


def test_cli_serve_tp(tmp_path, capsys):
    """The serve verb with --tp 2: params shard over 'model' (rule set
    'lm'), KV keeps the seq ring, the trace completes — and --fsdp on
    serve teaches toward --tp instead of shrugging."""
    out = _run(["serve", "--host-devices", "8", "--tp", "2",
                "--requests", "4", "--slots", "2", "--window", "4",
                "--path", str(tmp_path)], capsys)
    assert "serving mesh: tp=2 x seq=1" in out
    assert "params sharded by rule set 'lm'" in out
    assert "served: ok=4" in out
    with pytest.raises(SystemExit, match="use --tp"):
        cli.main(["serve", "--host-devices", "8", "--fsdp", "2",
                  "--requests", "1"])
    with pytest.raises(SystemExit, match="needs"):
        cli.main(["serve", "--host-devices", "8", "--tp", "16",
                  "--requests", "1"])

def test_cli_serve_save_ckpt_and_rollout(tmp_path, capsys):
    """ISSUE-17 acceptance from the product surface: one run mints a
    sharded checkpoint with --save-ckpt, the next canaries it onto live
    traffic with --rollout and promotes — every request served, the
    verdict line printed, the frozen ckpt_save/serve_rollout events in
    the jsonl. State-machine semantics are owned by
    tests/test_rollout.py; this drives the CLI wiring end to end."""
    import json

    model = ["--slots", "2", "--window", "4", "--t-max", "32",
             "--vocab", "11", "--embed-dim", "16", "--num-heads", "2",
             "--mlp-dim", "32", "--num-blocks", "1"]
    ckpt = tmp_path / "candidate"
    out = _run(["serve", "--host-devices", "8", "--requests", "4",
                "--seed", "1", "--save-ckpt", str(ckpt),
                "--path", str(tmp_path), *model], capsys)
    assert f"to {ckpt}" in out and "checkpoint: wrote" in out
    from idc_models_tpu.checkpoint import MANIFEST_NAME

    assert (ckpt / MANIFEST_NAME).exists()

    out = _run(["serve", "--host-devices", "8", "--requests", "24",
                "--rollout", str(ckpt), "--canary-fraction", "0.5",
                "--canary-requests", "3", "--rollout-at", "0.0",
                "--path", str(tmp_path), *model], capsys)
    assert "served: ok=24 timeout=0 rejected=0" in out
    assert "rollout: promoted after" in out
    line = [ln for ln in out.splitlines()
            if ln.startswith("serve summary:")][0]
    summary = json.loads(line.split("serve summary:", 1)[1])
    assert summary["serve_rollout_outcome"] == "promoted"
    assert summary["serve_rollout_stage"] == "promoted"
    events = {json.loads(l)["event"] for l in
              (tmp_path / "logs" / "serve.jsonl").read_text()
              .splitlines()}
    assert {"ckpt_save", "ckpt_restore", "serve_rollout"} <= events


def test_cli_serve_rollout_adapters(tmp_path, capsys):
    """--rollout-adapters: the cheap first rung — synthetic per-tenant
    adapters are armed at build time and a re-seeded bank hot-swaps in
    after the trace, with the tenant isolation epilogue intact."""
    out = _run(["serve", "--host-devices", "8", "--requests", "6",
                "--slots", "2", "--window", "4", "--t-max", "32",
                "--vocab", "11", "--embed-dim", "16", "--num-heads",
                "2", "--mlp-dim", "32", "--num-blocks", "1",
                "--tenants", "acme,beta", "--rollout-adapters", "3"],
               capsys)
    assert "served: ok=6" in out
    assert ("adapter rollout: hot-swapped rank-3 adapters for "
            "2 tenant(s)") in out
    assert "tenant acme:" in out and "tenant beta:" in out


def test_cli_serve_rollout_usage_errors(tmp_path, capsys):
    """ISSUE-17: every bad rollout knob dies as a TEACHING usage error
    before any pre-training or serving runs, never a traceback."""
    base = ["serve", "--host-devices", "8"]
    with pytest.raises(SystemExit,
                       match="--canary-fraction needs --rollout"):
        cli.main(base + ["--canary-fraction", "0.5"])
    with pytest.raises(SystemExit, match="--rollout-at needs"):
        cli.main(base + ["--rollout-at", "0.5"])
    # a fake but complete checkpoint lets the knob checks run; the
    # knobs are validated before the checkpoint is ever restored
    from idc_models_tpu.checkpoint import save_sharded

    ck = tmp_path / "ck"
    save_sharded(ck, {"w": np.zeros(3, np.float32)})
    with pytest.raises(SystemExit, match="promoting without evidence"):
        cli.main(base + ["--rollout", str(ck),
                         "--canary-fraction", "-0.5"])
    with pytest.raises(SystemExit, match="promoting without evidence"):
        cli.main(base + ["--rollout", str(ck),
                         "--canary-fraction", "1.5"])
    with pytest.raises(SystemExit, match="at least one canary finish"):
        cli.main(base + ["--rollout", str(ck),
                         "--canary-requests", "0"])
    with pytest.raises(SystemExit, match="drains before the rollout"):
        cli.main(base + ["--rollout", str(ck), "--rollout-at", "1.0"])
    with pytest.raises(SystemExit, match="MANIFEST.json"):
        cli.main(base + ["--rollout", str(tmp_path / "nothing_here")])
    with pytest.raises(SystemExit,
                       match="--rollout-adapters needs --tenants"):
        cli.main(base + ["--rollout-adapters", "3"])
    with pytest.raises(SystemExit, match="adapter rank"):
        cli.main(base + ["--tenants", "a,b", "--rollout-adapters", "0"])


def test_cli_checkpoint_every_usage_errors(capsys):
    """ISSUE-17: --checkpoint-every teaches on both training verbs —
    zero is never, and pacing without --resumable writes nothing."""
    with pytest.raises(SystemExit, match="must be >= 1"):
        cli.main(["vgg", "--host-devices", "8", "--checkpoint-every",
                  "0", "--epochs", "1"])
    with pytest.raises(SystemExit, match="needs --resumable"):
        cli.main(["vgg", "--host-devices", "8", "--checkpoint-every",
                  "2", "--epochs", "1"])
    with pytest.raises(SystemExit, match="must be >= 1"):
        cli.main(["fed", "--host-devices", "8", "--checkpoint-every",
                  "0", "--rounds", "1"])
