"""Pretrained weight import round-trips and graceful degradation."""

import warnings

import jax
import numpy as np
import pytest

from idc_models_tpu.models import pretrained, small_cnn


def _params():
    m = small_cnn(10, 3, 1)
    return m.init(jax.random.key(0)).params


def test_npz_roundtrip(tmp_path):
    p = _params()
    f = tmp_path / "w.npz"
    pretrained.save_npz(f, p)
    loaded = pretrained.load_npz(f)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), b)


def test_merge_partial_and_mismatch():
    p = _params()
    partial = {"head": {"kernel": np.zeros((8, 1), np.float32)}}
    merged, n, mis = pretrained.merge_pretrained(p, partial)
    assert n == 1 and not mis
    assert np.allclose(merged["head"]["kernel"], 0.0)
    # untouched leaves unchanged
    np.testing.assert_array_equal(np.asarray(p["conv1"]["kernel"]),
                                  np.asarray(merged["conv1"]["kernel"]))
    bad = {"head": {"kernel": np.zeros((9, 1), np.float32)}}
    _, n2, mis2 = pretrained.merge_pretrained(p, bad)
    assert n2 == 0 and len(mis2) == 1
    with pytest.raises(ValueError):
        pretrained.merge_pretrained(p, bad, strict=True)


def test_maybe_load_missing_warns():
    p = {"backbone": _params()}
    with pytest.warns(UserWarning, match="not found"):
        out = pretrained.maybe_load_pretrained(p, "/nonexistent/w.npz")
    assert out is p


def test_maybe_load_applies(tmp_path):
    inner = _params()
    p = {"backbone": inner, "head": {"kernel": np.ones((8, 1), np.float32)}}
    zeros = jax.tree.map(lambda x: np.zeros_like(np.asarray(x)), inner)
    f = tmp_path / "bb.npz"
    pretrained.save_npz(f, zeros)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = pretrained.maybe_load_pretrained(p, f)
    assert all(np.allclose(x, 0) for x in jax.tree.leaves(out["backbone"]))
    assert np.allclose(out["head"]["kernel"], 1.0)
