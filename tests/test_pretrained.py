"""Pretrained weight import round-trips and graceful degradation."""

import warnings

import jax
import numpy as np
import pytest

from idc_models_tpu.models import pretrained, small_cnn


def _params():
    m = small_cnn(10, 3, 1)
    return m.init(jax.random.key(0)).params


def test_npz_roundtrip(tmp_path):
    p = _params()
    f = tmp_path / "w.npz"
    pretrained.save_npz(f, p)
    loaded = pretrained.load_npz(f)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), b)


def test_merge_partial_and_mismatch():
    p = _params()
    partial = {"head": {"kernel": np.zeros((8, 1), np.float32)}}
    merged, n, mis = pretrained.merge_pretrained(p, partial)
    assert n == 1 and not mis
    assert np.allclose(merged["head"]["kernel"], 0.0)
    # untouched leaves unchanged
    np.testing.assert_array_equal(np.asarray(p["conv1"]["kernel"]),
                                  np.asarray(merged["conv1"]["kernel"]))
    bad = {"head": {"kernel": np.zeros((9, 1), np.float32)}}
    _, n2, mis2 = pretrained.merge_pretrained(p, bad)
    assert n2 == 0 and len(mis2) == 1
    with pytest.raises(ValueError):
        pretrained.merge_pretrained(p, bad, strict=True)


def test_maybe_load_missing_warns():
    p = {"backbone": _params()}
    with pytest.warns(UserWarning, match="not found"):
        out, st = pretrained.maybe_load_pretrained(p, "/nonexistent/w.npz")
    assert out is p and st is None


def test_maybe_load_applies(tmp_path):
    inner = _params()
    p = {"backbone": inner, "head": {"kernel": np.ones((8, 1), np.float32)}}
    zeros = jax.tree.map(lambda x: np.zeros_like(np.asarray(x)), inner)
    f = tmp_path / "bb.npz"
    pretrained.save_npz(f, zeros)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out, _ = pretrained.maybe_load_pretrained(p, f)
    assert all(np.allclose(x, 0) for x in jax.tree.leaves(out["backbone"]))
    assert np.allclose(out["head"]["kernel"], 1.0)


# ---------------------------------------------------------------------------
# Keras h5 conversion path (dist_model_tf_vgg.py:119 weights='imagenet')
# ---------------------------------------------------------------------------


def _write_keras_h5(path, layers):
    """Write a Keras `save_weights`-layout h5: one group per layer with a
    `weight_names` attr listing '<layer>/<var>:0' datasets."""
    h5py = pytest.importorskip("h5py")

    with h5py.File(path, "w") as f:
        for layer, weights in layers.items():
            g = f.create_group(layer)
            names = []
            for var, arr in weights.items():
                name = f"{layer}/{var}:0"
                g.create_dataset(name, data=arr)
                names.append(name.encode())
            g.attrs["weight_names"] = names


def test_keras_h5_roundtrip_into_vgg16_identical_logits(tmp_path):
    """Full path: h5 fixture -> load_keras_h5 -> merge into vgg16 ->
    identical logits to a model whose arrays were set directly."""
    from idc_models_tpu.models.vgg import vgg16

    model = vgg16(num_outputs=1)
    variables = model.init(jax.random.key(0))
    rng = np.random.default_rng(1)
    # deterministic "ImageNet" weights: shape-matched noise per conv layer
    h5_layers = {}
    for layer, leaves in variables.params["backbone"].items():
        h5_layers[layer] = {
            "kernel": rng.normal(0, 0.05, np.shape(leaves["kernel"]))
            .astype(np.float32),
            "bias": rng.normal(0, 0.05, np.shape(leaves["bias"]))
            .astype(np.float32),
        }
    f = tmp_path / "vgg16_imagenet.h5"
    _write_keras_h5(f, h5_layers)

    loaded_p, loaded_s = pretrained.load_keras_h5(f)
    assert not loaded_s  # VGG16 has no BN state
    merged, n, mis = pretrained.merge_pretrained(
        variables.params["backbone"], loaded_p)
    assert not mis
    assert n == sum(len(v) for v in h5_layers.values())

    params_h5, _ = pretrained.maybe_load_pretrained(
        variables.params, f, state=variables.state)
    params_direct = dict(variables.params, backbone=jax.tree.map(
        np.asarray, {k: dict(v) for k, v in h5_layers.items()}))
    x = np.random.default_rng(2).random((2, 50, 50, 3), np.float32)
    y_h5, _ = model.apply(params_h5, variables.state, x, train=False)
    y_direct, _ = model.apply(params_direct, variables.state, x, train=False)
    np.testing.assert_array_equal(np.asarray(y_h5), np.asarray(y_direct))
    # and it actually changed the function vs the random init
    y_init, _ = model.apply(variables.params, variables.state, x, train=False)
    assert not np.allclose(np.asarray(y_h5), np.asarray(y_init))


def test_keras_h5_depthwise_transpose_and_bn_state(tmp_path):
    """Depthwise kernels get their Keras (kh,kw,C,1) -> (kh,kw,1,C) swap
    and BN moving stats land in the state tree, not params."""
    dw = np.arange(3 * 3 * 4 * 1, dtype=np.float32).reshape(3, 3, 4, 1)
    f = tmp_path / "w.h5"
    _write_keras_h5(f, {
        "block_1_depthwise": {"kernel": dw},
        "block_1_depthwise_BN": {
            "gamma": np.ones((4,), np.float32),
            "beta": np.zeros((4,), np.float32),
            "moving_mean": np.full((4,), 2.0, np.float32),
            "moving_variance": np.full((4,), 3.0, np.float32),
        },
    })
    params, state = pretrained.load_keras_h5(f)
    assert params["block_1_depthwise"]["kernel"].shape == (3, 3, 1, 4)
    np.testing.assert_array_equal(
        params["block_1_depthwise"]["kernel"],
        np.transpose(dw, (0, 1, 3, 2)))
    assert set(params["block_1_depthwise_BN"]) == {"scale", "bias"}
    np.testing.assert_array_equal(
        state["block_1_depthwise_BN"]["mean"], np.full((4,), 2.0))
    np.testing.assert_array_equal(
        state["block_1_depthwise_BN"]["var"], np.full((4,), 3.0))


def _full_zoo_h5(model_name, path, seed=7):
    """Structurally-faithful keras.applications `save_weights` fixture for
    a zoo backbone: EVERY parameterized layer of the backbone emitted with
    the real Keras variable names and storage shapes — nested
    `layer/layer/var:0` dataset paths, `depthwise_kernel:0` stored
    (kh, kw, C, 1), BN as gamma/beta/moving_mean/moving_variance — so the
    conversion path is rehearsed against the layout the real ImageNet
    files use (VERDICT r2 #8; no network egress here, so layout fidelity
    is the strongest available evidence). Returns the (params, state)
    trees in OUR naming/shapes for direct comparison after conversion."""
    from idc_models_tpu.models import registry

    spec = registry.get_model(model_name)

    def init_shapes():
        v = spec.build(1, 3).init(jax.random.key(0))
        return {"p": v.params, "s": v.state}

    sh = jax.eval_shape(init_shapes)
    bb_p, bb_s = sh["p"]["backbone"], sh["s"].get("backbone", {})
    rng = np.random.default_rng(seed)

    def val(shape, positive=False):
        a = rng.normal(0.0, 0.05, shape).astype(np.float32)
        return np.abs(a) + 0.5 if positive else a

    layers: dict = {}
    expected_p: dict = {}
    expected_s: dict = {}
    for layer, leaves in bb_p.items():
        entry: dict = {}
        exp: dict = {}
        if "kernel" in leaves:
            k = val(tuple(leaves["kernel"].shape))
            exp["kernel"] = k
            kh, kw, cin, cout = k.shape
            if cin == 1 and cout > 3:  # DepthwiseConv2D
                entry["depthwise_kernel"] = np.transpose(k, (0, 1, 3, 2))
            else:
                entry["kernel"] = k
            if "bias" in leaves:
                exp["bias"] = entry["bias"] = val(tuple(leaves["bias"].shape))
        elif "scale" in leaves:  # BatchNorm: gamma/beta + moving stats
            exp["scale"] = entry["gamma"] = val(tuple(leaves["scale"].shape))
            exp["bias"] = entry["beta"] = val(tuple(leaves["bias"].shape))
            st = bb_s[layer]
            mean = val(tuple(st["mean"].shape))
            var = val(tuple(st["var"].shape), positive=True)
            entry["moving_mean"], entry["moving_variance"] = mean, var
            expected_s[layer] = {"mean": mean, "var": var}
        layers[layer] = entry
        expected_p[layer] = exp
    _write_keras_h5(path, layers)
    return expected_p, expected_s


@pytest.mark.parametrize("name", ["mobilenet_v2", "densenet201"])
def test_full_zoo_h5_convert_validate_load(tmp_path, capsys, name):
    """convert-weights on a FULL real-layout h5 for the BN-bearing zoo
    backbones: zero mismatches on params AND state, and the loaded
    artifact grafts every tensor bit-exactly (moving stats included)."""
    from idc_models_tpu import cli
    from idc_models_tpu.models import registry

    h5 = tmp_path / f"{name}.h5"
    expected_p, expected_s = _full_zoo_h5(name, h5)
    npz = tmp_path / f"{name}.npz"
    assert cli.main(["convert-weights", str(h5), str(npz),
                     "--model", name]) == 0
    out = capsys.readouterr().out
    assert out.count(", 0 mismatches") == 2  # params and state both clean

    model = registry.get_model(name).build(1, 3)
    variables = model.init(jax.random.key(0))
    params, state = pretrained.maybe_load_pretrained(
        variables.params, npz, state=variables.state)
    for layer, leaves in expected_p.items():
        for k, v in leaves.items():
            np.testing.assert_array_equal(
                np.asarray(params["backbone"][layer][k]), v,
                err_msg=f"{name} {layer}/{k}")
    for layer, leaves in expected_s.items():
        for k, v in leaves.items():
            np.testing.assert_array_equal(
                np.asarray(state["backbone"][layer][k]), v,
                err_msg=f"{name} state {layer}/{k}")
    # nothing was silently skipped: every backbone leaf came from the h5
    n_expected = (sum(len(v) for v in expected_p.values())
                  + sum(len(v) for v in expected_s.values()))
    n_model = (len(jax.tree.leaves(variables.params["backbone"]))
               + len(jax.tree.leaves(variables.state["backbone"])))
    assert n_expected == n_model


def test_convert_weights_cli_then_train_from_artifact(tmp_path, capsys):
    """End-to-end C5 parity: convert-weights CLI produces an .npz, and a
    two-phase fit demonstrably starts from it (baseline eval differs from
    the random-init baseline)."""
    from idc_models_tpu import cli
    from idc_models_tpu.models.vgg import vgg16

    model = vgg16(num_outputs=1)
    variables = model.init(jax.random.key(0))
    rng = np.random.default_rng(3)
    h5_layers = {
        layer: {k: rng.normal(0, 0.05, np.shape(v)).astype(np.float32)
                for k, v in leaves.items()}
        for layer, leaves in variables.params["backbone"].items()
    }
    h5 = tmp_path / "in.h5"
    _write_keras_h5(h5, h5_layers)
    npz = tmp_path / "out.npz"
    assert cli.main(["convert-weights", str(h5), str(npz),
                     "--model", "vgg16"]) == 0
    out = capsys.readouterr().out
    assert ", 0 mismatches" in out

    loaded_p, loaded_s = pretrained.load_pretrained_file(npz)
    merged, n, mis = pretrained.merge_pretrained(
        variables.params["backbone"], loaded_p)
    assert not mis and n == sum(len(v) for v in h5_layers.values())
    for layer, leaves in h5_layers.items():
        for k, v in leaves.items():
            np.testing.assert_array_equal(
                np.asarray(merged[layer][k]), v)
