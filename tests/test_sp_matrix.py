"""SP surface hardening (VERDICT r4 #7): non-power-of-2 rings are exact,
and every invalid knob combination fails at build/trace time with its
documented message — never as a crash from deeper in XLA/Mosaic.

The user-facing knob space multiplies (layout x block_impl x unroll x
remat x dropout x mesh shape); `zigzag_indices` supports any ring size
(tests/test_zigzag.py::test_zigzag_permutation_properties) but until
round 5 no ring-level exactness run left the powers of two."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models.attention import (
    attention_classifier, multi_head_attention,
)
from idc_models_tpu.ring_attention import (
    from_zigzag, full_attention, make_ring_attention, ring_attention,
    to_zigzag,
)

B, H, D = 2, 2, 8


def _qkv(t, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (B, t, H, D)), jnp.float32)
    return mk(), mk(), mk()


# ---------------------------------------------------------------------------
# non-power-of-2 ring exactness — both layouts, values and gradients
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_dev", [3, 5, 6])
@pytest.mark.parametrize("causal", [False, True])
def test_non_pow2_ring_matches_full(devices, n_dev, causal):
    t = 4 * n_dev
    q, k, v = _qkv(t, seed=n_dev)
    mesh = meshlib.seq_mesh(n_dev)
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n_dev", [3, 5, 6])
def test_non_pow2_zigzag_matches_full(devices, n_dev):
    """The balanced causal schedule has no power-of-2 assumption: stripe
    pairing (i, 2n-1-i) works for any n — pinned off the powers of two
    for values AND gradients (the schedule's quarter/half attends and
    the trailing accumulator hops are ring-size arithmetic, exactly
    where a latent divisibility assumption would hide)."""
    t = 4 * n_dev  # stripes of 2: t % 2n == 0, t_local = 4 (even)
    q, k, v = _qkv(t, seed=10 + n_dev)
    mesh = meshlib.seq_mesh(n_dev)
    ring = make_ring_attention(mesh, causal=True, layout="zigzag")

    def ring_loss(q, k, v):
        qz, kz, vz = (to_zigzag(x, n_dev) for x in (q, k, v))
        return jnp.sum(jnp.square(from_zigzag(ring(qz, kz, vz), n_dev)))

    def full_loss(q, k, v):
        return jnp.sum(jnp.square(full_attention(q, k, v, causal=True)))

    qz, kz, vz = (to_zigzag(x, n_dev) for x in (q, k, v))
    out = from_zigzag(ring(qz, kz, vz), n_dev)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("n_dev", [3, 6])
def test_non_pow2_model_learns_shape(devices, n_dev):
    """The full classifier runs (fwd + grads) over a non-power-of-2
    ring on a 1-D seq mesh — the model-level composition has no hidden
    power-of-2 assumption either."""
    mesh = meshlib.seq_mesh(n_dev)
    seq = 4 * n_dev
    model = attention_classifier(seq, 4, embed_dim=16, num_heads=2,
                                 mlp_dim=32, num_blocks=1, num_outputs=1,
                                 mesh=mesh, causal=True, layout="zigzag")
    variables = model.init(jax.random.key(0))
    x = jnp.asarray(np.random.default_rng(3).random((4, seq, 4)),
                    jnp.float32)

    def loss(p):
        y, _ = model.apply(p, {}, x)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    val, grads = jax.value_and_grad(loss)(variables.params)
    assert np.isfinite(float(val))
    assert all(bool(jnp.all(jnp.isfinite(g)))
               for g in jax.tree.leaves(grads))


# ---------------------------------------------------------------------------
# the rejection matrix: invalid knob combinations -> documented errors
# ---------------------------------------------------------------------------

def _build_case(kwargs, match):
    def run():
        make_ring_attention(meshlib.seq_mesh(4), **kwargs)
    return run, match


def _trace_case(n_dev, t, kwargs, match):
    def run():
        ring = make_ring_attention(meshlib.seq_mesh(n_dev), causal=True,
                                   **kwargs)
        ring(*_qkv(t))
    return run, match


REJECTIONS = {
    # build-time: bad enum knobs
    "bad_layout": _build_case(dict(layout="striped"), "unknown layout"),
    "bad_block_impl": _build_case(dict(block_impl="triton"),
                                  "unknown block_impl"),
    # trace-time: shape/ring incompatibilities, every message documented
    "t_not_divisible": _trace_case(4, 30, {},
                                   "not divisible by the ring size"),
    "zigzag_odd_local": _trace_case(8, 40, dict(layout="zigzag"),
                                    "even local block"),
    "zigzag_pallas_tile": _trace_case(
        8, 8 * 128, dict(layout="zigzag", block_impl="pallas"), "256"),
    "pallas_tile": _trace_case(4, 4 * 100, dict(block_impl="pallas"),
                               "multiples of 128"),
}


@pytest.mark.parametrize("case", sorted(REJECTIONS))
def test_ring_knob_rejections(devices, case):
    run, match = REJECTIONS[case]
    with pytest.raises(ValueError, match=match):
        run()


def test_model_knob_rejections(devices):
    mesh = meshlib.seq_mesh(4)
    # embed not divisible by heads
    with pytest.raises(ValueError, match="not divisible by"):
        multi_head_attention(30, 4, mesh=mesh)
    # mesh without a "seq" axis
    with pytest.raises(ValueError, match="no 'seq' axis"):
        multi_head_attention(32, 4, mesh=meshlib.data_mesh())
    # dropout out of range fails at build
    with pytest.raises(ValueError, match="rate must be"):
        attention_classifier(16, 4, embed_dim=16, num_heads=2,
                             mlp_dim=32, num_blocks=1, mesh=mesh,
                             dropout_rate=1.5)
    # zigzag seq_len not divisible into 2n stripes fails at trace with
    # the zigzag_indices message (remat/unroll/dropout change nothing
    # about validation: they compose with every valid combination and
    # add no invalid ones — bools and a validated float)
    model = attention_classifier(20, 4, embed_dim=16, num_heads=2,
                                 mlp_dim=32, num_blocks=1, mesh=mesh,
                                 causal=True, layout="zigzag", remat=True)
    variables = model.init(jax.random.key(0))
    x = jnp.zeros((2, 20, 4))
    with pytest.raises(ValueError, match="not divisible"):
        model.apply(variables.params, {}, x)
