"""The radix prefix cache (serve/prefix_cache.py) and its integration
into chunked admission: longest-prefix reuse, LRU eviction under a byte
budget, and the hard correctness contract — a hit is bit-identical to
recomputing, and a lookup after evict re-prefills (never stale KV).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models.lm import Generator, attention_lm
from idc_models_tpu.serve import LMServer, PrefixCache, Request

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


def _kw():
    return dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                t_max=SEQ, mesh=None, cache_dtype=jnp.float32)


def _serial_tokens(gen, prompt, steps):
    logits, caches = gen.prefill(jnp.asarray([prompt], jnp.int32))
    toks, _, _ = gen.decode(caches, logits, len(prompt), steps)
    return toks.tolist()[0]


def _snap(x):
    """A tiny fake snapshot whose nbytes are predictable."""
    return (np.full((x,), 1.0, np.float32),), np.zeros(4, np.float32)


# -- unit: the radix structure -------------------------------------------


def test_longest_prefix_lookup_on_chunk_grid():
    pc = PrefixCache(chunk=4, max_bytes=1 << 20)
    caches, logits = _snap(8)
    pc.insert(list(range(4)), caches, logits)          # depth 1
    pc.insert(list(range(8)), caches, logits)          # depth 2
    # deepest stored boundary wins; partial tail ignored
    start, c, l = pc.lookup(list(range(8)) + [99, 98])
    assert start == 8 and c is not None
    # a diverging second chunk falls back to the shared first chunk
    start, c, _ = pc.lookup(list(range(4)) + [7, 7, 7, 7])
    assert start == 4
    # unknown prefix misses outright
    start, c, _ = pc.lookup([9, 9, 9, 9, 9])
    assert start == 0 and c is None
    # prompts shorter than one chunk can never hit
    start, c, _ = pc.lookup([0, 1])
    assert start == 0
    assert pc.hits == 2 and pc.misses == 2
    with pytest.raises(ValueError, match="chunk"):
        pc.insert([1, 2, 3], caches, logits)           # off-grid length


def test_lookup_returns_copies_not_the_master():
    pc = PrefixCache(chunk=2, max_bytes=1 << 20)
    caches = (jnp.ones((4,), jnp.float32),)
    pc.insert([1, 2], caches, jnp.zeros(3))
    _, got, _ = pc.lookup([1, 2, 9])
    # mutating (or donating) the returned arrays must not touch the
    # stored master — simulate by checking distinct buffers
    assert got[0] is not pc._root.children[(1, 2)].snapshot[0][0]
    _, again, _ = pc.lookup([1, 2, 9])
    np.testing.assert_array_equal(np.asarray(got[0]),
                                  np.asarray(again[0]))


def test_lru_eviction_under_byte_budget():
    caches, logits = _snap(64)           # 256B + 16B logits per snap
    size = sum(a.nbytes for a in caches) + logits.nbytes
    pc = PrefixCache(chunk=2, max_bytes=2 * size)
    pc.insert([1, 1], caches, logits)
    pc.insert([2, 2], caches, logits)
    assert pc.n_snapshots == 2
    pc.lookup([1, 1, 5])                 # touch [1,1]: now MRU
    pc.insert([3, 3], caches, logits)    # evicts LRU = [2,2]
    assert pc.evictions == 1 and pc.n_snapshots == 2
    assert pc.lookup([2, 2, 5])[0] == 0          # evicted -> miss
    assert pc.lookup([1, 1, 5])[0] == 2          # survivor
    assert pc.lookup([3, 3, 5])[0] == 2
    assert pc.nbytes <= pc.max_bytes
    # a snapshot larger than the whole budget is refused, not stored
    big_caches, big_logits = _snap(10_000)
    assert not pc.insert([4, 4], big_caches, big_logits)
    assert pc.lookup([4, 4, 1])[0] == 0


def test_hit_proven_snapshots_outlive_speculative_ones():
    """Eviction prefers never-hit (speculative) snapshots over ones
    that have served a hit, regardless of recency: a burst of unique
    prompts churns its own useless boundary snapshots instead of
    flushing the shared system-prefix state."""
    caches, logits = _snap(64)
    size = sum(a.nbytes for a in caches) + logits.nbytes
    pc = PrefixCache(chunk=2, max_bytes=3 * size)
    pc.insert([1, 1], caches, logits)        # the shared prefix
    pc.lookup([1, 1, 9])                     # ...which serves a hit
    # unique-tail burst: newer stamps than the shared prefix
    pc.insert([2, 2], caches, logits)
    pc.insert([3, 3], caches, logits)
    pc.insert([4, 4], caches, logits)        # over budget -> evict
    pc.insert([5, 5], caches, logits)        # over budget -> evict
    assert pc.evictions == 2
    # the hit-proven shared prefix survived; speculative ones churned
    assert pc.lookup([1, 1, 9])[0] == 2
    assert pc.lookup([2, 2, 9])[0] == 0
    assert pc.lookup([3, 3, 9])[0] == 0


def test_insert_dedupes_and_budget_zero_disables():
    caches, logits = _snap(8)
    pc = PrefixCache(chunk=2, max_bytes=1 << 20)
    assert pc.insert([1, 2], caches, logits)
    assert not pc.insert([1, 2], caches, logits)   # already present
    assert pc.n_snapshots == 1
    off = PrefixCache(chunk=2, max_bytes=0)
    assert not off.insert([1, 2], caches, logits)
    assert off.lookup([1, 2, 3])[0] == 0


# -- integration: hits are exact, eviction is safe ------------------------


def test_prefix_hit_is_bit_identical_to_cold_prefill(devices, params):
    """The same request served COLD (miss, full chunked prefill) and
    WARM (prefix hit, suffix-only prefill) emits bit-identical tokens —
    the snapshot IS the chunk program's output, nothing approximate."""
    gen = Generator(params, **_kw())
    sys_p = tuple(int(x) for x in
                  np.random.default_rng(5).integers(0, VOCAB, 16))
    reqs = [Request(id=f"r{i}", prompt=sys_p + (i, i + 1),
                    max_new_tokens=6) for i in range(3)]
    server = LMServer(params, n_slots=2, window=4, prefill_chunk=8,
                      prefix_cache_mb=64.0, **_kw())
    server.run([(0.0, reqs[0])])                      # cold: populates
    sizes = server.engine.cache_sizes()
    server.run([(0.0, r) for r in reqs[1:]])          # warm: hits
    summary = server.summary()
    assert summary["serve_prefix_hits"] >= 2          # r1, r2 reuse r0
    assert summary["serve_prefix_hit_rate"] > 0
    # the hit path (truncated snapshot padded back under the ring
    # sharding) must feed the chunk program the EXACT layout it was
    # warmed with — a sharding mismatch would recompile here
    assert server.engine.cache_sizes() == sizes, (
        server.engine.cache_sizes(), sizes)
    for r in reqs:
        assert server.poll(r.id).tokens == _serial_tokens(
            gen, r.prompt, 6), r.id


def test_hit_after_evict_reprefills_never_stale(devices, params):
    """Eviction safety: after the shared prefix's snapshot is evicted,
    the next request MISSES and re-prefills from scratch — output still
    bit-identical to serial; under no circumstance is stale or
    partially-evicted KV served."""
    gen = Generator(params, **_kw())
    rng = np.random.default_rng(9)
    pa = tuple(int(x) for x in rng.integers(0, VOCAB, 16))
    pb = tuple(int(x) for x in rng.integers(0, VOCAB, 16))
    # budget ~ one request's boundary snapshots (stored TRUNCATED to
    # the prefix: boundaries at 8 and 16 tokens cost 8/SEQ and 16/SEQ
    # of a full row): admitting B must evict A's
    full = 2 * BLOCKS * SEQ * HEADS * (E // HEADS) * 4
    per_req = full * (8 + 16) // SEQ + 2 * VOCAB * 4
    server = LMServer(params, n_slots=1, window=4, prefill_chunk=8,
                      prefix_cache_mb=1.2 * per_req / (1024 * 1024),
                      **_kw())
    pc = server.engine.prefix_cache

    def serve_one(rid, prompt):
        server.run([(0.0, Request(id=rid, prompt=prompt,
                                  max_new_tokens=5))])
        return server.poll(rid).tokens

    assert serve_one("a0", pa + (1,)) == _serial_tokens(
        gen, pa + (1,), 5)
    assert serve_one("b0", pb + (2,)) == _serial_tokens(
        gen, pb + (2,), 5)
    assert pc.evictions > 0, (pc.nbytes, pc.max_bytes)
    hits_before = pc.hits
    # A's snapshots were evicted: this must MISS at depth 2 (or hit a
    # shallower surviving boundary) and still match serial exactly
    assert serve_one("a1", pa + (3,)) == _serial_tokens(
        gen, pa + (3,), 5)
    assert pc.misses > 0
    # and a re-populated prefix serves the next request from cache
    assert serve_one("a2", pa + (4,)) == _serial_tokens(
        gen, pa + (4,), 5)
    assert pc.hits > hits_before
