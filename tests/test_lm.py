"""The causal LM closes the train→serve loop: the cached decoder must
reproduce the training path's logits EXACTLY (fp tolerance), position
by position, from the same parameter tree — on rings, the 2-D mesh,
and for weights trained under the zigzag layout (which is a schedule
permutation, not a different function). Plus: the LM learns a
next-token task through the standard train step, and greedy generation
extends the pattern it learned."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models.lm import (
    Generator, attention_lm, generate, make_lm_decoder, next_token_loss,
)
from idc_models_tpu.train import (
    TrainState, jit_data_parallel, make_train_step, replicate, rmsprop,
    shard_batch,
)

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2


def _model(mesh, seq=SEQ, **kw):
    return attention_lm(VOCAB, seq, embed_dim=E, num_heads=HEADS,
                        mlp_dim=MLP, num_blocks=BLOCKS, mesh=mesh, **kw)


def _toks(n, seed=0, seq=SEQ):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, VOCAB, (n, seq)), jnp.int32)


def _decode_logits(params, tokens, mesh, t_max=SEQ):
    init_caches, step, _ = make_lm_decoder(
        params, embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
        t_max=t_max, mesh=mesh, cache_dtype=jnp.float32)
    caches = init_caches(tokens.shape[0])
    rows = []
    for pos in range(tokens.shape[1]):
        logits, caches = step(caches, tokens[:, pos], pos)
        rows.append(logits[:, None])
    return jnp.concatenate(rows, axis=1)


@pytest.mark.parametrize("n_ring,seq", [(1, 32), (3, 24), (4, 32)])
def test_incremental_equals_full(devices, n_ring, seq):
    """Teacher-forced cached decode == the training forward, every
    position, on rings incl. non-power-of-2 (seq divisible by ring)."""
    mesh = meshlib.seq_mesh(n_ring) if n_ring > 1 else None
    model = _model(mesh, seq=seq)
    params = model.init(jax.random.key(0)).params
    toks = _toks(2, seed=n_ring, seq=seq)
    full, _ = model.apply(params, {}, toks)
    inc = _decode_logits(params, toks, mesh, t_max=seq)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_zigzag_weights_decode_identically(devices):
    """Layout is a training knob, not a serving constraint: the zigzag
    model computes the same function, so its params decode through the
    natural-order cached path to the same logits."""
    mesh = meshlib.seq_mesh(4)
    zig = _model(mesh, layout="zigzag")
    params = zig.init(jax.random.key(1)).params
    toks = _toks(2, seed=9)
    full, _ = zig.apply(params, {}, toks)
    inc = _decode_logits(params, toks, mesh)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_lm_learns_and_generates(devices):
    """Golden loop: train next = (tok + 1) % VOCAB through the standard
    DP train step on the ("data", "seq") mesh, then greedy-generate the
    learned successor pattern through the cached decoder."""
    mesh = meshlib.data_seq_mesh(4, 2)
    model = _model(mesh)
    opt = rmsprop(3e-3)
    variables = model.init(jax.random.key(2))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, lambda lg, tk: next_token_loss(lg, tk)),
        mesh, axis="data")
    state = replicate(mesh, state)
    rng = np.random.default_rng(3)
    key = jax.random.key(4)
    loss = None
    for i in range(150):
        starts = rng.integers(0, VOCAB, (32, 1))
        seqs = (starts + np.arange(SEQ)) % VOCAB
        bx = shard_batch(mesh, jnp.asarray(seqs, jnp.int32), axis="data")
        key, sub = jax.random.split(key)
        state, m = step(state, bx, bx, sub)
        loss = float(m["loss"])
    assert loss < 0.1, loss
    params = jax.device_get(state.params)
    prompt = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
    out = generate(params, prompt, 8, embed_dim=E, num_heads=HEADS,
                   num_blocks=BLOCKS, t_max=SEQ,
                   cache_dtype=jnp.float32)
    want = [(3 + i) % VOCAB for i in range(12)]
    assert out.tolist() == [want], (out.tolist(), want)


def test_decoder_rejections(devices):
    model = _model(None)
    params = model.init(jax.random.key(0)).params
    with pytest.raises(ValueError, match="position table"):
        make_lm_decoder(params, embed_dim=E, num_heads=HEADS,
                        num_blocks=BLOCKS, t_max=SEQ * 2)
    with pytest.raises(ValueError, match="not divisible"):
        make_lm_decoder(params, embed_dim=30, num_heads=4,
                        num_blocks=BLOCKS, t_max=SEQ)
    with pytest.raises(ValueError, match="exceeds"):
        generate(params, jnp.zeros((1, 30), jnp.int32), 8,
                 embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
                 t_max=SEQ)


def test_lm_checkpoint_roundtrip(devices, tmp_path):
    """The LM rides the standard orbax checkpoint machinery (C8/§5):
    params saved after a few train steps restore to a tree that decodes
    IDENTICAL tokens — training, persistence, and serving all share one
    parameter pytree."""
    from idc_models_tpu.train import restore_checkpoint, save_checkpoint

    mesh = meshlib.data_seq_mesh(4, 2)
    model = _model(mesh)
    opt = rmsprop(3e-3)
    variables = model.init(jax.random.key(5))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, next_token_loss), mesh, axis="data")
    state = replicate(mesh, state)
    rng = np.random.default_rng(6)
    key = jax.random.key(7)
    for i in range(5):
        starts = rng.integers(0, VOCAB, (16, 1))
        seqs = (starts + np.arange(SEQ)) % VOCAB
        bx = shard_batch(mesh, jnp.asarray(seqs, jnp.int32), axis="data")
        key, sub = jax.random.split(key)
        state, _ = step(state, bx, bx, sub)
    save_checkpoint(tmp_path / "lm", state)
    template = jax.tree.map(np.zeros_like, jax.device_get(state))
    restored = restore_checkpoint(tmp_path / "lm", template)
    prompt = _toks(1, seed=8)[:, :5]
    a = generate(jax.device_get(state.params), prompt, 6, embed_dim=E,
                 num_heads=HEADS, num_blocks=BLOCKS, t_max=SEQ,
                 cache_dtype=jnp.float32)
    b = generate(restored.params, prompt, 6, embed_dim=E,
                 num_heads=HEADS, num_blocks=BLOCKS, t_max=SEQ,
                 cache_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prefill_tokens_equals_tokenwise(devices):
    """One-pass prompt prefill == feeding the prompt through step()
    token by token: caches and last-position logits equal to fp
    tolerance (the batched projections reassociate the same matmuls) —
    on the ring, so the prefilled caches land sharded correctly."""
    mesh = meshlib.seq_mesh(4)
    model = _model(mesh)
    params = model.init(jax.random.key(9)).params
    toks = _toks(2, seed=13)
    p_len = 20
    init_caches, step, prefill_tokens = make_lm_decoder(
        params, embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
        t_max=SEQ, mesh=mesh, cache_dtype=jnp.float32)
    # path A: token by token
    caches_a = init_caches(2)
    logits_a = None
    for pos in range(p_len):
        logits_a, caches_a = step(caches_a, toks[:, pos], pos)
    # path B: one pass
    logits_b, caches_b = prefill_tokens(toks[:, :p_len])
    np.testing.assert_allclose(np.asarray(logits_b),
                               np.asarray(logits_a),
                               rtol=2e-4, atol=2e-4)
    for (ka, va), (kb, vb) in zip(caches_a, caches_b):
        np.testing.assert_allclose(np.asarray(ka), np.asarray(kb),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                   rtol=1e-5, atol=1e-5)
    # rejections
    with pytest.raises(ValueError, match="non-empty"):
        prefill_tokens(jnp.zeros((2, 0), jnp.int32))
    with pytest.raises(ValueError, match="exceeds"):
        prefill_tokens(jnp.zeros((2, SEQ + 1), jnp.int32))


def test_prefill_runs_through_ring(devices):
    """Ring prefill == the single-device full-attention forward (the
    old prefill path) at the last prompt position — for prompts both
    divisible and NOT divisible by the ring (internal end-padding),
    with the caches landing ring-sharded and the pad region zero."""
    from idc_models_tpu.ring_decode import cache_sharding

    mesh = meshlib.seq_mesh(4)
    params = _model(mesh).init(jax.random.key(21)).params
    ref_model = _model(None)          # full_attention blocks
    toks = _toks(2, seed=17)
    full, _ = ref_model.apply(params, {}, toks)
    _, _, prefill_tokens = make_lm_decoder(
        params, embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
        t_max=SEQ, mesh=mesh, cache_dtype=jnp.float32)
    want = cache_sharding(mesh)
    for p_len in (16, 18):            # 18 % 4 != 0 -> padded internally
        logits, caches = prefill_tokens(toks[:, :p_len])
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, p_len - 1]),
                                   rtol=2e-4, atol=2e-4)
        for kc, vc in caches:
            assert kc.sharding.is_equivalent_to(want, kc.ndim)
            assert vc.sharding.is_equivalent_to(want, vc.ndim)
            # slots past the prompt stay zero — the fresh-cache
            # contract decode's visibility masking relies on
            assert not np.asarray(kc)[:, p_len:].any()
            assert not np.asarray(vc)[:, p_len:].any()


def _step_loop_reference(params, prompt, steps, mesh, temperature,
                         top_k, rng):
    """The pre-fused serving loop — prefill, then one pick + one step()
    dispatch per token — with pick's exact math inlined. The fused scan
    must reproduce its token sequence bit-for-bit (same rng split
    order: one split per emitted token, before the pick)."""
    _, step, prefill_tokens = make_lm_decoder(
        params, embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
        t_max=SEQ, mesh=mesh, cache_dtype=jnp.float32)
    logits, caches = prefill_tokens(prompt)
    p_len = prompt.shape[1]
    toks = [prompt]
    for s in range(steps):
        rng, sub = jax.random.split(rng)
        lg = logits.astype(jnp.float32)
        if top_k is not None and top_k < lg.shape[-1]:
            kth = jax.lax.top_k(lg, top_k)[0][:, -1]
            lg = jnp.where(lg >= kth[:, None], lg, -jnp.inf)
        if temperature == 0.0:
            tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        else:
            tok = jax.random.categorical(sub, lg / temperature,
                                         axis=-1).astype(jnp.int32)
        toks.append(tok[:, None])
        if s + 1 < steps:
            logits, caches = step(caches, tok, p_len + s)
    return jnp.concatenate(toks, axis=1)


def test_fused_decode_matches_step_loop(devices):
    """The one-dispatch scan decode emits the SAME token sequence as
    driving step() from the host, greedy and seeded top-k sampling."""
    mesh = meshlib.seq_mesh(4)
    params = _model(mesh).init(jax.random.key(23)).params
    prompt = _toks(2, seed=19)[:, :10]
    kw = dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
              t_max=SEQ, mesh=mesh, cache_dtype=jnp.float32)
    fused = generate(params, prompt, 8, **kw)
    ref = _step_loop_reference(params, prompt, 8, mesh, 0.0, None,
                               jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(ref))
    gen = Generator(params, temperature=1.3, top_k=4, **kw)
    fused = gen(prompt, 8, rng=jax.random.key(42))
    ref = _step_loop_reference(params, prompt, 8, mesh, 1.3, 4,
                               jax.random.key(42))
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(ref))


def test_generator_reuses_compilation(devices):
    """Zero recompilation on reuse: a second same-shape call — and a
    second Generator over a fresh same-shape parameter tree — must not
    grow any program's jit cache (the ADVICE r5 per-request re-jit)."""
    mesh = meshlib.seq_mesh(2)
    params = _model(mesh).init(jax.random.key(31)).params
    kw = dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
              t_max=SEQ, mesh=mesh, cache_dtype=jnp.float32)
    gen = Generator(params, **kw)
    prompt = _toks(2, seed=33)[:, :8]
    out1 = gen(prompt, 5)
    sizes = gen.cache_sizes()
    out2 = gen(prompt, 5)
    assert gen.cache_sizes() == sizes, (gen.cache_sizes(), sizes)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    params2 = jax.tree.map(lambda a: np.array(a), params)
    gen2 = Generator(params2, **kw)
    out3 = gen2(prompt, 5)
    assert gen2.cache_sizes() == sizes, (gen2.cache_sizes(), sizes)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out3))


def test_generator_chained_decode_windows(devices):
    """decode() windows chain exactly: two back-to-back windows through
    the returned (logits, caches) equal one window of the combined
    length — the contract the serving bench leans on."""
    params = _model(None).init(jax.random.key(35)).params
    kw = dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
              t_max=SEQ, cache_dtype=jnp.float32)
    gen = Generator(params, **kw)
    prompt = _toks(1, seed=37)[:, :6]
    one = gen(prompt, 10)
    logits, caches = gen.prefill(prompt)
    t1, logits, caches = gen.decode(caches, logits, 6, 4)
    t2, _, _ = gen.decode(caches, logits, 10, 6)
    two = jnp.concatenate([prompt, t1, t2], axis=1)
    np.testing.assert_array_equal(np.asarray(one), np.asarray(two))
    with pytest.raises(ValueError, match="exceeds t_max"):
        gen.decode(gen.init_caches(1), jnp.zeros((1, VOCAB)), SEQ - 2, 4)
    with pytest.raises(ValueError, match=">= 0"):
        gen.decode(gen.init_caches(1), jnp.zeros((1, VOCAB)), -1, 2)


def test_int_tokens_skip_compute_dtype_cast(devices):
    """bf16 train/eval steps must not round-trip token ids through the
    compute dtype: ids > 256 would corrupt before attention_lm's int32
    cast-back (ADVICE r5). With the integer-dtype skip, a bf16 step is
    bit-identical to the f32 step on the same int tokens."""
    from idc_models_tpu.train.step import make_eval_step
    from idc_models_tpu.train import rmsprop

    vocab, seq = 600, 8
    model = attention_lm(vocab, seq, embed_dim=16, num_heads=2,
                         mlp_dim=32, num_blocks=1)
    variables = model.init(jax.random.key(41))
    opt = rmsprop(1e-3)

    def fresh_state():
        return TrainState(step=jnp.zeros((), jnp.int32),
                          params=variables.params,
                          model_state=variables.state,
                          opt_state=opt.init(variables.params))

    toks = jnp.asarray([[1, 511, 512, 513, 300, 2, 3, 4]], jnp.int32)
    ev_bf = make_eval_step(model, next_token_loss,
                           compute_dtype=jnp.bfloat16)(
        fresh_state(), toks, toks)
    ev_f32 = make_eval_step(model, next_token_loss,
                            compute_dtype=jnp.float32)(
        fresh_state(), toks, toks)
    np.testing.assert_array_equal(np.asarray(ev_bf["logits"]),
                                  np.asarray(ev_f32["logits"]))
    key = jax.random.key(43)
    _, m_bf = make_train_step(model, opt, next_token_loss,
                              compute_dtype=jnp.bfloat16)(
        fresh_state(), toks, toks, key)
    _, m_f32 = make_train_step(model, opt, next_token_loss,
                               compute_dtype=jnp.float32)(
        fresh_state(), toks, toks, key)
    assert float(m_bf["loss"]) == float(m_f32["loss"])


def test_generator_bounds_edges(devices):
    """The t_max boundary exactly: a prompt of exactly t_max tokens
    prefills fine, but ANY decode from there must be rejected BEFORE
    dispatch (inside the fused scan an out-of-range append would be
    silently dropped); steps=0/negative are rejected with clear
    messages."""
    params = _model(None).init(jax.random.key(51)).params
    gen = Generator(params, embed_dim=E, num_heads=HEADS,
                    num_blocks=BLOCKS, t_max=SEQ, cache_dtype=jnp.float32)
    full = _toks(1, seed=53)                      # exactly t_max tokens
    assert full.shape[1] == SEQ
    logits, caches = gen.prefill(full)            # fine: fills the cache
    assert logits.shape == (1, VOCAB)
    for kc, _vc in caches:
        assert np.asarray(kc)[:, -1].any()        # last slot occupied
    # any decode from the full cache must fail before dispatch
    with pytest.raises(ValueError, match="exceeds t_max"):
        gen.decode(caches, logits, SEQ, 1)
    # __call__ refuses a full-length prompt + any steps the same way
    with pytest.raises(ValueError, match="exceeds"):
        gen(full, 1)
    # steps=0 / negative: rejected with a clear message, no dispatch
    with pytest.raises(ValueError, match="steps >= 1"):
        gen.decode(caches, logits, 4, 0)
    with pytest.raises(ValueError, match="steps >= 1"):
        gen.decode(caches, logits, 4, -3)
    with pytest.raises(ValueError, match="steps >= 1"):
        gen(full[:, :4], 0)


def test_prefill_buckets(devices):
    """Prompt length maps onto the fixed bucket set (n_ring * powers of
    two, capped at t_max) — the compile-set contract the serving engine
    warms up against."""
    from idc_models_tpu.models.lm import prefill_bucket, prefill_buckets

    assert prefill_buckets(32, 1) == (1, 2, 4, 8, 16, 32)
    assert prefill_buckets(32, 4) == (4, 8, 16, 32)
    assert prefill_buckets(24, 4) == (4, 8, 16, 24)
    for n_ring, t_max in ((1, 32), (4, 32), (4, 24), (3, 24)):
        buckets = prefill_buckets(t_max, n_ring)
        assert all(b % n_ring == 0 for b in buckets)
        for p in range(1, t_max + 1):
            b = prefill_bucket(p, t_max, n_ring)
            assert b in buckets and b >= p
    with pytest.raises(ValueError, match="outside"):
        prefill_bucket(0, 32, 1)
    with pytest.raises(ValueError, match="outside"):
        prefill_bucket(33, 32, 1)


def test_generate_sampling_modes(devices):
    """temperature/top_k: greedy is deterministic and equals the
    default; sampling varies with the rng but respects top_k=1 ==
    greedy; invalid knobs are rejected."""
    model = _model(None)
    params = model.init(jax.random.key(11)).params
    kw = dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
              t_max=SEQ, cache_dtype=jnp.float32)
    prompt = _toks(2, seed=15)[:, :6]
    greedy = generate(params, prompt, 6, **kw)
    np.testing.assert_array_equal(
        np.asarray(generate(params, prompt, 6, temperature=0.0, **kw)),
        np.asarray(greedy))
    # top_k=1 sampling has a single-token support -> exactly greedy
    np.testing.assert_array_equal(
        np.asarray(generate(params, prompt, 6, temperature=5.0,
                            top_k=1, rng=jax.random.key(0), **kw)),
        np.asarray(greedy))
    # high temperature over an untrained (near-uniform) head varies
    a = generate(params, prompt, 6, temperature=5.0,
                 rng=jax.random.key(1), **kw)
    c = generate(params, prompt, 6, temperature=5.0,
                 rng=jax.random.key(2), **kw)
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    with pytest.raises(ValueError, match="needs an rng"):
        generate(params, prompt, 2, temperature=1.0, **kw)
    with pytest.raises(ValueError, match="temperature"):
        generate(params, prompt, 2, temperature=-1.0, **kw)
    with pytest.raises(ValueError, match="top_k"):
        generate(params, prompt, 2, top_k=0, **kw)
