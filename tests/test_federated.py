"""FedAvg over an 8-client virtual mesh (reference D3/C9-C11 parity).

Covers the SURVEY.md §4 plan: FedAvg on identical shards equals centralized
training for one round; loss decreases over rounds; weighted aggregation
semantics; federated evaluation.
"""

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import partition_clients
from idc_models_tpu.federated import (
    initialize_server, make_fedavg_round, make_federated_eval,
    seed_server_with,
)
from idc_models_tpu.models import small_cnn
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

N_CLIENTS = 8


def _client_data(n_per_client=32, seed=0, identical=False):
    if identical:
        imgs, labels = synthetic.make_idc_like(n_per_client, size=10, seed=seed)
        return (np.broadcast_to(imgs, (N_CLIENTS,) + imgs.shape).copy(),
                np.broadcast_to(labels, (N_CLIENTS,) + labels.shape).copy())
    imgs, labels = synthetic.make_idc_like(n_per_client * N_CLIENTS, size=10,
                                           seed=seed)
    ds = ArrayDataset(imgs, labels)
    return partition_clients(ds, N_CLIENTS, iid=True, seed=seed)


def test_fedavg_loss_decreases(devices):
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    server = initialize_server(model, jax.random.key(0))
    round_fn = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                                 local_epochs=2, batch_size=16)
    imgs, labels = _client_data()
    weights = np.full((N_CLIENTS,), imgs.shape[1], np.float32)

    losses = []
    key = jax.random.key(1)
    for r in range(8):
        key, sub = jax.random.split(key)
        server, m = round_fn(server, imgs, labels, weights, sub)
        losses.append(float(m["loss"]))
    assert int(server.round) == 8
    assert losses[-1] < losses[0] * 0.9, losses


def _no_dropout_model():
    """A deterministic (dropout-free) model so per-client rng folds cannot
    introduce trajectory differences in the exactness tests."""
    from idc_models_tpu.models import core

    return core.sequential(
        [
            core.conv2d(3, 8, 3, stride=2, name="conv1"),
            core.relu(),
            core.flatten(),
            core.dense(8 * 5 * 5, 1, name="head"),
        ],
        name="tiny",
    )


def test_identical_shards_equal_local_training(devices):
    """Every client holds the same shard and a deterministic model: the
    averaged trajectory must EXACTLY reproduce a single client's trajectory
    (FedAvg == centralized for identical clients, SURVEY.md §4)."""
    mesh8 = meshlib.client_mesh(N_CLIENTS)
    mesh1 = meshlib.client_mesh(1)
    model = _no_dropout_model()
    opt = rmsprop(1e-3)
    loss = binary_cross_entropy
    imgs, labels = _client_data(identical=True)

    def run(mesh, n):
        server = initialize_server(model, jax.random.key(0))
        # full-batch, 1 epoch: per-client shuffles are permutations of one
        # batch, so ordering cannot differ either.
        rnd = make_fedavg_round(model, opt, loss, mesh, local_epochs=1,
                                batch_size=imgs.shape[1])
        w = np.ones((n,), np.float32)
        server, m = rnd(server, imgs[:n], labels[:n], w, jax.random.key(3))
        return jax.device_get(server.params), m

    p8, m8 = run(mesh8, N_CLIENTS)
    p1, m1 = run(mesh1, 1)
    for a, b in zip(jax.tree.leaves(p8), jax.tree.leaves(p1)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]),
                               rtol=1e-5)


def test_weight_concentration_selects_client(devices):
    """weights=[1,0,...]: the aggregate must equal client 0's local result."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    mesh1 = meshlib.client_mesh(1)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data(seed=5)
    rng = jax.random.key(9)

    server0 = initialize_server(model, jax.random.key(0))
    rnd8 = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                             local_epochs=1, batch_size=imgs.shape[1])
    w = np.zeros((N_CLIENTS,), np.float32)
    w[0] = 1.0
    s8, _ = rnd8(server0, imgs, labels, w, rng)

    server0b = initialize_server(model, jax.random.key(0))
    rnd1 = make_fedavg_round(model, opt, binary_cross_entropy, mesh1,
                             local_epochs=1, batch_size=imgs.shape[1])
    s1, _ = rnd1(server0b, imgs[:1], labels[:1], np.ones((1,), np.float32),
                 rng)
    for a, b in zip(jax.tree.leaves(jax.device_get(s8.params)),
                    jax.tree.leaves(jax.device_get(s1.params))):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_nonfinite_client_dropped_automatically(devices):
    """Failure DETECTION (the reference has none, SURVEY.md §5): a
    client whose update diverges to non-finite values is cut inside the
    round — the aggregate equals a manual weight-0 exclusion, and
    `clients_dropped` reports the cut."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data(seed=11)
    poisoned = np.array(imgs)
    poisoned[3] = np.nan            # client 3's data corrupts its update
    w = np.full((N_CLIENTS,), imgs.shape[1], np.float32)
    rng = jax.random.key(13)

    rnd = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                            local_epochs=1, batch_size=16)
    s_auto, m_auto = rnd(initialize_server(model, jax.random.key(0)),
                         poisoned, labels, w, rng)
    w_manual = w.copy()
    w_manual[3] = 0.0
    s_manual, m_manual = rnd(initialize_server(model, jax.random.key(0)),
                             poisoned, labels, w_manual, rng)

    assert int(m_auto["clients_dropped"]) == 1
    assert int(m_manual["clients_dropped"]) == 0   # weight-0 != failure
    assert np.isfinite(float(m_auto["loss"]))
    for a, b in zip(jax.tree.leaves(jax.device_get(s_auto.params)),
                    jax.tree.leaves(jax.device_get(s_manual.params))):
        np.testing.assert_array_equal(a, b)
    assert all(np.all(np.isfinite(l))
               for l in jax.tree.leaves(jax.device_get(s_auto.params)))

    # detection can be disabled: the poisoned client then poisons the
    # round (documenting why the default is on)
    rnd_off = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                                local_epochs=1, batch_size=16,
                                drop_nonfinite=False)
    s_off, _ = rnd_off(initialize_server(model, jax.random.key(0)),
                       poisoned, labels, w, rng)
    assert not all(np.all(np.isfinite(l))
                   for l in jax.tree.leaves(jax.device_get(s_off.params)))


def test_client_count_independent_of_device_count(devices):
    """k clients per device: the same 8 clients aggregated on an
    8-device mesh (k=1) and a 4-device mesh (k=2) produce the same
    round — client count is a workload property, not a hardware one.

    Skipped where the BACKEND itself is not layout-deterministic for
    this program shape (see tests/_layout_probe.py for the full
    root-cause): on such builds the assertion tests XLA's lowering, not
    the framework's math."""
    import pytest

    from _layout_probe import LAYOUT_SKIP_REASON, layout_invariant

    if not layout_invariant():
        pytest.skip(LAYOUT_SKIP_REASON)
    model = small_cnn(10, 3, 1)
    imgs, labels = _client_data(seed=7)
    w = np.full((N_CLIENTS,), imgs.shape[1], np.float32)
    rng = jax.random.key(3)

    def run(n_dev):
        mesh = meshlib.client_mesh(n_dev)
        server = initialize_server(model, jax.random.key(0))
        rnd = make_fedavg_round(model, rmsprop(1e-3), binary_cross_entropy,
                                mesh, local_epochs=2, batch_size=16)
        server, m = rnd(server, imgs, labels, w, rng)
        ev = make_federated_eval(model, binary_cross_entropy, mesh)
        em = ev(server, imgs, labels, w)
        return jax.device_get(server.params), m, em

    p8, m8, e8 = run(8)
    p4, m4, e4 = run(4)
    for a, b in zip(jax.tree.leaves(p8), jax.tree.leaves(p4)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(m8["loss"]), float(m4["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(e8["loss"]), float(e4["loss"]),
                               rtol=1e-5)


def test_padded_dummy_clients_are_inert(devices):
    """10 clients on an 8-device mesh: pad_clients adds 6 weight-0
    dummies (k=2); the aggregate equals the same 10 clients on a
    5-device mesh with no padding."""
    from idc_models_tpu.data.partition import pad_clients

    model = small_cnn(10, 3, 1)
    imgs10, labels10 = synthetic.make_idc_like(10 * 16, size=10, seed=9)
    ds = ArrayDataset(imgs10, labels10)
    imgs, labels = partition_clients(ds, 10, iid=True, seed=9)
    w = np.full((10,), 16.0, np.float32)
    rng = jax.random.key(4)

    def run(n_dev):
        mesh = meshlib.client_mesh(n_dev)
        ci, cl, cw = pad_clients(imgs, labels, w, multiple=n_dev)
        server = initialize_server(model, jax.random.key(0))
        rnd = make_fedavg_round(model, rmsprop(1e-3), binary_cross_entropy,
                                mesh, local_epochs=1, batch_size=16)
        server, _ = rnd(server, ci, cl, cw, rng)
        return jax.device_get(server.params)

    p8 = run(8)   # padded to 16 shards, 6 inert
    p5 = run(5)   # exact fit, k=2, no padding
    for a, b in zip(jax.tree.leaves(p8), jax.tree.leaves(p5)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    # mismatched weights (padded data, unpadded weights) fail loudly
    import pytest

    mesh = meshlib.client_mesh(8)
    ci, cl, _ = pad_clients(imgs, labels, w, multiple=8)
    rnd = make_fedavg_round(small_cnn(10, 3, 1), rmsprop(1e-3),
                            binary_cross_entropy, mesh,
                            local_epochs=1, batch_size=16)
    with pytest.raises(ValueError, match="pad them together"):
        rnd(initialize_server(small_cnn(10, 3, 1), jax.random.key(0)),
            ci, cl, w, jax.random.key(1))
    ev = make_federated_eval(small_cnn(10, 3, 1), binary_cross_entropy,
                             mesh)
    with pytest.raises(ValueError, match="pad them together"):
        ev(initialize_server(small_cnn(10, 3, 1), jax.random.key(0)),
           ci, cl, w)


def test_all_clients_dropped_keeps_server_state(devices):
    """Failure tolerance: a round where every client has weight 0 (all
    participants failed) is a no-op on the global model — never NaN,
    never a zero model — even when the dead clients' data is garbage."""
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    opt = rmsprop(1e-3)
    imgs, labels = _client_data(seed=4)
    imgs = np.full_like(imgs, np.nan)  # every client is poisoned
    server = initialize_server(model, jax.random.key(0))
    before = jax.device_get(server.params)
    rnd = make_fedavg_round(model, opt, binary_cross_entropy, mesh,
                            local_epochs=1, batch_size=imgs.shape[1])
    server, m = rnd(server, imgs, labels,
                    np.zeros((N_CLIENTS,), np.float32), jax.random.key(1))
    for a, b in zip(jax.tree.leaves(jax.device_get(server.params)),
                    jax.tree.leaves(before)):
        np.testing.assert_array_equal(a, b)
    assert int(server.round) == 1
    # a round with no contributors must not report a (perfect-looking)
    # 0.0 loss — it reports NaN
    assert np.isnan(float(m["loss"])) and np.isnan(float(m["accuracy"]))

    # same, reached through automatic detection: every client diverges,
    # drop_nonfinite cuts them all, the state is kept and metrics are NaN
    server2 = initialize_server(model, jax.random.key(0))
    server2, m2 = rnd(server2, imgs, labels,
                      np.full((N_CLIENTS,), 16.0, np.float32),
                      jax.random.key(1))
    assert int(m2["clients_dropped"]) == N_CLIENTS
    assert np.isnan(float(m2["loss"]))
    for a, b in zip(jax.tree.leaves(jax.device_get(server2.params)),
                    jax.tree.leaves(before)):
        np.testing.assert_array_equal(a, b)


def test_federated_eval(devices):
    mesh = meshlib.client_mesh(N_CLIENTS)
    model = small_cnn(10, 3, 1)
    server = initialize_server(model, jax.random.key(0))
    eval_fn = make_federated_eval(model, binary_cross_entropy, mesh)
    imgs, labels = _client_data(seed=7)
    weights = np.full((N_CLIENTS,), imgs.shape[1], np.float32)
    m = eval_fn(server, imgs, labels, weights)
    assert np.isfinite(float(m["loss"]))
    assert 0.0 <= float(m["accuracy"]) <= 1.0

    # weighted mean across clients == direct eval on the pooled examples
    logits, _ = model.apply(server.params, server.model_state,
                            jnp.asarray(imgs.reshape(-1, *imgs.shape[2:])),
                            train=False)
    pooled_loss = float(binary_cross_entropy(logits, labels.reshape(-1)))
    np.testing.assert_allclose(float(m["loss"]), pooled_loss, rtol=1e-5)


def test_server_state_checkpoint_roundtrip(devices, tmp_path):
    """Federated round-loop resume: ServerState (including the round
    counter) survives an orbax save/restore (SURVEY.md §5: checkpoint all
    loops, not just the pretrainer)."""
    from idc_models_tpu.train import restore_checkpoint, save_checkpoint

    model = small_cnn(10, 3, 1)
    mesh = meshlib.client_mesh(N_CLIENTS)
    server = initialize_server(model, jax.random.key(0))
    rnd = make_fedavg_round(model, rmsprop(1e-3), binary_cross_entropy,
                            mesh, local_epochs=1, batch_size=16)
    imgs, labels = _client_data()
    w = np.ones((N_CLIENTS,), np.float32)
    server, _ = rnd(server, imgs, labels, w, jax.random.key(1))
    server, _ = rnd(server, imgs, labels, w, jax.random.key(2))

    path = tmp_path / "fed_server"
    save_checkpoint(path, jax.device_get(server))
    target = initialize_server(model, jax.random.key(9))
    restored = restore_checkpoint(path, target)
    assert int(restored.round) == 2
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(server)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_seed_server_with(devices):
    model = small_cnn(10, 3, 1)
    server = initialize_server(model, jax.random.key(0))
    pretrained = model.init(jax.random.key(123))
    seeded = seed_server_with(server, pretrained.params, pretrained.state)
    a = jax.tree.leaves(seeded.params)
    b = jax.tree.leaves(pretrained.params)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
