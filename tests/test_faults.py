"""The fault-injection harness (faults.py): deterministic plans, each
fault kind's observable effect inside the round, bit-identical replay,
and the transient-read hooks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu import faults
from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import partition_clients
from idc_models_tpu.federated import initialize_server, make_fedavg_round
from idc_models_tpu.models import small_cnn
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

N = 8


def _clients(seed=0):
    imgs, labels = synthetic.make_idc_like(N * 16, size=10, seed=seed)
    ci, cl = partition_clients(ArrayDataset(imgs, labels), N, iid=True,
                               seed=seed)
    return ci, cl, np.full((N,), 16.0, np.float32)


def _round(plan=None, **kw):
    model = small_cnn(10, 3, 1)
    mesh = meshlib.client_mesh(N)
    rnd = make_fedavg_round(model, rmsprop(1e-3), binary_cross_entropy,
                            mesh, local_epochs=1, batch_size=16,
                            faults=plan, **kw)
    return model, rnd


def test_plan_codes_and_spec_parse():
    plan = faults.FaultPlan(4, [
        faults.Fault("crash", 0, rounds=(1,)),
        faults.Fault("sign_flip", 2, scale=100.0),
    ])
    c0, s0 = plan.codes(0)
    c1, _ = plan.codes(1)
    assert c0.tolist() == [0, 0, faults.SIGN_FLIP, 0]
    assert c1.tolist() == [faults.CRASH, 0, faults.SIGN_FLIP, 0]
    assert s0[2] == 100.0

    parsed = faults.parse_fault_spec("sign_flip:0-2:x1000,crash:3", 8)
    kinds = {(f.kind, f.client) for f in parsed.faults}
    assert kinds == {("sign_flip", 0), ("sign_flip", 1),
                     ("sign_flip", 2), ("crash", 3)}
    assert all(f.scale == 1000.0 for f in parsed.faults
               if f.kind == "sign_flip")
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultPlan(4, [faults.Fault("meteor", 0)])
    with pytest.raises(ValueError, match="covers"):
        faults.FaultPlan(2, [faults.Fault("crash", 5)])
    # one stale tree per round: mixed straggler lags are refused, not
    # silently collapsed to the max
    with pytest.raises(ValueError, match="single staleness"):
        faults.FaultPlan(4, [faults.Fault("straggler", 0, staleness=1),
                             faults.Fault("straggler", 1, staleness=3)])
    # the third spec field is the kind's OWN parameter: staleness for
    # straggler, rejected for kinds that take none
    lagged = faults.parse_fault_spec("straggler:3:2", 8)
    assert lagged.faults[0].staleness == 2
    with pytest.raises(ValueError, match="takes no parameter"):
        faults.parse_fault_spec("crash:2:x100", 8)
    # ISSUE-8 satellite: every parse failure enumerates the valid kinds
    # and shows the grammar (shared with the serve fault specs), so a
    # mistyped drill flag teaches its own syntax
    for bad in ("meteor:3", "crash", "crash:2:x100", "scale:1:huge",
                "crash:one"):
        with pytest.raises(ValueError) as ei:
            faults.parse_fault_spec(bad, 8)
        msg = str(ei.value)
        assert "grammar" in msg, (bad, msg)
        for kind in faults.KINDS:
            assert kind in msg, (bad, kind, msg)
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.parse_fault_spec("meteor:3", 8)
    with pytest.raises(ValueError, match="bad parameter"):
        faults.parse_fault_spec("scale:1:huge", 8)
    with pytest.raises(ValueError, match="bad clients field"):
        faults.parse_fault_spec("crash:one", 8)
    # seeded sampling is deterministic
    a = faults.FaultPlan.byzantine(10, 3, seed=5)
    b = faults.FaultPlan.byzantine(10, 3, seed=5)
    assert [f.client for f in a.faults] == [f.client for f in b.faults]


def test_population_fault_spec_addresses_virtual_ids():
    """ISSUE-13 satellite: the population grammar addresses the VIRTUAL
    population — explicit c-prefixed ids (comma-joined inside one
    group), round ranges, kind params, and seeded fractions — with the
    PR 8 teaching-error treatment on every failure mode."""
    plan = faults.parse_population_fault_spec(
        "straggler:3-6:2@c97,c4012", 10000, delay_unit_s=0.25)
    f = plan.faults[0]
    assert (f.kind, f.rounds, f.clients, f.staleness) == \
        ("straggler", (3, 4, 5, 6), (97, 4012), 2)
    ids = np.array([5, 97, 4012, 9000])
    codes, _ = plan.codes_for(4, ids)
    assert codes.tolist() == [0, faults.STRAGGLER, faults.STRAGGLER, 0]
    assert plan.codes_for(7, ids)[0].tolist() == [0, 0, 0, 0]
    # the staleness lag doubles as the wall delay (k * delay_unit_s)
    np.testing.assert_allclose(plan.delay_s(4, ids),
                               [0.0, 0.5, 0.5, 0.0])
    assert plan.delay_s(7, ids).tolist() == [0.0] * 4

    # fraction-based selection: stable per client across rounds,
    # deterministic per plan seed, roughly the asked-for rate
    frac = faults.parse_population_fault_spec("crash:2:10%", 1000,
                                              seed=4)
    all_ids = np.arange(1000)
    c2, _ = frac.codes_for(2, all_ids)
    hit = c2 == faults.CRASH
    assert 50 <= hit.sum() <= 150
    np.testing.assert_array_equal(
        c2, faults.parse_population_fault_spec("crash:2:10%", 1000,
                                               seed=4).codes_for(
            2, all_ids)[0])
    assert (frac.codes_for(0, all_ids)[0] == 0).all()   # round-scoped
    # two fraction faults in one plan select INDEPENDENTLY: with a
    # shared uniform the 10% crash set would be a strict subset of the
    # 20% straggler set and last-listed-wins would erase every crash
    both = faults.parse_population_fault_spec(
        "crash:*:10%,straggler:*:20%", 1000, seed=4)
    cb, _ = both.codes_for(0, all_ids)
    assert (cb == faults.CRASH).sum() > 40
    assert (cb == faults.STRAGGLER).sum() > 100

    # '*' = every round; scale param with @clients
    allr = faults.parse_population_fault_spec("sign_flip:*:x1000@c5",
                                              100)
    codes, scales = allr.codes_for(17, np.array([5, 6]))
    assert codes.tolist() == [faults.SIGN_FLIP, 0]
    assert scales[0] == 1000.0

    # teaching errors: every failure names the group, the grammar, and
    # the valid kinds; out-of-range ids are loud
    for bad in ("meteor:2:5%", "crash:2:0.5", "crash:2", "crash:one:5%",
                "crash:2:200%", "straggler:1:2@d4", "sign_flip:1:x3",
                "crash:2:5%@c1"):
        with pytest.raises(ValueError) as ei:
            faults.parse_population_fault_spec(bad, 100)
        msg = str(ei.value)
        assert "grammar" in msg, (bad, msg)
        for kind in faults.KINDS:
            assert kind in msg, (bad, kind, msg)
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.parse_population_fault_spec("meteor:2:5%", 100)
    with pytest.raises(ValueError, match="population has 100"):
        faults.parse_population_fault_spec("crash:1@c150", 100)
    with pytest.raises(ValueError, match="single staleness"):
        faults.PopulationFaultPlan(10, [
            faults.PopulationFault("straggler", clients=(1,),
                                   staleness=1),
            faults.PopulationFault("straggler", clients=(2,),
                                   staleness=3)])


def test_crash_equals_manual_weight_zero(devices):
    """A crash fault is indistinguishable from the caller zeroing the
    client's weight: same aggregate, bit for bit."""
    ci, cl, w = _clients()
    rng = jax.random.key(3)
    model, rnd_fault = _round(
        faults.FaultPlan(N, [faults.Fault("crash", 2)]))
    _, rnd_plain = _round()
    server = initialize_server(model, jax.random.key(0))
    s_f, m_f = rnd_fault(server, ci, cl, w, rng)
    w_manual = w.copy()
    w_manual[2] = 0.0
    server2 = initialize_server(model, jax.random.key(0))
    s_m, m_m = rnd_plain(server2, ci, cl, w_manual, rng)
    for a, b in zip(jax.tree.leaves(jax.device_get(s_f.params)),
                    jax.tree.leaves(jax.device_get(s_m.params))):
        np.testing.assert_array_equal(a, b)
    assert int(m_f["clients_dropped"]) == 0   # crash != divergence


def test_nan_inf_poisoners_are_dropped(devices):
    """NaN/Inf poisoners produce non-finite updates — exactly what
    drop_nonfinite exists for: both are cut, the server stays finite."""
    ci, cl, w = _clients(seed=1)
    model, rnd = _round(faults.FaultPlan(N, [
        faults.Fault("nan", 1), faults.Fault("inf", 4)]))
    server = initialize_server(model, jax.random.key(0))
    server, m = rnd(server, ci, cl, w, jax.random.key(5))
    assert int(m["clients_dropped"]) == 2
    assert all(np.all(np.isfinite(l))
               for l in jax.tree.leaves(jax.device_get(server.params)))
    assert np.isfinite(float(m["loss"]))


def test_scale_and_sign_flip_survive_finiteness_check(devices):
    """The Byzantine attackers stay FINITE, so drop_nonfinite cannot see
    them — the mean aggregate is steered far from the honest one (the
    gap robust aggregators close)."""
    ci, cl, w = _clients(seed=2)
    model, rnd_att = _round(faults.FaultPlan(N, [
        faults.Fault("sign_flip", 0, scale=1000.0),
        faults.Fault("scale", 3, scale=1000.0)]))
    _, rnd_plain = _round()
    rng = jax.random.key(7)
    s_a, m_a = rnd_att(initialize_server(model, jax.random.key(0)),
                       ci, cl, w, rng)
    s_p, _ = rnd_plain(initialize_server(model, jax.random.key(0)),
                       ci, cl, w, rng)
    assert int(m_a["clients_dropped"]) == 0          # invisible to detection
    assert all(np.all(np.isfinite(l))
               for l in jax.tree.leaves(jax.device_get(s_a.params)))
    delta = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(
        jax.tree.leaves(s_a.params), jax.tree.leaves(s_p.params)))
    # RMSprop's normalized step is ~lr per coordinate, so an honest
    # round moves the mean by ~1e-3; the x1000 attackers at weight 2/8
    # steer it ~250x that
    assert delta > 0.1, delta


def test_straggler_replays_stale_params(devices):
    """A straggler's update is the server params from round r-k: with
    the round-1 weight concentrated on the straggler, the round-1
    aggregate equals the round-0 INCOMING state."""
    ci, cl, w = _clients(seed=3)
    model, rnd = _round(faults.FaultPlan(N, [
        faults.Fault("straggler", 0, rounds=(1,), staleness=1)]))
    server = initialize_server(model, jax.random.key(0))
    initial = jax.device_get(server.params)
    server, _ = rnd(server, ci, cl, w, jax.random.key(1))     # round 0
    w1 = np.zeros_like(w)
    w1[0] = 1.0                         # only the straggler contributes
    server, _ = rnd(server, ci, cl, w1, jax.random.key(2))    # round 1
    for a, b in zip(jax.tree.leaves(jax.device_get(server.params)),
                    jax.tree.leaves(initial)):
        np.testing.assert_array_equal(a, b)


def test_fault_plan_replays_bit_identically(devices):
    """Two fresh builds under the same plan + seeds produce the same
    multi-round trajectory down to the last bit (the harness's core
    contract: failures are REPRODUCIBLE)."""
    ci, cl, w = _clients(seed=4)
    plan = faults.FaultPlan.byzantine(N, 2, kind="sign_flip",
                                      scale=50.0, seed=9)

    def run():
        model, rnd = _round(plan)
        server = initialize_server(model, jax.random.key(0))
        for r in range(3):
            server, m = rnd(server, ci, cl, w,
                            jax.random.fold_in(jax.random.key(1), r))
        return jax.device_get(server.params)

    p1, p2 = run(), run()
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(a, b)


def test_flaky_reads_and_retries():
    """Transient-read hooks: seeded failure schedule replays exactly;
    with_retries absorbs transient failures and re-raises persistent
    ones."""
    calls = []

    def read(i):
        calls.append(i)
        return i * 2

    def schedule(seed):
        f = faults.flaky(read, failure_rate=0.5, seed=seed)
        out = []
        for i in range(20):
            try:
                f(i)
                out.append(True)
            except faults.TransientReadError:
                out.append(False)
        return out

    assert schedule(3) == schedule(3)           # deterministic replay
    assert not all(schedule(3)) and any(schedule(3))

    # retries recover every transient failure at rate << 1
    flaky_read = faults.flaky(read, failure_rate=0.3, seed=1)
    robust_read = faults.with_retries(flaky_read, attempts=30)
    assert [robust_read(i) for i in range(10)] == [i * 2
                                                   for i in range(10)]
    # a permanent failure still surfaces
    always = faults.flaky(read, failure_rate=1.0, seed=0)
    with pytest.raises(faults.TransientReadError):
        faults.with_retries(always, attempts=3)(0)
