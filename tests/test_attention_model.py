"""Ring attention as a TRAINING capability (VERDICT r3 #4): the
attention classifier — whose every self-attention is a sequence-
parallel ring over a ("data", "seq") 2-D mesh — must LEARN a
position-sensitive synthetic task to >=0.9 train accuracy through the
REAL train step (optimizer, freeze machinery, jit_data_parallel), and
must compute the same function as its un-meshed full-attention
counterpart."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data import synthetic
from idc_models_tpu.models import core
from idc_models_tpu.models.attention import attention_classifier
from idc_models_tpu.train import (
    TrainState, jit_data_parallel, make_train_step, replicate, rmsprop,
    shard_batch,
)
from idc_models_tpu.train.losses import binary_cross_entropy
from idc_models_tpu.train.state import freeze_where

SEQ, FEAT = 32, 8
THRESHOLD = 0.9


def _model(mesh, **kw):
    return attention_classifier(SEQ, FEAT, embed_dim=32, num_heads=2,
                                mlp_dim=64, num_blocks=2, num_outputs=1,
                                mesh=mesh, causal=True, **kw)


def _train(mesh, model, steps=250, batch=64, lr=1e-3, seed=0):
    x, y = synthetic.make_sequence_task(512, SEQ, FEAT, seed=5)
    opt = rmsprop(lr)
    variables = model.init(jax.random.key(seed))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, binary_cross_entropy), mesh,
        axis="data")
    state = replicate(mesh, state)
    key = jax.random.key(1)
    accs = []
    rng = np.random.default_rng(7)
    for i in range(steps):
        sel = rng.integers(0, len(x), batch)
        bx, by = shard_batch(mesh, x[sel], y[sel], axis="data")
        key, sub = jax.random.split(key)
        state, m = step(state, bx, by, sub)
        accs.append(float(m["accuracy"]))
    return state, accs


def test_attention_classifier_learns_on_2d_mesh(devices):
    """Golden learning: >=0.9 train accuracy within 250 steps on the
    ("data", "seq") mesh — every attention call is a 4-device ring, the
    batch is sharded 2-way, and the step is the standard DP train step
    (XLA inserts the cross-"data" grad reduction around the in-step
    ring collectives)."""
    mesh = meshlib.data_seq_mesh(4, 2)
    _, accs = _train(mesh, _model(mesh))
    assert max(accs[-20:]) >= THRESHOLD, accs[-20:]


def test_attention_classifier_learns_zigzag(devices):
    """The same task learns through the zigzag causal layout (the
    internal one-time permutation must not break learning) — with
    residual dropout 0.1 on, so learning-under-dropout rides this run
    instead of costing a third 250-step training."""
    mesh = meshlib.data_seq_mesh(4, 2)
    _, accs = _train(mesh, _model(mesh, layout="zigzag",
                                  dropout_rate=0.1))
    assert max(accs[-20:]) >= THRESHOLD, accs[-20:]


def test_meshed_model_equals_unmeshed(devices):
    """The ("data", "seq")-meshed model computes the SAME function as
    the mesh=None full-attention model on identical params."""
    mesh = meshlib.data_seq_mesh(4, 2)
    meshed = _model(mesh)
    plain = _model(None)
    variables = plain.init(jax.random.key(3))
    x, _ = synthetic.make_sequence_task(8, SEQ, FEAT, seed=9)
    y_plain, _ = plain.apply(variables.params, {}, jnp.asarray(x))
    y_mesh, _ = meshed.apply(variables.params, {}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y_mesh), np.asarray(y_plain),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("block_impl", ["jnp", "pallas"])
def test_remat_identical_values_and_grads(devices, block_impl):
    """remat=True (jax.checkpoint per block) must change MEMORY only:
    outputs and gradients are identical to the stored-activation
    model on the same params — on BOTH block engines (checkpoint's
    forward recompute re-enters the pallas custom_vjp ring under
    shard_map) — and the rematerialized backward still flows through
    the ring collectives."""
    # pallas: T=512 over the 4-ring = the kernel's exact 128 tile, ONE
    # block — interpret mode is pure-Python slow and checkpoint's
    # recompute doubles it; any bigger risks the XLA CPU collective
    # rendezvous abort (>40 s to a collective on a contended 1-core
    # host — the simulator limit README documents)
    seq = 512 if block_impl == "pallas" else SEQ
    blocks = 1 if block_impl == "pallas" else 2
    mesh = meshlib.data_seq_mesh(4, 2)

    def build(**kw):
        return attention_classifier(seq, FEAT, embed_dim=32, num_heads=2,
                                    mlp_dim=64, num_blocks=blocks,
                                    num_outputs=1, mesh=mesh, causal=True,
                                    block_impl=block_impl, **kw)

    plain = build()
    rem = build(remat=True)
    variables = plain.init(jax.random.key(7))
    x, y = synthetic.make_sequence_task(8, seq, FEAT, seed=15)
    x = jnp.asarray(x)

    def loss(model, params):
        out, _ = model.apply(params, {}, x, train=True,
                             rng=jax.random.key(0))
        return jnp.sum(out.astype(jnp.float32) ** 2)

    l_p, g_p = jax.value_and_grad(lambda p: loss(plain, p))(
        variables.params)
    l_r, g_r = jax.value_and_grad(lambda p: loss(rem, p))(
        variables.params)
    np.testing.assert_allclose(float(l_r), float(l_p), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_r), jax.tree.leaves(g_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_predict_on_2d_mesh(devices):
    """The shared batched-forward surface (predict) drives the
    attention model on the ("data", "seq") mesh, including a
    non-dividing final batch, and equals a direct apply."""
    from idc_models_tpu.train.loop import predict

    mesh = meshlib.data_seq_mesh(4, 2)
    model = _model(mesh)
    variables = model.init(jax.random.key(0))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state, opt_state=())
    x, _ = synthetic.make_sequence_task(20, SEQ, FEAT, seed=3)
    logits = predict(model, state, x, mesh, batch_size=8)
    ref, _ = model.apply(variables.params, {}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_dropout_behaviour(devices):
    """Residual dropout: train-mode outputs vary with the rng and
    differ from eval; eval mode is deterministic and identical to the
    rate-0 model (dropout must vanish at inference); training still
    learns with dropout on."""
    mesh = meshlib.data_seq_mesh(4, 2)
    drop = _model(mesh, dropout_rate=0.3)
    plain = _model(mesh)
    variables = drop.init(jax.random.key(0))
    x, _ = synthetic.make_sequence_task(8, SEQ, FEAT, seed=17)
    x = jnp.asarray(x)

    t1, _ = drop.apply(variables.params, {}, x, train=True,
                       rng=jax.random.key(1))
    t2, _ = drop.apply(variables.params, {}, x, train=True,
                       rng=jax.random.key(2))
    assert not np.allclose(np.asarray(t1), np.asarray(t2))
    e1, _ = drop.apply(variables.params, {}, x, train=False)
    e2, _ = drop.apply(variables.params, {}, x, train=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
    p1, _ = plain.apply(variables.params, {}, x, train=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(p1))
    # out-of-range rates fail loudly at build time (core.dropout)
    with pytest.raises(ValueError, match="rate must be"):
        _model(mesh, dropout_rate=1.0)
    with pytest.raises(ValueError, match="rate must be"):
        _model(mesh, dropout_rate=-0.5)
    # learning WITH dropout is covered by the zigzag golden run
    # (dropout_rate=0.1 there), not a third 250-step training here


def _compiled_step_text(mesh, model, seq, feat):
    """Post-SPMD HLO of the standard train step for `model` — shapes in
    it are PER-DEVICE (local) shapes, so a full-length activation is
    textually visible."""
    opt = rmsprop(1e-3)
    variables = model.init(jax.random.key(0))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, binary_cross_entropy), mesh,
        axis="data")
    state = replicate(mesh, state)
    x, y = synthetic.make_sequence_task(8, seq, feat, seed=21)
    bx, by = shard_batch(mesh, x, y, axis="data")
    return step.lower(state, bx, by, jax.random.key(1)).compile().as_text()


@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_residual_stream_stays_seq_sharded(devices, layout):
    """The long-context claim at the MODEL level (VERDICT r4 #2): on the
    ("data", "seq") mesh, no [B, T, E]-shaped activation — embed output,
    block residuals, MLP hidden, per-head q/k/v — may be replicated over
    "seq" between ring calls. The compiled module's shapes are local, so
    the gate greps the partitioned HLO for any tensor whose sequence dim
    is the FULL T=64 rather than T/2: `_seq_pin`'s constraints (and the
    zigzag input-side permute) are what make this hold."""
    import re

    from idc_models_tpu.models import attention as attn_mod

    seq, feat = 64, 8
    mesh = meshlib.data_seq_mesh(2, 4)
    model = attention_classifier(seq, feat, embed_dim=48, num_heads=2,
                                 mlp_dim=96, num_blocks=2, num_outputs=1,
                                 mesh=mesh, causal=True, layout=layout)
    text = _compiled_step_text(mesh, model, seq, feat)
    # full-T residual/MLP/head-split activations, with a leading batch
    # dim (the 2-D [64,48] pos PARAM is replicated by design and must
    # not trip the gate)
    full_t = re.compile(r"\[\d+,64,(48|96)\]|\[\d+,64,2,24\]")
    hits = sorted(set(full_t.findall(text)))
    assert not hits, (
        f"full-length activations replicated over 'seq' in the "
        f"partitioned module ({layout}): {hits}")

    # positive control: the detector must SEE a violation when one is
    # forced — re-pin the stream replicated-over-seq and require the
    # full-T shape to appear
    real_pin = attn_mod._seq_pin
    try:
        def bad_pin(mesh_, axis=meshlib.SEQ_AXIS):
            if mesh_ is None:
                return lambda h: h
            others = tuple(a for a in mesh_.axis_names if a != axis)
            sh = NamedSharding(mesh_, P(others if others else None,
                                        None, None))
            return lambda h: jax.lax.with_sharding_constraint(h, sh)

        attn_mod._seq_pin = bad_pin
        bad_model = attention_classifier(
            seq, feat, embed_dim=48, num_heads=2, mlp_dim=96,
            num_blocks=2, num_outputs=1, mesh=mesh, causal=True,
            layout=layout)
    finally:
        attn_mod._seq_pin = real_pin
    bad_text = _compiled_step_text(mesh, bad_model, seq, feat)
    assert full_t.search(bad_text), (
        "positive control failed: detector cannot see a replicated "
        "full-length activation")


def test_freeze_machinery_applies(devices):
    """head_only_mask freezes everything but the head THROUGH the ring:
    one step with the masked optimizer moves head params and nothing
    else."""
    mesh = meshlib.data_seq_mesh(4, 2)
    model = _model(mesh)
    variables = model.init(jax.random.key(0))
    mask = core.head_only_mask(variables.params)
    opt = freeze_where(rmsprop(1e-2), mask)
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, binary_cross_entropy), mesh,
        axis="data")
    state = replicate(mesh, state)
    # host copies: the step donates the state, invalidating its buffers
    before = jax.tree.map(np.asarray, variables.params)
    x, y = synthetic.make_sequence_task(16, SEQ, FEAT, seed=11)
    bx, by = shard_batch(mesh, x, y, axis="data")
    new_state, _ = step(state, bx, by, jax.random.key(2))
    after = new_state.params
    assert not np.allclose(np.asarray(after["head"]["kernel"]),
                           np.asarray(before["head"]["kernel"]))
    for name in ("embed", "pos", "block0", "block1", "ln_f"):
        for a, b in zip(jax.tree.leaves(after[name]),
                        jax.tree.leaves(before[name])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)
