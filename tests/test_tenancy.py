"""Multi-tenant serving (serve/tenancy.py, ISSUE 14) against its
contracts:

1. PARITY — a tenant's greedy/seeded stream under MIXED-tenant load is
   bit-identical to the same requests on a single-tenant server, at
   the engine level (SlotEngine + adapter bank, window AND verify
   programs, contiguous AND paged) and the server level (LMServer +
   TenantRegistry). The adapter gather is slot-indexed inside the
   fused programs, so this is parity by construction — these tests
   gate that the construction holds.
2. ZERO RECOMPILATION — tenant arrival patterns are VALUES, not
   shapes: after warmup, any mix of tenants admits with no jit cache
   growth.
3. ISOLATION — per-tenant quotas (slots, queued, KV pages) bound one
   tenant without starving its neighbors (the admission scan skips a
   quota-blocked entry instead of head-of-line blocking everyone),
   per-tenant SLOs breach independently, and a tenant's brownout
   sheds only that tenant.
4. TEACHING ERRORS — unknown tenants, bad quotas, duplicate
   registration, and adapter-shape mismatches fail loudly at build,
   never at the first request.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from idc_models_tpu.models.lm import attention_lm
from idc_models_tpu.serve import (
    LMServer, Request, SlotEngine, TenantQuota, TenantRegistry,
)
from idc_models_tpu.serve.journal import RequestJournal, pending_requests
from idc_models_tpu.serve.tenancy import AdapterBank

VOCAB, SEQ, E, HEADS, MLP, BLOCKS = 11, 32, 32, 2, 64, 2
RANK = 3


@pytest.fixture(scope="module")
def params():
    model = attention_lm(VOCAB, SEQ, embed_dim=E, num_heads=HEADS,
                         mlp_dim=MLP, num_blocks=BLOCKS)
    return model.init(jax.random.key(0)).params


def _kw(**over):
    kw = dict(embed_dim=E, num_heads=HEADS, num_blocks=BLOCKS,
              t_max=SEQ, cache_dtype=jnp.float32)
    kw.update(over)
    return kw


def _adapter(seed, scale=0.5):
    rng = np.random.default_rng(seed)
    return (rng.normal(0, scale, (VOCAB, RANK)).astype(np.float32),
            rng.normal(0, scale, (RANK, VOCAB)).astype(np.float32))


def _bank(*adapters):
    """Stack explicit (u, v) pairs (None = zero rows) into an
    AdapterBank — the engine-level fixture, registry-free."""
    u = np.zeros((len(adapters), VOCAB, RANK), np.float32)
    v = np.zeros((len(adapters), RANK, VOCAB), np.float32)
    for i, a in enumerate(adapters):
        if a is not None:
            u[i], v[i] = a
    return AdapterBank(u=u, v=v, rank=RANK, vocab=VOCAB)


def _registry(*, quotas=None, slos=None, adapters=None):
    reg = TenantRegistry()
    for name in ("acme", "globex"):
        reg.register(
            name,
            adapter=(adapters or {}).get(name),
            quota=(quotas or {}).get(name),
            slo_ttft_p95_ms=(slos or {}).get(name))
    return reg


# -- registry / build teaching errors ----------------------------------


def test_registry_validation_teaching_errors():
    reg = TenantRegistry()
    reg.register("acme")
    with pytest.raises(ValueError, match="already registered"):
        reg.register("acme")
    with pytest.raises(ValueError, match="non-empty string"):
        reg.register("")
    with pytest.raises(ValueError, match="admit nothing ever"):
        TenantQuota(max_resident_slots=0)
    with pytest.raises(ValueError, match="admit nothing ever"):
        TenantQuota(max_queued=-1)
    with pytest.raises(ValueError, match="slo_ttft_p95_ms"):
        reg.register("b", slo_ttft_p95_ms=0)
    with pytest.raises(ValueError, match="TenantQuota"):
        reg.register("c", quota=3)
    with pytest.raises(ValueError, match="no tenants"):
        TenantRegistry().build()
    bad = TenantRegistry(default="ghost")
    bad.register("x")
    with pytest.raises(ValueError, match="default tenant"):
        bad.build()
    built = TenantRegistry()
    built.register("only")
    built.build()
    with pytest.raises(ValueError, match="already built"):
        built.register("late")


def test_adapter_shape_mismatch_rejected_at_build():
    u, v = _adapter(0)
    reg = TenantRegistry()
    with pytest.raises(ValueError, match=r"\(u, v\) pair"):
        reg.register("a", adapter=u)
    with pytest.raises(ValueError, match="transposes"):
        reg.register("a", adapter=(u, v.T))
    reg.register("a", adapter=(u, v))
    rng = np.random.default_rng(9)
    other = (rng.normal(size=(VOCAB, RANK + 2)).astype(np.float32),
             rng.normal(size=(RANK + 2, VOCAB)).astype(np.float32))
    with pytest.raises(ValueError, match="share one \\[V, r\\]"):
        reg.register("b", adapter=other)
    # vocab mismatch surfaces at BUILD against the model's head
    with pytest.raises(ValueError, match="model vocab"):
        reg.build(vocab=VOCAB + 5)


def test_engine_rejects_wrong_vocab_bank_and_bad_tid(params):
    bank = AdapterBank(
        u=np.zeros((2, VOCAB + 1, RANK), np.float32),
        v=np.zeros((2, RANK, VOCAB + 1), np.float32),
        rank=RANK, vocab=VOCAB + 1)
    with pytest.raises(ValueError, match="model vocab"):
        SlotEngine(params, n_slots=2, adapter_bank=bank, **_kw())
    eng = SlotEngine(params, n_slots=2,
                     adapter_bank=_bank(_adapter(0), None), **_kw())
    with pytest.raises(ValueError, match="out of range"):
        eng.admit(0, [1, 2, 3], 4, tid=2)


def test_unknown_tenant_is_a_loud_caller_error(params):
    server = LMServer(params, n_slots=2, tenancy=_registry(), **_kw())
    with pytest.raises(ValueError, match="unknown tenant"):
        server.submit(Request(id="x", prompt=(1, 2), max_new_tokens=2,
                              tenant="ghost"))


# -- parity: engine level (window + verify, contiguous + paged) ---------


def _engine_tokens(eng, prompt, budget, tid, *, rng=None):
    eng.admit(0, prompt, budget, tid=tid, rng=rng)
    out = []
    while not eng.finished(0):
        out.extend(eng.step_window(4).get(0, []))
    eng.release(0)
    return out


def test_engine_mixed_vs_single_tenant_parity_greedy_and_sampled(
        params, devices):
    """The acceptance gate at ENGINE level: tenant A's stream through
    a 2-tenant bank (A = tid 1, gathered) is bit-identical to a
    1-tenant bank's (A = tid 0) — greedy and seeded top-k — and the
    adapter genuinely changes the stream vs the base model."""
    a = _adapter(7)
    mixed = SlotEngine(params, n_slots=2,
                       adapter_bank=_bank(_adapter(3), a), **_kw())
    solo = SlotEngine(params, n_slots=2, adapter_bank=_bank(a),
                      **_kw())
    base = SlotEngine(params, n_slots=2, **_kw())
    prompt = [1, 4, 2, 7, 5]
    want = _engine_tokens(solo, prompt, 8, 0)
    assert _engine_tokens(mixed, prompt, 8, 1) == want
    assert _engine_tokens(base, prompt, 8, 0) != want

    m_s = SlotEngine(params, n_slots=2, temperature=0.9, top_k=5,
                     adapter_bank=_bank(_adapter(3), a), **_kw())
    s_s = SlotEngine(params, n_slots=2, temperature=0.9, top_k=5,
                     adapter_bank=_bank(a), **_kw())
    assert (_engine_tokens(m_s, prompt, 8, 1, rng=123)
            == _engine_tokens(s_s, prompt, 8, 0, rng=123))


def test_engine_verify_program_applies_adapter_identically(params):
    """The VERIFY program's adapter path: same scripted drafts into a
    mixed-bank engine (tid 1) and a solo-bank engine (tid 0) emit
    bit-identical accept/bonus tokens."""
    a = _adapter(11)
    outs = []
    for bank, tid in ((_bank(_adapter(5), a), 1), (_bank(a), 0)):
        eng = SlotEngine(params, n_slots=2, draft_k=3,
                         adapter_bank=bank, **_kw())
        eng.admit(0, [2, 6, 1], 10, tid=tid)
        drafts = np.zeros((2, 3), np.int32)
        drafts[0] = [3, 1, 4]
        vlive = np.array([True, False])
        eng.begin_verify(drafts, vlive)
        outs.append(eng.collect()[0])
    assert outs[0] == outs[1] and outs[0]


def test_server_mixed_vs_single_tenant_parity_paged(params, devices):
    """Server-level parity on the PAGED engine: mixed two-tenant load
    vs a single-tenant paged server, bit-identical per request (the
    PR 11 one-device paged==contiguous contract composes with the
    adapter gather)."""
    a, g = _adapter(21), _adapter(22)
    paged = dict(prefill_chunk=4, kv_page_size=4, kv_pages=24)
    mixed = LMServer(
        params, n_slots=3, window=4,
        tenancy=_registry(adapters={"acme": a, "globex": g}),
        **_kw(), **paged)
    reqs = [Request(id=f"r{i}",
                    prompt=tuple([1 + i, 2, 3 + i, 4, 5][:3 + i % 3]),
                    max_new_tokens=5 + i % 4,
                    tenant=("acme" if i % 2 else "globex"))
            for i in range(6)]
    got = {r.id: r.tokens for r in mixed.run([(0.0, r) for r in reqs])}
    for name, adapter in (("acme", a), ("globex", g)):
        reg = TenantRegistry()
        reg.register(name, adapter=adapter)
        solo = LMServer(params, n_slots=3, window=4, tenancy=reg,
                        **_kw(), **paged)
        for r in reqs:
            if r.tenant != name:
                continue
            want = solo.run([(0.0, Request(
                id=r.id, prompt=r.prompt,
                max_new_tokens=r.max_new_tokens, tenant=name))])[0]
            assert got[r.id] == want.tokens, (r.id, got[r.id],
                                              want.tokens)


def test_zero_recompile_across_tenant_arrival_patterns(params):
    """The acceptance gate: after warmup + a first mixed wave, ANY
    tenant arrival pattern admits with zero jit cache growth — tenant
    ids are traced values, never shapes."""
    server = LMServer(
        params, n_slots=3, window=4,
        tenancy=_registry(adapters={"acme": _adapter(1),
                                    "globex": _adapter(2)}),
        **_kw())
    rng = np.random.default_rng(3)

    def wave(tag, tenants):
        return [(0.0, Request(
            id=f"{tag}{i}",
            prompt=tuple(int(x) for x in
                         rng.integers(0, VOCAB, 3 + i % 5)),
            max_new_tokens=3 + i % 4, tenant=t))
            for i, t in enumerate(tenants)]

    server.run(wave("w", ["acme", "globex"]))
    sizes = server.engine.cache_sizes()
    # bursts of one tenant, alternation, reversed mixes — all values
    server.run(wave("a", ["acme"] * 4))
    server.run(wave("b", ["globex"] * 4))
    server.run(wave("c", ["globex", "acme", "acme", "globex"]))
    assert server.engine.cache_sizes() == sizes, (
        server.engine.cache_sizes(), sizes)


# -- isolation: quotas, SLOs, per-tenant brownout -----------------------


def test_slot_quota_caps_tenant_without_starving_neighbor(params):
    """acme is capped at 1 resident slot on a 3-slot engine; a burst
    of acme work must never hold >1 slot while globex fills the rest
    — the admission scan skips the quota-blocked backlog instead of
    head-of-line blocking it."""
    server = LMServer(
        params, n_slots=3, window=4,
        tenancy=_registry(
            quotas={"acme": TenantQuota(max_resident_slots=1)}),
        **_kw())
    reqs = ([Request(id=f"a{i}", prompt=(1, 2, 3), max_new_tokens=8,
                     tenant="acme") for i in range(4)]
            + [Request(id=f"g{i}", prompt=(4, 5), max_new_tokens=8,
                       tenant="globex") for i in range(4)])
    for r in reqs:
        assert server.submit(r)
    peak_acme = 0
    while not server.scheduler.idle():
        server.step()
        slots, _ = server.scheduler._tenant_residency()
        peak_acme = max(peak_acme, slots.get("acme", 0))
        # with acme capped at 1, globex must reach >= 2 of 3 slots
    assert peak_acme == 1
    assert all(server.poll(r.id).status == "ok" for r in reqs)
    # quotas released everything at drain
    slots, pages = server.scheduler._tenant_residency()
    assert slots == {} and pages == {}


def test_queue_quota_rejects_flood_without_touching_neighbors(params):
    server = LMServer(
        params, n_slots=1, window=4,
        tenancy=_registry(quotas={"acme": TenantQuota(max_queued=2)}),
        **_kw())
    acc = [server.submit(Request(id=f"a{i}", prompt=(1, 2),
                                 max_new_tokens=4, tenant="acme"))
           for i in range(6)]
    # the first fills the free slot path... all queue until a step;
    # at most 2 queued acme accepted beyond, rest refused
    assert sum(acc) < 6 and acc.count(False) >= 3
    # globex is untouched by acme's refusals
    assert server.submit(Request(id="g0", prompt=(3,),
                                 max_new_tokens=4, tenant="globex"))
    server.drain()
    s = server.summary()["serve_tenants"]
    assert s["acme"]["quota_rejections"] == acc.count(False)
    assert s["globex"]["quota_rejections"] == 0
    assert s["globex"]["requests"] == 1


def test_page_quota_bounds_tenant_kv_reservations(params):
    """Paged engine: acme's admissions may hold at most 3 pool pages;
    its second request waits for its own releases while globex keeps
    admitting from the same pool."""
    server = LMServer(
        params, n_slots=3, window=4, prefill_chunk=4, kv_page_size=4,
        kv_pages=24,
        tenancy=_registry(
            quotas={"acme": TenantQuota(kv_page_budget=3)}),
        **_kw())
    # each request: prompt 4 + budget 8 -> 12 tokens -> 3 pages
    reqs = ([Request(id=f"a{i}", prompt=(1, 2, 3, 4),
                     max_new_tokens=8, tenant="acme")
             for i in range(3)]
            + [Request(id=f"g{i}", prompt=(5, 6, 7, 8),
                       max_new_tokens=8, tenant="globex")
               for i in range(3)])
    for r in reqs:
        assert server.submit(r)
    peak_acme_pages = 0
    while not server.scheduler.idle():
        server.step()
        _, pages = server.scheduler._tenant_residency()
        peak_acme_pages = max(peak_acme_pages, pages.get("acme", 0))
    assert peak_acme_pages == 3          # exactly one resident at a time
    assert all(server.poll(r.id).status == "ok" for r in reqs)


def test_per_tenant_slo_breach_and_brownout_are_tenant_scoped():
    """The admission signal: only the burning tenant's ttft:<name>
    objective breaches, and only ITS brownout escalates — evaluated
    on a fake clock, no serving needed."""
    t = {"now": 0.0}
    clock = lambda: t["now"]    # noqa: E731
    reg = _registry(slos={"acme": 100.0, "globex": 100.0})
    ten = reg.build(clock=clock, slo_short_window_s=10.0,
                    slo_min_samples=5, brownout_dwell_s=0.0)
    for i in range(20):
        t["now"] += 0.1
        ten.observe_ttft("acme", 0.5)       # 5x the 100ms objective
        ten.observe_ttft("globex", 0.01)
    ten.evaluate()
    assert ten.breached("acme") and not ten.breached("globex")
    for _ in range(4):
        ten.brownouts["acme"].evaluate(queue_depth=0)
        ten.brownouts["globex"].evaluate(queue_depth=0)
        t["now"] += 1.0
    assert ten.brownouts["acme"].shedding
    assert ten.brownouts["globex"].stage == 0


def test_tenant_shed_refuses_only_that_tenant(params):
    reg = _registry(quotas={"acme": TenantQuota(max_queued=8)})
    ten = reg.build()
    ten.brownouts["acme"].force_stage(3, reason="drill")
    server = LMServer(params, n_slots=2, tenancy=ten, **_kw())
    assert not server.submit(Request(id="a0", prompt=(1, 2),
                                     max_new_tokens=2, tenant="acme"))
    assert server.poll("a0").status == "shed"
    assert server.submit(Request(id="g0", prompt=(1, 2),
                                 max_new_tokens=2, tenant="globex"))
    server.drain()
    assert server.poll("g0").status == "ok"
    s = server.summary()["serve_tenants"]
    assert s["acme"]["shed"] == 1 and s["globex"]["shed"] == 0


# -- journal / trace tag preservation -----------------------------------


def test_journal_preserves_tenant_tags(params, tmp_path):
    """Recovery bills the SAME tenant: journaled submits carry the
    tenant tag, pending_requests reconstructs it, and a rebuilt
    server's resubmission lands under that tenant's rollup."""
    path = tmp_path / "wal.jsonl"
    server = LMServer(params, n_slots=2, tenancy=_registry(),
                      journal=str(path), **_kw())
    for i, tenant in enumerate(["acme", "globex", "acme"]):
        assert server.submit(Request(id=f"r{i}", prompt=(1, 2, 3),
                                     max_new_tokens=3, tenant=tenant))
    server.close()                       # crash stand-in: nothing ran
    pend = pending_requests(path)
    assert [r.tenant for r in pend] == ["acme", "globex", "acme"]
    server2 = LMServer(params, n_slots=2, tenancy=_registry(),
                       journal=str(path), **_kw())
    assert server2.resubmit_pending(path) == ["r0", "r1", "r2"]
    server2.drain()
    s = server2.summary()["serve_tenants"]
    assert s["acme"]["requests"] == 2 and s["globex"]["requests"] == 1


def test_journal_without_tenants_stays_byte_identical(tmp_path):
    """Tenant-less journals must not grow a tenant key — old files and
    old consumers see the exact historical record shape."""
    import json

    from idc_models_tpu.serve.scheduler import Entry

    path = tmp_path / "wal.jsonl"
    j = RequestJournal(path)
    j.record_submit(Entry(rid="r0", prompt=np.array([1, 2]), budget=3),
                    deadline_s=None)
    j.close()
    rec = json.loads(path.read_text().splitlines()[0])
    assert "tenant" not in rec


def test_recovery_skips_decommissioned_tenant_without_aborting(
        params, tmp_path):
    """A WAL entry for a tenant the REBUILT server no longer registers
    must not abort the whole recovery: it is skipped with a warning
    (staying in the WAL for a rerun) while every other tenant's
    requests come back."""
    path = tmp_path / "wal.jsonl"
    server = LMServer(params, n_slots=2, tenancy=_registry(),
                      journal=str(path), **_kw())
    for i, tenant in enumerate(["acme", "globex", "acme"]):
        assert server.submit(Request(id=f"r{i}", prompt=(1, 2, 3),
                                     max_new_tokens=3, tenant=tenant))
    server.close()
    reg = TenantRegistry()
    reg.register("acme")                 # globex decommissioned
    server2 = LMServer(params, n_slots=2, tenancy=reg,
                       journal=str(path), **_kw())
    with pytest.warns(UserWarning, match="skipped request 'r1'"):
        recovered = server2.resubmit_pending(path)
    assert recovered == ["r0", "r2"]
    server2.drain()
    assert server2.summary()["serve_tenants"]["acme"]["requests"] == 2
    # the skipped entry is still pending in the WAL for a fixed rerun
    server2.close()
    assert [r.id for r in pending_requests(path)] == ["r1"]
