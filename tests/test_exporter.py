"""ISSUE 7 tentpole (a): the live /metrics endpoint — scrape output
byte-identical to `prometheus_text()`, the /healthz document, error
paths, and clean lifecycle."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from idc_models_tpu.observe import MetricsExporter, MetricsRegistry
from idc_models_tpu.observe.exporter import LAST_TICK_GAUGE


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def test_metrics_scrape_byte_identical_to_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("reqs_total", "requests", labels=("status",)).inc(
        3, status="ok")
    reg.gauge("depth", "queue depth").set(2)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    with MetricsExporter(reg, port=0) as exp:
        status, ctype, body = _get(exp.url + "/metrics")
        assert status == 200
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        # the acceptance bar: the scrape IS the exposition — no
        # translation layer to drift
        assert body == reg.prometheus_text()
        # a second scrape after a mutation reflects it
        reg.gauge("depth").set(7)
        _, _, body2 = _get(exp.url + "/metrics")
        assert body2 == reg.prometheus_text()
        assert "depth 7" in body2


def test_healthz_reports_tick_age_queue_and_occupancy():
    reg = MetricsRegistry()
    with MetricsExporter(reg, port=0) as exp:
        # nothing registered yet: every field null, status still ok
        # (a trainer exposing /metrics has no serve gauges)
        _, ctype, body = _get(exp.url + "/healthz")
        doc = json.loads(body)
        assert ctype.startswith("application/json")
        assert doc == {"status": "ok", "last_tick_age_s": None,
                       "queue_depth": None, "slot_occupancy": None,
                       "kv_pages_used": None, "kv_pages_total": None,
                       "brownout_stage": None}
        # the serve gauges appear -> the document fills in
        reg.gauge(LAST_TICK_GAUGE, "tick stamp").set(time.monotonic())
        reg.gauge("serve_queue_depth", "depth").set(3)
        reg.gauge("serve_slot_occupancy", "occ").set(0.5)
        doc = json.loads(_get(exp.url + "/healthz")[2])
        assert doc["queue_depth"] == 3.0
        assert doc["slot_occupancy"] == 0.5
        assert 0.0 <= doc["last_tick_age_s"] < 5.0


def test_healthz_reports_page_headroom_and_brownout_stage():
    """ISSUE 12 satellite: the gauges the cluster router routes on —
    paged-KV pool occupancy and the brownout stage — surface on
    /healthz (they previously existed only in /metrics)."""
    reg = MetricsRegistry()
    with MetricsExporter(reg, port=0) as exp:
        reg.gauge("serve_kv_pages_used", "pool pages used").set(12)
        reg.gauge("serve_kv_pages_total", "pool size").set(64)
        reg.gauge("serve_brownout_stage", "degradation stage").set(2)
        doc = json.loads(_get(exp.url + "/healthz")[2])
        assert doc["kv_pages_used"] == 12.0
        assert doc["kv_pages_total"] == 64.0
        # the stage is an ENUM, handed back as an int so an LB config
        # can compare it against the shed threshold without float fuzz
        assert doc["brownout_stage"] == 2
        assert isinstance(doc["brownout_stage"], int)


def test_healthz_ignores_wrong_kind_and_labeled_series():
    """A COUNTER named like the gauge, or a gauge with only labeled
    series, must not be misread into the health document."""
    reg = MetricsRegistry()
    reg.counter("serve_queue_depth", "wrong kind").inc(9)
    reg.gauge("serve_slot_occupancy", "labeled only",
              labels=("tenant",)).set(0.9, tenant="a")
    with MetricsExporter(reg, port=0) as exp:
        doc = json.loads(_get(exp.url + "/healthz")[2])
        assert doc["queue_depth"] is None
        assert doc["slot_occupancy"] is None


def test_unknown_path_404_and_server_survives():
    reg = MetricsRegistry()
    reg.counter("c_total", "c").inc()
    with MetricsExporter(reg, port=0) as exp:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(exp.url + "/nope")
        assert ei.value.code == 404
        # the 404 did not kill the server
        assert _get(exp.url + "/metrics")[0] == 200


def test_concurrent_scrapes_do_not_interleave():
    """ThreadingHTTPServer + per-instrument locks: parallel scrapers
    each get a complete, parseable exposition."""
    reg = MetricsRegistry()
    c = reg.counter("spins_total", "spins")
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            c.inc()

    bodies = []
    with MetricsExporter(reg, port=0) as exp:
        t = threading.Thread(target=spin, daemon=True)
        t.start()
        try:
            threads = [threading.Thread(
                target=lambda: bodies.append(
                    _get(exp.url + "/metrics")[2]))
                for _ in range(4)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        finally:
            stop.set()
            t.join()
    assert len(bodies) == 4
    for b in bodies:
        assert "# TYPE spins_total counter" in b
        val = [l for l in b.splitlines()
               if l.startswith("spins_total ")][0]
        assert float(val.split()[1]) >= 0


def test_lifecycle_close_idempotent_and_port_errors():
    reg = MetricsRegistry()
    exp = MetricsExporter(reg, port=0)
    with pytest.raises(RuntimeError):
        _ = exp.port                 # not started yet
    exp.start()
    port = exp.port
    with pytest.raises(RuntimeError):
        exp.start()                  # double start is loud
    exp.close()
    exp.close()                      # idempotent
    # the socket really was released: a new exporter can take the port
    exp2 = MetricsExporter(reg, port=port).start()
    try:
        assert exp2.port == port
    finally:
        exp2.close()


def test_default_registry_is_process_registry():
    from idc_models_tpu.observe import REGISTRY

    exp = MetricsExporter(port=0)
    assert exp.registry is REGISTRY


def test_healthz_grows_tenant_block_when_tenant_gauges_exist():
    """ISSUE 14: the per-tenant health block — queue depth, slots,
    page reservations, the tenant's OWN brownout stage — appears only
    when the tenant-labeled gauges exist (tenant-less servers keep the
    historical document byte-identical, gated above)."""
    reg = MetricsRegistry()
    with MetricsExporter(reg, port=0) as exp:
        doc = json.loads(_get(exp.url + "/healthz")[2])
        assert "tenants" not in doc
        q = reg.gauge("serve_tenant_queue_depth", "per-tenant depth",
                      labels=("tenant",))
        s = reg.gauge("serve_tenant_slots_used", "per-tenant slots",
                      labels=("tenant",))
        b = reg.gauge("serve_tenant_brownout_stage", "per-tenant stage",
                      labels=("tenant",))
        q.set(4, tenant="acme")
        s.set(2, tenant="acme")
        b.set(3, tenant="acme")
        q.set(0, tenant="globex")
        doc = json.loads(_get(exp.url + "/healthz")[2])
        assert set(doc["tenants"]) == {"acme", "globex"}
        assert doc["tenants"]["acme"] == {
            "queue_depth": 4.0, "slots_used": 2.0,
            "kv_pages_used": None, "brownout_stage": 3}
        assert isinstance(doc["tenants"]["acme"]["brownout_stage"], int)
        assert doc["tenants"]["globex"]["queue_depth"] == 0.0
        assert doc["tenants"]["globex"]["brownout_stage"] is None
